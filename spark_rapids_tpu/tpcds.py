"""TPC-DS data generation + query corpus over the DataFrame API.

Role of the reference's NDS/TPC-DS integration suite (SURVEY §2.13,
§6): the star schema with dsdgen-style deterministic generators — three
sales channels (store_sales / web_sales / catalog_sales) with matching
returns fact tables, a Julian-day-keyed date_dim with calendar
derivations, and the dimension tables the first query tranche touches —
plus a ``QUERIES`` registry of representative queries chosen to exercise
the DS-specific operator shapes the TPC-H suite does not reach:

  * ROLLUP / grouping sets through the Expand lowering with
    ``grouping()`` / ``grouping_id()`` (q27, q36, q70, q86)
  * window ranking over category hierarchies (q36, q70, q86) and
    partition-total revenue ratios (q12, q20, q98)
  * multi-fact UNION ALL "channel" queries (q33, q56, q60, q76)
  * date_dim-driven filters and semi joins on every fact table

Spec-shaped types throughout: money is decimal(7,2), surrogate keys are
int64 starting at 1, dates are date32, quantities/calendar fields int32.
Row counts scale linearly with ``scale`` (scale=1.0 -> SF1-ish counts);
fixed-size dimensions (date_dim, time_dim, demographics, reason) do not
scale, exactly as dsdgen keeps them scale-independent.  Value
distributions follow the spec's shapes (uniform ranges, cyclic dimension
attributes, nullable foreign keys) without the full dsdgen grammar; the
query parameter substitutions are chosen so every query is non-empty at
the tiny tier-1 test scale.
"""
from __future__ import annotations

import datetime as pydt
from typing import Dict

import numpy as np
import pyarrow as pa

from .plan import expressions as E
from .plan.aggregates import Average, Count, Sum
from .session import DataFrame, TpuSession, col
from .tpch import money_from_cents
from . import types as _t

DTYPE_DATE = _t.DATE

_EPOCH = pydt.date(1970, 1, 1)
# date_sk is the Julian day number, as dsdgen assigns it
# (2000-01-01 -> 2451545)
_JDN_OFFSET = 1721425

CATEGORIES = ["Books", "Children", "Electronics", "Home", "Jewelry",
              "Men", "Music", "Shoes", "Sports", "Women"]
STATES = ["TN", "SC", "AL", "GA", "SD", "MI", "OH", "TX", "KY", "MN",
          "NE", "IA", "IL", "MO", "KS", "WI", "VA", "NC", "IN", "WV"]
DAY_NAMES = ["Monday", "Tuesday", "Wednesday", "Thursday", "Friday",
             "Saturday", "Sunday"]
COLORS = ["slate", "blanched", "burnished", "red", "green", "blue",
          "khaki", "ivory"]
BUY_POTENTIAL = [">10000", "unknown", "1001-5000", "5001-10000",
                 "501-1000", "0-500"]
_FIRST = ["James", "Mary", "John", "Linda", "Robert", "Susan", "Michael",
          "Karen", "William", "Betty"]
_LAST = ["Smith", "Johnson", "Brown", "Jones", "Miller", "Davis",
         "Garcia", "Wilson", "Moore", "Taylor"]


def _jdn(d: pydt.date) -> int:
    return d.toordinal() + _JDN_OFFSET


def _days(d: pydt.date) -> int:
    return (d - _EPOCH).days


def money7(cents: np.ndarray) -> pa.Array:
    """decimal(7,2) money lane; cents clipped to the type's domain."""
    return money_from_cents(
        np.clip(cents.astype(np.int64), -9_999_999, 9_999_999), 7, 2)


def _cyc(values, n: int) -> pa.Array:
    """Cyclic dimension attribute: deterministic, every value present
    once n >= len(values) (dsdgen keeps low-cardinality attributes
    uniformly covered, so point filters never come back empty)."""
    reps = -(-n // len(values))
    return pa.array((list(values) * reps)[:n])


def _sk(n: int) -> pa.Array:
    return pa.array(np.arange(1, n + 1), pa.int64())


def _fk(rng, hi: int, n: int, null_frac: float = 0.02) -> pa.Array:
    """Foreign key sample over 1..hi with the spec's nullable fks."""
    vals = rng.integers(1, hi + 1, n).astype(np.int64)
    if null_frac <= 0:
        return pa.array(vals, pa.int64())
    return pa.array(vals, pa.int64(), mask=rng.random(n) < null_frac)


def gen_date_dim() -> pa.Table:
    """Calendar 1998-01-01 .. 2003-12-31 with the spec's derivations.
    d_month_seq counts months since 1900-01 (Jan-2000 -> 1200), the
    convention the monthly-window queries (q65, q70, q86) rely on."""
    start = pydt.date(1998, 1, 1)
    end = pydt.date(2003, 12, 31)
    n = (end - start).days + 1
    dates = [start + pydt.timedelta(days=i) for i in range(n)]
    moy = np.array([d.month for d in dates], np.int32)
    return pa.table({
        "d_date_sk": pa.array([_jdn(d) for d in dates], pa.int64()),
        "d_date_id": pa.array([f"AAAAAAAA{_jdn(d):08d}" for d in dates]),
        "d_date": pa.array(np.array([_days(d) for d in dates], np.int32),
                           pa.int32()).cast(pa.date32()),
        "d_year": pa.array(np.array([d.year for d in dates], np.int32),
                           pa.int32()),
        "d_moy": pa.array(moy, pa.int32()),
        "d_dom": pa.array(np.array([d.day for d in dates], np.int32),
                          pa.int32()),
        "d_qoy": pa.array((moy - 1) // 3 + 1, pa.int32()),
        "d_dow": pa.array(np.array([d.weekday() for d in dates], np.int32),
                          pa.int32()),
        "d_day_name": pa.array([DAY_NAMES[d.weekday()] for d in dates]),
        "d_month_seq": pa.array(
            np.array([(d.year - 1900) * 12 + d.month - 1 for d in dates],
                     np.int32), pa.int32()),
    })


def gen_time_dim() -> pa.Table:
    """All 86400 seconds of the day (fixed size, as dsdgen)."""
    sk = np.arange(86400, dtype=np.int64)
    return pa.table({
        "t_time_sk": pa.array(sk, pa.int64()),
        "t_time": pa.array(sk.astype(np.int32), pa.int32()),
        "t_hour": pa.array((sk // 3600).astype(np.int32), pa.int32()),
        "t_minute": pa.array(((sk // 60) % 60).astype(np.int32),
                             pa.int32()),
        "t_second": pa.array((sk % 60).astype(np.int32), pa.int32()),
    })


def gen_tables(scale: float = 0.01, seed: int = 20250804
               ) -> Dict[str, pa.Table]:
    rng = np.random.default_rng(seed)
    n_item = max(int(18_000 * scale), 200)
    n_cust = max(int(100_000 * scale), 100)
    n_ca = max(int(50_000 * scale), 60)
    n_store = max(int(12 * scale), 6)
    n_promo = max(int(300 * scale), 30)
    n_ss = max(int(2_880_404 * scale), 2500)
    n_ws = max(int(719_384 * scale), 900)
    n_cs = max(int(1_441_548 * scale), 1300)

    date_dim = gen_date_dim()
    time_dim = gen_time_dim()
    # facts sell during 1998..2002 (the tranche's filter years)
    sell_lo = _jdn(pydt.date(1998, 1, 1))
    sell_hi = _jdn(pydt.date(2002, 12, 31))

    # -- item ---------------------------------------------------------------
    isk = np.arange(1, n_item + 1)
    cat_id = (isk - 1) % 10 + 1
    class_id = (isk - 1) % 16 + 1
    brand_id = (isk - 1) % 1000 + 1001
    manufact_id = (isk - 1) % 1000 + 1
    manager_id = (isk - 1) % 100 + 1
    item = pa.table({
        "i_item_sk": pa.array(isk, pa.int64()),
        "i_item_id": pa.array([f"AAAAAAAA{k:08d}" for k in isk]),
        "i_item_desc": pa.array([f"item description {k}" for k in isk]),
        "i_current_price": money7(rng.integers(99, 9999, n_item)),
        "i_wholesale_cost": money7(rng.integers(50, 6000, n_item)),
        "i_brand_id": pa.array(brand_id.astype(np.int32), pa.int32()),
        "i_brand": pa.array([f"Brand#{b}" for b in brand_id]),
        "i_class_id": pa.array(class_id.astype(np.int32), pa.int32()),
        "i_class": pa.array([f"class{c:02d}" for c in class_id]),
        "i_category_id": pa.array(cat_id.astype(np.int32), pa.int32()),
        "i_category": pa.array([CATEGORIES[c - 1] for c in cat_id]),
        "i_manufact_id": pa.array(manufact_id.astype(np.int32),
                                  pa.int32()),
        "i_manufact": pa.array([f"Manufacturer#{m}" for m in manufact_id]),
        "i_manager_id": pa.array(manager_id.astype(np.int32), pa.int32()),
        "i_color": _cyc(COLORS, n_item),
    })

    # -- customer_demographics: fixed cross product (dsdgen keeps cd
    # scale-independent); sk enumerates the attribute combinations -------
    genders = ["M", "F"]
    maritals = ["M", "S", "D", "W", "U"]
    educations = ["Primary", "Secondary", "College", "2 yr Degree",
                  "4 yr Degree", "Advanced Degree", "Unknown"]
    credits = ["Low Risk", "High Risk", "Good", "Unknown"]
    combos = [(g, m, e, c) for c in credits for e in educations
              for m in maritals for g in genders]
    n_cd = len(combos)
    customer_demographics = pa.table({
        "cd_demo_sk": _sk(n_cd),
        "cd_gender": pa.array([g for g, _m, _e, _c in combos]),
        "cd_marital_status": pa.array([m for _g, m, _e, _c in combos]),
        "cd_education_status": pa.array([e for _g, _m, e, _c in combos]),
        "cd_credit_rating": pa.array([c for _g, _m, _e, c in combos]),
    })

    # -- household_demographics: fixed 20 x 6 x 10 x 6 cross product ------
    hd = [(ib, bp, dep, veh)
          for ib in range(1, 21) for bp in BUY_POTENTIAL
          for dep in range(10) for veh in range(-1, 5)]
    n_hd = len(hd)
    household_demographics = pa.table({
        "hd_demo_sk": _sk(n_hd),
        "hd_income_band_sk": pa.array([x[0] for x in hd], pa.int64()),
        "hd_buy_potential": pa.array([x[1] for x in hd]),
        "hd_dep_count": pa.array(np.array([x[2] for x in hd], np.int32),
                                 pa.int32()),
        "hd_vehicle_count": pa.array(np.array([x[3] for x in hd],
                                              np.int32), pa.int32()),
    })

    # -- customer / customer_address --------------------------------------
    csk = np.arange(1, n_cust + 1)
    customer = pa.table({
        "c_customer_sk": pa.array(csk, pa.int64()),
        "c_customer_id": pa.array([f"AAAAAAAA{k:08d}" for k in csk]),
        "c_current_cdemo_sk": _fk(rng, n_cd, n_cust),
        "c_current_hdemo_sk": _fk(rng, n_hd, n_cust),
        "c_current_addr_sk": _fk(rng, n_ca, n_cust, null_frac=0.0),
        "c_first_name": _cyc(_FIRST, n_cust),
        "c_last_name": _cyc(_LAST, n_cust),
        "c_salutation": _cyc(["Mr.", "Mrs.", "Ms.", "Dr.", "Sir"], n_cust),
        "c_preferred_cust_flag": _cyc(["Y", "N"], n_cust),
        "c_birth_year": pa.array(rng.integers(1924, 1993, n_cust)
                                 .astype(np.int32), pa.int32()),
        "c_birth_country": _cyc(["UNITED STATES", "CANADA", "MEXICO",
                                 "GERMANY", "JAPAN"], n_cust),
    })
    ca_state = _cyc(STATES, n_ca)
    customer_address = pa.table({
        "ca_address_sk": _sk(n_ca),
        "ca_city": _cyc(["Midway", "Fairview", "Oakland", "Unionville",
                         "Pleasant Hill", "Centerville"], n_ca),
        "ca_county": pa.array([f"{s} County {i % 7}" for i, s in
                               enumerate(ca_state.to_pylist())]),
        "ca_state": ca_state,
        "ca_zip": pa.array([f"{(k * 7919) % 100000:05d}"
                            for k in range(1, n_ca + 1)]),
        "ca_country": pa.array(["United States"] * n_ca),
        "ca_gmt_offset": money_from_cents(
            np.array([-500, -600, -700, -800][:] * (n_ca // 4 + 1),
                     np.int64)[:n_ca], 5, 2),
    })

    # -- store / promotion / reason ---------------------------------------
    ssk = np.arange(1, n_store + 1)
    s_state = _cyc(STATES[:8], n_store)
    store = pa.table({
        "s_store_sk": pa.array(ssk, pa.int64()),
        "s_store_id": pa.array([f"AAAAAAAA{k:08d}" for k in ssk]),
        "s_store_name": _cyc(["ese", "ose", "able", "ought", "bar",
                              "cally"], n_store),
        "s_number_employees": pa.array(
            rng.integers(200, 301, n_store).astype(np.int32), pa.int32()),
        "s_city": _cyc(["Midway", "Fairview"], n_store),
        "s_county": pa.array([f"{s} County 0" for s in
                              s_state.to_pylist()]),
        "s_state": s_state,
        "s_zip": pa.array([f"{(k * 7919) % 100000:05d}" for k in ssk]),
        "s_gmt_offset": money_from_cents(
            np.array([-500, -600] * (n_store // 2 + 1),
                     np.int64)[:n_store], 5, 2),
    })
    psk = np.arange(1, n_promo + 1)
    promotion = pa.table({
        "p_promo_sk": pa.array(psk, pa.int64()),
        "p_promo_id": pa.array([f"AAAAAAAA{k:08d}" for k in psk]),
        "p_channel_email": _cyc(["N", "N", "Y"], n_promo),
        "p_channel_event": _cyc(["N", "Y"], n_promo),
        "p_channel_dmail": _cyc(["Y", "N"], n_promo),
    })
    reason = pa.table({
        "r_reason_sk": _sk(35),
        "r_reason_id": pa.array([f"AAAAAAAA{k:08d}" for k in range(1, 36)]),
        "r_reason_desc": pa.array([f"reason {k}" for k in range(1, 36)]),
    })

    # -- fact helpers -------------------------------------------------------
    def _prices(n, qty):
        """The per-row money columns every channel shares, in cents."""
        wholesale = rng.integers(100, 10_000, n)
        list_p = (wholesale * rng.integers(110, 300, n)) // 100
        sales_p = (list_p * rng.integers(30, 101, n)) // 100
        ext_sales = sales_p * qty
        ext_wholesale = wholesale * qty
        ext_list = list_p * qty
        ext_discount = (list_p - sales_p) * qty
        ext_tax = (ext_sales * rng.integers(0, 9, n)) // 100
        coupon = np.where(rng.random(n) < 0.1,
                          (ext_sales * rng.integers(5, 30, n)) // 100, 0)
        net_paid = ext_sales - coupon
        net_profit = net_paid - ext_wholesale
        return {
            "wholesale_cost": wholesale, "list_price": list_p,
            "sales_price": sales_p, "ext_discount_amt": ext_discount,
            "ext_sales_price": ext_sales,
            "ext_wholesale_cost": ext_wholesale, "ext_list_price": ext_list,
            "ext_tax": ext_tax, "coupon_amt": coupon, "net_paid": net_paid,
            "net_paid_inc_tax": net_paid + ext_tax,
            "net_profit": net_profit,
        }

    # -- store_sales + store_returns ---------------------------------------
    ss_qty = rng.integers(1, 101, n_ss)
    ss_money = _prices(n_ss, ss_qty)
    ss_sold = rng.integers(sell_lo, sell_hi + 1, n_ss).astype(np.int64)
    ss_ticket = rng.integers(1, max(n_ss // 3, 2), n_ss).astype(np.int64)
    store_sales = pa.table({
        "ss_sold_date_sk": pa.array(ss_sold, pa.int64()),
        "ss_sold_time_sk": _fk(rng, 86399, n_ss),
        "ss_item_sk": pa.array(rng.integers(1, n_item + 1, n_ss)
                               .astype(np.int64), pa.int64()),
        "ss_customer_sk": _fk(rng, n_cust, n_ss),
        "ss_cdemo_sk": _fk(rng, n_cd, n_ss),
        "ss_hdemo_sk": _fk(rng, n_hd, n_ss),
        "ss_addr_sk": _fk(rng, n_ca, n_ss),
        "ss_store_sk": _fk(rng, n_store, n_ss, null_frac=0.04),
        "ss_promo_sk": _fk(rng, n_promo, n_ss),
        "ss_ticket_number": pa.array(ss_ticket, pa.int64()),
        "ss_quantity": pa.array(ss_qty.astype(np.int32), pa.int32()),
        **{f"ss_{k}": money7(v) for k, v in ss_money.items()},
    })
    n_sr = max(n_ss // 10, 100)
    ret_rows = rng.choice(n_ss, n_sr, replace=False)
    sr_ret_qty = np.minimum(rng.integers(1, 101, n_sr), ss_qty[ret_rows])
    sr_amt = ss_money["sales_price"][ret_rows] * sr_ret_qty
    store_returns = pa.table({
        "sr_returned_date_sk": pa.array(
            np.minimum(ss_sold[ret_rows] + rng.integers(1, 91, n_sr),
                       _jdn(pydt.date(2003, 12, 31))), pa.int64()),
        "sr_item_sk": store_sales["ss_item_sk"].take(
            pa.array(ret_rows)).combine_chunks(),
        "sr_customer_sk": store_sales["ss_customer_sk"].take(
            pa.array(ret_rows)).combine_chunks(),
        "sr_ticket_number": pa.array(ss_ticket[ret_rows], pa.int64()),
        "sr_reason_sk": _fk(rng, 35, n_sr),
        "sr_return_quantity": pa.array(
            sr_ret_qty.astype(np.int32), pa.int32(),
            mask=rng.random(n_sr) < 0.05),
        "sr_return_amt": money7(sr_amt),
        "sr_return_tax": money7((sr_amt * rng.integers(0, 9, n_sr)) // 100),
        "sr_fee": money7(rng.integers(50, 10_000, n_sr)),
        "sr_net_loss": money7(sr_amt // 2 +
                              rng.integers(50, 5_000, n_sr)),
    })

    # -- web_sales + web_returns -------------------------------------------
    ws_qty = rng.integers(1, 101, n_ws)
    ws_money = _prices(n_ws, ws_qty)
    ws_sold = rng.integers(sell_lo, sell_hi + 1, n_ws).astype(np.int64)
    ws_order = rng.integers(1, max(n_ws // 3, 2), n_ws).astype(np.int64)
    web_sales = pa.table({
        "ws_sold_date_sk": pa.array(ws_sold, pa.int64()),
        "ws_sold_time_sk": _fk(rng, 86399, n_ws),
        "ws_item_sk": pa.array(rng.integers(1, n_item + 1, n_ws)
                               .astype(np.int64), pa.int64()),
        "ws_bill_customer_sk": _fk(rng, n_cust, n_ws),
        "ws_bill_cdemo_sk": _fk(rng, n_cd, n_ws),
        "ws_bill_addr_sk": _fk(rng, n_ca, n_ws),
        "ws_ship_customer_sk": _fk(rng, n_cust, n_ws, null_frac=0.04),
        "ws_promo_sk": _fk(rng, n_promo, n_ws),
        "ws_order_number": pa.array(ws_order, pa.int64()),
        "ws_quantity": pa.array(ws_qty.astype(np.int32), pa.int32()),
        **{f"ws_{k}": money7(v) for k, v in ws_money.items()},
    })
    n_wr = max(n_ws // 10, 50)
    wret = rng.choice(n_ws, n_wr, replace=False)
    wr_qty = np.minimum(rng.integers(1, 101, n_wr), ws_qty[wret])
    wr_amt = ws_money["sales_price"][wret] * wr_qty
    web_returns = pa.table({
        "wr_returned_date_sk": pa.array(
            np.minimum(ws_sold[wret] + rng.integers(1, 91, n_wr),
                       _jdn(pydt.date(2003, 12, 31))), pa.int64()),
        "wr_item_sk": web_sales["ws_item_sk"].take(
            pa.array(wret)).combine_chunks(),
        "wr_order_number": pa.array(ws_order[wret], pa.int64()),
        "wr_reason_sk": _fk(rng, 35, n_wr),
        "wr_return_quantity": pa.array(wr_qty.astype(np.int32),
                                       pa.int32()),
        "wr_return_amt": money7(wr_amt),
        "wr_net_loss": money7(wr_amt // 2 + rng.integers(50, 5_000, n_wr)),
    })

    # -- catalog_sales + catalog_returns -----------------------------------
    cs_qty = rng.integers(1, 101, n_cs)
    cs_money = _prices(n_cs, cs_qty)
    cs_sold = rng.integers(sell_lo, sell_hi + 1, n_cs).astype(np.int64)
    cs_order = rng.integers(1, max(n_cs // 3, 2), n_cs).astype(np.int64)
    catalog_sales = pa.table({
        "cs_sold_date_sk": pa.array(cs_sold, pa.int64()),
        "cs_sold_time_sk": _fk(rng, 86399, n_cs),
        "cs_item_sk": pa.array(rng.integers(1, n_item + 1, n_cs)
                               .astype(np.int64), pa.int64()),
        "cs_bill_customer_sk": _fk(rng, n_cust, n_cs),
        "cs_bill_cdemo_sk": _fk(rng, n_cd, n_cs),
        "cs_bill_addr_sk": _fk(rng, n_ca, n_cs),
        "cs_ship_addr_sk": _fk(rng, n_ca, n_cs, null_frac=0.04),
        "cs_promo_sk": _fk(rng, n_promo, n_cs),
        "cs_order_number": pa.array(cs_order, pa.int64()),
        "cs_quantity": pa.array(cs_qty.astype(np.int32), pa.int32()),
        **{f"cs_{k}": money7(v) for k, v in cs_money.items()},
    })
    n_cr = max(n_cs // 10, 50)
    cret = rng.choice(n_cs, n_cr, replace=False)
    cr_qty = np.minimum(rng.integers(1, 101, n_cr), cs_qty[cret])
    cr_amt = cs_money["sales_price"][cret] * cr_qty
    catalog_returns = pa.table({
        "cr_returned_date_sk": pa.array(
            np.minimum(cs_sold[cret] + rng.integers(1, 91, n_cr),
                       _jdn(pydt.date(2003, 12, 31))), pa.int64()),
        "cr_item_sk": catalog_sales["cs_item_sk"].take(
            pa.array(cret)).combine_chunks(),
        "cr_order_number": pa.array(cs_order[cret], pa.int64()),
        "cr_reason_sk": _fk(rng, 35, n_cr),
        "cr_return_quantity": pa.array(cr_qty.astype(np.int32),
                                       pa.int32()),
        "cr_return_amount": money7(cr_amt),
        "cr_net_loss": money7(cr_amt // 2 + rng.integers(50, 5_000, n_cr)),
    })

    return {
        "date_dim": date_dim, "time_dim": time_dim, "item": item,
        "customer": customer, "customer_address": customer_address,
        "customer_demographics": customer_demographics,
        "household_demographics": household_demographics,
        "store": store, "promotion": promotion, "reason": reason,
        "store_sales": store_sales, "store_returns": store_returns,
        "web_sales": web_sales, "web_returns": web_returns,
        "catalog_sales": catalog_sales, "catalog_returns": catalog_returns,
    }


# ---------------------------------------------------------------------------
# Query corpus
# ---------------------------------------------------------------------------
# Parameter substitutions are chosen wide enough that every query returns
# rows at the tier-1 tiny scale (the spec's qgen randomizes them anyway);
# the operator SHAPE of each query follows the spec text.

def _dd(s: TpuSession, t, **eq) -> DataFrame:
    """date_dim with equality filters, e.g. _dd(s, t, d_year=2000)."""
    df = s.from_arrow(t["date_dim"])
    for k, v in eq.items():
        df = df.filter(E.EqualTo(col(k), E.Literal(v)))
    return df


def _between(c, lo, hi) -> E.Expression:
    return E.And(E.GreaterThanOrEqual(c, E.Literal(lo)),
                 E.LessThanOrEqual(c, E.Literal(hi)))


def _dbl(c) -> E.Expression:
    return E.Cast(c, _t.DOUBLE)


def q3(s: TpuSession, t) -> DataFrame:
    """Brand revenue for a manufacturer band in November."""
    j = (_dd(s, t, d_moy=11)
         .join(s.from_arrow(t["store_sales"]),
               left_on=["d_date_sk"], right_on=["ss_sold_date_sk"])
         .join(s.from_arrow(t["item"]).filter(
             _between(col("i_manufact_id"), 120, 140)),
             left_on=["ss_item_sk"], right_on=["i_item_sk"]))
    return (j.group_by("d_year", "i_brand_id", "i_brand")
            .agg((Sum(col("ss_ext_sales_price")), "sum_agg"))
            .sort(("d_year", True, True), ("sum_agg", False, False),
                  ("i_brand_id", True, True))
            .limit(100))


def q7(s: TpuSession, t) -> DataFrame:
    """Demographic averages by item (cd + promotion dims)."""
    cd = s.from_arrow(t["customer_demographics"]).filter(E.And(
        E.And(E.EqualTo(col("cd_gender"), E.Literal("M")),
              E.EqualTo(col("cd_marital_status"), E.Literal("S"))),
        E.EqualTo(col("cd_education_status"), E.Literal("College"))))
    promo = s.from_arrow(t["promotion"]).filter(
        E.Or(E.EqualTo(col("p_channel_email"), E.Literal("N")),
             E.EqualTo(col("p_channel_event"), E.Literal("N"))))
    j = (s.from_arrow(t["store_sales"])
         .join(cd, left_on=["ss_cdemo_sk"], right_on=["cd_demo_sk"])
         .join(_dd(s, t, d_year=2000),
               left_on=["ss_sold_date_sk"], right_on=["d_date_sk"])
         .join(s.from_arrow(t["item"]),
               left_on=["ss_item_sk"], right_on=["i_item_sk"])
         .join(promo, left_on=["ss_promo_sk"], right_on=["p_promo_sk"]))
    return (j.group_by("i_item_id")
            .agg((Average(_dbl(col("ss_quantity"))), "agg1"),
                 (Average(_dbl(col("ss_list_price"))), "agg2"),
                 (Average(_dbl(col("ss_coupon_amt"))), "agg3"),
                 (Average(_dbl(col("ss_sales_price"))), "agg4"))
            .sort("i_item_id").limit(100))


def _revenue_ratio(s, t, fact, date_fk, item_fk, price, keys_sort):
    """q12/q20/q98 shape: per-item revenue + class-partition revenue
    ratio via a window total (100 * rev / sum(rev) over i_class)."""
    d_lo = _days(pydt.date(1999, 2, 22))
    dd = s.from_arrow(t["date_dim"]).filter(E.And(
        E.GreaterThanOrEqual(col("d_date"), E.Literal(d_lo, DTYPE_DATE)),
        E.LessThanOrEqual(col("d_date"),
                          E.Literal(d_lo + 30, DTYPE_DATE))))
    item = s.from_arrow(t["item"]).filter(
        E.In(col("i_category"), ["Sports", "Books", "Home"]))
    j = (s.from_arrow(t[fact])
         .join(item, left_on=[item_fk], right_on=["i_item_sk"])
         .join(dd, left_on=[date_fk], right_on=["d_date_sk"]))
    g = (j.group_by("i_item_id", "i_item_desc", "i_category", "i_class",
                    "i_current_price")
         .agg((Sum(col(price)), "itemrevenue")))
    g = g.with_column("rev_d", _dbl(col("itemrevenue")))
    from .plan.window import WinSum
    w = g.window([(WinSum(col("rev_d")), "class_rev")],
                 partition_by=["i_class"])
    ratio = E.Divide(E.Multiply(col("rev_d"), E.Literal(100.0)),
                     col("class_rev"))
    return (w.select(col("i_item_id"), col("i_item_desc"),
                     col("i_category"), col("i_class"),
                     col("i_current_price"), col("itemrevenue"), ratio,
                     names=["i_item_id", "i_item_desc", "i_category",
                            "i_class", "i_current_price", "itemrevenue",
                            "revenueratio"])
            .sort(*keys_sort).limit(100))


_RATIO_SORT = (("i_category", True, True), ("i_class", True, True),
               ("i_item_id", True, True), ("i_item_desc", True, True),
               ("revenueratio", True, True))


def q12(s: TpuSession, t) -> DataFrame:
    """Web revenue ratio within item class (window partition total)."""
    return _revenue_ratio(s, t, "web_sales", "ws_sold_date_sk",
                          "ws_item_sk", "ws_ext_sales_price", _RATIO_SORT)


def q19(s: TpuSession, t) -> DataFrame:
    """Brand revenue where customer and store are in different zips."""
    from .plan.strings import Substring
    j = (_dd(s, t, d_moy=11, d_year=1998)
         .join(s.from_arrow(t["store_sales"]),
               left_on=["d_date_sk"], right_on=["ss_sold_date_sk"])
         .join(s.from_arrow(t["item"]).filter(
             _between(col("i_manager_id"), 1, 20)),
             left_on=["ss_item_sk"], right_on=["i_item_sk"])
         .join(s.from_arrow(t["customer"]),
               left_on=["ss_customer_sk"], right_on=["c_customer_sk"])
         .join(s.from_arrow(t["customer_address"]),
               left_on=["c_current_addr_sk"], right_on=["ca_address_sk"])
         .join(s.from_arrow(t["store"]),
               left_on=["ss_store_sk"], right_on=["s_store_sk"])
         .filter(E.Not(E.EqualTo(Substring(col("ca_zip"), 1, 5),
                                 Substring(col("s_zip"), 1, 5)))))
    return (j.group_by("i_brand_id", "i_brand", "i_manufact_id",
                       "i_manufact")
            .agg((Sum(col("ss_ext_sales_price")), "ext_price"))
            .sort(("ext_price", False, False), ("i_brand", True, True),
                  ("i_brand_id", True, True), ("i_manufact_id", True, True),
                  ("i_manufact", True, True))
            .limit(100))


def q20(s: TpuSession, t) -> DataFrame:
    """Catalog revenue ratio within item class."""
    return _revenue_ratio(s, t, "catalog_sales", "cs_sold_date_sk",
                          "cs_item_sk", "cs_ext_sales_price", _RATIO_SORT)


def q26(s: TpuSession, t) -> DataFrame:
    """Catalog demographic averages by item (q7's catalog twin)."""
    cd = s.from_arrow(t["customer_demographics"]).filter(E.And(
        E.And(E.EqualTo(col("cd_gender"), E.Literal("M")),
              E.EqualTo(col("cd_marital_status"), E.Literal("S"))),
        E.EqualTo(col("cd_education_status"), E.Literal("College"))))
    promo = s.from_arrow(t["promotion"]).filter(
        E.Or(E.EqualTo(col("p_channel_email"), E.Literal("N")),
             E.EqualTo(col("p_channel_event"), E.Literal("N"))))
    j = (s.from_arrow(t["catalog_sales"])
         .join(cd, left_on=["cs_bill_cdemo_sk"], right_on=["cd_demo_sk"])
         .join(_dd(s, t, d_year=2000),
               left_on=["cs_sold_date_sk"], right_on=["d_date_sk"])
         .join(s.from_arrow(t["item"]),
               left_on=["cs_item_sk"], right_on=["i_item_sk"])
         .join(promo, left_on=["cs_promo_sk"], right_on=["p_promo_sk"]))
    return (j.group_by("i_item_id")
            .agg((Average(_dbl(col("cs_quantity"))), "agg1"),
                 (Average(_dbl(col("cs_list_price"))), "agg2"),
                 (Average(_dbl(col("cs_coupon_amt"))), "agg3"),
                 (Average(_dbl(col("cs_sales_price"))), "agg4"))
            .sort("i_item_id").limit(100))


def q27(s: TpuSession, t) -> DataFrame:
    """Store demographics under ROLLUP(i_item_id, s_state) with
    grouping(s_state) — the Expand lowering end to end."""
    cd = s.from_arrow(t["customer_demographics"]).filter(E.And(
        E.And(E.EqualTo(col("cd_gender"), E.Literal("M")),
              E.EqualTo(col("cd_marital_status"), E.Literal("S"))),
        E.EqualTo(col("cd_education_status"), E.Literal("College"))))
    j = (s.from_arrow(t["store_sales"])
         .join(cd, left_on=["ss_cdemo_sk"], right_on=["cd_demo_sk"])
         .join(_dd(s, t, d_year=2000),
               left_on=["ss_sold_date_sk"], right_on=["d_date_sk"])
         .join(s.from_arrow(t["store"]).filter(
             E.In(col("s_state"), ["TN", "SC", "AL", "GA", "SD", "MI"])),
             left_on=["ss_store_sk"], right_on=["s_store_sk"])
         .join(s.from_arrow(t["item"]),
               left_on=["ss_item_sk"], right_on=["i_item_sk"]))
    r = j.rollup("i_item_id", "s_state")
    g = r.agg((Average(_dbl(col("ss_quantity"))), "agg1"),
              (Average(_dbl(col("ss_list_price"))), "agg2"),
              (Average(_dbl(col("ss_coupon_amt"))), "agg3"),
              (Average(_dbl(col("ss_sales_price"))), "agg4"))
    return (g.select(col("i_item_id"), col("s_state"),
                     r.grouping("s_state"), col("agg1"), col("agg2"),
                     col("agg3"), col("agg4"),
                     names=["i_item_id", "s_state", "g_state", "agg1",
                            "agg2", "agg3", "agg4"])
            .sort(("i_item_id", True, True), ("s_state", True, True))
            .limit(100))


def _channel_union(s, t, sel_items, sel_key, group_col,
                   d_year, d_moy):
    """q33/q56/q60 shape: the same (date, address, item, item-subset
    semi join, group, sum) pipeline over all three sales channels,
    UNION ALLed and re-aggregated."""
    channels = [("store_sales", "ss_sold_date_sk", "ss_addr_sk",
                 "ss_item_sk", "ss_ext_sales_price"),
                ("web_sales", "ws_sold_date_sk", "ws_bill_addr_sk",
                 "ws_item_sk", "ws_ext_sales_price"),
                ("catalog_sales", "cs_sold_date_sk", "cs_bill_addr_sk",
                 "cs_item_sk", "cs_ext_sales_price")]
    parts = []
    for fact, date_fk, addr_fk, item_fk, price in channels:
        ca = s.from_arrow(t["customer_address"]).filter(
            E.EqualTo(col("ca_gmt_offset"),
                      E.Literal(__import__("decimal").Decimal("-5.00"))))
        j = (s.from_arrow(t[fact])
             .join(_dd(s, t, d_year=d_year, d_moy=d_moy),
                   left_on=[date_fk], right_on=["d_date_sk"])
             .join(ca, left_on=[addr_fk], right_on=["ca_address_sk"])
             .join(s.from_arrow(t["item"]),
                   left_on=[item_fk], right_on=["i_item_sk"])
             .join(sel_items(s), how="left_semi",
                   left_on=[group_col], right_on=[sel_key]))
        parts.append(
            j.group_by(group_col)
            .agg((Sum(_dbl(col(price))), "total_sales")))
    u = parts[0].union(parts[1]).union(parts[2])
    return (u.group_by(group_col)
            .agg((Sum(col("total_sales")), "total_sales"))
            .sort(("total_sales", True, True), (group_col, True, True))
            .limit(100))


def q33(s: TpuSession, t) -> DataFrame:
    """Electronics manufacturer revenue across all three channels."""
    def sel(sess):
        return (sess.from_arrow(t["item"])
                .filter(E.EqualTo(col("i_category"),
                                  E.Literal("Electronics")))
                .select(col("i_manufact_id"), names=["sel_manufact_id"]))
    return _channel_union(s, t, sel, "sel_manufact_id", "i_manufact_id",
                          1998, 5)


def q36(s: TpuSession, t) -> DataFrame:
    """Gross margin hierarchy: ROLLUP(i_category, i_class) + rank()
    within each hierarchy level (grouping_id-driven window)."""
    from .plan.window import Rank
    j = (s.from_arrow(t["store_sales"])
         .join(_dd(s, t, d_year=2001),
               left_on=["ss_sold_date_sk"], right_on=["d_date_sk"])
         .join(s.from_arrow(t["item"]),
               left_on=["ss_item_sk"], right_on=["i_item_sk"])
         .join(s.from_arrow(t["store"]).filter(
             E.In(col("s_state"), ["TN", "SC", "AL", "GA", "SD", "MI",
                                   "OH", "TX"])),
             left_on=["ss_store_sk"], right_on=["s_store_sk"]))
    r = j.rollup("i_category", "i_class")
    g = r.agg((Sum(col("ss_net_profit")), "profit"),
              (Sum(col("ss_ext_sales_price")), "sales"))
    margin = E.Divide(_dbl(col("profit")), _dbl(col("sales")))
    lochier = E.Add(r.grouping("i_category"), r.grouping("i_class"))
    parent = E.CaseWhen(
        [(E.EqualTo(r.grouping("i_class"), E.Literal(0)),
          col("i_category"))], E.Literal(None, _t.STRING))
    g = g.select(margin, col("i_category"), col("i_class"), lochier,
                 parent,
                 names=["gross_margin", "i_category", "i_class",
                        "lochierarchy", "parent_cat"])
    w = g.window([(Rank(), "rank_within_parent")],
                 partition_by=["lochierarchy", "parent_cat"],
                 order_by=[("gross_margin", True, True)])
    sort_cat = E.CaseWhen(
        [(E.EqualTo(col("lochierarchy"), E.Literal(0)),
          col("i_category"))], E.Literal(None, _t.STRING))
    w = w.with_column("sort_cat", sort_cat)
    return (w.select(col("gross_margin"), col("i_category"),
                     col("i_class"), col("lochierarchy"),
                     col("rank_within_parent"), col("sort_cat"),
                     names=["gross_margin", "i_category", "i_class",
                            "lochierarchy", "rank_within_parent",
                            "sort_cat"])
            .sort(("lochierarchy", False, False),
                  ("sort_cat", True, True),
                  ("rank_within_parent", True, True),
                  ("i_category", True, True), ("i_class", True, True))
            .limit(100))


def q42(s: TpuSession, t) -> DataFrame:
    """Category revenue for a manager band in November."""
    j = (_dd(s, t, d_moy=11, d_year=2000)
         .join(s.from_arrow(t["store_sales"]),
               left_on=["d_date_sk"], right_on=["ss_sold_date_sk"])
         .join(s.from_arrow(t["item"]).filter(
             _between(col("i_manager_id"), 1, 10)),
             left_on=["ss_item_sk"], right_on=["i_item_sk"]))
    return (j.group_by("d_year", "i_category_id", "i_category")
            .agg((Sum(col("ss_ext_sales_price")), "total_sales"))
            .sort(("total_sales", False, False), ("d_year", True, True),
                  ("i_category_id", True, True),
                  ("i_category", True, True))
            .limit(100))


def q43(s: TpuSession, t) -> DataFrame:
    """Store sales pivoted by day-of-week (CASE WHEN sums)."""
    import decimal as pydec
    j = (_dd(s, t, d_year=2000)
         .join(s.from_arrow(t["store_sales"]),
               left_on=["d_date_sk"], right_on=["ss_sold_date_sk"])
         .join(s.from_arrow(t["store"]).filter(
             E.EqualTo(col("s_gmt_offset"),
                       E.Literal(pydec.Decimal("-5.00")))),
             left_on=["ss_store_sk"], right_on=["s_store_sk"]))
    zero = E.Literal(pydec.Decimal("0.00"))

    def day_sum(day):
        return Sum(E.CaseWhen(
            [(E.EqualTo(col("d_day_name"), E.Literal(day)),
              col("ss_sales_price"))], zero))
    return (j.group_by("s_store_name", "s_store_id")
            .agg((day_sum("Sunday"), "sun_sales"),
                 (day_sum("Monday"), "mon_sales"),
                 (day_sum("Tuesday"), "tue_sales"),
                 (day_sum("Wednesday"), "wed_sales"),
                 (day_sum("Thursday"), "thu_sales"),
                 (day_sum("Friday"), "fri_sales"),
                 (day_sum("Saturday"), "sat_sales"))
            .sort("s_store_name", "s_store_id").limit(100))


def q52(s: TpuSession, t) -> DataFrame:
    """Brand revenue, November 2000 (q3's manager-filter twin)."""
    j = (_dd(s, t, d_moy=11, d_year=2000)
         .join(s.from_arrow(t["store_sales"]),
               left_on=["d_date_sk"], right_on=["ss_sold_date_sk"])
         .join(s.from_arrow(t["item"]).filter(
             _between(col("i_manager_id"), 1, 10)),
             left_on=["ss_item_sk"], right_on=["i_item_sk"]))
    return (j.group_by("d_year", "i_brand_id", "i_brand")
            .agg((Sum(col("ss_ext_sales_price")), "ext_price"))
            .sort(("d_year", True, True), ("ext_price", False, False),
                  ("i_brand_id", True, True))
            .limit(100))


def q55(s: TpuSession, t) -> DataFrame:
    """Brand revenue for one manager's items."""
    j = (_dd(s, t, d_moy=11, d_year=1999)
         .join(s.from_arrow(t["store_sales"]),
               left_on=["d_date_sk"], right_on=["ss_sold_date_sk"])
         .join(s.from_arrow(t["item"]).filter(
             _between(col("i_manager_id"), 20, 40)),
             left_on=["ss_item_sk"], right_on=["i_item_sk"]))
    return (j.group_by("i_brand_id", "i_brand")
            .agg((Sum(col("ss_ext_sales_price")), "ext_price"))
            .sort(("ext_price", False, False), ("i_brand_id", True, True))
            .limit(100))


def q56(s: TpuSession, t) -> DataFrame:
    """Colored-item revenue across all three channels by item id."""
    def sel(sess):
        return (sess.from_arrow(t["item"])
                .filter(E.In(col("i_color"),
                             ["slate", "blanched", "burnished"]))
                .select(col("i_item_id"), names=["sel_item_id"]))
    return _channel_union(s, t, sel, "sel_item_id", "i_item_id", 2001, 2)


def q60(s: TpuSession, t) -> DataFrame:
    """Music-category revenue across all three channels by item id."""
    def sel(sess):
        return (sess.from_arrow(t["item"])
                .filter(E.EqualTo(col("i_category"), E.Literal("Music")))
                .select(col("i_item_id"), names=["sel_item_id"]))
    return _channel_union(s, t, sel, "sel_item_id", "i_item_id", 1998, 9)


def q65(s: TpuSession, t) -> DataFrame:
    """Under-performing items: per-(store,item) revenue vs 10% of the
    store's average item revenue (two aggregate subqueries joined)."""
    dd = s.from_arrow(t["date_dim"]).filter(
        _between(col("d_month_seq"), 1176, 1187))
    rev = (s.from_arrow(t["store_sales"])
           .join(dd, left_on=["ss_sold_date_sk"], right_on=["d_date_sk"])
           .group_by("ss_store_sk", "ss_item_sk")
           .agg((Sum(col("ss_sales_price")), "revenue")))
    rev = rev.select(col("ss_store_sk"), col("ss_item_sk"),
                     _dbl(col("revenue")),
                     names=["ss_store_sk", "ss_item_sk", "revenue"])
    ave = (rev.group_by("ss_store_sk")
           .agg((Average(col("revenue")), "ave"))
           .select(col("ss_store_sk"), col("ave"),
                   names=["avg_store_sk", "ave"]))
    j = (rev.join(ave, left_on=["ss_store_sk"], right_on=["avg_store_sk"])
         .filter(E.LessThanOrEqual(
             col("revenue"), E.Multiply(E.Literal(0.1), col("ave"))))
         .join(s.from_arrow(t["store"]),
               left_on=["ss_store_sk"], right_on=["s_store_sk"])
         .join(s.from_arrow(t["item"]),
               left_on=["ss_item_sk"], right_on=["i_item_sk"]))
    return (j.select(col("s_store_name"), col("i_item_desc"),
                     col("revenue"), col("i_current_price"),
                     col("i_wholesale_cost"), col("i_brand"))
            .sort(("s_store_name", True, True), ("i_item_desc", True, True),
                  ("revenue", True, True))
            .limit(100))


def q70(s: TpuSession, t) -> DataFrame:
    """Profit hierarchy over ROLLUP(s_state, s_county), restricted to
    the top-5 states by a ranking-window subquery."""
    from .plan.window import Rank
    dd = s.from_arrow(t["date_dim"]).filter(
        _between(col("d_month_seq"), 1200, 1211))
    base = (s.from_arrow(t["store_sales"])
            .join(dd, left_on=["ss_sold_date_sk"], right_on=["d_date_sk"])
            .join(s.from_arrow(t["store"]),
                  left_on=["ss_store_sk"], right_on=["s_store_sk"]))
    per_state = (base.group_by("s_state")
                 .agg((Sum(col("ss_net_profit")), "sp"))
                 .select(col("s_state"), _dbl(col("sp")),
                         names=["t_state", "sp"]))
    top = (per_state.window([(Rank(), "ranking")],
                            order_by=[("sp", False, False)])
           .filter(E.LessThanOrEqual(col("ranking"), E.Literal(5)))
           .select(col("t_state"), names=["top_state"]))
    j = base.join(top, how="left_semi",
                  left_on=["s_state"], right_on=["top_state"])
    r = j.rollup("s_state", "s_county")
    g = r.agg((Sum(col("ss_net_profit")), "total_sum"))
    lochier = E.Add(r.grouping("s_state"), r.grouping("s_county"))
    parent = E.CaseWhen(
        [(E.EqualTo(r.grouping("s_county"), E.Literal(0)),
          col("s_state"))], E.Literal(None, _t.STRING))
    g = g.select(col("total_sum"), col("s_state"), col("s_county"),
                 lochier, parent, _dbl(col("total_sum")),
                 names=["total_sum", "s_state", "s_county",
                        "lochierarchy", "parent_state", "total_d"])
    w = g.window([(Rank(), "rank_within_parent")],
                 partition_by=["lochierarchy", "parent_state"],
                 order_by=[("total_d", False, False)])
    sort_state = E.CaseWhen(
        [(E.EqualTo(col("lochierarchy"), E.Literal(0)),
          col("s_state"))], E.Literal(None, _t.STRING))
    w = w.with_column("sort_state", sort_state)
    return (w.select(col("total_sum"), col("s_state"), col("s_county"),
                     col("lochierarchy"), col("rank_within_parent"),
                     col("sort_state"),
                     names=["total_sum", "s_state", "s_county",
                            "lochierarchy", "rank_within_parent",
                            "sort_state"])
            .sort(("lochierarchy", False, False),
                  ("sort_state", True, True),
                  ("rank_within_parent", True, True),
                  ("s_state", True, True), ("s_county", True, True))
            .limit(100))


def q73(s: TpuSession, t) -> DataFrame:
    """Ticket counts per customer for high-dependency households."""
    hd = s.from_arrow(t["household_demographics"]).filter(E.And(
        E.And(E.Or(E.EqualTo(col("hd_buy_potential"),
                             E.Literal(">10000")),
                   E.EqualTo(col("hd_buy_potential"),
                             E.Literal("unknown"))),
              E.GreaterThan(col("hd_vehicle_count"), E.Literal(0))),
        E.GreaterThan(
            E.Divide(_dbl(col("hd_dep_count")),
                     _dbl(col("hd_vehicle_count"))),
            E.Literal(1.0))))
    dd = s.from_arrow(t["date_dim"]).filter(E.And(
        _between(col("d_dom"), 1, 2),
        E.In(col("d_year"), [1999, 2000, 2001])))
    j = (s.from_arrow(t["store_sales"])
         .join(dd, left_on=["ss_sold_date_sk"], right_on=["d_date_sk"])
         .join(s.from_arrow(t["store"]),
               left_on=["ss_store_sk"], right_on=["s_store_sk"])
         .join(hd, left_on=["ss_hdemo_sk"], right_on=["hd_demo_sk"]))
    dj = (j.group_by("ss_ticket_number", "ss_customer_sk")
          .agg((Count(None), "cnt"))
          .filter(_between(col("cnt"), 1, 5)))
    out = dj.join(s.from_arrow(t["customer"]),
                  left_on=["ss_customer_sk"], right_on=["c_customer_sk"])
    return (out.select(col("c_last_name"), col("c_first_name"),
                       col("c_salutation"), col("c_preferred_cust_flag"),
                       col("ss_ticket_number"), col("cnt"))
            .sort(("cnt", False, False), ("c_last_name", True, True),
                  ("ss_ticket_number", True, True)))


def q76(s: TpuSession, t) -> DataFrame:
    """NULL-key sales per channel: UNION ALL with literal channel tags
    over rows whose customer/store/address fk is null."""
    channels = [
        ("store", "ss_store_sk", "store_sales", "ss_sold_date_sk",
         "ss_item_sk", "ss_ext_sales_price"),
        ("web", "ws_ship_customer_sk", "web_sales", "ws_sold_date_sk",
         "ws_item_sk", "ws_ext_sales_price"),
        ("catalog", "cs_ship_addr_sk", "catalog_sales", "cs_sold_date_sk",
         "cs_item_sk", "cs_ext_sales_price"),
    ]
    parts = []
    for chan, null_col, fact, date_fk, item_fk, price in channels:
        j = (s.from_arrow(t[fact]).filter(E.IsNull(col(null_col)))
             .join(s.from_arrow(t["item"]),
                   left_on=[item_fk], right_on=["i_item_sk"])
             .join(s.from_arrow(t["date_dim"]),
                   left_on=[date_fk], right_on=["d_date_sk"]))
        parts.append(j.select(
            E.Literal(chan), E.Literal(null_col), col("d_year"),
            col("d_qoy"), col("i_category"), _dbl(col(price)),
            names=["channel", "col_name", "d_year", "d_qoy", "i_category",
                   "ext_sales_price"]))
    u = parts[0].union(parts[1]).union(parts[2])
    return (u.group_by("channel", "col_name", "d_year", "d_qoy",
                       "i_category")
            .agg((Count(None), "sales_cnt"),
                 (Sum(col("ext_sales_price")), "sales_amt"))
            .sort("channel", "col_name", "d_year", "d_qoy", "i_category")
            .limit(100))


def q86(s: TpuSession, t) -> DataFrame:
    """Web net-paid hierarchy: ROLLUP(i_category, i_class) + rank()
    within each hierarchy level."""
    from .plan.window import Rank
    dd = s.from_arrow(t["date_dim"]).filter(
        _between(col("d_month_seq"), 1200, 1211))
    j = (s.from_arrow(t["web_sales"])
         .join(dd, left_on=["ws_sold_date_sk"], right_on=["d_date_sk"])
         .join(s.from_arrow(t["item"]),
               left_on=["ws_item_sk"], right_on=["i_item_sk"]))
    r = j.rollup("i_category", "i_class")
    g = r.agg((Sum(col("ws_net_paid")), "total_sum"))
    lochier = E.Add(r.grouping("i_category"), r.grouping("i_class"))
    parent = E.CaseWhen(
        [(E.EqualTo(r.grouping("i_class"), E.Literal(0)),
          col("i_category"))], E.Literal(None, _t.STRING))
    g = g.select(col("total_sum"), col("i_category"), col("i_class"),
                 lochier, parent, _dbl(col("total_sum")),
                 names=["total_sum", "i_category", "i_class",
                        "lochierarchy", "parent_cat", "total_d"])
    w = g.window([(Rank(), "rank_within_parent")],
                 partition_by=["lochierarchy", "parent_cat"],
                 order_by=[("total_d", False, False)])
    sort_cat = E.CaseWhen(
        [(E.EqualTo(col("lochierarchy"), E.Literal(0)),
          col("i_category"))], E.Literal(None, _t.STRING))
    w = w.with_column("sort_cat", sort_cat)
    return (w.select(col("total_sum"), col("i_category"), col("i_class"),
                     col("lochierarchy"), col("rank_within_parent"),
                     col("sort_cat"),
                     names=["total_sum", "i_category", "i_class",
                            "lochierarchy", "rank_within_parent",
                            "sort_cat"])
            .sort(("lochierarchy", False, False),
                  ("sort_cat", True, True),
                  ("rank_within_parent", True, True),
                  ("i_category", True, True), ("i_class", True, True))
            .limit(100))


def q93(s: TpuSession, t) -> DataFrame:
    """Actual sales after returns: left-outer against store_returns,
    CASE over the nullable return quantity, reason-coded returns only."""
    sr = (s.from_arrow(t["store_returns"])
          .join(s.from_arrow(t["reason"]).filter(
              E.EqualTo(col("r_reason_desc"), E.Literal("reason 28"))),
              left_on=["sr_reason_sk"], right_on=["r_reason_sk"]))
    j = s.from_arrow(t["store_sales"]).join(
        sr, how="inner",
        left_on=["ss_item_sk", "ss_ticket_number"],
        right_on=["sr_item_sk", "sr_ticket_number"])
    act = E.CaseWhen(
        [(E.IsNotNull(col("sr_return_quantity")),
          E.Multiply(_dbl(E.Subtract(col("ss_quantity"),
                                     col("sr_return_quantity"))),
                     _dbl(col("ss_sales_price"))))],
        E.Multiply(_dbl(col("ss_quantity")), _dbl(col("ss_sales_price"))))
    g = (j.select(col("ss_customer_sk"), act,
                  names=["ss_customer_sk", "act_sales"])
         .group_by("ss_customer_sk")
         .agg((Sum(col("act_sales")), "sumsales")))
    return (g.sort(("sumsales", True, True), ("ss_customer_sk", True, True))
            .limit(100))


def q96(s: TpuSession, t) -> DataFrame:
    """Evening-rush ticket count (time_dim + household filters)."""
    td = s.from_arrow(t["time_dim"]).filter(E.And(
        E.EqualTo(col("t_hour"), E.Literal(20)),
        E.GreaterThanOrEqual(col("t_minute"), E.Literal(30))))
    hd = s.from_arrow(t["household_demographics"]).filter(
        E.EqualTo(col("hd_dep_count"), E.Literal(7)))
    st = s.from_arrow(t["store"]).filter(
        E.EqualTo(col("s_store_name"), E.Literal("ese")))
    j = (s.from_arrow(t["store_sales"])
         .join(td, left_on=["ss_sold_time_sk"], right_on=["t_time_sk"])
         .join(hd, left_on=["ss_hdemo_sk"], right_on=["hd_demo_sk"])
         .join(st, left_on=["ss_store_sk"], right_on=["s_store_sk"]))
    return j.agg((Count(None), "cnt"))


def q98(s: TpuSession, t) -> DataFrame:
    """Store revenue ratio within item class (q12's store twin)."""
    return _revenue_ratio(s, t, "store_sales", "ss_sold_date_sk",
                          "ss_item_sk", "ss_ext_sales_price", _RATIO_SORT)


QUERIES = {"q3": q3, "q7": q7, "q12": q12, "q19": q19, "q20": q20,
           "q26": q26, "q27": q27, "q33": q33, "q36": q36, "q42": q42,
           "q43": q43, "q52": q52, "q55": q55, "q56": q56, "q60": q60,
           "q65": q65, "q70": q70, "q73": q73, "q76": q76, "q86": q86,
           "q93": q93, "q96": q96, "q98": q98}
