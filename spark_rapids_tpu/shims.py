"""Shim seam: version-dependent Spark semantics behind one interface.

Role of the reference's shim system (SURVEY §2.12): 26 per-version source
trees + ShimLoader's parallel-worlds classloader let one plugin binary
serve Spark 3.1.1→4.0.0.  The engine targets one Spark line first but
keeps the seam (the survey's explicit porting guidance): every
version-dependent behavior the engine implements routes through a
`SparkShims` instance selected by `spark.rapids.tpu.spark.version`, so
adding a version is a new shim class, not edits across the engine.

Behaviors currently routed through the seam (each consumed in-engine):
- `legacy_statistical_aggregate`: Spark < 3.1.0 returns Double.NaN for
  var_samp/stddev_samp over a single row; 3.1+ returns null
  (SPARK-33726, reference GpuShimsUtils equivalents) — consumed by
  plan/aggregates.py variance family on BOTH device and CPU paths.
- `ansi_default`: spark.sql.ansi.enabled defaults false through 3.x and
  true in 4.0 preview — consumed by TpuConf.ansi when the session does
  not set the key explicitly.
- `unavailable_expressions`: expressions that do not exist in the
  pinned Spark version (e.g. SplitPart/Median arrived in 3.4) — the
  overrides engine tags them so explain output mirrors what that Spark
  version could even produce.
"""
from __future__ import annotations

from typing import Dict, FrozenSet, Tuple


class SparkShims:
    """Default = newest supported 3.x line (3.5)."""
    version_prefix = "3.5"
    legacy_statistical_aggregate = False
    ansi_default = False
    unavailable_expressions: FrozenSet[str] = frozenset()

    def describe(self) -> str:
        return f"SparkShims[{self.version_prefix}]"


class Spark30XShims(SparkShims):
    version_prefix = "3.0"
    legacy_statistical_aggregate = True
    unavailable_expressions = frozenset({"SplitPart", "Median"})


class Spark31XShims(SparkShims):
    version_prefix = "3.1"
    unavailable_expressions = frozenset({"SplitPart", "Median"})


class Spark32XShims(SparkShims):
    version_prefix = "3.2"
    unavailable_expressions = frozenset({"SplitPart", "Median"})


class Spark33XShims(SparkShims):
    version_prefix = "3.3"
    unavailable_expressions = frozenset({"SplitPart", "Median"})


class Spark34XShims(SparkShims):
    version_prefix = "3.4"


class Spark35XShims(SparkShims):
    version_prefix = "3.5"


class Spark40XShims(SparkShims):
    version_prefix = "4.0"
    ansi_default = True


_REGISTRY: Dict[str, type] = {}


def register_shim(cls: type) -> type:
    _REGISTRY[cls.version_prefix] = cls
    return cls


for _c in (Spark30XShims, Spark31XShims, Spark32XShims, Spark33XShims,
           Spark34XShims, Spark35XShims, Spark40XShims):
    register_shim(_c)

_CACHE: Dict[str, SparkShims] = {}


def get_shims(version: str) -> SparkShims:
    """Longest-prefix match, like SparkShimServiceProvider version
    detection (ShimLoader.scala:38-60)."""
    if version in _CACHE:
        return _CACHE[version]
    best: Tuple[int, type] = (-1, SparkShims)
    for prefix, cls in _REGISTRY.items():
        if version.startswith(prefix) and len(prefix) > best[0]:
            best = (len(prefix), cls)
    if best[0] < 0:
        raise ValueError(
            f"unsupported Spark version {version!r}; known lines: "
            f"{sorted(_REGISTRY)}")
    _CACHE[version] = best[1]()
    return _CACHE[version]
