"""Delta Lake table subset — the delta-lake/ module family (SURVEY §2.11).

Reference: GpuOptimisticTransaction (GPU-written files with per-file
stats, GpuOptimisticTransaction.scala:64 + GpuStatisticsCollection),
GpuDeleteCommand / GpuUpdateCommand, GpuMergeIntoCommand's
find-touched-files → rewrite shape (delta-24x GpuMergeIntoCommand.scala:
244), JSON _delta_log commit protocol.

TPU-first shape: data files are written/rewritten by THIS engine (scans,
filters, joins and per-file min/max/nullCount stats all run through the
device path); only the transaction-log JSON handling is host logic, as in
the reference (log commits are CPU Delta-lib work there too).

Subset implemented: create/append/overwrite, snapshot reads (with version
time travel), stats-carrying add actions, DELETE, UPDATE, MERGE (matched
update/delete + not-matched insert) via per-file touched-file discovery
and rewrite, parquet checkpoints + _last_checkpoint, deletion-vector
READS (delta/dv.py: roaring-bitmap-array parser per the public
PROTOCOL.md layout; u/p/i storage types, CRC + cardinality checks) and
column-mapping (mode=name/id) reads via per-file physical->logical
renames.  DML over DV-bearing or column-mapped snapshots is rejected
explicitly (read path only).
"""
from __future__ import annotations

import datetime as _dt
import json
import os
import time
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from .. import types as t
from ..columnar.host import schema_to_struct, struct_to_schema


def _split_partitions(table: pa.Table, parts: Sequence[str]):
    """-> [(partition_values dict, sub-table)] by distinct partition
    tuple (vectorized arrow group discovery, then filtered takes)."""
    import pyarrow.compute as pc
    keys = table.select(list(parts))
    distinct = keys.group_by(list(parts)).aggregate([])
    out = []
    for row in distinct.to_pylist():
        mask = None
        for k, v in row.items():
            m = pc.is_null(table.column(k)) if v is None \
                else pc.equal(table.column(k), pa.scalar(v))
            m = pc.fill_null(m, False)
            mask = m if mask is None else pc.and_(mask, m)
        out.append((row, table.filter(mask)))
    return out


def _checkpoint_schema() -> pa.Schema:
    """The standard Delta checkpoint parquet layout (one action per row,
    one struct column per action type)."""
    add_t = pa.struct([
        ("path", pa.string()),
        ("partitionValues", pa.map_(pa.string(), pa.string())),
        ("size", pa.int64()),
        ("modificationTime", pa.int64()),
        ("dataChange", pa.bool_()),
        ("stats", pa.string()),
    ])
    remove_t = pa.struct([
        ("path", pa.string()),
        ("deletionTimestamp", pa.int64()),
        ("dataChange", pa.bool_()),
    ])
    meta_t = pa.struct([
        ("id", pa.string()),
        ("name", pa.string()),
        ("description", pa.string()),
        ("format", pa.struct([("provider", pa.string()),
                              ("options", pa.map_(pa.string(),
                                                  pa.string()))])),
        ("schemaString", pa.string()),
        ("partitionColumns", pa.list_(pa.string())),
        ("configuration", pa.map_(pa.string(), pa.string())),
        ("createdTime", pa.int64()),
    ])
    protocol_t = pa.struct([
        ("minReaderVersion", pa.int32()),
        ("minWriterVersion", pa.int32()),
    ])
    txn_t = pa.struct([
        ("appId", pa.string()),
        ("version", pa.int64()),
        ("lastUpdated", pa.int64()),
    ])
    return pa.schema([
        pa.field("txn", txn_t), pa.field("add", add_t),
        pa.field("remove", remove_t), pa.field("metaData", meta_t),
        pa.field("protocol", protocol_t)])


class DeltaConcurrentModification(RuntimeError):
    """Another writer committed this version first (optimistic conflict)."""


def _version_name(v: int) -> str:
    return f"{v:020d}.json"


class DeltaTable:
    def __init__(self, path: str, conf=None):
        from ..config import DEFAULT_CONF, TpuConf
        self.path = path
        self.conf = conf if isinstance(conf, TpuConf) else (
            TpuConf(conf) if conf else DEFAULT_CONF)
        self.log_dir = os.path.join(path, "_delta_log")

    # ------------------------------------------------------------------
    # log
    # ------------------------------------------------------------------
    def _versions(self) -> List[int]:
        if not os.path.isdir(self.log_dir):
            return []
        out = []
        for f in os.listdir(self.log_dir):
            if f.endswith(".json"):
                try:
                    out.append(int(f[:-5]))
                except ValueError:
                    pass
        return sorted(out)

    def version(self) -> int:
        vs = self._versions()
        latest = vs[-1] if vs else -1
        cp = self._last_checkpoint()
        if cp is not None and cp > latest:
            latest = cp            # JSON commits expired past a checkpoint
        return latest

    def _last_checkpoint(self, upto: Optional[int] = None) -> Optional[int]:
        """Latest checkpoint version <= upto, preferring the
        _last_checkpoint pointer (delta-lake/common checkpoint contract);
        falls back to a directory listing for tables whose pointer is
        stale or missing."""
        cands = []
        ptr = os.path.join(self.log_dir, "_last_checkpoint")
        if os.path.exists(ptr):
            try:
                with open(ptr) as f:
                    v = int(json.load(f)["version"])
                if (upto is None or v <= upto) and os.path.exists(
                        os.path.join(self.log_dir,
                                     f"{v:020d}.checkpoint.parquet")):
                    cands.append(v)
            except (ValueError, KeyError, json.JSONDecodeError):
                pass
        if not cands and os.path.isdir(self.log_dir):
            for f in os.listdir(self.log_dir):
                if f.endswith(".checkpoint.parquet"):
                    try:
                        v = int(f.split(".")[0])
                    except ValueError:
                        continue
                    if upto is None or v <= upto:
                        cands.append(v)
        return max(cands) if cands else None

    @staticmethod
    def _checkpoint_row_to_actions(row: dict) -> List[dict]:
        out = []
        for key in ("protocol", "metaData", "add", "remove", "txn"):
            v = row.get(key)
            if v is None:
                continue
            v = {k: x for k, x in v.items() if x is not None}
            if key == "metaData" and isinstance(
                    v.get("format"), dict):
                v["format"] = {k: x for k, x in v["format"].items()
                               if x is not None}
            out.append({key: v})
        return out

    def _read_actions(self, upto: Optional[int] = None) -> List[dict]:
        actions = []
        start = 0
        cp = self._last_checkpoint(upto)
        if cp is not None:
            cp_path = os.path.join(self.log_dir,
                                   f"{cp:020d}.checkpoint.parquet")
            for row in pq.read_table(cp_path).to_pylist():
                actions.extend(self._checkpoint_row_to_actions(row))
            start = cp + 1
        for v in self._versions():
            if v < start:
                continue
            if upto is not None and v > upto:
                break
            with open(os.path.join(self.log_dir, _version_name(v))) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        actions.append(json.loads(line))
        return actions

    def checkpoint(self, version: Optional[int] = None) -> int:
        """Write a parquet checkpoint of the log state at `version`
        (default: latest) + the _last_checkpoint pointer — real Delta
        readers (and this engine) then replay from the checkpoint instead
        of the full JSON chain (delta-lake/common checkpoint role)."""
        v = self.version() if version is None else version
        if v < 0:
            raise ValueError("cannot checkpoint an empty log")
        active: Dict[str, dict] = {}
        meta = protocol = None
        for a in self._read_actions(v):
            if "add" in a:
                active[a["add"]["path"]] = a["add"]
            elif "remove" in a:
                active.pop(a["remove"]["path"], None)
            elif "metaData" in a:
                meta = a["metaData"]
            elif "protocol" in a:
                protocol = a["protocol"]
        rows = []
        if protocol is not None:
            rows.append({"protocol": protocol})
        if meta is not None:
            rows.append({"metaData": meta})
        for add in active.values():
            rows.append({"add": add})
        cp_schema = _checkpoint_schema()
        full_rows = [{k: r.get(k) for k in cp_schema.names} for r in rows]
        tbl = pa.Table.from_pylist(full_rows, cp_schema)
        pq.write_table(tbl, os.path.join(
            self.log_dir, f"{v:020d}.checkpoint.parquet"))
        with open(os.path.join(self.log_dir, "_last_checkpoint"),
                  "w") as f:
            json.dump({"version": v, "size": len(rows)}, f)
        return v

    def snapshot_files(self, version: Optional[int] = None) -> List[str]:
        """Active data files after log replay (add minus remove)."""
        active: Dict[str, dict] = {}
        for a in self._read_actions(version):
            if "add" in a:
                active[a["add"]["path"]] = a["add"]
            elif "remove" in a:
                active.pop(a["remove"]["path"], None)
        return [os.path.join(self.path, p) for p in sorted(active)]

    def schema(self, version: Optional[int] = None) -> Optional[pa.Schema]:
        meta = None
        for a in self._read_actions(version):
            if "metaData" in a:
                meta = a["metaData"]
        if meta is None:
            return None
        fields = []
        for f in json.loads(meta["schemaString"])["fields"]:
            fields.append(pa.field(f["name"],
                                   _delta_type_to_arrow(f["type"]),
                                   f.get("nullable", True)))
        return pa.schema(fields)

    def _commit(self, version: int, actions: List[dict]) -> None:
        """Atomic optimistic commit: exclusive-create of the version file
        (the log-store PUT-if-absent contract)."""
        os.makedirs(self.log_dir, exist_ok=True)
        target = os.path.join(self.log_dir, _version_name(version))
        try:
            fd = os.open(target, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            raise DeltaConcurrentModification(
                f"version {version} was committed concurrently")
        with os.fdopen(fd, "w") as f:
            for a in actions:
                f.write(json.dumps(a) + "\n")

    def _commit_info(self, op: str, params: dict) -> dict:
        return {"commitInfo": {
            "timestamp": int(time.time() * 1000), "operation": op,
            "operationParameters": params,
            "engineInfo": "spark-rapids-tpu"}}

    # ------------------------------------------------------------------
    # writes
    # ------------------------------------------------------------------
    def _write_file(self, tbl: pa.Table,
                    part_values: Optional[Dict[str, object]] = None
                    ) -> Tuple[str, dict]:
        """One parquet data file + its stats-bearing add action
        (GpuStatisticsCollection role: per-file min/max/nullCount).
        `part_values` places the file under hive-style col=val/ dirs and
        records partitionValues (GpuFileFormatDataWriter dynamic-partition
        role)."""
        import pyarrow.compute as pc
        name = f"part-{uuid.uuid4().hex}.parquet"
        if part_values:
            segs = []
            for k, v in part_values.items():
                segs.append(f"{k}={'__HIVE_DEFAULT_PARTITION__' if v is None else v}")
            name = "/".join(segs + [name])
        full = os.path.join(self.path, name)
        os.makedirs(os.path.dirname(full), exist_ok=True)
        pq.write_table(tbl, full, compression="zstd")
        mins, maxs, nulls = {}, {}, {}
        for c in tbl.schema.names:
            col = tbl.column(c)
            nulls[c] = col.null_count
            try:
                mins[c] = _json_stat(pc.min(col).as_py())
                maxs[c] = _json_stat(pc.max(col).as_py())
            except (pa.ArrowNotImplementedError, pa.ArrowInvalid):
                pass
        stats = {"numRecords": tbl.num_rows, "minValues": mins,
                 "maxValues": maxs, "nullCount": nulls}
        add = {"add": {
            "path": name,
            "partitionValues": {} if not part_values else
            {k: (None if v is None else str(v))
             for k, v in part_values.items()},
            "size": os.path.getsize(full),
            "modificationTime": int(time.time() * 1000),
            "dataChange": True, "stats": json.dumps(stats)}}
        return name, add

    def _meta_action(self, schema: pa.Schema,
                     partition_by: Optional[Sequence[str]] = None) -> dict:
        fields = [{"name": n, "type": _arrow_type_to_delta(schema.field(n).type),
                   "nullable": schema.field(n).nullable, "metadata": {}}
                  for n in schema.names]
        return {"metaData": {
            "id": str(uuid.uuid4()),
            "format": {"provider": "parquet", "options": {}},
            "schemaString": json.dumps({"type": "struct",
                                        "fields": fields}),
            "partitionColumns": list(partition_by or []),
            "configuration": {},
            "createdTime": int(time.time() * 1000)}}

    def partition_columns(self, version: Optional[int] = None) -> List[str]:
        meta = None
        for a in self._read_actions(version):
            if "metaData" in a:
                meta = a["metaData"]
        return list((meta or {}).get("partitionColumns") or [])

    def write(self, table: pa.Table, mode: str = "append",
              partition_by: Optional[Sequence[str]] = None) -> int:
        """append | overwrite; creates the table if absent.  Returns the
        committed version.  `partition_by` (create-time, or inherited
        from the table's metadata) splits rows into hive-style
        partition directories with per-partition stats-bearing files —
        the reference's dynamic-partition writer
        (GpuFileFormatDataWriter.scala)."""
        assert mode in ("append", "overwrite")
        version = self.version() + 1
        existing_parts = self.partition_columns() if version > 0 else []
        parts = list(partition_by) if partition_by is not None             else existing_parts
        if version > 0 and partition_by is not None and                 list(partition_by) != existing_parts:
            raise ValueError(
                f"table is partitioned by {existing_parts}, "
                f"got {list(partition_by)}")
        actions = [self._commit_info("WRITE", {"mode": mode})]
        if version == 0:
            actions.append({"protocol": {"minReaderVersion": 1,
                                         "minWriterVersion": 2}})
            actions.append(self._meta_action(table.schema, parts))
        if mode == "overwrite":
            for p in self.snapshot_files():
                actions.append({"remove": {
                    "path": os.path.relpath(p, self.path),
                    "deletionTimestamp": int(time.time() * 1000),
                    "dataChange": True}})
        if table.num_rows:
            if parts:
                for pv, sub in _split_partitions(table, parts):
                    _name, add = self._write_file(
                        sub.drop_columns(list(parts)), pv)
                    actions.append(add)
            else:
                _name, add = self._write_file(table)
                actions.append(add)
        self._commit(version, actions)
        return version

    def optimize(self, zorder_by: Optional[List[str]] = None,
                 target_rows: Optional[int] = None) -> int:
        """OPTIMIZE [ZORDER BY]: compact the snapshot into ~target_rows
        files; with zorder_by, rows are first reordered along the Morton
        curve over those columns (ops/zorder.py, the reference's
        GpuOptimizeExecutor + ZOrder JNI role — delta-lake/
        GpuOptimisticTransaction.scala + zorder/ dir).  Rewrites carry
        dataChange=false so streaming readers skip them, and the add
        actions keep per-file min/max stats so z-ordered files prune.
        Returns the committed version."""
        if target_rows is None:
            from ..config import DELTA_OPTIMIZE_TARGET_ROWS
            target_rows = self.conf.get(DELTA_OPTIMIZE_TARGET_ROWS)
        files = self.snapshot_files()
        if not files:
            return self.version()
        tbl = pa.concat_tables([pq.read_table(p, partitioning=None)
                                for p in files])
        if zorder_by:
            from ..ops.zorder import zorder_sort_indices
            cols = [_zorder_lane(tbl.column(name), name)
                    for name in zorder_by]
            tbl = tbl.take(pa.array(zorder_sort_indices(cols)))
        version = self.version() + 1
        op = "OPTIMIZE"
        params = {"targetRows": target_rows}
        if zorder_by:
            params["zOrderBy"] = json.dumps(list(zorder_by))
        actions = [self._commit_info(op, params)]
        for p in files:
            actions.append({"remove": {
                "path": os.path.relpath(p, self.path),
                "deletionTimestamp": int(time.time() * 1000),
                "dataChange": False}})
        for start in range(0, tbl.num_rows, target_rows):
            chunk = tbl.slice(start, target_rows)
            _name, add = self._write_file(chunk)
            add["add"]["dataChange"] = False
            actions.append(add)
        self._commit(version, actions)
        return version

    # ------------------------------------------------------------------
    # reads
    # ------------------------------------------------------------------
    def snapshot_adds(self, version: Optional[int] = None) -> List[dict]:
        active: Dict[str, dict] = {}
        for a in self._read_actions(version):
            if "add" in a:
                active[a["add"]["path"]] = a["add"]
            elif "remove" in a:
                active.pop(a["remove"]["path"], None)
        return [active[p] for p in sorted(active)]

    def _snapshot_state(self, version: Optional[int] = None):
        """ONE log replay -> (metaData action or None, active adds) —
        the log (and any parquet checkpoint behind it) is decoded once
        per snapshot operation, not once per question asked of it."""
        meta = None
        active: Dict[str, dict] = {}
        for a in self._read_actions(version):
            if "metaData" in a:
                meta = a["metaData"]
            elif "add" in a:
                active[a["add"]["path"]] = a["add"]
            elif "remove" in a:
                active.pop(a["remove"]["path"], None)
        return meta, [active[p] for p in sorted(active)]

    @staticmethod
    def _mapping_mode_of(meta: Optional[dict]) -> str:
        if meta is None:
            return "none"
        return (meta.get("configuration") or {}).get(
            "delta.columnMapping.mode", "none")

    @staticmethod
    def _physical_names_of(meta: Optional[dict]) -> Dict[str, str]:
        """logical -> physical column name (columnMapping mode=name/id:
        files store physical names from each field's
        delta.columnMapping.physicalName metadata)."""
        out: Dict[str, str] = {}
        if meta is None:
            return out
        for f in json.loads(meta["schemaString"])["fields"]:
            phys = (f.get("metadata") or {}).get(
                "delta.columnMapping.physicalName")
            out[f["name"]] = phys or f["name"]
        return out

    def column_mapping_mode(self, version: Optional[int] = None) -> str:
        return self._mapping_mode_of(self._snapshot_state(version)[0])

    def _read_data_file(self, add: dict, sch: pa.Schema,
                        phys: Optional[Dict[str, str]],
                        part_cols=()) -> pa.Table:
        """One add action -> its table slice: parquet decode, physical->
        logical rename (column mapping), deletion-vector row mask,
        null-fill for columns the file predates (schema evolution —
        column mapping exists precisely to allow add/rename/drop)."""
        # partitioning=None: pyarrow >= 13 infers hive partitioning from
        # k=v path segments and would resurrect partition columns the
        # writer deliberately dropped (they come from partitionValues)
        tbl = pq.read_table(os.path.join(self.path, add["path"]),
                            partitioning=None)
        if phys:
            # physical -> logical for the columns present in the file
            rename = {p: l for l, p in phys.items()}
            tbl = tbl.rename_columns(
                [rename.get(n, n) for n in tbl.schema.names])
        dv = add.get("deletionVector")
        if dv:
            from .dv import read_deletion_vector
            deleted = read_deletion_vector(dv, self.path)
            mask = np.ones(tbl.num_rows, bool)
            in_range = deleted[deleted < tbl.num_rows]
            mask[in_range.astype(np.int64)] = False
            tbl = tbl.filter(pa.array(mask))
        for f in sch:
            if f.name not in tbl.schema.names and f.name not in part_cols:
                tbl = tbl.append_column(f, pa.nulls(tbl.num_rows, f.type))
        return tbl

    def to_logical(self, version: Optional[int] = None):
        """LogicalParquetScan over the snapshot (device-decoded).
        Partitioned tables materialize partition columns from each add
        action's partitionValues (the files don't store them);
        DV-bearing or column-mapped files decode host-side first (row
        masks / physical-name renames are per-file log facts the
        streaming scan cannot know)."""
        from ..io.parquet import LogicalParquetScan
        from ..plan import logical as L
        meta, adds = self._snapshot_state(version)
        parts = (meta or {}).get("partitionColumns") or []
        sch = self.schema(version) or pa.schema([])
        if not adds:
            return L.LogicalScan(pa.Table.from_batches([], sch))
        mapping = self._mapping_mode_of(meta)
        phys = self._physical_names_of(meta) if mapping != "none" else None
        has_dv = any(a.get("deletionVector") for a in adds)
        if not parts and not has_dv and not phys:
            return LogicalParquetScan(
                [os.path.join(self.path, a["path"]) for a in adds])
        pieces = []
        for a in adds:
            tbl = self._read_data_file(a, sch, phys, set(parts))
            pv = a.get("partitionValues") or {}
            n = tbl.num_rows
            for c in parts:
                want = sch.field(c).type
                # under columnMapping the log keys partitionValues by
                # PHYSICAL column name (Delta PROTOCOL.md writer
                # requirement) — translate, falling back to the logical
                # name for writers that used it
                raw = pv.get(phys.get(c, c)) if phys else pv.get(c)
                if raw is None and phys:
                    raw = pv.get(c)
                if raw is None or raw == "__HIVE_DEFAULT_PARTITION__":
                    col = pa.nulls(n, want)
                else:
                    col = pa.array([raw] * n, pa.string()).cast(want)
                tbl = tbl.append_column(pa.field(c, want), col)
            pieces.append(tbl.select(sch.names))
        return L.LogicalScan(pa.concat_tables(pieces))

    def read(self, version: Optional[int] = None) -> pa.Table:
        from ..plan.overrides import apply_overrides
        return apply_overrides(self.to_logical(version)).collect()

    # ------------------------------------------------------------------
    # DML (reference GpuDeleteCommand / GpuUpdateCommand /
    # GpuMergeIntoCommand)
    # ------------------------------------------------------------------
    def _file_matches(self, path: str, condition) -> bool:
        """Does this file contain any matching row?  Predicate runs on
        the device path over the single file."""
        from ..io.parquet import LogicalParquetScan
        from ..plan import logical as L
        from ..plan.aggregates import Count
        from ..plan.overrides import apply_overrides
        plan = L.LogicalAggregate(
            [], [(Count(None), "c")],
            L.LogicalFilter(condition, LogicalParquetScan([path])))
        out = apply_overrides(plan).collect()
        return out.column("c").to_pylist()[0] > 0

    def _no_partition_dml(self, op: str):
        if self.partition_columns():
            raise NotImplementedError(
                f"{op} on partitioned Delta tables is not yet supported "
                "(per-file rewrites need partition-value columns "
                "attached)")
        meta, adds = self._snapshot_state()
        if any(a.get("deletionVector") for a in adds) or \
                self._mapping_mode_of(meta) != "none":
            raise NotImplementedError(
                f"{op} on DV-bearing/column-mapped Delta tables is not "
                "yet supported (read path only)")

    def delete(self, condition) -> int:
        self._no_partition_dml("DELETE")
        return self._delete_impl(condition)

    def _delete_impl(self, condition) -> int:
        """DELETE WHERE condition: rewrite only the touched files."""
        from ..io.parquet import LogicalParquetScan
        from ..plan import expressions as E
        from ..plan import logical as L
        from ..plan.overrides import apply_overrides
        version = self.version() + 1
        actions = [self._commit_info("DELETE", {})]
        changed = False
        for full in self.snapshot_files():
            if not self._file_matches(full, condition):
                continue
            changed = True
            keep = apply_overrides(L.LogicalFilter(
                E.Not(_null_safe(condition)),
                LogicalParquetScan([full]))).collect()
            actions.append({"remove": {
                "path": os.path.relpath(full, self.path),
                "deletionTimestamp": int(time.time() * 1000),
                "dataChange": True}})
            if keep.num_rows:
                _n, add = self._write_file(keep)
                actions.append(add)
        if not changed:
            return self.version()
        self._commit(version, actions)
        return version

    def update(self, condition, assignments: Dict[str, object]) -> int:
        self._no_partition_dml("UPDATE")
        """UPDATE SET col=expr WHERE condition (touched files only)."""
        from ..io.parquet import LogicalParquetScan
        from ..plan import expressions as E
        from ..plan import logical as L
        from ..plan.overrides import apply_overrides
        version = self.version() + 1
        actions = [self._commit_info("UPDATE", {})]
        changed = False
        for full in self.snapshot_files():
            if not self._file_matches(full, condition):
                continue
            changed = True
            scan = LogicalParquetScan([full])
            cols = schema_to_struct(pq.read_schema(full)).names
            exprs = []
            for c in cols:
                if c in assignments:
                    exprs.append(E.If(_null_safe(condition),
                                      assignments[c], E.ColumnRef(c)))
                else:
                    exprs.append(E.ColumnRef(c))
            new = apply_overrides(
                L.LogicalProject(exprs, scan, names=cols)).collect()
            actions.append({"remove": {
                "path": os.path.relpath(full, self.path),
                "deletionTimestamp": int(time.time() * 1000),
                "dataChange": True}})
            _n, add = self._write_file(new)
            actions.append(add)
        if not changed:
            return self.version()
        self._commit(version, actions)
        return version

    def merge(self, source: pa.Table, on: Tuple[str, str],  # noqa: C901
              when_matched_update: Optional[Dict[str, object]] = None,
              when_matched_delete: bool = False,
              when_not_matched_insert: bool = True) -> int:
        """MERGE INTO target USING source ON target.k = source.k —
        find-touched-files then rewrite (GpuMergeIntoCommand shape):
          1. touched = files with keys present in the source (device
             semi-join per file);
          2. rewrite each: unmatched target rows kept, matched rows
             updated (or dropped for delete);
          3. not-matched source rows appended as a new file.
        """
        self._no_partition_dml("MERGE")
        from ..io.parquet import LogicalParquetScan
        from ..plan import expressions as E
        from ..plan import logical as L
        from ..plan.overrides import apply_overrides
        tk, sk = on
        version = self.version() + 1
        actions = [self._commit_info("MERGE", {"on": f"{tk}={sk}"})]
        src = L.LogicalScan(source)
        files = self.snapshot_files()

        from ..plan.aggregates import Count
        for full in files:
            scan = LogicalParquetScan([full])
            semi = L.LogicalJoin("left_semi", scan, src, [tk], [sk])
            n_match = apply_overrides(L.LogicalAggregate(
                [], [(Count(None), "c")],
                semi)).collect().column("c").to_pylist()[0]
            if n_match == 0:
                continue
            # unmatched target rows survive
            keep = apply_overrides(L.LogicalJoin(
                "left_anti", LogicalParquetScan([full]), src,
                [tk], [sk])).collect()
            parts = [keep] if keep.num_rows else []
            if when_matched_update is not None and not when_matched_delete:
                matched = L.LogicalJoin(
                    "inner", LogicalParquetScan([full]), src, [tk], [sk])
                cols = schema_to_struct(pq.read_schema(full)).names
                exprs = [when_matched_update.get(c, E.ColumnRef(c))
                         for c in cols]
                upd = apply_overrides(L.LogicalProject(
                    exprs, matched, names=cols)).collect()
                if upd.num_rows:
                    parts.append(upd.select(keep.schema.names
                                            if keep.num_rows else cols))
            actions.append({"remove": {
                "path": os.path.relpath(full, self.path),
                "deletionTimestamp": int(time.time() * 1000),
                "dataChange": True}})
            if parts:
                merged = pa.concat_tables(parts) if len(parts) > 1 \
                    else parts[0]
                _n, add = self._write_file(merged)
                actions.append(add)

        if when_not_matched_insert:
            tgt = self.to_logical()
            anti = L.LogicalJoin("left_anti", src, tgt, [sk], [tk])
            inserts = apply_overrides(anti).collect()
            if inserts.num_rows:
                tgt_schema = self.schema()
                if tgt_schema is not None:
                    inserts = inserts.rename_columns(
                        [tk if n == sk else n
                         for n in inserts.schema.names]).select(
                        tgt_schema.names).cast(tgt_schema)
                _n, add = self._write_file(inserts)
                actions.append(add)

        self._commit(version, actions)
        return version


def _zorder_lane(arr: pa.ChunkedArray, name: str) -> np.ndarray:
    """Any clusterable column -> float64 lane for the Morton key:
    numerics/decimals cast directly, date/timestamp via their integer
    representation, strings by value rank.  Nulls cluster first."""
    dt = arr.type
    if pa.types.is_string(dt) or pa.types.is_large_string(dt):
        vals = arr.to_pylist()
        uniq = sorted({v for v in vals if v is not None})
        rank = {v: i for i, v in enumerate(uniq)}
        return np.array([-1.0 if v is None else float(rank[v])
                         for v in vals], np.float64)
    if pa.types.is_timestamp(dt) or pa.types.is_date(dt):
        arr = arr.cast(pa.int64() if pa.types.is_timestamp(dt)
                       else pa.int32())
    try:
        f = arr.cast(pa.float64())
    except (pa.ArrowInvalid, pa.ArrowNotImplementedError) as e:
        raise TypeError(f"ZORDER BY {name}: type {dt} is not "
                        f"clusterable") from e
    if f.null_count:
        import pyarrow.compute as pc
        lo = pc.min(f).as_py()
        f = f.fill_null((lo if lo is not None else 0.0) - 1.0)
    return np.asarray(f.combine_chunks())


def _null_safe(condition):
    """Treat NULL predicate results as False (SQL WHERE semantics)."""
    from ..plan import expressions as E
    return E.Coalesce(condition, E.Literal(False, t.BOOLEAN))


def _json_stat(v):
    import decimal
    if isinstance(v, (_dt.date, _dt.datetime)):
        return v.isoformat()
    if isinstance(v, decimal.Decimal):
        return str(v)
    if isinstance(v, bytes):
        return None
    return v


_DELTA_TYPES = {
    pa.int8(): "byte", pa.int16(): "short", pa.int32(): "integer",
    pa.int64(): "long", pa.float32(): "float", pa.float64(): "double",
    pa.bool_(): "boolean", pa.string(): "string", pa.date32(): "date",
}


def _arrow_type_to_delta(at: pa.DataType) -> str:
    if pa.types.is_timestamp(at):
        return "timestamp"
    if pa.types.is_decimal(at):
        return f"decimal({at.precision},{at.scale})"
    for k, v in _DELTA_TYPES.items():
        if at.equals(k):
            return v
    return "string"


def _delta_type_to_arrow(dt) -> pa.DataType:
    if isinstance(dt, str):
        if dt.startswith("decimal("):
            p, s = dt[8:-1].split(",")
            return pa.decimal128(int(p), int(s))
        rev = {v: k for k, v in _DELTA_TYPES.items()}
        rev["timestamp"] = pa.timestamp("us", tz="UTC")
        return rev.get(dt, pa.string())
    return pa.string()
