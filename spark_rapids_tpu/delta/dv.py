"""Delta deletion-vector READ support (protocol `deletionVectors`
feature; reference delta-spark341db DV handling).

A deletion vector marks rows of one data file as deleted without
rewriting the file.  The add action carries a descriptor::

    {"storageType": "u" | "i" | "p",
     "pathOrInlineDv": ...,  "offset": int,
     "sizeInBytes": int,     "cardinality": int}

  * "u": the DV lives in a file under the table root named
    ``deletion_vector_<uuid>.bin`` — pathOrInlineDv is an optional
    random directory prefix followed by the z85-encoded 16-byte UUID
    (last 20 characters).
  * "p": pathOrInlineDv is an absolute path to the DV file.
  * "i": pathOrInlineDv IS the z85-encoded serialized bitmap.

On-disk DV file layout (Delta PROTOCOL.md): 1 format-version byte, then
at ``offset``: a 4-byte big-endian payload size, the payload, and a
4-byte CRC32.  The payload (and the inline form) is a serialized
RoaringBitmapArray in "portable" format: int32-LE magic 1681511377,
int64-LE number of 32-bit bitmaps, then each bitmap in the standard
32-bit roaring portable serialization; deleted row index = (bitmap
ordinal << 32) | value.

The roaring parser below implements the public portable spec (array,
bitmap and run containers, both cookies) directly — no external roaring
dependency exists in this image.
"""
from __future__ import annotations

import os
import struct
import zlib
from typing import List, Optional

import numpy as np

_MAGIC = 1681511377
_SERIAL_COOKIE_NO_RUN = 12346
_SERIAL_COOKIE = 12347
_NO_OFFSET_THRESHOLD = 4

_Z85_CHARS = ("0123456789abcdefghijklmnopqrstuvwxyz"
              "ABCDEFGHIJKLMNOPQRSTUVWXYZ.-:+=^!/*?&<>()[]{}@%$#")
_Z85_MAP = {c: i for i, c in enumerate(_Z85_CHARS)}


def z85_decode(text: str) -> bytes:
    """ZeroMQ Z85: 5 chars -> 4 bytes (big-endian base-85)."""
    if len(text) % 5:
        raise ValueError(f"z85 length {len(text)} not a multiple of 5")
    out = bytearray()
    for i in range(0, len(text), 5):
        v = 0
        for c in text[i:i + 5]:
            v = v * 85 + _Z85_MAP[c]
        out += v.to_bytes(4, "big")
    return bytes(out)


def _parse_roaring32(buf: memoryview, pos: int):
    """One 32-bit roaring bitmap in portable form -> (uint32 array, end)."""
    (cookie,) = struct.unpack_from("<i", buf, pos)
    if (cookie & 0xFFFF) == _SERIAL_COOKIE:
        size = (cookie >> 16) + 1
        pos += 4
        n_run_bytes = (size + 7) // 8
        run_flags = bytes(buf[pos:pos + n_run_bytes])
        pos += n_run_bytes
        has_offsets = size >= _NO_OFFSET_THRESHOLD
    elif cookie == _SERIAL_COOKIE_NO_RUN:
        (size,) = struct.unpack_from("<i", buf, pos + 4)
        pos += 8
        run_flags = b"\x00" * ((size + 7) // 8)
        has_offsets = True
    else:
        raise ValueError(f"bad roaring cookie {cookie}")
    keys = np.zeros(size, np.uint32)
    cards = np.zeros(size, np.int64)
    for i in range(size):
        k, c = struct.unpack_from("<HH", buf, pos)
        keys[i] = k
        cards[i] = c + 1
        pos += 4
    if has_offsets:
        pos += 4 * size                  # container offsets (unused)
    vals: List[np.ndarray] = []
    for i in range(size):
        is_run = bool(run_flags[i // 8] & (1 << (i % 8)))
        base = np.uint32(keys[i]) << np.uint32(16)
        if is_run:
            (n_runs,) = struct.unpack_from("<H", buf, pos)
            pos += 2
            runs = np.frombuffer(buf, np.uint16, 2 * n_runs, pos)
            pos += 4 * n_runs
            starts = runs[0::2].astype(np.uint32)
            lens = runs[1::2].astype(np.uint32) + 1
            parts = [np.arange(s, s + l, dtype=np.uint32)
                     for s, l in zip(starts, lens)]
            lo = np.concatenate(parts) if parts \
                else np.zeros(0, np.uint32)
        elif cards[i] <= 4096:
            lo = np.frombuffer(buf, np.uint16, cards[i], pos) \
                .astype(np.uint32)
            pos += 2 * int(cards[i])
        else:                             # bitset container: 8 KiB
            bits = np.frombuffer(buf, np.uint8, 8192, pos)
            pos += 8192
            lo = np.nonzero(np.unpackbits(bits, bitorder="little"))[0] \
                .astype(np.uint32)
        vals.append(base | lo)
    out = np.concatenate(vals) if vals else np.zeros(0, np.uint32)
    return out, pos


def parse_roaring_array(payload: bytes) -> np.ndarray:
    """Serialized RoaringBitmapArray -> sorted uint64 row indexes."""
    buf = memoryview(payload)
    magic, count = struct.unpack_from("<iq", buf, 0)
    if magic != _MAGIC:
        raise ValueError(f"bad RoaringBitmapArray magic {magic}")
    pos = 12
    parts: List[np.ndarray] = []
    for hi in range(count):
        vals, pos = _parse_roaring32(buf, pos)
        parts.append(vals.astype(np.uint64) | (np.uint64(hi) << np.uint64(32)))
    if not parts:
        return np.zeros(0, np.uint64)
    return np.sort(np.concatenate(parts))


def dv_file_path(descriptor: dict, table_path: str) -> Optional[str]:
    st = descriptor["storageType"]
    if st == "p":
        return descriptor["pathOrInlineDv"]
    if st == "u":
        enc = descriptor["pathOrInlineDv"]
        prefix, uuid_part = enc[:-20], enc[-20:]
        raw = z85_decode(uuid_part)
        import uuid as _uuid
        name = f"deletion_vector_{_uuid.UUID(bytes=raw)}.bin"
        return os.path.join(table_path, prefix, name) if prefix \
            else os.path.join(table_path, name)
    return None                           # inline


def read_deletion_vector(descriptor: dict, table_path: str) -> np.ndarray:
    """Descriptor -> sorted uint64 deleted-row indexes of the file."""
    if descriptor["storageType"] == "i":
        payload = z85_decode(descriptor["pathOrInlineDv"])
        bitmap = parse_roaring_array(payload)
        src = "inline deletion vector"
    else:
        path = dv_file_path(descriptor, table_path)
        with open(path, "rb") as f:
            data = f.read()
        off = descriptor.get("offset", 1) or 1
        (size,) = struct.unpack_from(">i", data, off)
        payload = data[off + 4: off + 4 + size]
        (crc,) = struct.unpack_from(">i", data, off + 4 + size)
        if (zlib.crc32(payload) & 0xFFFFFFFF) != (crc & 0xFFFFFFFF):
            raise ValueError(f"deletion vector CRC mismatch in {path}")
        bitmap = parse_roaring_array(payload)
        src = path
    card = descriptor.get("cardinality")
    if card is not None and card != len(bitmap):
        raise ValueError(
            f"deletion vector cardinality {len(bitmap)} != descriptor "
            f"{card} in {src}")
    return bitmap
