from .table import DeltaTable, DeltaConcurrentModification   # noqa: F401
