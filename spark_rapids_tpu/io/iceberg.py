"""Iceberg read path: metadata.json -> manifest lists -> manifests ->
parquet data files, with v2 delete-file filtering.

Role of the reference's iceberg support (SURVEY §2.6: sql-plugin
com/nvidia/spark/rapids/iceberg ~6k LoC Java — scan with GPU parquet
decode including deletes filtering; IcebergProviderImpl.scala loaded
reflectively).  The reference ports Iceberg's own reader glue; here the
table format is small enough to read directly: the metadata chain is
JSON + Avro (io/avro.py), data files are parquet reused from the
standard scan path, and delete files are applied on host before upload
(position deletes by row index, equality deletes as an anti-join on the
equality-id columns) — the same semantics Iceberg's DeleteFilter
applies, expressed over arrow tables.

Supported: format-version 1 and 2, snapshot selection (time travel by
snapshot-id), position deletes, equality deletes, ADDED/EXISTING vs
DELETED manifest entry status.  Writes are out of scope (read path
only, like the reference).
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

import pyarrow as pa
import pyarrow.parquet as pq

from ..columnar.host import schema_to_struct
from .avro import read_avro_rows
from .text import _TextLogicalScan, CpuTextScanExec, TextScanExec


def _local(path: str) -> str:
    """Iceberg metadata stores absolute URIs; strip file:// for local."""
    if path.startswith("file://"):
        return path[len("file://"):]
    return path


class IcebergSnapshot:
    """Resolved file sets of one snapshot.

    ``seq_of`` maps every file path to its *data sequence number* (Iceberg
    v2 spec): inherited from the manifest-list entry when the manifest
    entry's own sequence_number is null and status is ADDED.  ``None``
    means the table carries no sequence metadata (v1 / legacy layouts).
    """

    def __init__(self, data_files: List[str],
                 pos_delete_files: List[str],
                 eq_deletes: List[Tuple[str, List[int]]],
                 schema: Optional[dict], snapshot_id: Optional[int],
                 seq_of: Optional[Dict[str, Optional[int]]] = None):
        self.data_files = data_files
        self.pos_delete_files = pos_delete_files
        self.eq_deletes = eq_deletes        # (path, equality_field_ids)
        self.schema = schema
        self.snapshot_id = snapshot_id
        self.seq_of = seq_of or {}


def load_table_metadata(table_path: str) -> dict:
    """Latest metadata json via version-hint.text or highest vN."""
    meta_dir = os.path.join(table_path, "metadata")
    hint = os.path.join(meta_dir, "version-hint.text")
    if os.path.exists(hint):
        with open(hint) as f:
            v = f.read().strip()
        cand = os.path.join(meta_dir, f"v{v}.metadata.json")
    else:
        versions = sorted(
            (f for f in os.listdir(meta_dir)
             if f.endswith(".metadata.json")),
            key=lambda n: int(n.split(".")[0].lstrip("v"))
            if n.split(".")[0].lstrip("v").isdigit() else -1)
        if not versions:
            raise FileNotFoundError(f"no metadata.json under {meta_dir}")
        cand = os.path.join(meta_dir, versions[-1])
    with open(cand) as f:
        return json.load(f)


def resolve_snapshot(table_path: str,
                     snapshot_id: Optional[int] = None) -> IcebergSnapshot:
    meta = load_table_metadata(table_path)
    snaps = meta.get("snapshots", [])
    sid = snapshot_id if snapshot_id is not None \
        else meta.get("current-snapshot-id")
    snap = next((s for s in snaps if s["snapshot-id"] == sid), None)
    if snap is None:
        if snapshot_id is not None:
            raise ValueError(f"snapshot {snapshot_id} not found")
        return IcebergSnapshot([], [], [], _current_schema(meta), None)

    data, pos_del, eq_del = [], [], []
    seq_of: Dict[str, Optional[int]] = {}
    _, manifests = read_avro_rows(_local(snap["manifest-list"]))
    for m in manifests:
        mpath = _local(m["manifest_path"])
        mseq = m.get("sequence_number")      # manifest's data sequence num
        # content: 0=data manifest, 1=delete manifest (v1 files omit it)
        _, entries = read_avro_rows(mpath)
        for e in entries:
            if e.get("status") == 2:               # DELETED entry
                continue
            df = e["data_file"]
            fpath = _local(df["file_path"])
            # v2 spec: null entry sequence_number on an ADDED entry
            # inherits the manifest's sequence number.
            eseq = e.get("sequence_number")
            if eseq is None and e.get("status") == 1:
                eseq = mseq
            seq_of[fpath] = eseq
            content = df.get("content", 0)
            if content == 0:
                data.append(fpath)
            elif content == 1:
                pos_del.append(fpath)
            elif content == 2:
                eq_ids = df.get("equality_ids") or []
                eq_del.append((fpath, list(eq_ids)))
    return IcebergSnapshot(data, pos_del, eq_del,
                           _current_schema(meta), sid, seq_of)


def _current_schema(meta: dict) -> Optional[dict]:
    sid = meta.get("current-schema-id")
    for sc in meta.get("schemas", []):
        if sc.get("schema-id") == sid:
            return sc
    return meta.get("schema")


def _field_names_by_id(schema: Optional[dict]) -> Dict[int, str]:
    if not schema:
        return {}
    return {f["id"]: f["name"] for f in schema.get("fields", [])}


def _delete_applies(data_seq: Optional[int], del_seq: Optional[int],
                    strict: bool) -> bool:
    """Iceberg v2 sequence-number scoping: an equality delete applies only
    to data files with *strictly lower* data sequence number; a position
    delete applies to files with lower-or-equal sequence number.  Tables
    without sequence metadata (v1/legacy) apply deletes everywhere."""
    if data_seq is None or del_seq is None:
        return True
    return data_seq < del_seq if strict else data_seq <= del_seq


def read_iceberg(table_path: str,
                 snapshot_id: Optional[int] = None) -> pa.Table:
    """Materialize a snapshot as one arrow table, deletes applied."""
    snap = resolve_snapshot(table_path, snapshot_id)
    if not snap.data_files:
        return pa.table({})

    # position deletes: {data file path -> [(position, delete_seq)]}
    pos_by_file: Dict[str, list] = {}
    for pf in snap.pos_delete_files:
        t = pq.read_table(pf)
        dseq = snap.seq_of.get(pf)
        for fp, pos in zip(t.column("file_path").to_pylist(),
                           t.column("pos").to_pylist()):
            pos_by_file.setdefault(_local(fp), []).append((pos, dseq))

    names = _field_names_by_id(snap.schema)
    eq_tables = [(pq.read_table(p),
                  [names.get(i) for i in ids] if ids else None,
                  snap.seq_of.get(p))
                 for p, ids in snap.eq_deletes]

    parts = []
    for fpath in snap.data_files:
        t = pq.read_table(fpath)
        fseq = snap.seq_of.get(fpath)
        dead = {pos for pos, dseq in pos_by_file.get(fpath, ())
                if _delete_applies(fseq, dseq, strict=False)}
        if dead:
            keep = [i for i in range(t.num_rows) if i not in dead]
            t = t.take(keep)
        for dt, cols, dseq in eq_tables:
            if not _delete_applies(fseq, dseq, strict=True):
                continue
            key_cols = cols or dt.schema.names
            key_cols = [c for c in key_cols if c in t.schema.names]
            if not key_cols:
                continue
            dead_keys = set(zip(*[dt.column(c).to_pylist()
                                  for c in key_cols]))
            mask = [tuple(vals) not in dead_keys for vals in zip(
                *[t.column(c).to_pylist() for c in key_cols])]
            t = t.filter(pa.array(mask, pa.bool_()))
        parts.append(t)
    return pa.concat_tables(parts) if parts else pa.table({})


# ---------------------------------------------------------------------------
# scan plumbing
# ---------------------------------------------------------------------------

def _read_iceberg_scan(path: str, schema, opts) -> pa.Table:
    tbl = read_iceberg(path, (opts or {}).get("snapshot_id"))
    if schema is not None:
        keep = [f.name for f in schema if f.name in tbl.schema.names]
        tbl = tbl.select(keep)
    return tbl


class LogicalIcebergScan(_TextLogicalScan):
    """Iceberg snapshot scan (IcebergProviderImpl role). paths = one
    table root; opts: snapshot_id for time travel."""
    reader = staticmethod(_read_iceberg_scan)
    fmt = "iceberg"

    def _resolve_schema(self):
        if self.arrow_schema is not None:
            return schema_to_struct(self.arrow_schema)
        tbl = read_iceberg(self.paths[0], self.opts.get("snapshot_id"))
        return schema_to_struct(tbl.schema)
