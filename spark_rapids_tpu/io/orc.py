"""ORC scan + writer.

Reference: GpuOrcScan.scala:76 (same three reader strategies as parquet:
stripe stitching, protobuf footer rewrite, device decode) and
GpuOrcFileFormat.  Host decode is pyarrow.orc (stripe-parallel via the
shared threaded stream), producing the engine's standard host batch
stream uploaded to device — the same reasoning as io/parquet.py: columnar
file decode is host work feeding the chip, overlapped with H2D."""
from __future__ import annotations

from typing import Iterator, Optional, Sequence

import pyarrow as pa
import pyarrow.orc as paorc

from .. import types as t
from ..columnar.host import schema_to_struct
from .text import (CpuTextScanExec, TextScanExec, _TextLogicalScan)


def _read_orc(path: str, schema, opts) -> pa.Table:
    f = paorc.ORCFile(path)
    cols = opts.get("columns")
    if cols is None and schema is not None:
        cols = list(schema.names)
    tbl = f.read(columns=cols)
    if schema is not None:
        tbl = tbl.select(schema.names).cast(schema)
    return tbl


class LogicalOrcScan(_TextLogicalScan):
    reader = staticmethod(_read_orc)
    fmt = "orc"

    def _resolve_schema(self):
        if self.arrow_schema is not None:
            return schema_to_struct(self.arrow_schema)
        f = paorc.ORCFile(self.paths[0])
        sch = f.schema
        cols = self.opts.get("columns")
        if cols:
            sch = pa.schema([sch.field(c) for c in cols])
        return schema_to_struct(sch)


class OrcScanExec(TextScanExec):
    pass


class CpuOrcScanExec(CpuTextScanExec):
    pass


def write_orc(table: pa.Table, path: str,
              compression: str = "zstd") -> None:
    """Write one ORC file (GpuOrcFileFormat role; host encode)."""
    paorc.write_table(table, path, compression=compression.upper())
