"""Parquet scan + write.

Reference: GpuParquetScan.scala (2897 LoC, three reader strategies selected
by spark.rapids.sql.format.parquet.reader.type), GpuMultiFileReader.scala:342
(MULTITHREADED cloud reader: thread pool reads+filters footers and buffers
files in parallel), GpuParquetFileFormat.scala + ColumnarOutputWriter.scala
(device-encoded writes streamed to the filesystem).

TPU realization: decode happens on host via pyarrow (Arrow C++ SIMD decode —
the host-decode role the reference gives the GPU is deliberately NOT mapped
to the TPU: XLA has no parquet decoder and byte-twiddling decode is a poor
MXU/VPU fit; the win comes from overlapping decode with H2D upload and
keeping all *compute* on device).  Strategies:

  * PERFILE      — one file at a time, row-group granularity, in order.
  * MULTITHREADED— a thread pool decodes (file, row-group) units ahead of
                   the consumer (GpuMultiFileReader.scala:342 analogue);
                   bounded lookahead caps host memory.
  * COALESCING   — like MULTITHREADED but small row groups are concatenated
                   up to the batch row target before upload (the
                   MultiFileParquetPartitionReader stitching analogue).
  * AUTO         — MULTITHREADED (the cloud-default heuristic).

Row-group pruning: conjunctive `col <op> literal` predicates prune row
groups via footer min/max statistics before any column data is read
(GpuParquetFileFilterHandler analogue).
"""
from __future__ import annotations

import concurrent.futures as cf
from typing import Iterator, List, Optional, Sequence, Tuple

import pyarrow as pa
import pyarrow.parquet as pq

from .. import types as t
from ..columnar.device import (DeviceBatch, merge_origin,
                               to_device)
from ..columnar.host import HostBatch, schema_to_struct, struct_to_schema
from ..config import (PARQUET_MT_THREADS, PARQUET_READER_TYPE, TpuConf)
from ..exec.host_exec import HostNode
from ..exec.plan import ExecContext, PlanNode
from ..plan import expressions as E
from ..plan import logical as L
from ..plan.misc import set_current_input_file


# ---------------------------------------------------------------------------
# Predicate pushdown: expression tree -> conjunctive (col, op, value) terms
# ---------------------------------------------------------------------------

_CMP = {E.EqualTo: "=", E.LessThan: "<", E.LessThanOrEqual: "<=",
        E.GreaterThan: ">", E.GreaterThanOrEqual: ">="}


def conjunctive_terms(expr: Optional[E.Expression]
                      ) -> List[Tuple[str, str, object]]:
    """Best-effort extraction of ANDed `col <op> literal` terms.  Terms that
    don't fit the shape are skipped (pruning stays conservative)."""
    if expr is None:
        return []
    if isinstance(expr, E.And):
        return conjunctive_terms(expr.children[0]) + \
            conjunctive_terms(expr.children[1])
    op = _CMP.get(type(expr))
    if op is None:
        return []
    l, r = expr.children
    if isinstance(l, E.ColumnRef) and isinstance(r, E.Literal) \
            and r.value is not None:
        return [(l.name, op, r.value)]
    if isinstance(r, E.ColumnRef) and isinstance(l, E.Literal) \
            and l.value is not None:
        flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "="}
        return [(r.name, flip[op], l.value)]
    return []


def _rg_maybe_matches(meta, name_to_idx, terms) -> bool:
    """False only when stats PROVE no row in the group can match."""
    for col, op, val in terms:
        i = name_to_idx.get(col)
        if i is None:
            continue
        st = meta.column(i).statistics
        if st is None or not st.has_min_max:
            continue
        lo, hi = st.min, st.max
        try:
            if op == "=" and (val < lo or val > hi):
                return False
            if op in ("<", "<=") and not (lo < val or (op == "<=" and lo <= val)):
                return False
            if op in (">", ">=") and not (hi > val or (op == ">=" and hi >= val)):
                return False
        except TypeError:
            continue      # incomparable stat types: keep the group
    return True


# ---------------------------------------------------------------------------
# Host-side batch production (shared by device scan and CPU fallback scan)
# ---------------------------------------------------------------------------

def _scan_units(paths: Sequence[str], terms) -> List[Tuple[str, int]]:
    """(path, row_group) work units after row-group stat pruning."""
    units = []
    for p in paths:
        pf = pq.ParquetFile(p)
        schema = pf.schema_arrow
        name_to_idx = {n: i for i, n in enumerate(schema.names)}
        for rg in range(pf.metadata.num_row_groups):
            if _rg_maybe_matches(pf.metadata.row_group(rg), name_to_idx,
                                 terms):
                units.append((p, rg))
    return units


def _read_unit(unit: Tuple[str, int], columns) -> pa.Table:
    path, rg = unit
    return pq.ParquetFile(path).read_row_group(rg, columns=columns)


def host_batch_stream(paths: Sequence[str], columns, conf: TpuConf,
                      filter_expr: Optional[E.Expression] = None,
                      ) -> Iterator[pa.RecordBatch]:
    """Ordered stream of decoded record batches per the reader strategy."""
    for rb, _origin in host_batch_stream_with_origin(
            paths, columns, conf, filter_expr):
        yield rb


def host_batch_stream_with_origin(
        paths: Sequence[str], columns, conf: TpuConf,
        filter_expr: Optional[E.Expression] = None,
        ) -> Iterator[Tuple[pa.RecordBatch, str]]:
    """(batch, source file) pairs — scan provenance for
    input_file_name (GpuInputFileName role).  COALESCING batches that
    stitched multiple files report "" (mixed provenance)."""
    strategy = str(conf.get(PARQUET_READER_TYPE)).upper()
    if strategy == "AUTO":
        strategy = "MULTITHREADED"
    terms = conjunctive_terms(filter_expr)
    units = _scan_units(paths, terms)
    target = conf.batch_size_rows

    def split(tbl: pa.Table, origin: str):
        for rb in tbl.combine_chunks().to_batches(max_chunksize=target):
            yield rb, origin

    if strategy == "PERFILE" or not units:
        for u in units:
            yield from split(_read_unit(u, columns), u[0])
        return

    threads = conf.get(PARQUET_MT_THREADS)
    lookahead = max(2, threads)
    coalesce = strategy == "COALESCING"
    pending: List[pa.Table] = []
    pending_files: set = set()
    pending_rows = 0
    with cf.ThreadPoolExecutor(max_workers=threads) as pool:
        futures = [pool.submit(_read_unit, u, columns) for u in
                   units[:lookahead]]
        nxt = lookahead
        for i in range(len(units)):
            tbl = futures[i].result()
            if nxt < len(units):
                futures.append(pool.submit(_read_unit, units[nxt], columns))
                nxt += 1
            if not coalesce:
                yield from split(tbl, units[i][0])
                continue
            pending.append(tbl)
            pending_files.add(units[i][0])
            pending_rows += tbl.num_rows
            if pending_rows >= target:
                yield from split(pa.concat_tables(pending),
                                 merge_origin(pending_files))
                pending, pending_rows = [], 0
                pending_files = set()
        if pending:
            yield from split(pa.concat_tables(pending),
                             merge_origin(pending_files))


def parquet_schema(paths: Sequence[str], columns=None) -> t.StructType:
    schema = pq.ParquetFile(paths[0]).schema_arrow
    st = schema_to_struct(schema)
    if columns:
        return t.StructType([st[c] for c in columns])
    return st


# ---------------------------------------------------------------------------
# Plan nodes
# ---------------------------------------------------------------------------

class LogicalParquetScan(L.LogicalPlan):
    def __init__(self, paths: Sequence[str], columns=None):
        super().__init__()
        self.paths = list(paths)
        self.columns = list(columns) if columns else None
        self.pushed_filter: Optional[E.Expression] = None

    def _resolve_schema(self):
        return parquet_schema(self.paths, self.columns)

    def describe(self):
        extra = f", pushed={self.pushed_filter!r}" if self.pushed_filter else ""
        return f"ParquetScan[{len(self.paths)} files{extra}]"


class ParquetScanExec(PlanNode):
    """Device scan: threaded host decode overlapped with H2D upload."""

    def __init__(self, paths, columns, schema: t.StructType,
                 filter_expr: Optional[E.Expression] = None):
        super().__init__()
        self.paths = list(paths)
        self.columns = columns
        self._schema = schema
        self.filter_expr = filter_expr

    @property
    def output_schema(self) -> t.StructType:
        return self._schema

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        for rb, origin in host_batch_stream_with_origin(
                self.paths, self.columns, ctx.conf, self.filter_expr):
            ctx.bump("scanned_rows", rb.num_rows)
            db = to_device(HostBatch(rb), ctx.conf)
            db.origin_file = origin      # input_file_name provenance
            set_current_input_file(origin)
            yield db

    def describe(self):
        return f"ParquetScanExec[{len(self.paths)} files]"


class CpuParquetScanExec(HostNode):
    def __init__(self, paths, columns, schema: t.StructType,
                 filter_expr: Optional[E.Expression] = None):
        super().__init__()
        self.paths = list(paths)
        self.columns = columns
        self._schema = schema
        self.filter_expr = filter_expr

    @property
    def output_schema(self) -> t.StructType:
        return self._schema

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        for rb, origin in host_batch_stream_with_origin(
                self.paths, self.columns, ctx.conf, self.filter_expr):
            set_current_input_file(origin)
            yield rb


# ---------------------------------------------------------------------------
# Writer (GpuParquetFileFormat / ColumnarOutputWriter analogue)
# ---------------------------------------------------------------------------

def write_parquet(df, path: str, partition_by: Optional[Sequence[str]] = None,
                  compression: str = "zstd",
                  row_group_rows: int = 1 << 20,
                  bucket_by: Optional[Tuple[Sequence[str], int]] = None
                  ) -> None:
    """Stream query results into parquet without materializing the whole
    result (the reference streams device-encoded chunks through
    HostBufferConsumer; here host batches stream into ParquetWriter).

    `bucket_by=(cols, n)` writes Spark-compatible bucketed output: rows
    route to n files by the bit-exact Spark Murmur3 hash of the bucket
    columns (pmod n), file names carrying the bucket id the way Spark's
    FileFormatWriter does (reference GpuFileFormatDataWriter bucketing
    with device Murmur3)."""
    q = df.physical()
    schema = struct_to_schema(df.schema)
    if bucket_by:
        import pathlib
        from ..plan import expressions as E
        cols, n_buckets = bucket_by
        tbl = q.collect()
        bound = E.Murmur3Hash(
            *[E.ColumnRef(c) for c in cols]).bind(
            schema_to_struct(tbl.schema))
        rb = tbl.combine_chunks().to_batches()[0] if tbl.num_rows else None
        root = pathlib.Path(path)
        root.mkdir(parents=True, exist_ok=True)
        if rb is None:
            return
        import numpy as np
        import pyarrow.compute as pc
        h = bound.eval_cpu(rb)
        hv = np.asarray(h.to_numpy(zero_copy_only=False), np.int64)
        b = ((hv % n_buckets) + n_buckets) % n_buckets   # Spark pmod
        for bid in range(n_buckets):
            sub = tbl.filter(pa.array(b == bid))
            if sub.num_rows == 0:
                continue
            pq.write_table(sub, str(
                root / f"part-00000-{bid:05d}.c000.parquet"),
                compression=compression)
        return
    if partition_by:
        import pyarrow.dataset as ds
        tbl = q.collect()
        ds.write_dataset(tbl, path, format="parquet",
                         partitioning=ds.partitioning(
                             pa.schema([schema.field(c) for c in partition_by]),
                             flavor="hive"),
                         existing_data_behavior="overwrite_or_ignore")
        return
    import pathlib
    p = pathlib.Path(path)
    if p.suffix != ".parquet":
        p.mkdir(parents=True, exist_ok=True)
        p = p / "part-00000.parquet"
    writer = pq.ParquetWriter(str(p), schema, compression=compression)
    try:
        for rb in q.execute_host_batches():
            if rb.num_rows == 0:
                continue
            writer.write_batch(rb.cast(schema) if rb.schema != schema else rb,
                               row_group_size=row_group_rows)
    finally:
        writer.close()
