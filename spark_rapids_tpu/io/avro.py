"""Avro scan: pure-Python Object Container File codec + scan execs.

Role of the reference's GpuAvroScan.scala + AvroDataFileReader.scala
(SURVEY §2.6): the reference parses Avro container blocks in pure JVM
code and decodes on device.  Like CSV/JSON (io/text.py), record decoding
is not TPU work — the host decodes to arrow and the standard host->device
upload path takes over; a minimal writer exists for tests/round-trips.

Container format: magic 'Obj\\x01', file-metadata map (avro.schema JSON,
avro.codec), 16-byte sync marker, then blocks of (row count, byte size,
payload, sync).  Codecs: null, deflate (raw zlib).  Types: all Avro
primitives, records, enums, fixed, arrays, maps, nullable unions, and the
date / timestamp-millis / timestamp-micros / decimal logical types.
"""
from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, Iterator, List, Sequence, Tuple

import pyarrow as pa

MAGIC = b"Obj\x01"


# ---------------------------------------------------------------------------
# binary primitives
# ---------------------------------------------------------------------------

class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def read(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        if len(b) != n:
            raise EOFError("truncated avro data")
        self.pos += n
        return b

    def at_end(self) -> bool:
        return self.pos >= len(self.buf)

    def zlong(self) -> int:
        shift = 0
        accum = 0
        while True:
            if self.pos >= len(self.buf):
                raise EOFError("truncated avro data")
            b = self.buf[self.pos]
            self.pos += 1
            accum |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        return (accum >> 1) ^ -(accum & 1)   # zigzag decode

    def zbytes(self) -> bytes:
        return self.read(self.zlong())


def _zigzag(n: int) -> bytes:
    n = (n << 1) ^ (n >> 63) if n < 0 else n << 1
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


# ---------------------------------------------------------------------------
# schema -> decoder / arrow type
# ---------------------------------------------------------------------------

def _logical(sc: dict):
    lt = sc.get("logicalType")
    ty = sc["type"]
    if lt == "date" and ty == "int":
        return pa.date32()
    if lt == "timestamp-micros" and ty == "long":
        return pa.timestamp("us", tz="UTC")
    if lt == "timestamp-millis" and ty == "long":
        return pa.timestamp("ms", tz="UTC")
    if lt == "decimal" and ty in ("bytes", "fixed"):
        return pa.decimal128(sc["precision"], sc.get("scale", 0))
    return None


_PRIMITIVE_ARROW = {
    "null": pa.null(), "boolean": pa.bool_(), "int": pa.int32(),
    "long": pa.int64(), "float": pa.float32(), "double": pa.float64(),
    "bytes": pa.binary(), "string": pa.string(),
}


def schema_to_arrow(sc) -> pa.DataType:
    if isinstance(sc, str):
        return _PRIMITIVE_ARROW[sc]
    if isinstance(sc, list):                       # union
        non_null = [s for s in sc if s != "null"]
        if len(non_null) != 1:
            raise NotImplementedError(f"general unions: {sc}")
        return schema_to_arrow(non_null[0])
    ty = sc["type"]
    lt = _logical(sc)
    if lt is not None:
        return lt
    if ty == "record":
        return pa.struct([(f["name"], schema_to_arrow(f["type"]))
                          for f in sc["fields"]])
    if ty == "enum":
        return pa.string()
    if ty == "fixed":
        return pa.binary(sc["size"])
    if ty == "array":
        return pa.list_(schema_to_arrow(sc["items"]))
    if ty == "map":
        return pa.map_(pa.string(), schema_to_arrow(sc["values"]))
    return schema_to_arrow(ty)                      # {"type": "int"} wrapper


def _decode(sc, r: _Reader) -> Any:
    if isinstance(sc, str):
        if sc == "null":
            return None
        if sc == "boolean":
            return r.read(1) != b"\x00"
        if sc in ("int", "long"):
            return r.zlong()
        if sc == "float":
            return struct.unpack("<f", r.read(4))[0]
        if sc == "double":
            return struct.unpack("<d", r.read(8))[0]
        if sc == "bytes":
            return r.zbytes()
        if sc == "string":
            return r.zbytes().decode("utf-8")
        raise NotImplementedError(sc)
    if isinstance(sc, list):                       # union: branch index
        return _decode(sc[r.zlong()], r)
    ty = sc["type"]
    lt = sc.get("logicalType")
    if lt == "decimal" and ty in ("bytes", "fixed"):
        import decimal as pydec
        raw = (r.read(sc["size"]) if ty == "fixed" else r.zbytes())
        unscaled = int.from_bytes(raw, "big", signed=True)
        return pydec.Decimal(unscaled).scaleb(-sc.get("scale", 0))
    if ty == "record":
        return {f["name"]: _decode(f["type"], r) for f in sc["fields"]}
    if ty == "enum":
        return sc["symbols"][r.zlong()]
    if ty == "fixed":
        return r.read(sc["size"])
    if ty == "array":
        out = []
        while True:
            n = r.zlong()
            if n == 0:
                return out
            if n < 0:                               # block with byte size
                n = -n
                r.zlong()
            for _ in range(n):
                out.append(_decode(sc["items"], r))
    if ty == "map":
        out = []
        while True:
            n = r.zlong()
            if n == 0:
                return out
            if n < 0:
                n = -n
                r.zlong()
            for _ in range(n):
                k = r.zbytes().decode("utf-8")
                out.append((k, _decode(sc["values"], r)))
    return _decode(ty, r)                           # wrapper / logical base


# ---------------------------------------------------------------------------
# container file
# ---------------------------------------------------------------------------

def read_avro_rows(path: str) -> Tuple[dict, List[dict]]:
    """Decode a container file to (schema, row dicts)."""
    with open(path, "rb") as f:
        data = f.read()
    r = _Reader(data)
    if r.read(4) != MAGIC:
        raise ValueError(f"{path}: not an avro container file")
    meta = dict(_decode({"type": "map", "values": "bytes"}, r))
    sync = r.read(16)
    schema = json.loads(meta["avro.schema"])
    codec = meta.get("avro.codec", b"null").decode()
    if schema.get("type") != "record":
        raise NotImplementedError("top-level schema must be a record")
    rows: List[dict] = []
    while not r.at_end():
        count = r.zlong()
        payload = r.zbytes()
        if r.read(16) != sync:
            raise ValueError(f"{path}: sync marker mismatch")
        if codec == "deflate":
            payload = zlib.decompress(payload, -15)
        elif codec != "null":
            raise NotImplementedError(f"avro codec {codec}")
        br = _Reader(payload)
        for _ in range(count):
            rows.append(_decode(schema, br))
    return schema, rows


def read_avro(path: str, schema=None, opts=None) -> pa.Table:
    avsc, rows = read_avro_rows(path)
    fields = [(f["name"], schema_to_arrow(f["type"]))
              for f in avsc["fields"]]
    arrow_schema = pa.schema(fields)
    cols = {name: [row[name] for row in rows] for name, _ in fields}
    return pa.table(
        {name: pa.array(cols[name], type=ty) for name, ty in fields},
        schema=arrow_schema)


# ---------------------------------------------------------------------------
# minimal writer (tests + round-trips)
# ---------------------------------------------------------------------------

_ARROW_TO_AVRO = {
    pa.bool_(): "boolean", pa.int32(): "int", pa.int64(): "long",
    pa.float32(): "float", pa.float64(): "double",
    pa.string(): "string", pa.binary(): "bytes",
}


def _avro_schema_of(field: pa.Field) -> Any:
    ty = field.type
    if ty in _ARROW_TO_AVRO:
        base = _ARROW_TO_AVRO[ty]
    elif pa.types.is_date32(ty):
        base = {"type": "int", "logicalType": "date"}
    elif pa.types.is_timestamp(ty):
        unit = "timestamp-micros" if ty.unit == "us" else "timestamp-millis"
        base = {"type": "long", "logicalType": unit}
    elif pa.types.is_decimal(ty):
        base = {"type": "bytes", "logicalType": "decimal",
                "precision": ty.precision, "scale": ty.scale}
    elif pa.types.is_list(ty):
        base = {"type": "array",
                "items": _avro_schema_of(pa.field("item", ty.value_type))}
    else:
        raise NotImplementedError(f"avro write: {ty}")
    return ["null", base] if field.nullable else base


def _encode(sc, v, out: bytearray) -> None:
    if isinstance(sc, list):                       # nullable union
        if v is None:
            out += _zigzag(sc.index("null"))
            return
        idx = next(i for i, s in enumerate(sc) if s != "null")
        out += _zigzag(idx)
        _encode(sc[idx], v, out)
        return
    if isinstance(sc, str):
        if sc == "null":
            return
        if sc == "boolean":
            out += b"\x01" if v else b"\x00"
        elif sc in ("int", "long"):
            out += _zigzag(int(v))
        elif sc == "float":
            out += struct.pack("<f", v)
        elif sc == "double":
            out += struct.pack("<d", v)
        elif sc in ("bytes", "string"):
            b = v.encode("utf-8") if isinstance(v, str) else bytes(v)
            out += _zigzag(len(b)) + b
        else:
            raise NotImplementedError(sc)
        return
    ty, lt = sc["type"], sc.get("logicalType")
    if lt == "decimal":
        unscaled = int(v.scaleb(sc.get("scale", 0)))
        nbytes = max(1, (unscaled.bit_length() + 8) // 8)
        out += _zigzag(nbytes) + unscaled.to_bytes(nbytes, "big", signed=True)
    elif lt == "date":
        import datetime as pydt
        days = (v - pydt.date(1970, 1, 1)).days if hasattr(v, "year") else int(v)
        out += _zigzag(days)
    elif lt in ("timestamp-micros", "timestamp-millis"):
        if hasattr(v, "timestamp"):
            # integer arithmetic: float epoch-seconds can't hold micros
            import datetime as pydt
            if v.tzinfo is None:
                v = v.replace(tzinfo=pydt.timezone.utc)
            epoch = pydt.datetime(1970, 1, 1, tzinfo=pydt.timezone.utc)
            unit = pydt.timedelta(
                microseconds=1 if lt == "timestamp-micros" else 1000)
            out += _zigzag((v - epoch) // unit)
        else:
            out += _zigzag(int(v))
    elif ty == "array":
        if v:
            out += _zigzag(len(v))
            for item in v:
                _encode(sc["items"], item, out)
        out += _zigzag(0)
    elif ty == "record":
        for f in sc["fields"]:
            _encode(f["type"], v[f["name"]], out)
    elif ty == "map":
        items = list(v.items()) if isinstance(v, dict) else list(v or ())
        if items:
            out += _zigzag(len(items))
            for k, mv in items:
                kb = k.encode("utf-8")
                out += _zigzag(len(kb)) + kb
                _encode(sc["values"], mv, out)
        out += _zigzag(0)
    else:
        _encode(ty, v, out)


def write_avro_records(avsc: dict, rows: Sequence[dict], path: str,
                       codec: str = "deflate") -> None:
    """Write dict rows under an explicit Avro record schema (nested
    records/arrays/maps supported) — used by Iceberg manifest writing in
    tests and by any caller that needs non-tabular Avro."""
    sync = os.urandom(16)
    out = io.BytesIO()
    out.write(MAGIC)
    meta = {"avro.schema": json.dumps(avsc).encode(),
            "avro.codec": codec.encode()}
    out.write(_zigzag(len(meta)))
    for k, v in meta.items():
        kb = k.encode()
        out.write(_zigzag(len(kb)) + kb + _zigzag(len(v)) + v)
    out.write(_zigzag(0))
    out.write(sync)
    block = bytearray()
    for row in rows:
        _encode(avsc, row, block)
    payload = bytes(block)
    if codec == "deflate":
        payload = zlib.compress(payload)[2:-4]
    elif codec != "null":
        raise NotImplementedError(f"avro codec {codec}")
    if rows:
        out.write(_zigzag(len(rows)))
        out.write(_zigzag(len(payload)) + payload)
        out.write(sync)
    with open(path, "wb") as f:
        f.write(out.getvalue())


def write_avro(table: pa.Table, path: str, codec: str = "deflate") -> None:
    avsc = {"type": "record", "name": "topLevelRecord",
            "fields": [{"name": f.name, "type": _avro_schema_of(f)}
                       for f in table.schema]}
    sync = os.urandom(16)
    out = io.BytesIO()
    out.write(MAGIC)
    meta = {"avro.schema": json.dumps(avsc).encode(),
            "avro.codec": codec.encode()}
    out.write(_zigzag(len(meta)))
    for k, v in meta.items():
        kb = k.encode()
        out.write(_zigzag(len(kb)) + kb + _zigzag(len(v)) + v)
    out.write(_zigzag(0))
    out.write(sync)

    cols = [table.column(f.name).to_pylist() for f in table.schema]
    schemas = [s["type"] for s in avsc["fields"]]
    block = bytearray()
    nrows = table.num_rows
    for i in range(nrows):
        for sc, col in zip(schemas, cols):
            _encode(sc, col[i], block)
    payload = bytes(block)
    if codec == "deflate":
        payload = zlib.compress(payload)[2:-4]      # raw, no zlib wrapper
    elif codec != "null":
        raise NotImplementedError(f"avro codec {codec}")
    if nrows:
        out.write(_zigzag(nrows))
        out.write(_zigzag(len(payload)) + payload)
        out.write(sync)
    with open(path, "wb") as f:
        f.write(out.getvalue())


# ---------------------------------------------------------------------------
# scan plumbing (same shape as ORC over the text-scan infra)
# ---------------------------------------------------------------------------

from ..columnar.host import schema_to_struct                  # noqa: E402
from .text import (_TextLogicalScan, CpuTextScanExec,          # noqa: E402
                   TextScanExec)


def _read_avro_scan(path: str, schema, opts) -> pa.Table:
    tbl = read_avro(path)
    if schema is not None:
        tbl = tbl.select([f.name for f in schema])
    return tbl


class LogicalAvroScan(_TextLogicalScan):
    """Avro container scan (GpuAvroScan.scala role)."""
    reader = staticmethod(_read_avro_scan)
    fmt = "avro"

    def _resolve_schema(self):
        if self.arrow_schema is not None:
            return schema_to_struct(self.arrow_schema)
        avsc, _ = read_avro_rows(self.paths[0])
        arrow = pa.schema([(f["name"], schema_to_arrow(f["type"]))
                           for f in avsc["fields"]])
        return schema_to_struct(arrow)


