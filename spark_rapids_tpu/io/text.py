"""CSV / JSON scans.

Reference: GpuCSVScan.scala:223 + GpuTextBasedPartitionReader (host line
framing, device decode via Table.readCSV/readJSON), catalyst/json/rapids
GpuJsonScan.  Here decode is pyarrow.csv / pyarrow.json on host threads
(same reasoning as io/parquet.py: text parsing is not TPU work), producing
the engine's standard host batch stream with threaded per-file lookahead.
"""
from __future__ import annotations

import concurrent.futures as cf
from typing import Iterator, List, Optional, Sequence

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.json as pajson

from .. import types as t
from ..columnar.device import DeviceBatch, to_device
from ..columnar.host import HostBatch, schema_to_struct, struct_to_schema
from ..exec.host_exec import HostNode
from ..exec.plan import ExecContext, PlanNode
from ..plan import logical as L


def _read_csv(path: str, schema, opts) -> pa.Table:
    convert = pacsv.ConvertOptions(
        column_types=schema if schema is not None else None)
    parse = pacsv.ParseOptions(delimiter=opts.get("sep", ","))
    read = pacsv.ReadOptions(
        column_names=opts.get("column_names"),
        autogenerate_column_names=opts.get("header", True) is False
        and opts.get("column_names") is None)
    return pacsv.read_csv(path, read_options=read, parse_options=parse,
                          convert_options=convert)


def _read_json(path: str, schema, opts) -> pa.Table:
    parse = pajson.ParseOptions(
        explicit_schema=schema if schema is not None else None)
    return pajson.read_json(path, parse_options=parse)


def _stream(paths: Sequence[str], schema, opts, conf, reader
            ) -> Iterator[pa.RecordBatch]:
    target = conf.batch_size_rows
    with cf.ThreadPoolExecutor(max_workers=min(8, max(1, len(paths)))) as pool:
        futs = [pool.submit(reader, p, schema, opts) for p in paths]
        for f in futs:
            tbl = f.result()
            yield from tbl.combine_chunks().to_batches(max_chunksize=target)


class _TextLogicalScan(L.LogicalPlan):
    reader = None
    fmt = "text"

    def __init__(self, paths: Sequence[str], schema=None, opts=None):
        super().__init__()
        self.paths = list(paths)
        self.arrow_schema = schema
        self.opts = dict(opts or {})

    def _resolve_schema(self):
        if self.arrow_schema is not None:
            return schema_to_struct(self.arrow_schema)
        tbl = type(self).reader(self.paths[0], None, self.opts)
        return schema_to_struct(tbl.schema)

    def describe(self):
        return f"{type(self).__name__}[{len(self.paths)} files]"


class LogicalCsvScan(_TextLogicalScan):
    reader = staticmethod(_read_csv)
    fmt = "csv"


class LogicalJsonScan(_TextLogicalScan):
    reader = staticmethod(_read_json)
    fmt = "json"


def _read_hive_text(path: str, schema, opts) -> pa.Table:
    """Hive default text serde: ctrl-A field delimiter, \\N nulls, no
    header (GpuHiveTextFileFormat.scala role)."""
    opts = dict(opts or {})
    names = opts.get("column_names")
    if names is None and schema is not None:
        names = [f.name for f in schema]
    convert = pacsv.ConvertOptions(
        column_types=schema if schema is not None else None,
        null_values=["\\N"], strings_can_be_null=True,
        quoted_strings_can_be_null=False)
    parse = pacsv.ParseOptions(delimiter=opts.get("sep", "\x01"),
                               quote_char=False, escape_char="\\",
                               newlines_in_values=True)
    read = pacsv.ReadOptions(column_names=names,
                             autogenerate_column_names=names is None)
    return pacsv.read_csv(path, read_options=read, parse_options=parse,
                          convert_options=convert)


class LogicalHiveTextScan(_TextLogicalScan):
    reader = staticmethod(_read_hive_text)
    fmt = "hivetext"


def write_hive_text(table: pa.Table, path: str, sep: str = "\x01") -> None:
    """Writer half of the hive text serde: \\N for null, backslash-
    escaped delimiter/newline/CR/backslash (LazySimpleSerDe escaping;
    the reader's escape_char reverses it).  Known deviation: a field
    whose VALUE is exactly the 2-char string '\\N' reads back as null —
    arrow matches null markers after unescaping, so Hive's \\N-vs-\\\\N
    distinction is not representable without a custom parser.  Binary
    columns are rejected (text serde; use parquet/orc/avro)."""
    for field in table.schema:
        if pa.types.is_binary(field.type) or \
                pa.types.is_large_binary(field.type):
            raise TypeError(f"hive text cannot carry binary column "
                            f"{field.name}; use parquet/orc/avro")

    def esc(v) -> str:
        s = v if isinstance(v, str) else str(v)
        return (s.replace("\\", "\\\\").replace(sep, "\\" + sep)
                .replace("\n", "\\\n").replace("\r", "\\\r"))

    # the reader unescapes before null matching, so the on-disk marker
    # is the ESCAPED form backslash-backslash-N (unescapes to \N)
    null_marker = "\\\\N"
    with open(path, "w", encoding="utf-8") as f:
        cols = [table.column(n).to_pylist() for n in table.schema.names]
        for row in zip(*cols):
            f.write(sep.join(null_marker if v is None else esc(v)
                             for v in row) + "\n")


class TextScanExec(PlanNode):
    def __init__(self, logical: _TextLogicalScan, schema: t.StructType):
        super().__init__()
        self.logical = logical
        self._schema = schema

    @property
    def output_schema(self) -> t.StructType:
        return self._schema

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        lg = self.logical
        want = struct_to_schema(self._schema)
        for rb in _stream(lg.paths, lg.arrow_schema, lg.opts, ctx.conf,
                          type(lg).reader):
            ctx.bump("scanned_rows", rb.num_rows)
            if rb.schema != want:
                rb = pa.Table.from_batches([rb]).cast(want) \
                    .combine_chunks().to_batches()[0]
            yield to_device(HostBatch(rb), ctx.conf)


class CpuTextScanExec(HostNode):
    def __init__(self, logical: _TextLogicalScan, schema: t.StructType):
        super().__init__()
        self.logical = logical
        self._schema = schema

    @property
    def output_schema(self) -> t.StructType:
        return self._schema

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        lg = self.logical
        yield from _stream(lg.paths, lg.arrow_schema, lg.opts, ctx.conf,
                           type(lg).reader)
