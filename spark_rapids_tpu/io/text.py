"""CSV / JSON scans.

Reference: GpuCSVScan.scala:223 + GpuTextBasedPartitionReader (host line
framing, device decode via Table.readCSV/readJSON), catalyst/json/rapids
GpuJsonScan.  Here decode is pyarrow.csv / pyarrow.json on host threads
(same reasoning as io/parquet.py: text parsing is not TPU work), producing
the engine's standard host batch stream with threaded per-file lookahead.
"""
from __future__ import annotations

import concurrent.futures as cf
from typing import Iterator, List, Optional, Sequence, Tuple

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.json as pajson

from .. import types as t
from ..columnar.device import DeviceBatch, to_device
from ..columnar.host import HostBatch, schema_to_struct, struct_to_schema
from ..exec.host_exec import HostNode
from ..exec.plan import ExecContext, PlanNode
from ..plan import logical as L


def _read_csv(path: str, schema, opts) -> pa.Table:
    convert = pacsv.ConvertOptions(
        column_types=schema if schema is not None else None)
    parse = pacsv.ParseOptions(delimiter=opts.get("sep", ","))
    read = pacsv.ReadOptions(
        column_names=opts.get("column_names"),
        autogenerate_column_names=opts.get("header", True) is False
        and opts.get("column_names") is None)
    return pacsv.read_csv(path, read_options=read, parse_options=parse,
                          convert_options=convert)


def _read_json(path: str, schema, opts) -> pa.Table:
    parse = pajson.ParseOptions(
        explicit_schema=schema if schema is not None else None)
    return pajson.read_json(path, parse_options=parse)


def _stream(paths: Sequence[str], schema, opts, conf, reader
            ) -> Iterator[Tuple[pa.RecordBatch, str]]:
    """(batch, source path) pairs — provenance for input_file_name."""
    target = conf.batch_size_rows
    with cf.ThreadPoolExecutor(max_workers=min(8, max(1, len(paths)))) as pool:
        futs = [pool.submit(reader, p, schema, opts) for p in paths]
        for f, path in zip(futs, paths):
            tbl = f.result()
            for rb in tbl.combine_chunks().to_batches(max_chunksize=target):
                yield rb, path


class _TextLogicalScan(L.LogicalPlan):
    reader = None
    fmt = "text"

    def __init__(self, paths: Sequence[str], schema=None, opts=None):
        super().__init__()
        self.paths = list(paths)
        self.arrow_schema = schema
        self.opts = dict(opts or {})

    def _resolve_schema(self):
        if self.arrow_schema is not None:
            return schema_to_struct(self.arrow_schema)
        tbl = type(self).reader(self.paths[0], None, self.opts)
        return schema_to_struct(tbl.schema)

    def describe(self):
        return f"{type(self).__name__}[{len(self.paths)} files]"


class LogicalCsvScan(_TextLogicalScan):
    reader = staticmethod(_read_csv)
    fmt = "csv"


class LogicalJsonScan(_TextLogicalScan):
    reader = staticmethod(_read_json)
    fmt = "json"


def _read_hive_text(path: str, schema, opts) -> pa.Table:
    """Hive default text serde: ctrl-A field delimiter, \\N nulls, no
    header (GpuHiveTextFileFormat.scala role).

    Hive's LazySimpleSerDe matches the \\N null marker BEFORE
    unescaping (so \\N is null while \\\\N is the literal 2-char string
    \\N).  Arrow's csv reader unescapes first, which cannot reproduce
    that, so files containing any backslash go through a token-level
    parser with Hive's exact semantics; backslash-free files (the
    common case) take the vectorized arrow path."""
    opts = dict(opts or {})
    sep = opts.get("sep", "\x01")
    names = opts.get("column_names")
    if names is None and schema is not None:
        names = [f.name for f in schema]
    # read once as bytes (escaped \r payloads survive; the arrow fast
    # path consumes the same buffer, no second disk pass)
    with open(path, "rb") as f:
        raw = f.read()
    if b"\\" in raw:
        return _parse_hive_escaped(raw.decode("utf-8"), sep, names,
                                   schema)
    # no backslashes -> no \N markers and no escapes.  Only the empty
    # field is null (and only for non-string types, as in Hive);
    # arrow's default marker list ('NULL', 'NA', ...) must NOT apply —
    # those are legitimate string values.
    parse = pacsv.ParseOptions(delimiter=sep, quote_char=False,
                               escape_char=False)
    read = pacsv.ReadOptions(column_names=names,
                             autogenerate_column_names=names is None)
    try:
        convert = pacsv.ConvertOptions(
            column_types=schema if schema is not None else None,
            null_values=[""], strings_can_be_null=False)
        return pacsv.read_csv(pa.BufferReader(raw), read_options=read,
                              parse_options=parse,
                              convert_options=convert)
    except pa.ArrowInvalid:
        # unparseable primitive tokens: Hive yields null, never errors —
        # re-read untyped and convert per column with the null-on-error
        # contract (_cast_or_null)
        tbl = pacsv.read_csv(
            pa.BufferReader(raw), read_options=read,
            parse_options=parse,
            convert_options=pacsv.ConvertOptions(
                column_types={n: pa.string() for n in (names or [])}
                if names else None,
                null_values=[""], strings_can_be_null=False))
        if schema is None:
            return tbl
        cols = [_cast_or_null(
            tbl.column(n).combine_chunks().to_pylist(),
            schema.field(n).type) for n in tbl.schema.names]
        return pa.table(dict(zip(tbl.schema.names, cols)))


def _parse_hive_escaped(data: str, sep: str, names, schema) -> pa.Table:
    """Token-level hive parse: split rows/fields on UNESCAPED newline/
    delimiter, null-match raw tokens against \\N, then unescape."""
    import re
    rows: List[List] = []
    fields: List = []
    tok: List[str] = []
    esc = False

    def end_field():
        raw_tok = "".join(tok)
        if raw_tok == "\\N":
            fields.append(None)
        else:
            fields.append(re.sub(r"\\(.)", r"\1", raw_tok,
                                 flags=re.DOTALL))
        tok.clear()

    for ch in data:
        if esc:
            tok.append(ch)
            esc = False
        elif ch == "\\":
            tok.append("\\")
            esc = True
        elif ch == sep:
            end_field()
        elif ch == "\n":
            end_field()
            rows.append(list(fields))
            fields.clear()
        else:
            tok.append(ch)
    if tok or fields:
        end_field()
        rows.append(list(fields))
    ncols = max((len(r) for r in rows), default=0)
    if names is None:
        names = [f"f{i}" for i in range(ncols)]
    cols = []
    for i, name in enumerate(names):
        vals = [r[i] if i < len(r) else None for r in rows]
        ty = schema.field(name).type if schema is not None \
            else pa.string()
        cols.append(_cast_or_null(vals, ty))
    return pa.table(dict(zip(names, cols)))


def _cast_or_null(vals, ty: pa.DataType) -> pa.Array:
    """Hive primitive conversion: unparseable or empty fields become
    null, never errors (LazySimpleSerDe contract)."""
    if pa.types.is_string(ty) or pa.types.is_large_string(ty):
        return pa.array(vals, ty)
    vals = [None if v == "" else v for v in vals]
    try:
        return pa.array(vals, pa.string()).cast(ty)
    except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
        out = []
        for v in vals:
            if v is None:
                out.append(None)
                continue
            try:
                out.append(pa.array([v], pa.string()).cast(ty)[0].as_py())
            except (pa.ArrowInvalid, pa.ArrowNotImplementedError,
                    ValueError):
                out.append(None)
        return pa.array(out, ty)


class LogicalHiveTextScan(_TextLogicalScan):
    reader = staticmethod(_read_hive_text)
    fmt = "hivetext"


def write_hive_text(table: pa.Table, path: str, sep: str = "\x01") -> None:
    """Writer half of the hive text serde: the on-disk \\N null marker,
    backslash-escaped delimiter/newline/CR/backslash (LazySimpleSerDe
    escaping; a literal \\N VALUE round-trips as \\\\N exactly like
    Hive).  Binary columns are rejected (text serde; use parquet/orc/
    avro)."""
    for field in table.schema:
        if pa.types.is_binary(field.type) or \
                pa.types.is_large_binary(field.type):
            raise TypeError(f"hive text cannot carry binary column "
                            f"{field.name}; use parquet/orc/avro")

    def esc(v) -> str:
        s = v if isinstance(v, str) else str(v)
        return (s.replace("\\", "\\\\").replace(sep, "\\" + sep)
                .replace("\n", "\\\n").replace("\r", "\\\r"))

    # hive's marker: the 2 bytes backslash-N, matched BEFORE unescaping
    null_marker = "\\N"
    with open(path, "w", encoding="utf-8", newline="") as f:
        cols = [table.column(n).to_pylist() for n in table.schema.names]
        for row in zip(*cols):
            f.write(sep.join(null_marker if v is None else esc(v)
                             for v in row) + "\n")


class TextScanExec(PlanNode):
    def __init__(self, logical: _TextLogicalScan, schema: t.StructType):
        super().__init__()
        self.logical = logical
        self._schema = schema

    @property
    def output_schema(self) -> t.StructType:
        return self._schema

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        from ..plan.misc import set_current_input_file
        lg = self.logical
        want = struct_to_schema(self._schema)
        for rb, origin in _stream(lg.paths, lg.arrow_schema, lg.opts,
                                  ctx.conf, type(lg).reader):
            ctx.bump("scanned_rows", rb.num_rows)
            if rb.schema != want:
                rb = pa.Table.from_batches([rb]).cast(want) \
                    .combine_chunks().to_batches()[0]
            db = to_device(HostBatch(rb), ctx.conf)
            db.origin_file = origin
            set_current_input_file(origin)
            yield db


class CpuTextScanExec(HostNode):
    def __init__(self, logical: _TextLogicalScan, schema: t.StructType):
        super().__init__()
        self.logical = logical
        self._schema = schema

    @property
    def output_schema(self) -> t.StructType:
        return self._schema

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        from ..plan.misc import set_current_input_file
        lg = self.logical
        for rb, origin in _stream(lg.paths, lg.arrow_schema, lg.opts,
                                  ctx.conf, type(lg).reader):
            set_current_input_file(origin)
            yield rb
