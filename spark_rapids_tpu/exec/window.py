"""WindowExec: partition-sorted window evaluation in one device program.

Reference: GpuWindowExec (window/GpuWindowExec.scala:146) and its batched
variants evaluate window expressions per partition using cuDF rolling /
scan aggregations after the planner guarantees child ordering.

TPU shape: the exec
  1. concatenates the child stream (windows need whole partitions; the
     reference's RequireSingleBatch goal for generic windows —
     GpuWindowExec.scala batching policy),
  2. projects partition keys / order keys / function inputs as appended
     internal columns (one fused projection program),
  3. lexsorts by (partition, order) keys (ops/sort.py),
  4. runs ONE jit window program (ops/window.py) computing every window
     expression, and emits the child columns + window outputs in sorted
     order (Spark's WindowExec also emits child order = sort order).

Out-of-core inputs: batches are merged under the memory budget's retry
machinery upstream (exec/plan.py CoalesceBatchesExec); partition-chunked
OOC windows (GpuCachedDoublePassWindowExec analogue) can layer on the same
kernel later without changing it.
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import types as t
from ..columnar.device import DeviceBatch, DeviceColumn
from ..ops.batch_ops import concat_batches
from ..ops.sort import SortKey, sort_batch
from ..ops.window import window_trace
from ..plan import expressions as E
from ..plan.window import (WindowFrame, WindowFunctionSpec, default_frame)
from .evaluator import evaluate_projection
from .plan import ExecContext, PlanNode

_WINDOW_JIT_CACHE = {}


class WindowExec(PlanNode):
    """window_exprs: (WindowFunctionSpec, out_name) pairs.
    partition_keys: expressions; order_keys: (expr, asc, nulls_first)."""

    def __init__(self, window_exprs: Sequence[Tuple[WindowFunctionSpec, str]],
                 partition_keys: Sequence[E.Expression],
                 order_keys: Sequence[Tuple[E.Expression, bool, bool]],
                 child: PlanNode):
        from ..plan.window import check_window_analysis
        super().__init__(child)
        check_window_analysis(window_exprs, order_keys)
        schema = child.output_schema
        self.window_exprs = [(spec.bind(schema), name)
                             for spec, name in window_exprs]
        self.partition_keys = [e.bind(schema) for e in partition_keys]
        self.order_keys = [(e.bind(schema), asc, nf)
                           for e, asc, nf in order_keys]

    @property
    def output_schema(self) -> t.StructType:
        fields = list(self.child.output_schema.fields)
        for spec, name in self.window_exprs:
            fields.append(t.StructField(name, spec.dtype))
        return t.StructType(fields)

    def _resolved_frame(self, spec: WindowFunctionSpec) -> WindowFrame:
        if spec.frame is not None:
            return spec.frame
        if spec.kind in ("row_number", "rank", "dense_rank", "percent_rank",
                         "cume_dist", "ntile", "lead", "lag"):
            return WindowFrame("range", None, None)   # structural; unused
        return default_frame(bool(self.order_keys))

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        batches = [db for db in self.child.execute(ctx)
                   if int(db.num_rows) > 0]
        if not batches:
            return
        db = batches[0] if len(batches) == 1 \
            else concat_batches(batches, ctx.conf)

        child_names = list(db.names)
        n_child = len(child_names)

        # --- 2. append internal key/input columns via one projection ---
        aug_exprs: List[E.Expression] = [
            E.ColumnRef(n).bind(db.schema) for n in child_names]
        aug_names = list(child_names)
        p_idx, o_idx, v_idx = [], [], []
        for i, e in enumerate(self.partition_keys):
            aug_exprs.append(e)
            aug_names.append(f"__w_p{i}")
            p_idx.append(len(aug_exprs) - 1)
        for i, (e, _a, _nf) in enumerate(self.order_keys):
            aug_exprs.append(e)
            aug_names.append(f"__w_o{i}")
            o_idx.append(len(aug_exprs) - 1)
        inputs: List[E.Expression] = []
        spec_input_idx: List[int] = []
        for spec, _name in self.window_exprs:
            if spec.child is None:
                spec_input_idx.append(-1)
                continue
            aug_exprs.append(spec.child)
            aug_names.append(f"__w_v{len(inputs)}")
            inputs.append(spec.child)
            v_idx.append(len(aug_exprs) - 1)
            spec_input_idx.append(len(inputs) - 1)
        aug = evaluate_projection(aug_exprs, aug_names, db, ctx.conf)

        # --- 3. sort by (partition, order) ---
        sort_keys = [SortKey(i, True, True) for i in p_idx]
        sort_keys += [SortKey(i, asc, nf) for i, (_e, asc, nf)
                      in zip(o_idx, self.order_keys)]
        s = sort_batch(aug, sort_keys, ctx.conf) if sort_keys else aug

        # --- 4. the window program ---
        specs_frames = [(spec, self._resolved_frame(spec), vi)
                        for (spec, _n), vi in zip(self.window_exprs,
                                                  spec_input_idx)]
        part_cols = [s.columns[i] for i in p_idx]
        order_cols = [s.columns[i] for i in o_idx]
        val_cols = [s.columns[i] for i in v_idx]

        # sort directions only shape the traced program for value-offset
        # RANGE frames — keep them out of the cache key otherwise
        has_value_range = any(f.is_value_offset
                              for _s, f, _i in specs_frames)
        order_dirs = tuple((asc, nf) for _e, asc, nf in self.order_keys) \
            if has_value_range else ()
        from .aggregate import _seg_knobs
        scatter_free, max_ops, _ds = _seg_knobs(ctx.conf)
        key = ("window", s.capacity,
               tuple(sp.fingerprint() for sp, _f, _i in specs_frames),
               tuple(f.fp() for _s, f, _i in specs_frames),
               tuple(i for _s, _f, i in specs_frames),
               order_dirs, scatter_free, max_ops,
               tuple((c.dtype.simple_string, str(c.data.dtype))
                     for c in part_cols + order_cols + val_cols))
        fn = _WINDOW_JIT_CACHE.get(key)
        if fn is None:
            traced = window_trace(
                tuple((c.dtype,) for c in part_cols),
                tuple((c.dtype,) for c in order_cols),
                tuple((c.dtype,) for c in val_cols),
                specs_frames, s.capacity, order_dirs=order_dirs,
                scatter_free=scatter_free, max_sort_operands=max_ops)
            fn = jax.jit(traced)
            _WINDOW_JIT_CACHE[key] = fn

        outs = fn(tuple(c.data for c in part_cols),
                  tuple(c.validity for c in part_cols),
                  tuple(c.data for c in order_cols),
                  tuple(c.validity for c in order_cols),
                  tuple(c.data for c in val_cols),
                  tuple(c.validity for c in val_cols),
                  s.row_mask())

        cols = list(s.columns[:n_child])
        names = list(child_names)
        for (spec, name), vi, (data, valid) in zip(self.window_exprs,
                                                   spec_input_idx, outs):
            dictionary = None
            if isinstance(spec.dtype, t.StringType) and vi >= 0:
                # value pass-through functions keep the input dictionary
                dictionary = val_cols[vi].dictionary
            cols.append(DeviceColumn(data, valid, spec.dtype, dictionary))
            names.append(name)
        yield DeviceBatch(cols, s.num_rows, names)

    def describe(self):
        return (f"WindowExec[{[n for _, n in self.window_exprs]}, "
                f"part={len(self.partition_keys)}, "
                f"order={len(self.order_keys)}]")
