"""Out-of-core execution tier: shared budget/partition policy.

Reference: GpuSubPartitionHashJoin.scala:32 (re-hash-partition both
sides into sub-joins) and the reference's spill framework sizing;
Sparkle's memory tiering (PAPERS.md) is the degradation model, Theseus
(PAPERS.md) the argument for sizing the resident window from a *byte*
budget rather than row counts.

This module centralizes what the three out-of-core operators (hash
join `exec/join.py`, spill-partitioned aggregation `exec/ooc_agg.py`,
out-of-core sort `exec/ooc_sort.py`) share:

  * the **resident window** — `sql.ooc.residentFraction` x the HBM
    budget: the bytes one operator may hold on device at a time.  The
    spill-partition count is derived from measured bytes vs this
    window (`partition_count`), never from `2 x batch_size_rows` rows
    (wide payload rows used to blow past the row gate before it
    tripped);
  * the **`ooc` chaos site** — `fire()` emits an `ooc_state` instant
    (so a fatal crash dump's flight-recorder tail embeds the bucket
    state the pass was in) and then fires the injector;
  * the **`tpu_ooc_*` metric families** (obs/registry.py) every
    election/partition pass publishes, which the acceptance tests and
    `bench.py --ooc` assert the tier — not the query-level replay rung
    — carried an oversized query.

The degradation ladder placement (docs/ROBUSTNESS.md): operators elect
OOC *proactively* when measured bytes exceed the window (or the cost
oracle predicted they will — `elect_proactive`), and the query-level
retry escalates into the OOC rung (`ctx.ooc_force`) before the final
whole-query replay rung when an OOM still escapes the operator ladders.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from ..config import (OOC_ENABLED, OOC_FORCE, OOC_MAX_DEPTH,
                      OOC_MAX_PARTITIONS, OOC_RESIDENT_FRACTION)


@dataclasses.dataclass(frozen=True)
class OocPolicy:
    """Resolved out-of-core policy for one ExecContext."""
    enabled: bool
    force: bool                  # sql.ooc.force OR an escalated context
    window: Optional[int]        # resident window bytes; None = unlimited
    max_partitions: int
    max_depth: int

    def bytes_trip(self, nbytes: int) -> bool:
        """Whether `nbytes` of working set exceeds the resident window."""
        return self.enabled and self.window is not None and \
            nbytes > self.window


def ooc_policy(ctx) -> OocPolicy:
    """The out-of-core policy for this query context.  `window` derives
    from the SAME budget instance the operators register spillables
    with, so electing OOC and fitting under the budget agree."""
    conf = ctx.conf
    enabled = bool(conf.get(OOC_ENABLED))
    force = enabled and (bool(conf.get(OOC_FORCE)) or
                         bool(getattr(ctx, "ooc_force", False)))
    window = None
    if enabled:
        limit = ctx.budget.limit
        if limit:
            window = max(int(limit * float(conf.get(OOC_RESIDENT_FRACTION))),
                         1 << 14)
    return OocPolicy(enabled, force, window,
                     int(conf.get(OOC_MAX_PARTITIONS)),
                     int(conf.get(OOC_MAX_DEPTH)))


def batch_bytes(db) -> int:
    """Approximate LIVE bytes of a device batch (row-scaled: padding
    does not count toward the working set the window must hold)."""
    cap = max(int(db.capacity), 1)
    rows = db.num_rows
    rows = int(rows) if isinstance(rows, int) else cap
    return max((db.nbytes() * min(rows, cap)) // cap, 0)


def partition_count(total_bytes: int, policy: OocPolicy,
                    rows_k: int = 1) -> int:
    """Spill-partition fan-out for `total_bytes` of working set: enough
    pow2 buckets that each holds ~one resident window, floored by the
    legacy row-derived count `rows_k` and clamped to
    sql.ooc.maxPartitions (skew re-partitions recursively instead of
    widening past the clamp)."""
    k_bytes = 1
    if policy.window:
        need = -(-max(total_bytes, 1) // policy.window)    # ceil div
        k_bytes = 1 << max(need - 1, 0).bit_length()
    k = max(rows_k, k_bytes, 2)
    return min(k, max(policy.max_partitions, 2))


def fire(ctx, op: str, **state) -> None:
    """One out-of-core pass boundary: publish the bucket state to the
    flight recorder FIRST (`ooc_state` instant — a fatal dump's tail
    then shows exactly which pass died), then fire the `ooc` chaos
    site with the same state in the injected-fault record.  Each pass
    boundary is also a cooperative cancellation checkpoint: a
    deadline-armed query cancels between buckets, with every spilled
    bucket's reservation released by the unwinding scopes."""
    ctx.tracer.instant("ooc_state", "runtime", op=op, **state)
    from ..runtime.faults import get_injector
    get_injector(ctx.conf).fire("ooc", op=op, **state)
    ctx.checkpoint(f"ooc_{op}")


def record_election(ctx, op: str, mode: str) -> None:
    from ..obs.registry import OOC_ELECTIONS
    OOC_ELECTIONS.inc(op=op, mode=mode)
    ctx.bump(f"ooc.{op}_elections")


def record_partitions(ctx, op: str, k: int, nbytes: int) -> None:
    from ..obs.registry import OOC_BYTES, OOC_PARTITIONS
    OOC_PARTITIONS.inc(k, op=op)
    if nbytes > 0:
        OOC_BYTES.inc(nbytes, op=op)
    ctx.bump(f"ooc.{op}_partitions", k)
    ctx.bump(f"ooc.{op}_bytes", nbytes)


def record_recursion(ctx, op: str) -> None:
    from ..obs.registry import OOC_RECURSIONS
    OOC_RECURSIONS.inc(op=op)
    ctx.bump(f"ooc.{op}_recursions")


def escalate(ctx) -> bool:
    """Arm the OOC rung on an escaped OOM (the ladder step between
    operator retries and the whole-query replay): forces every eligible
    operator out-of-core on the replay.  Returns False when the tier is
    disabled or already forced (the caller then falls through to the
    query-replay rung)."""
    if not ctx.conf.get(OOC_ENABLED) or getattr(ctx, "ooc_force", False):
        return False
    ctx.ooc_force = True
    ctx.bump("query_ooc_escalations")
    record_election(ctx, "query", "reactive")
    ctx.tracer.instant("ooc_escalation", "runtime")
    return True


def elect_proactive(pq, ctx) -> bool:
    """Plan-time OOC election from the cost oracle (obs/estimator.py):
    when the structure's MEASURED working-set history exceeds the HBM
    budget, run spilled from the start instead of discovering the OOM
    mid-query.  One cached conf check when the history plane is off."""
    if not ctx.conf.get(OOC_ENABLED) or getattr(ctx, "ooc_force", False):
        return False
    try:
        from ..obs.estimator import estimate_query
        est = estimate_query(pq)
    except Exception:                                    # noqa: BLE001
        return False                 # the oracle must never fail a query
    if not est or est.get("ws_basis") != "measured":
        return False
    ws = int(est.get("working_set_bytes") or 0)
    limit = ctx.budget.limit
    if not limit or ws <= limit:
        return False
    ctx.ooc_force = True
    record_election(ctx, "query", "proactive")
    ctx.tracer.instant("ooc_proactive", "runtime", working_set_bytes=ws,
                       budget_bytes=limit)
    return True
