"""Device percentile aggregation exec.

Role of the reference's GpuPercentile (Histogram JNI) and
GpuApproximatePercentile (t-digest) execution paths (SURVEY §2.5): an
aggregation whose functions are ALL percentile-family runs fully on
device via the sort-based kernel (ops/percentile.py).  Mixed
percentile+other aggregations stay on the CPU fallback (tagged by
AggregateMeta) — the reference similarly routes percentile through a
dedicated aggregation path.

Percentile is holistic (needs every group row at once), so the exec
concatenates the child stream and runs one traced sort+segment+gather
program per distinct input expression; group segmentation is identical
across runs because lexsort is stable and the group-key lanes agree.
"""
from __future__ import annotations

from typing import Iterator, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import types as t
from ..columnar.device import DeviceBatch, DeviceColumn
from ..ops import percentile as P
from ..ops.batch_ops import (concat_batches, ensure_unique_dict,
                             shrink_to_rows)
from ..plan import expressions as E
from ..plan.aggregates import Percentile, _resolved
from .evaluator import evaluate_projection
from .plan import ExecContext, PlanNode

_TRACE_CACHE: dict = {}


class PercentileAggregateExec(PlanNode):
    def __init__(self, key_exprs: Sequence[E.Expression],
                 key_names: Sequence[str],
                 aggs: Sequence[Tuple[Percentile, str]],
                 child: PlanNode):
        super().__init__(child)
        schema = child.output_schema
        self.key_exprs = [e.bind(schema) for e in key_exprs]
        self.key_names = list(key_names)
        self.aggs = [(fn.bind(schema), name) for fn, name in aggs]
        assert all(isinstance(fn, Percentile) for fn, _ in self.aggs)

    @property
    def output_schema(self) -> t.StructType:
        fields = [t.StructField(n, e.dtype)
                  for n, e in zip(self.key_names, self.key_exprs)]
        for fn, n in self.aggs:
            fields.append(t.StructField(n, t.DOUBLE))
        return t.StructType(fields)

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        conf = ctx.conf
        batches = [db for db in self.child.execute(ctx)
                   if int(db.num_rows) > 0]
        if not batches:
            if not self.key_exprs:
                yield self._null_row(conf)
            return
        from ..plan.aggregates import ApproximatePercentile
        if len(batches) > 1 and all(isinstance(fn, ApproximatePercentile)
                                    for fn, _ in self.aggs):
            # PARTIAL/FINAL split: per-partition device sketches merged
            # on host — the distributed shape (each batch = one
            # partition's rows; multi-host shards arrive the same way).
            # Ref: GpuApproximatePercentile.scala t-digest partial/merge.
            yield self._sketched(batches, ctx)
            return
        merged = concat_batches(batches, conf)

        # one value column per DISTINCT input expression; each carries
        # the q list of the aggs that share it
        val_exprs: List[E.Expression] = []
        val_map: List[Tuple[int, float]] = []   # agg i -> (col j, q)
        fps = {}
        for fn, _name in self.aggs:
            fp = repr(fn.child)
            if fp not in fps:
                fps[fp] = len(val_exprs)
                val_exprs.append(_resolved(E.Cast(fn.child, t.DOUBLE)))
            val_map.append((fps[fp], fn.percentage))

        nk = len(self.key_exprs)
        proj = evaluate_projection(
            self.key_exprs + val_exprs,
            [f"_k{i}" for i in range(nk)] +
            [f"_v{j}" for j in range(len(val_exprs))], merged, conf)
        key_cols = [ensure_unique_dict(c) for c in proj.columns[:nk]]
        val_cols = proj.columns[nk:]
        live = merged.row_mask()
        capacity = merged.capacity

        info = tuple((c.dtype, True, str(c.data.dtype)) for c in key_cols)
        from .aggregate import _seg_knobs, holistic_pack_spec
        pack = holistic_pack_spec(key_cols, self.key_exprs, self.child)
        scatter_free, max_ops, _ds = _seg_knobs(conf)
        results: List[Tuple] = [None] * len(self.aggs)
        out_keys = n_groups = None
        for j, vcol in enumerate(val_cols):
            qs = sorted({q for (jj, q) in val_map if jj == j})
            sig = (info, tuple(qs), capacity,
                   str(vcol.data.dtype), pack, scatter_free, max_ops)
            fn = _TRACE_CACHE.get(sig)
            if fn is None:
                fn = jax.jit(P.percentile_trace(
                    list(info), qs, capacity, capacity, pack_spec=pack,
                    scatter_free=scatter_free,
                    max_sort_operands=max_ops))
                _TRACE_CACHE[sig] = fn
            from ..ops.kernels import compute_view
            vdata = compute_view(vcol.data, vcol.dtype)
            ok, per_q, ng = fn(
                tuple(c.data for c in key_cols),
                tuple(c.validity for c in key_cols),
                vdata.astype(jnp.float64), vcol.validity, live)
            if out_keys is None:
                out_keys, n_groups = ok, int(ng)
            q_pos = {q: i for i, q in enumerate(qs)}
            for i, (jj, q) in enumerate(val_map):
                if jj == j:
                    results[i] = per_q[q_pos[q]]

        cols = []
        for (kd, kv), kc in zip(out_keys, key_cols):
            cols.append(DeviceColumn(kd, kv, kc.dtype, kc.dictionary,
                                     kc.data_hi))
        for data, valid in results:
            cols.append(DeviceColumn(data, valid, t.DOUBLE))
        n_out = max(n_groups, 1) if not self.key_exprs else n_groups
        db = DeviceBatch(cols, n_out,
                         self.key_names + [n for _f, n in self.aggs])
        yield shrink_to_rows(db, n_out, conf)

    def _sketched(self, batches, ctx: ExecContext) -> DeviceBatch:
        """Device sketch build per input batch (the PARTIAL), host merge
        per group across batches, interpolated FINAL."""
        import numpy as np
        import pyarrow as pa
        from ..columnar.device import to_device
        from ..columnar.host import HostBatch, dtype_to_arrow
        from ..ops.kernels import compute_view
        from ..config import APPROX_PERCENTILE_SKETCH_K
        from ..ops.quantile_sketch import merge_sketches, query_sketch
        conf = ctx.conf
        DEFAULT_K = conf.get(APPROX_PERCENTILE_SKETCH_K)
        nk = len(self.key_exprs)
        val_exprs: List[E.Expression] = []
        val_map: List[Tuple[int, float]] = []
        fps = {}
        for fn, _name in self.aggs:
            fp = repr(fn.child)
            if fp not in fps:
                fps[fp] = len(val_exprs)
                val_exprs.append(_resolved(E.Cast(fn.child, t.DOUBLE)))
            val_map.append((fps[fp], fn.percentage))

        # group key tuple -> per value-expr list of (count, points)
        merged_sketches: dict = {}
        key_dtypes = [e.dtype for e in self.key_exprs]
        for db in batches:
            proj = evaluate_projection(
                self.key_exprs + val_exprs,
                [f"_k{i}" for i in range(nk)] +
                [f"_v{j}" for j in range(len(val_exprs))], db, conf)
            key_cols = [ensure_unique_dict(c) for c in proj.columns[:nk]]
            val_cols = proj.columns[nk:]
            live = db.row_mask()
            capacity = db.capacity
            info = tuple((c.dtype, True, str(c.data.dtype))
                         for c in key_cols)
            from .aggregate import _seg_knobs, holistic_pack_spec
            pack = holistic_pack_spec(key_cols, self.key_exprs,
                                      self.child)
            scatter_free, max_ops, _ds = _seg_knobs(conf)
            for j, vcol in enumerate(val_cols):
                sig = ("sketch", info, DEFAULT_K, capacity,
                       str(vcol.data.dtype), pack, scatter_free,
                       max_ops)
                fn = _TRACE_CACHE.get(sig)
                if fn is None:
                    fn = jax.jit(P.sketch_trace(
                        list(info), DEFAULT_K, capacity, capacity,
                        pack_spec=pack, scatter_free=scatter_free,
                        max_sort_operands=max_ops))
                    _TRACE_CACHE[sig] = fn
                vdata = compute_view(vcol.data, vcol.dtype)
                ok, cnt, pts, ng = fn(
                    tuple(c.data for c in key_cols),
                    tuple(c.validity for c in key_cols),
                    vdata.astype(jnp.float64), vcol.validity, live)
                ng = int(ng)
                fetched = jax.device_get(
                    ([(kd[:ng], kv[:ng]) for kd, kv in ok],
                     cnt[:ng], pts[:ng]))
                oks, cnt_h, pts_h = fetched
                for g in range(ng):
                    kt = []
                    for (kd, kv), kc in zip(oks, key_cols):
                        if not kv[g]:
                            kt.append(None)
                        elif kc.dictionary is not None:
                            kt.append(str(kc.dictionary[int(kd[g])]))
                        elif isinstance(kc.dtype, t.DoubleType) and \
                                np.asarray(kd).dtype == np.int64:
                            # host-loaded doubles ride as f64 BIT
                            # PATTERNS in the int64 storage lane
                            kt.append(float(np.int64(kd[g]).view(
                                np.float64)))
                        else:
                            kt.append(kd[g].item())
                    slot = merged_sketches.setdefault(
                        tuple(kt), [[] for _ in val_exprs])
                    slot[j].append((int(cnt_h[g]), pts_h[g]))

        if not merged_sketches and not self.key_exprs:
            merged_sketches[()] = [[] for _ in val_exprs]
        keys_out = sorted(merged_sketches.keys(),
                          key=lambda kt: tuple(
                              (v is None, v) for v in kt))
        arrays = []
        for i in range(nk):
            vals = [kt[i] for kt in keys_out]
            arrays.append(pa.array(vals, dtype_to_arrow(key_dtypes[i])))
        # merge once per (group, value column); percentiles share it
        final = {kt: [merge_sketches(slots[jj], k=DEFAULT_K)
                      for jj in range(len(val_exprs))]
                 for kt, slots in merged_sketches.items()}
        for i, (jj, q) in enumerate(val_map):
            arrays.append(pa.array(
                [query_sketch(*final[kt][jj], q) for kt in keys_out],
                pa.float64()))
        names = self.key_names + [n for _f, n in self.aggs]
        rb = pa.RecordBatch.from_arrays(
            arrays, schema=pa.schema(
                [pa.field(n, a.type) for n, a in zip(names, arrays)]))
        return to_device(HostBatch(rb), conf)

    def _null_row(self, conf) -> DeviceBatch:
        from ..columnar.device import bucket_capacity
        cap = bucket_capacity(1, conf)
        cols = [DeviceColumn(jnp.zeros((cap,), jnp.float64),
                             jnp.zeros((cap,), bool), t.DOUBLE)
                for _ in self.aggs]
        return DeviceBatch(cols, 1, [n for _f, n in self.aggs])

    def describe(self):
        return (f"PercentileAggregateExec[keys={self.key_names}, "
                f"{[n for _f, n in self.aggs]}]")
