"""CPU physical operators (per-operator fallback path) + transitions.

The reference keeps unreplaced Spark operators running on the CPU and
bridges with GpuRowToColumnarExec / GpuColumnarToRowExec
(GpuTransitionOverrides.scala:50).  Here the CPU engine is pyarrow: host
operators stream pyarrow RecordBatches and evaluate expressions through
their `eval_cpu` oracle path — the same code that serves as the test
oracle, which is exactly the reference's "same query, two backends"
correctness strategy (SURVEY §4).

Transitions:
  * HostToDeviceExec — device PlanNode over a HostNode child (the
    HostColumnarToGpu role), slicing oversized host batches to the
    configured row target before upload.
  * DeviceToHostExec — HostNode over a device PlanNode child (the
    GpuColumnarToRowExec / BringBackToHost role).
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import pyarrow as pa
import pyarrow.compute as pc

from .. import types as t
from ..columnar.device import DeviceBatch, to_device, to_host
from ..columnar.host import HostBatch, dtype_to_arrow, struct_to_schema
from ..plan import expressions as E
from ..plan.aggregates import AggregateFunction
from .plan import ExecContext, PlanNode


def sort_indices_per_key(keys) -> pa.Array:
    """pc.sort_indices with PER-KEY null ordering.

    pyarrow's SortOptions carries one GLOBAL null_placement (its sort
    keys are strictly (name, order) pairs), but Spark's SortOrder sets
    nulls-first/last per key.  Each key whose column can hold nulls gets
    an explicit is-null rank column ahead of its value column, so the
    per-key placement is exact and the value columns' global placement
    becomes irrelevant.

    keys: [(array_or_chunked, ascending, nulls_first)].
    """
    work, sk = {}, []
    for i, (arr, asc, nf) in enumerate(keys):
        a = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
        if a.null_count:
            work[f"_n{i}"] = pc.cast(pc.is_null(a), pa.int8())
            sk.append((f"_n{i}", "descending" if nf else "ascending"))
        work[f"_k{i}"] = a
        sk.append((f"_k{i}", "ascending" if asc else "descending"))
    return pc.sort_indices(pa.table(work), sort_keys=sk)


class HostNode:
    """Base CPU operator: streams pyarrow RecordBatches."""

    def __init__(self, *children: "HostNode"):
        self.children = list(children)

    @property
    def child(self) -> "HostNode":
        return self.children[0]

    @property
    def output_schema(self) -> t.StructType:
        raise NotImplementedError

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        raise NotImplementedError

    def name(self) -> str:
        return type(self).__name__

    def describe(self) -> str:
        return self.name()

    def tree_string(self, indent: int = 0) -> str:
        lines = ["  " * indent + self.describe()]
        for c in self.children:
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)

    def collect(self, ctx: Optional[ExecContext] = None) -> pa.Table:
        ctx = ctx or ExecContext()
        rbs = [rb for rb in self.execute(ctx) if rb.num_rows > 0]
        schema = struct_to_schema(self.output_schema)
        if not rbs:
            return pa.Table.from_batches([], schema)
        return pa.Table.from_batches(rbs, rbs[0].schema)

    def _table(self, ctx) -> pa.Table:
        """Materialize the child stream as one table."""
        rbs = [rb for rb in self.child.execute(ctx) if rb.num_rows > 0]
        schema = struct_to_schema(self.child.output_schema)
        if not rbs:
            return pa.Table.from_batches([], schema)
        return pa.Table.from_batches(rbs, rbs[0].schema)


# ---------------------------------------------------------------------------
# Transitions
# ---------------------------------------------------------------------------

class HostToDeviceExec(PlanNode):
    """Upload a host stream to device (HostColumnarToGpu role)."""

    def __init__(self, host_child: HostNode):
        super().__init__()
        self.host_child = host_child

    @property
    def output_schema(self) -> t.StructType:
        return self.host_child.output_schema

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        from ..runtime.retry import retry_io
        target = ctx.conf.batch_size_rows
        for rb in self.host_child.execute(ctx):
            for off in range(0, max(rb.num_rows, 1), target):
                sl = rb.slice(off, min(target, rb.num_rows - off))
                if rb.num_rows and sl.num_rows == 0:
                    continue
                ctx.bump("h2d_rows", sl.num_rows)
                ctx.tracer.add_bytes("h2d_bytes", sl.nbytes)
                with ctx.tracer.span("upload", "transition",
                                     node=getattr(self, "_node_id", None)):
                    db = retry_io(ctx.conf, "h2d",
                                  lambda: to_device(HostBatch(sl),
                                                    ctx.conf))
                yield db

    def tree_string(self, indent: int = 0) -> str:
        lines = ["  " * indent + "HostToDeviceExec"]
        lines.append(self.host_child.tree_string(indent + 1))
        return "\n".join(lines)


class DeviceToHostExec(HostNode):
    """Fetch a device stream to host (GpuColumnarToRowExec role)."""

    def __init__(self, device_child: PlanNode):
        super().__init__()
        self.device_child = device_child

    @property
    def output_schema(self) -> t.StructType:
        return self.device_child.output_schema

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        from ..runtime.retry import retry_io
        for db in self.device_child.execute(ctx):
            if int(db.num_rows) == 0:
                continue
            ctx.bump("d2h_rows", int(db.num_rows))
            with ctx.tracer.span("fetch", "transition",
                                 node=getattr(self, "_node_id", None)):
                rb = retry_io(ctx.conf, "d2h",
                              lambda: to_host(db)).rb
            ctx.tracer.add_bytes("d2h_bytes", rb.nbytes)
            yield rb

    def tree_string(self, indent: int = 0) -> str:
        lines = ["  " * indent + "DeviceToHostExec"]
        lines.append(self.device_child.tree_string(indent + 1))
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# CPU operators
# ---------------------------------------------------------------------------

class HostSourceExec(HostNode):
    """Leaf over an in-memory Arrow table."""

    def __init__(self, table: pa.Table, batch_rows: Optional[int] = None):
        super().__init__()
        self.table = table
        self.batch_rows = batch_rows

    @property
    def output_schema(self) -> t.StructType:
        from ..columnar.host import schema_to_struct
        return schema_to_struct(self.table.schema)

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        tbl = self.table.combine_chunks()
        yield from tbl.to_batches(max_chunksize=self.batch_rows)

    def describe(self):
        return f"HostSourceExec[{self.table.num_rows} rows]"


def _eval_named(exprs: Sequence[E.Expression], names: Sequence[str],
                rb: pa.RecordBatch) -> pa.RecordBatch:
    arrays, fields = [], []
    for e, n in zip(exprs, names):
        a = e.eval_cpu(rb)
        if isinstance(a, pa.ChunkedArray):
            a = a.combine_chunks()
        if isinstance(a, pa.Scalar):
            a = pa.array([a.as_py()] * rb.num_rows, type=a.type)
        arrays.append(a)
        fields.append(pa.field(n, a.type))
    return pa.RecordBatch.from_arrays(arrays, schema=pa.schema(fields))


class CpuProjectExec(HostNode):
    def __init__(self, exprs: Sequence[E.Expression], names: Sequence[str],
                 child: HostNode):
        super().__init__(child)
        self.exprs = [e.bind(child.output_schema) for e in exprs]
        self.names = list(names)

    @property
    def output_schema(self) -> t.StructType:
        return t.StructType([t.StructField(n, e.dtype, e.nullable)
                             for n, e in zip(self.names, self.exprs)])

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        for rb in self.child.execute(ctx):
            yield _eval_named(self.exprs, self.names, rb)

    def describe(self):
        return f"CpuProjectExec[{', '.join(self.names)}]"


class CpuFilterExec(HostNode):
    def __init__(self, condition: E.Expression, child: HostNode):
        super().__init__(child)
        self.condition = condition.bind(child.output_schema)

    @property
    def output_schema(self) -> t.StructType:
        return self.child.output_schema

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        for rb in self.child.execute(ctx):
            mask = self.condition.eval_cpu(rb)
            mask = pc.fill_null(mask, False)
            tbl = pa.Table.from_batches([rb]).filter(mask)
            for out in tbl.combine_chunks().to_batches():
                yield out

    def describe(self):
        return f"CpuFilterExec[{self.condition!r}]"


class CpuSampleExec(HostNode):
    """Bernoulli sample on the host stream.  Shares the device path's
    counter-based hash (exec.plan.sample_hash_u32) so CPU and device
    keep exactly the same rows for a given seed."""

    def __init__(self, fraction: float, seed: int, child: HostNode):
        super().__init__(child)
        self.fraction = float(fraction)
        self.seed = int(seed)

    @property
    def output_schema(self) -> t.StructType:
        return self.child.output_schema

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        import numpy as np
        from .plan import sample_hash_u32, sample_threshold
        threshold = np.uint32(sample_threshold(self.fraction))
        offset = 0
        for rb in self.child.execute(ctx):
            n = rb.num_rows
            if n == 0:
                continue
            if self.fraction >= 1.0:
                yield rb
                offset += n
                continue
            idx = (offset + np.arange(n, dtype=np.int64)).astype(np.uint32)
            offset += n
            keep = sample_hash_u32(idx, self.seed) < threshold
            tbl = pa.Table.from_batches([rb]).filter(pa.array(keep))
            for out in tbl.combine_chunks().to_batches():
                yield out

    def describe(self):
        return f"CpuSampleExec[{self.fraction}, seed={self.seed}]"


def _clear_scan_provenance():
    """Materializing operators (sort/agg/join/window) drain their whole
    input before emitting, so per-batch scan provenance no longer
    corresponds to output rows — input_file_name above them is ""
    (Spark's behavior past a materialization point within the task)."""
    from ..plan.misc import set_current_input_file
    set_current_input_file("")


class CpuAggregateExec(HostNode):
    """Hash aggregate on pyarrow TableGroupBy / compute reductions."""

    def __init__(self, keys: Sequence[E.Expression], key_names: Sequence[str],
                 aggs: Sequence[Tuple[AggregateFunction, str]],
                 child: HostNode):
        super().__init__(child)
        schema = child.output_schema
        self.keys = [k.bind(schema) for k in keys]
        self.key_names = list(key_names)
        self.aggs = [(fn.bind(schema), n) for fn, n in aggs]

    @property
    def output_schema(self) -> t.StructType:
        fields = [t.StructField(n, k.dtype)
                  for n, k in zip(self.key_names, self.keys)]
        for fn, n in self.aggs:
            fields.append(t.StructField(n, fn.dtype))
        return t.StructType(fields)

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        tbl = self._table(ctx)
        rb = HostBatch.from_table(tbl).rb
        # project keys + agg children into a working table
        arrays, names = [], []
        for i, k in enumerate(self.keys):
            _clear_scan_provenance()
            arrays.append(self._arr(k.eval_cpu(rb), rb.num_rows))
            names.append(f"_k{i}")
        agg_specs = []
        for j, (fn, _) in enumerate(self.aggs):
            child = fn.child
            col = f"_a{j}"
            if child is None:
                # count(*): count over an all-valid dummy column
                arrays.append(pa.array([True] * rb.num_rows))
            elif getattr(fn, "child2", None) is not None:
                # binary statistical aggregates ride a struct column whose
                # pylist dicts the _py callable unpacks (corr/covar)
                x = self._arr(child.eval_cpu(rb), rb.num_rows)
                y = self._arr(fn.child2.eval_cpu(rb), rb.num_rows)
                arrays.append(pa.StructArray.from_arrays([x, y],
                                                         ["x", "y"]))
            else:
                arrays.append(self._arr(child.eval_cpu(rb), rb.num_rows))
            names.append(col)
            agg_specs.append((col, fn))
        work = pa.table(dict(zip(names, arrays)))

        if not self.keys:
            out_arrays, out_fields = [], []
            for (col, fn), (_, oname) in zip(agg_specs, self.aggs):
                fname, opts = fn.cpu_agg()
                want = dtype_to_arrow(fn.dtype)
                if fname == "_py":
                    v = opts(work[col].to_pylist())
                    arr = pa.array([v], type=want) if v is not None \
                        else pa.nulls(1, want)
                else:
                    val = self._global_agg(work[col], fname, opts)
                    arr = pa.array([val.as_py()], type=want) \
                        if val is not None else pa.nulls(1, want)
                out_arrays.append(arr)
                out_fields.append(pa.field(oname, want))
            yield pa.RecordBatch.from_arrays(out_arrays,
                                             schema=pa.schema(out_fields))
            return

        # "_py" aggregates that decompose into arrow parts (decimal avg ->
        # sum+count) keep the whole grouped path on C++ kernels; only
        # undecomposable ones force the python loop
        splits = {}
        for col, fn in agg_specs:
            if fn.cpu_agg()[0] == "_py":
                sp = fn.cpu_agg_split()
                if sp is None:
                    yield self._python_grouped(work, agg_specs)
                    return
                splits[col] = sp

        gb_aggs = []
        for col, fn in agg_specs:
            if col in splits:
                for fname, opts in splits[col][0]:
                    gb_aggs.append((col, fname, opts))
            else:
                fname, opts = fn.cpu_agg()
                gb_aggs.append((col, fname, opts))
        res = work.group_by([f"_k{i}" for i in range(len(self.keys))],
                            use_threads=False).aggregate(gb_aggs)
        # order output columns: keys then aggs, cast to declared types
        out_arrays, out_fields = [], []
        for i, (kname, k) in enumerate(zip(self.key_names, self.keys)):
            a = res[f"_k{i}"].combine_chunks()
            out_arrays.append(a)
            out_fields.append(pa.field(kname, a.type))
        for j, ((col, fn), (_, oname)) in enumerate(zip(agg_specs, self.aggs)):
            want = dtype_to_arrow(fn.dtype)
            if col in splits:
                parts, finish = splits[col]
                lanes = [res[f"{col}_{fname}"].to_pylist()
                         for fname, _o in parts]
                vals = [finish(*row) for row in zip(*lanes)]
                a = pa.array(vals, want)
            else:
                fname, _ = fn.cpu_agg()
                a = res[f"{col}_{fname}"].combine_chunks().cast(want)
            out_arrays.append(a)
            out_fields.append(pa.field(oname, a.type))
        tbl = pa.Table.from_arrays(out_arrays, schema=pa.schema(out_fields))
        yield HostBatch.from_table(tbl).rb

    def _python_grouped(self, work: pa.Table, agg_specs) -> pa.RecordBatch:
        """Pure-python grouped aggregation: the exact-semantics path for
        aggregates pyarrow's TableGroupBy can't express (e.g. decimal avg
        at Spark's result scale)."""
        nk = len(self.keys)
        key_cols = [work[f"_k{i}"].to_pylist() for i in range(nk)]
        val_cols = [work[col].to_pylist() for col, _fn in agg_specs]
        groups: dict = {}
        order = []
        for row in range(work.num_rows):
            key = tuple(kc[row] for kc in key_cols)
            g = groups.get(key)
            if g is None:
                g = groups[key] = [[] for _ in agg_specs]
                order.append(key)
            for j in range(len(agg_specs)):
                g[j].append(val_cols[j][row])

        def wrap64(v):
            # Spark/device integral sums wrap to int64 two's complement
            # (non-ANSI); unbounded python ints must match
            return (int(v) + 2 ** 63) % 2 ** 64 - 2 ** 63

        def apply(fn, fname, opts, values):
            nn = [v for v in values if v is not None]
            if fname == "_py":
                return opts(values)
            if fname == "count":
                mode = getattr(opts, "mode", "only_valid")
                return len(values) if mode == "all" else len(nn)
            if not nn:
                return None
            out = {"sum": sum, "min": min, "max": max,
                   "mean": lambda v: sum(v) / len(v),
                   "first": lambda v: v[0], "last": lambda v: v[-1],
                   }[fname](nn)
            if fname == "sum" and t.is_integral(fn.dtype):
                out = wrap64(out)
            return out

        out_arrays, out_fields = [], []
        for i, (kname, k) in enumerate(zip(self.key_names, self.keys)):
            out_arrays.append(pa.array([key[i] for key in order],
                                       dtype_to_arrow(k.dtype)))
            out_fields.append(pa.field(kname, dtype_to_arrow(k.dtype)))
        for j, ((_col, fn), (_, oname)) in enumerate(zip(agg_specs, self.aggs)):
            fname, opts = fn.cpu_agg()
            vals = [apply(fn, fname, opts, groups[key][j]) for key in order]
            out_arrays.append(pa.array(vals, dtype_to_arrow(fn.dtype)))
            out_fields.append(pa.field(oname, dtype_to_arrow(fn.dtype)))
        return pa.RecordBatch.from_arrays(out_arrays,
                                          schema=pa.schema(out_fields))

    @staticmethod
    def _arr(a, n):
        if isinstance(a, pa.ChunkedArray):
            a = a.combine_chunks()
        if isinstance(a, pa.Scalar):
            a = pa.array([a.as_py()] * n, type=a.type)
        return a

    @staticmethod
    def _global_agg(col: pa.ChunkedArray, fname: str, opts):
        fn = {"sum": pc.sum, "min": pc.min, "max": pc.max, "mean": pc.mean,
              "count": pc.count, "first": lambda c, options=None:
                  c[0] if len(c) else None,
              "last": lambda c, options=None: c[-1] if len(c) else None,
              }[fname]
        if fname in ("first", "last"):
            vals = col.drop_null() if opts is not None and \
                getattr(opts, "skip_nulls", False) else col
            return fn(vals)
        return fn(col, options=opts) if opts is not None else fn(col)

    def describe(self):
        return (f"CpuAggregateExec[keys={self.key_names}, "
                f"aggs={[n for _, n in self.aggs]}]")


class CpuSortExec(HostNode):
    def __init__(self, orders, child: HostNode):
        """orders: (bound-or-unbound expr, ascending, nulls_first) tuples."""
        super().__init__(child)
        self.orders = [(e.bind(child.output_schema), asc, nf)
                       for e, asc, nf in orders]

    @property
    def output_schema(self) -> t.StructType:
        return self.child.output_schema

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        tbl = self._table(ctx)
        rb = HostBatch.from_table(tbl).rb
        keys = []
        for e, asc, nf in self.orders:
            _clear_scan_provenance()
            keys.append((CpuAggregateExec._arr(e.eval_cpu(rb), rb.num_rows),
                         asc, nf))
        idx = sort_indices_per_key(keys)
        out = pa.Table.from_batches([rb]).take(idx)
        yield HostBatch.from_table(out).rb

    def describe(self):
        return f"CpuSortExec[{len(self.orders)} keys]"


class CpuLimitExec(HostNode):
    def __init__(self, limit: int, child: HostNode):
        super().__init__(child)
        self.limit = limit

    @property
    def output_schema(self) -> t.StructType:
        return self.child.output_schema

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        remaining = self.limit
        for rb in self.child.execute(ctx):
            if remaining <= 0:
                return
            if rb.num_rows <= remaining:
                remaining -= rb.num_rows
                yield rb
            else:
                yield rb.slice(0, remaining)
                return


_PA_JOIN = {"inner": "inner", "left_outer": "left outer",
            "right_outer": "right outer", "full_outer": "full outer",
            "left_semi": "left semi", "left_anti": "left anti"}


class CpuJoinExec(HostNode):
    def __init__(self, join_type: str, left_keys, right_keys,
                 left: HostNode, right: HostNode):
        super().__init__(left, right)
        self.join_type = join_type
        self.left_keys = [k.bind(left.output_schema) for k in left_keys]
        self.right_keys = [k.bind(right.output_schema) for k in right_keys]

    @property
    def output_schema(self) -> t.StructType:
        lf = list(self.children[0].output_schema.fields)
        if self.join_type in ("left_semi", "left_anti"):
            return t.StructType(lf)
        return t.StructType(lf + list(self.children[1].output_schema.fields))

    def _side_table(self, ctx, side: int) -> pa.Table:
        rbs = [rb for rb in self.children[side].execute(ctx) if rb.num_rows > 0]
        schema = struct_to_schema(self.children[side].output_schema)
        if not rbs:
            return pa.Table.from_batches([], schema)
        return pa.Table.from_batches(rbs, rbs[0].schema)

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        lt = self._side_table(ctx, 0)
        rt = self._side_table(ctx, 1)
        if self.join_type == "cross":
            yield from self._cross(lt, rt)
            return
        lrb = HostBatch.from_table(lt).rb
        rrb = HostBatch.from_table(rt).rb
        lkeys = [f"_jk{i}" for i in range(len(self.left_keys))]
        lt2 = lt
        for name, e in zip(lkeys, self.left_keys):
            _clear_scan_provenance()
            lt2 = lt2.append_column(name,
                                    CpuAggregateExec._arr(e.eval_cpu(lrb), lrb.num_rows))
        rt2 = rt
        for name, e in zip(lkeys, self.right_keys):
            rt2 = rt2.append_column(name,
                                    CpuAggregateExec._arr(e.eval_cpu(rrb), rrb.num_rows))
        # avoid output name collisions: suffix right columns on conflict
        out = lt2.join(rt2, keys=lkeys, join_type=_PA_JOIN[self.join_type],
                       left_suffix="", right_suffix="_r",
                       coalesce_keys=False)
        drop = [c for c in out.column_names if c.startswith("_jk")]
        out = out.drop_columns(drop)
        want = struct_to_schema(self.output_schema)
        out = out.rename_columns(want.names)
        out = out.cast(want)
        yield HostBatch.from_table(out).rb

    def _cross(self, lt: pa.Table, rt: pa.Table):
        import numpy as np
        nl, nr = lt.num_rows, rt.num_rows
        if nl == 0 or nr == 0:
            return
        li = np.repeat(np.arange(nl), nr)
        ri = np.tile(np.arange(nr), nl)
        lo = lt.take(li)
        ro = rt.take(ri)
        cols = list(lo.columns) + list(ro.columns)
        names = list(self.output_schema.names)
        yield HostBatch.from_table(
            pa.table(dict(zip(names, cols)))).rb

    def describe(self):
        return f"CpuJoinExec[{self.join_type}]"


class CpuUnionExec(HostNode):
    def __init__(self, *children: HostNode):
        super().__init__(*children)

    @property
    def output_schema(self) -> t.StructType:
        return self.children[0].output_schema

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        names = struct_to_schema(self.output_schema).names
        for c in self.children:
            for rb in c.execute(ctx):
                yield pa.RecordBatch.from_arrays(
                    list(rb.columns), schema=rb.schema.with_metadata(None)
                ).rename_columns(names)


class CpuRangeExec(HostNode):
    def __init__(self, start, end, step=1, name="id",
                 batch_rows: Optional[int] = None):
        super().__init__()
        self.start, self.end, self.step = start, end, step
        self.col_name = name
        self.batch_rows = batch_rows

    @property
    def output_schema(self) -> t.StructType:
        return t.StructType([t.StructField(self.col_name, t.LongType(), False)])

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        import numpy as np
        vals = np.arange(self.start, self.end, self.step, dtype=np.int64)
        chunk = self.batch_rows or ctx.conf.batch_size_rows
        for off in range(0, len(vals), chunk):
            yield pa.RecordBatch.from_arrays(
                [pa.array(vals[off:off + chunk])],
                schema=pa.schema([pa.field(self.col_name, pa.int64(), False)]))


class CpuExpandExec(HostNode):
    def __init__(self, projections, names, child: HostNode):
        super().__init__(child)
        self.projections = [[e.bind(child.output_schema) for e in p]
                            for p in projections]
        self.names = list(names)

    @property
    def output_schema(self) -> t.StructType:
        return t.StructType([t.StructField(n, e.dtype) for n, e in
                             zip(self.names, self.projections[0])])

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        for rb in self.child.execute(ctx):
            for proj in self.projections:
                yield _eval_named(proj, self.names, rb)


class CpuWindowExec(HostNode):
    """CPU window fallback: numpy over the partition-sorted table.

    Independent of the device kernel (ops/window.py) — row-at-a-time /
    numpy formulations of Spark's window semantics, usable as both the
    per-operator fallback and the correctness cross-check (SURVEY §4
    "same query, two backends").  Decimal inputs compute through float64
    (documented fallback-precision deviation)."""

    def __init__(self, window_exprs, partition_keys, order_keys,
                 child: HostNode):
        from ..plan.window import check_window_analysis
        super().__init__(child)
        check_window_analysis(window_exprs, order_keys)
        schema = child.output_schema
        self.window_exprs = [(spec.bind(schema), name)
                             for spec, name in window_exprs]
        self.partition_keys = [e.bind(schema) for e in partition_keys]
        self.order_keys = [(e.bind(schema), asc, nf)
                           for e, asc, nf in order_keys]

    @property
    def output_schema(self) -> t.StructType:
        fields = list(self.child.output_schema.fields)
        for spec, name in self.window_exprs:
            fields.append(t.StructField(name, spec.dtype))
        return t.StructType(fields)

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        import numpy as np
        import pandas as pd
        from ..plan.window import default_frame

        tbl = self._table(ctx)
        rb = HostBatch.from_table(tbl).rb
        n = rb.num_rows
        arr = CpuAggregateExec._arr

        key_cols, key_specs = [], []
        for i, e in enumerate(self.partition_keys):
            _clear_scan_provenance()
            key_cols.append((f"_p{i}", arr(e.eval_cpu(rb), n), True, True))
        for i, (e, asc, nf) in enumerate(self.order_keys):
            key_cols.append((f"_o{i}", arr(e.eval_cpu(rb), n), asc, nf))
        if key_cols and n:
            idx = sort_indices_per_key(
                [(c, asc, nf) for _nm, c, asc, nf in key_cols]
            ).to_numpy(zero_copy_only=False)
        else:
            idx = np.arange(n)
        srb = pa.Table.from_batches([rb]).take(idx)
        srb = HostBatch.from_table(srb).rb

        # boundary structure via per-column factorized codes (nulls equal)
        def codes_of(a):
            return pd.factorize(a.take(pa.array(idx)).to_pandas(),
                                use_na_sentinel=False)[0]

        np_idx = np.arange(n, dtype=np.int64)
        part_b = np.zeros(n, bool)
        peer_b = np.zeros(n, bool)
        if n:
            part_b[0] = peer_b[0] = True
        for nm, a, _asc, _nf in key_cols:
            c = codes_of(a)
            diff = np.zeros(n, bool)
            diff[1:] = c[1:] != c[:-1]
            if nm.startswith("_p"):
                part_b |= diff
            peer_b |= diff
        seg = np.cumsum(part_b) - 1 if n else np.zeros(0, np.int64)
        pg = np.cumsum(peer_b) - 1 if n else np.zeros(0, np.int64)

        def seg_edges(ids):
            starts = np.zeros(n, np.int64)
            ends = np.zeros(n, np.int64)
            if not n:
                return starts, ends
            first = np.zeros(ids.max() + 1, np.int64)
            last = np.zeros(ids.max() + 1, np.int64)
            b = np.ones(n, bool)
            b[1:] = ids[1:] != ids[:-1]
            first[ids[b]] = np_idx[b]
            e_mask = np.ones(n, bool)
            e_mask[:-1] = ids[1:] != ids[:-1]
            last[ids[e_mask]] = np_idx[e_mask]
            return first[ids], last[ids]

        part_start, part_end = seg_edges(seg)
        peer_start, peer_end = seg_edges(pg)
        part_rows = part_end - part_start + 1
        rn0 = np_idx - part_start

        # value-offset RANGE frames: per-partition searchsorted over the
        # single numeric order key (Spark's analyzer requirement)
        def range_bounds(frame):
            from ..plan.window import WindowAnalysisError
            if len(self.order_keys) != 1:
                raise WindowAnalysisError(
                    "a value-offset RANGE frame requires exactly one "
                    "window ORDER BY expression")
            oe, oasc, _onf = self.order_keys[0]
            odt = oe.dtype
            if not (t.is_numeric(odt) or isinstance(
                    odt, (t.DateType, t.TimestampType, t.DecimalType))):
                raise WindowAnalysisError(
                    f"value-offset RANGE frame over "
                    f"{odt.simple_string} order key")
            oa = key_cols[len(self.partition_keys)][1].take(pa.array(idx))
            ovalid = pc.is_valid(oa).to_numpy(zero_copy_only=False)
            ov = oa.cast(pa.float64()).fill_null(0.0) \
                .to_numpy(zero_copy_only=False)
            vvv = ov if oasc else -ov     # ascending comparison lane
            lo = np.empty(n, np.int64)
            hi = np.empty(n, np.int64)
            starts = np.nonzero(part_b)[0]
            for s, e in zip(starts, np.append(starts[1:], n)):
                vidx = np.nonzero(ovalid[s:e])[0]
                if not len(vidx):
                    lo[s:e] = s
                    hi[s:e] = e - 1
                    continue
                vs, ve = int(vidx[0]), int(vidx[-1])
                sub = vvv[s + vs:s + ve + 1]
                l_ = np.zeros(len(sub), np.int64) if frame.lower is None \
                    else np.searchsorted(sub, sub + frame.lower, "left")
                h_ = np.full(len(sub), len(sub) - 1, np.int64) \
                    if frame.upper is None \
                    else np.searchsorted(sub, sub + frame.upper,
                                         "right") - 1
                lo[s + vs:s + ve + 1] = s + vs + l_
                hi[s + vs:s + ve + 1] = s + vs + h_
                # null order rows form their own peer frame
                if vs > 0:
                    lo[s:s + vs] = s
                    hi[s:s + vs] = s + vs - 1
                if s + ve + 1 < e:
                    lo[s + ve + 1:e] = s + ve + 1
                    hi[s + ve + 1:e] = e - 1
            return lo, hi

        out_arrays = []
        for spec, _name in self.window_exprs:
            frame = spec.frame
            if frame is None:
                if spec.kind in ("row_number", "rank", "dense_rank",
                                 "percent_rank", "cume_dist", "ntile",
                                 "lead", "lag"):
                    frame = None
                else:
                    frame = default_frame(bool(self.order_keys))
            gather_source = None
            order_lane = None
            rank_order = None
            default_slot = None
            if spec.child is not None:
                va = arr(spec.child.eval_cpu(srb), n)
                valid = pc.is_valid(va).to_numpy(zero_copy_only=False)
                dt = spec.child.dtype
                if isinstance(dt, (t.StringType, t.BinaryType)):
                    # value-carrying functions gather from the source array;
                    # their numeric lane carries row indices (min/max order
                    # rows by value rank).  Structural functions over string
                    # inputs (count) never touch the value lane.
                    if spec.kind in ("lead", "lag", "first_value",
                                     "last_value", "agg_min", "agg_max"):
                        gather_source = va
                    vals = np.arange(n, dtype=np.int64)
                    if spec.kind in ("lead", "lag") and \
                            spec.default is not None:
                        # default rides as an extra slot at index n
                        gather_source = pa.concat_arrays(
                            [va.combine_chunks()
                             if isinstance(va, pa.ChunkedArray) else va,
                             pa.array([spec.default], va.type)])
                        default_slot = n
                    if spec.kind in ("agg_min", "agg_max"):
                        rank_order = pc.sort_indices(
                            va, null_placement="at_end"
                        ).to_numpy(zero_copy_only=False).astype(np.int64)
                        order_lane = np.empty(n, np.int64)
                        order_lane[rank_order] = np.arange(n)
                elif isinstance(dt, (t.FloatType, t.DoubleType)):
                    vals = va.cast(pa.float64()).fill_null(0.0) \
                        .to_numpy(zero_copy_only=False)
                elif isinstance(dt, t.DecimalType):
                    # decimal through float64: documented fallback deviation
                    vals = va.cast(pa.float64()).fill_null(0.0) \
                        .to_numpy(zero_copy_only=False)
                else:
                    # exact int64 lane for integral/bool/date/timestamp —
                    # no float64 round trip (lossy beyond 2^53)
                    vals = va.cast(pa.int64()).fill_null(0) \
                        .to_numpy(zero_copy_only=False)
            else:
                va, valid, vals, dt = None, np.ones(n, bool), None, None

            data, ok = self._one(spec, frame, n, np_idx, part_start,
                                 part_end, part_rows, peer_start, peer_end,
                                 rn0, part_b, peer_b, vals, valid,
                                 gather_source is not None, order_lane,
                                 default_slot, range_bounds, seg,
                                 rank_order)
            out_arrays.append(self._to_arrow(spec, data, ok, gather_source))

        cols = list(srb.columns) + out_arrays
        names = list(srb.schema.names) + [nm for _, nm in self.window_exprs]
        yield pa.RecordBatch.from_arrays(cols, names=names)

    @staticmethod
    def _one(spec, frame, n, np_idx, part_start, part_end, part_rows,
             peer_start, peer_end, rn0, part_b, peer_b, vals, valid,
             as_index, order_lane, default_slot=None, range_bounds=None,
             seg_of=None, rank_order=None):
        """Returns (ndarray, validity ndarray).  With `as_index` (string/
        binary inputs) the value lane carries row indices and min/max order
        by `order_lane` (value ranks); the caller gathers real values."""
        import numpy as np
        k = spec.kind
        live = np.ones(n, bool)
        if k == "row_number":
            return rn0 + 1, live
        if k == "rank":
            return peer_start - part_start + 1, live
        if k == "dense_rank":
            dr = np.zeros(n, np.int64)
            cur = 0
            for i in range(n):
                cur = 1 if part_b[i] else (cur + (1 if peer_b[i] else 0))
                dr[i] = cur
            return dr, live
        if k == "percent_rank":
            denom = np.maximum(part_rows - 1, 1)
            out = (peer_start - part_start) / denom
            return np.where(part_rows == 1, 0.0, out), live
        if k == "cume_dist":
            return (peer_end - part_start + 1) / part_rows, live
        if k == "ntile":
            nt = spec.n
            kk = part_rows // nt
            rem = part_rows % nt
            cut = rem * (kk + 1)
            bucket = np.where(rn0 < cut, rn0 // np.maximum(kk + 1, 1),
                              rem + (rn0 - cut) // np.maximum(kk, 1))
            bucket = np.where(part_rows < nt, rn0, bucket)
            return bucket + 1, live
        if k in ("lead", "lag"):
            shift = spec.offset * (1 if k == "lead" else -1)
            src = np_idx + shift
            in_part = (src >= part_start) & (src <= part_end)
            srcc = np.clip(src, 0, max(n - 1, 0))
            sd = vals[srcc] if n else vals
            sv = valid[srcc] if n else valid
            if spec.default is not None:
                dflt = vals.dtype.type(default_slot if as_index
                                       else spec.default)
                data = np.where(in_part, sd, dflt)
                return data, np.where(in_part, sv, True)
            return np.where(in_part, sd, vals.dtype.type(0)), in_part & sv

        # framed aggregates / first_value / last_value
        value_range = frame.kind == "range" and (
            frame.lower not in (None, 0) or frame.upper not in (None, 0))
        if value_range:
            lo, hi = range_bounds(frame)     # searchsorted value offsets
        elif frame.kind == "range":
            lo = part_start if frame.lower is None else peer_start
            hi = part_end if frame.upper is None else peer_end
        else:
            lo = part_start if frame.lower is None \
                else np.maximum(part_start, np_idx + frame.lower)
            hi = part_end if frame.upper is None \
                else np.minimum(part_end, np_idx + frame.upper)
        nonempty = hi >= lo
        if k == "first_value" or k == "last_value":
            pick = np.clip(lo if k == "first_value" else hi, 0,
                           max(n - 1, 0))
            return vals[pick], valid[pick] & nonempty
        # prefix windows
        vmask = valid
        cnt_lane = (vmask if spec.child is not None
                    else np.ones(n, bool)).astype(np.int64)
        pc_cnt = np.cumsum(cnt_lane)
        loc = np.clip(lo - 1, -1, n - 1)
        base_c = np.where(lo > 0, pc_cnt[loc], 0)
        hic = np.clip(hi, 0, max(n - 1, 0))
        cnt = np.where(nonempty, pc_cnt[hic] - base_c, 0)
        if k == "agg_count":
            return cnt, live
        if k in ("agg_sum", "agg_avg"):
            zero = vals.dtype.type(0)
            ps = np.cumsum(np.where(vmask, vals, zero))
            base = np.where(lo > 0, ps[loc], zero)
            s = np.where(nonempty, ps[hic] - base, zero)
            if k == "agg_sum":
                return s, cnt > 0
            return s / np.maximum(cnt, 1), cnt > 0
        return CpuWindowExec._minmax(
            spec, frame, n, part_b, seg_of, lo, hi, nonempty, cnt, vals,
            valid, order_lane, rank_order)

    @staticmethod
    def _minmax(spec, frame, n, part_b, seg_of, lo, hi, nonempty, cnt,
                vals, valid, order_lane, rank_order):
        """Window min/max.  Selection happens in an *order lane* (value
        ranks for strings, NaN-mapped-to-+inf floats, exact ints); the
        result row's true value is emitted, so NaN inputs and null-fill
        slots are never confused (nulls are excluded from selection
        entirely).  O(n) paths cover the always-on-CPU shapes (running /
        unbounded frames — string min/max never runs on device); bounded
        and value-range frames use a per-row selection loop."""
        import numpy as np
        k = spec.kind
        is_min = k == "agg_min"
        olane = order_lane if order_lane is not None else vals
        is_float = np.issubdtype(np.asarray(olane).dtype, np.floating)
        if is_float:
            nan_mask = np.isnan(olane) & valid
            olane = np.where(np.isnan(olane), np.inf, olane)
            ident = np.inf if is_min else -np.inf
        else:
            nan_mask = None
            info = np.iinfo(olane.dtype)
            ident = olane.dtype.type(info.max if is_min else info.min)
        masked = np.where(valid, olane, ident)
        op = np.minimum if is_min else np.maximum
        starts = np.nonzero(part_b)[0]

        def decode(red_olane, frame_cnt, frame_nan_cnt):
            """Order-lane result -> (value lane, validity)."""
            okv = (frame_cnt > 0) & nonempty
            if rank_order is not None:
                # string rank -> winning row index (vals carries indices)
                r = np.clip(red_olane, 0, max(n - 1, 0)).astype(np.int64)
                return rank_order[r], okv
            if is_float and frame_nan_cnt is not None:
                non_nan = frame_cnt - frame_nan_cnt
                if is_min:     # NaN only when every valid value is NaN
                    red = np.where((frame_cnt > 0) & (non_nan == 0),
                                   np.nan, red_olane)
                else:          # NaN greatest: any NaN wins the max
                    red = np.where(frame_nan_cnt > 0, np.nan, red_olane)
                return red, okv
            return red_olane, okv

        running = frame.kind == "rows" and frame.lower is None and \
            frame.upper == 0
        range_running = frame.kind == "range" and frame.lower is None and \
            frame.upper == 0
        unbounded = frame.lower is None and frame.upper is None
        if running or range_running or unbounded:
            nan_cnt_pref = None
            if nan_mask is not None:
                nan_cnt_pref = np.cumsum(nan_mask.astype(np.int64))

            def frame_nan(lo_, hi_):
                if nan_cnt_pref is None:
                    return None
                base = np.where(lo_ > 0,
                                nan_cnt_pref[np.clip(lo_ - 1, 0, n - 1)], 0)
                return nan_cnt_pref[np.clip(hi_, 0, max(n - 1, 0))] - base
            if unbounded:
                red = op.reduceat(masked, starts)[seg_of] if n \
                    else masked
                return decode(red, cnt, frame_nan(lo, hi))
            acc = np.empty_like(masked)
            for s, e in zip(starts, np.append(starts[1:], n)):
                acc[s:e] = op.accumulate(masked[s:e])
            if range_running:   # include current row's peers
                acc = acc[np.clip(hi, 0, max(n - 1, 0))]
            return decode(acc, cnt, frame_nan(lo, hi))

        # bounded / value-range frames: per-row selection among VALID rows
        out = np.zeros(n, vals.dtype)
        ok = np.zeros(n, bool)
        for i in range(n):
            if not nonempty[i]:
                continue
            w = np.arange(lo[i], hi[i] + 1)
            wvalid = w[valid[w]]
            if not len(wvalid):
                continue
            cand = olane[wvalid]
            nans = nan_mask[wvalid] if nan_mask is not None else None
            j = int(np.argmin(cand) if is_min else np.argmax(cand))
            sel = wvalid[j]
            if nans is not None:
                if is_min and nans.all():
                    out[i] = np.nan
                    ok[i] = True
                    continue
                if not is_min and nans.any():
                    out[i] = np.nan
                    ok[i] = True
                    continue
            out[i] = vals[sel]
            ok[i] = True
        return out, ok

    @staticmethod
    def _to_arrow(spec, data, ok, gather_source):
        import numpy as np
        dt = spec.dtype
        atype = dtype_to_arrow(dt)
        mask = ~np.asarray(ok, bool)
        if gather_source is not None:
            # pass-through over strings/binary: data carries row indices
            idx = np.clip(np.asarray(data, np.int64), 0,
                          max(len(gather_source) - 1, 0))
            taken = gather_source.take(pa.array(idx))
            return pc.if_else(pa.array(~mask), taken,
                              pa.nulls(len(mask), atype))
        if isinstance(dt, t.DecimalType):
            import decimal as _d
            q = _d.Decimal(1).scaleb(-dt.scale)
            pyvals = [None if m else _d.Decimal(repr(float(v))).quantize(
                q, rounding=_d.ROUND_HALF_UP)
                for v, m in zip(np.asarray(data, np.float64), mask)]
            return pa.array(pyvals, type=atype)
        # logical (arrow) representation, NOT the device storage lane —
        # DOUBLE's physical lane is int64 bit patterns and must not be
        # used to round-trip host-computed floats
        data = np.asarray(data)
        if isinstance(dt, (t.FloatType, t.DoubleType)):
            return pa.array(data.astype(np.float64), pa.float64(),
                            mask=mask).cast(atype)
        if isinstance(dt, t.BooleanType):
            return pa.array(data.astype(bool), atype, mask=mask)
        ints = np.rint(data).astype(np.int64) \
            if not np.issubdtype(data.dtype, np.integer) else data
        if isinstance(dt, (t.DateType, t.TimestampType)):
            w = pa.int32() if isinstance(dt, t.DateType) else pa.int64()
            return pa.array(ints, pa.int64(), mask=mask).cast(w).cast(atype)
        return pa.array(ints, pa.int64(), mask=mask).cast(atype)

    def describe(self):
        return f"CpuWindowExec[{[n for _, n in self.window_exprs]}]"


class CpuGenerateExec(HostNode):
    """explode / posexplode (+outer): replicate parent rows per array
    element, appending pos/col columns (GpuGenerateExec semantics:
    non-outer drops rows whose array is null/empty; outer keeps them with
    null generated columns)."""

    def __init__(self, generator, output_names, child: HostNode):
        super().__init__(child)
        self.generator = generator.bind(child.output_schema)
        gen_fields = self.generator.output_fields()
        self.output_names = list(output_names) or \
            [f.name for f in gen_fields]
        self._gen_fields = gen_fields

    @property
    def output_schema(self) -> t.StructType:
        fields = list(self.child.output_schema.fields)
        for f, n in zip(self._gen_fields, self.output_names):
            fields.append(t.StructField(n, f.data_type, f.nullable))
        return t.StructType(fields)

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        from ..columnar.host import dtype_to_arrow
        from ..plan.json_fns import JsonTupleGen
        gen = self.generator
        if isinstance(gen, JsonTupleGen):
            yield from self._execute_json_tuple(ctx)
            return
        for rb in self.child.execute(ctx):
            arrays = CpuAggregateExec._arr(gen.child.eval_cpu(rb),
                                           rb.num_rows).to_pylist()
            take_idx: List[int] = []
            poss: List[Optional[int]] = []
            vals: List = []
            for i, arr in enumerate(arrays):
                if arr is None or len(arr) == 0:
                    if gen.outer:
                        take_idx.append(i)
                        poss.append(None)
                        vals.append(None)
                    continue
                for p, v in enumerate(arr):
                    take_idx.append(i)
                    poss.append(p)
                    vals.append(v)
            base = rb.take(pa.array(take_idx, pa.int64()))
            cols = list(base.columns)
            names = list(base.schema.names)
            fi = 0
            if gen.pos:
                cols.append(pa.array(poss, pa.int32()))
                names.append(self.output_names[fi])
                fi += 1
            et = dtype_to_arrow(gen.child.dtype.element_type)
            cols.append(pa.array(vals, et))
            names.append(self.output_names[fi])
            yield pa.RecordBatch.from_arrays(cols, names=names)

    def _execute_json_tuple(self, ctx) -> Iterator[pa.RecordBatch]:
        """json_tuple generator: one output row per input row, k string
        field columns (GpuJsonTuple role)."""
        import json as _json
        gen = self.generator
        for rb in self.child.execute(ctx):
            vals = CpuAggregateExec._arr(gen.child.eval_cpu(rb),
                                         rb.num_rows).cast(
                pa.string()).to_pylist()
            outs = [[] for _ in gen.fields]
            from ..plan.json_fns import _render
            for v in vals:
                obj = None
                if v is not None:
                    try:
                        obj = _json.loads(v)
                    except (ValueError, TypeError):
                        obj = None
                for j, f in enumerate(gen.fields):
                    if isinstance(obj, dict) and f in obj:
                        outs[j].append(_render(obj[f]))
                    else:
                        outs[j].append(None)
            cols = list(rb.columns)
            names = list(rb.schema.names)
            for j, name in enumerate(self.output_names):
                cols.append(pa.array(outs[j], pa.string()))
                names.append(name)
            yield pa.RecordBatch.from_arrays(cols, names=names)

    def describe(self):
        return f"CpuGenerateExec[{self.generator!r}]"
