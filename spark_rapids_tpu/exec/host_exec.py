"""CPU physical operators (per-operator fallback path) + transitions.

The reference keeps unreplaced Spark operators running on the CPU and
bridges with GpuRowToColumnarExec / GpuColumnarToRowExec
(GpuTransitionOverrides.scala:50).  Here the CPU engine is pyarrow: host
operators stream pyarrow RecordBatches and evaluate expressions through
their `eval_cpu` oracle path — the same code that serves as the test
oracle, which is exactly the reference's "same query, two backends"
correctness strategy (SURVEY §4).

Transitions:
  * HostToDeviceExec — device PlanNode over a HostNode child (the
    HostColumnarToGpu role), slicing oversized host batches to the
    configured row target before upload.
  * DeviceToHostExec — HostNode over a device PlanNode child (the
    GpuColumnarToRowExec / BringBackToHost role).
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import pyarrow as pa
import pyarrow.compute as pc

from .. import types as t
from ..columnar.device import DeviceBatch, to_device, to_host
from ..columnar.host import HostBatch, dtype_to_arrow, struct_to_schema
from ..plan import expressions as E
from ..plan.aggregates import AggregateFunction
from .plan import ExecContext, PlanNode


class HostNode:
    """Base CPU operator: streams pyarrow RecordBatches."""

    def __init__(self, *children: "HostNode"):
        self.children = list(children)

    @property
    def child(self) -> "HostNode":
        return self.children[0]

    @property
    def output_schema(self) -> t.StructType:
        raise NotImplementedError

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        raise NotImplementedError

    def name(self) -> str:
        return type(self).__name__

    def describe(self) -> str:
        return self.name()

    def tree_string(self, indent: int = 0) -> str:
        lines = ["  " * indent + self.describe()]
        for c in self.children:
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)

    def collect(self, ctx: Optional[ExecContext] = None) -> pa.Table:
        ctx = ctx or ExecContext()
        rbs = [rb for rb in self.execute(ctx) if rb.num_rows > 0]
        schema = struct_to_schema(self.output_schema)
        if not rbs:
            return pa.Table.from_batches([], schema)
        return pa.Table.from_batches(rbs, rbs[0].schema)

    def _table(self, ctx) -> pa.Table:
        """Materialize the child stream as one table."""
        rbs = [rb for rb in self.child.execute(ctx) if rb.num_rows > 0]
        schema = struct_to_schema(self.child.output_schema)
        if not rbs:
            return pa.Table.from_batches([], schema)
        return pa.Table.from_batches(rbs, rbs[0].schema)


# ---------------------------------------------------------------------------
# Transitions
# ---------------------------------------------------------------------------

class HostToDeviceExec(PlanNode):
    """Upload a host stream to device (HostColumnarToGpu role)."""

    def __init__(self, host_child: HostNode):
        super().__init__()
        self.host_child = host_child

    @property
    def output_schema(self) -> t.StructType:
        return self.host_child.output_schema

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        target = ctx.conf.batch_size_rows
        for rb in self.host_child.execute(ctx):
            for off in range(0, max(rb.num_rows, 1), target):
                sl = rb.slice(off, min(target, rb.num_rows - off))
                if rb.num_rows and sl.num_rows == 0:
                    continue
                ctx.bump("h2d_rows", sl.num_rows)
                yield to_device(HostBatch(sl), ctx.conf)

    def tree_string(self, indent: int = 0) -> str:
        lines = ["  " * indent + "HostToDeviceExec"]
        lines.append(self.host_child.tree_string(indent + 1))
        return "\n".join(lines)


class DeviceToHostExec(HostNode):
    """Fetch a device stream to host (GpuColumnarToRowExec role)."""

    def __init__(self, device_child: PlanNode):
        super().__init__()
        self.device_child = device_child

    @property
    def output_schema(self) -> t.StructType:
        return self.device_child.output_schema

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        for db in self.device_child.execute(ctx):
            if int(db.num_rows) == 0:
                continue
            ctx.bump("d2h_rows", int(db.num_rows))
            yield to_host(db).rb

    def tree_string(self, indent: int = 0) -> str:
        lines = ["  " * indent + "DeviceToHostExec"]
        lines.append(self.device_child.tree_string(indent + 1))
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# CPU operators
# ---------------------------------------------------------------------------

class HostSourceExec(HostNode):
    """Leaf over an in-memory Arrow table."""

    def __init__(self, table: pa.Table, batch_rows: Optional[int] = None):
        super().__init__()
        self.table = table
        self.batch_rows = batch_rows

    @property
    def output_schema(self) -> t.StructType:
        from ..columnar.host import schema_to_struct
        return schema_to_struct(self.table.schema)

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        tbl = self.table.combine_chunks()
        yield from tbl.to_batches(max_chunksize=self.batch_rows)

    def describe(self):
        return f"HostSourceExec[{self.table.num_rows} rows]"


def _eval_named(exprs: Sequence[E.Expression], names: Sequence[str],
                rb: pa.RecordBatch) -> pa.RecordBatch:
    arrays, fields = [], []
    for e, n in zip(exprs, names):
        a = e.eval_cpu(rb)
        if isinstance(a, pa.ChunkedArray):
            a = a.combine_chunks()
        if isinstance(a, pa.Scalar):
            a = pa.array([a.as_py()] * rb.num_rows, type=a.type)
        arrays.append(a)
        fields.append(pa.field(n, a.type))
    return pa.RecordBatch.from_arrays(arrays, schema=pa.schema(fields))


class CpuProjectExec(HostNode):
    def __init__(self, exprs: Sequence[E.Expression], names: Sequence[str],
                 child: HostNode):
        super().__init__(child)
        self.exprs = [e.bind(child.output_schema) for e in exprs]
        self.names = list(names)

    @property
    def output_schema(self) -> t.StructType:
        return t.StructType([t.StructField(n, e.dtype, e.nullable)
                             for n, e in zip(self.names, self.exprs)])

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        for rb in self.child.execute(ctx):
            yield _eval_named(self.exprs, self.names, rb)

    def describe(self):
        return f"CpuProjectExec[{', '.join(self.names)}]"


class CpuFilterExec(HostNode):
    def __init__(self, condition: E.Expression, child: HostNode):
        super().__init__(child)
        self.condition = condition.bind(child.output_schema)

    @property
    def output_schema(self) -> t.StructType:
        return self.child.output_schema

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        for rb in self.child.execute(ctx):
            mask = self.condition.eval_cpu(rb)
            mask = pc.fill_null(mask, False)
            tbl = pa.Table.from_batches([rb]).filter(mask)
            for out in tbl.combine_chunks().to_batches():
                yield out

    def describe(self):
        return f"CpuFilterExec[{self.condition!r}]"


class CpuAggregateExec(HostNode):
    """Hash aggregate on pyarrow TableGroupBy / compute reductions."""

    def __init__(self, keys: Sequence[E.Expression], key_names: Sequence[str],
                 aggs: Sequence[Tuple[AggregateFunction, str]],
                 child: HostNode):
        super().__init__(child)
        schema = child.output_schema
        self.keys = [k.bind(schema) for k in keys]
        self.key_names = list(key_names)
        self.aggs = [(fn.bind(schema), n) for fn, n in aggs]

    @property
    def output_schema(self) -> t.StructType:
        fields = [t.StructField(n, k.dtype)
                  for n, k in zip(self.key_names, self.keys)]
        for fn, n in self.aggs:
            fields.append(t.StructField(n, fn.dtype))
        return t.StructType(fields)

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        tbl = self._table(ctx)
        rb = HostBatch.from_table(tbl).rb
        # project keys + agg children into a working table
        arrays, names = [], []
        for i, k in enumerate(self.keys):
            arrays.append(self._arr(k.eval_cpu(rb), rb.num_rows))
            names.append(f"_k{i}")
        agg_specs = []
        for j, (fn, _) in enumerate(self.aggs):
            child = fn.child
            col = f"_a{j}"
            if child is None:
                # count(*): count over an all-valid dummy column
                arrays.append(pa.array([True] * rb.num_rows))
            else:
                arrays.append(self._arr(child.eval_cpu(rb), rb.num_rows))
            names.append(col)
            agg_specs.append((col, fn))
        work = pa.table(dict(zip(names, arrays)))

        if not self.keys:
            out_arrays, out_fields = [], []
            for (col, fn), (_, oname) in zip(agg_specs, self.aggs):
                fname, opts = fn.cpu_agg()
                want = dtype_to_arrow(fn.dtype)
                if fname == "_py":
                    v = opts(work[col].to_pylist())
                    arr = pa.array([v], type=want) if v is not None \
                        else pa.nulls(1, want)
                else:
                    val = self._global_agg(work[col], fname, opts)
                    arr = pa.array([val.as_py()], type=want) \
                        if val is not None else pa.nulls(1, want)
                out_arrays.append(arr)
                out_fields.append(pa.field(oname, want))
            yield pa.RecordBatch.from_arrays(out_arrays,
                                             schema=pa.schema(out_fields))
            return

        if any(fn.cpu_agg()[0] == "_py" for _c, fn in agg_specs):
            yield self._python_grouped(work, agg_specs)
            return

        gb_aggs = []
        for col, fn in agg_specs:
            fname, opts = fn.cpu_agg()
            gb_aggs.append((col, fname, opts))
        res = work.group_by([f"_k{i}" for i in range(len(self.keys))],
                            use_threads=False).aggregate(gb_aggs)
        # order output columns: keys then aggs, cast to declared types
        out_arrays, out_fields = [], []
        for i, (kname, k) in enumerate(zip(self.key_names, self.keys)):
            a = res[f"_k{i}"].combine_chunks()
            out_arrays.append(a)
            out_fields.append(pa.field(kname, a.type))
        for j, ((col, fn), (_, oname)) in enumerate(zip(agg_specs, self.aggs)):
            fname, _ = fn.cpu_agg()
            a = res[f"{col}_{fname}"].combine_chunks().cast(
                dtype_to_arrow(fn.dtype))
            out_arrays.append(a)
            out_fields.append(pa.field(oname, a.type))
        tbl = pa.Table.from_arrays(out_arrays, schema=pa.schema(out_fields))
        yield HostBatch.from_table(tbl).rb

    def _python_grouped(self, work: pa.Table, agg_specs) -> pa.RecordBatch:
        """Pure-python grouped aggregation: the exact-semantics path for
        aggregates pyarrow's TableGroupBy can't express (e.g. decimal avg
        at Spark's result scale)."""
        nk = len(self.keys)
        key_cols = [work[f"_k{i}"].to_pylist() for i in range(nk)]
        val_cols = [work[col].to_pylist() for col, _fn in agg_specs]
        groups: dict = {}
        order = []
        for row in range(work.num_rows):
            key = tuple(kc[row] for kc in key_cols)
            g = groups.get(key)
            if g is None:
                g = groups[key] = [[] for _ in agg_specs]
                order.append(key)
            for j in range(len(agg_specs)):
                g[j].append(val_cols[j][row])

        def apply(fn, fname, opts, values):
            nn = [v for v in values if v is not None]
            if fname == "_py":
                return opts(values)
            if fname == "count":
                mode = getattr(opts, "mode", "only_valid")
                return len(values) if mode == "all" else len(nn)
            if not nn:
                return None
            return {"sum": sum, "min": min, "max": max,
                    "mean": lambda v: sum(v) / len(v),
                    "first": lambda v: v[0], "last": lambda v: v[-1],
                    }[fname](nn)

        out_arrays, out_fields = [], []
        for i, (kname, k) in enumerate(zip(self.key_names, self.keys)):
            out_arrays.append(pa.array([key[i] for key in order],
                                       dtype_to_arrow(k.dtype)))
            out_fields.append(pa.field(kname, dtype_to_arrow(k.dtype)))
        for j, ((_col, fn), (_, oname)) in enumerate(zip(agg_specs, self.aggs)):
            fname, opts = fn.cpu_agg()
            vals = [apply(fn, fname, opts, groups[key][j]) for key in order]
            out_arrays.append(pa.array(vals, dtype_to_arrow(fn.dtype)))
            out_fields.append(pa.field(oname, dtype_to_arrow(fn.dtype)))
        return pa.RecordBatch.from_arrays(out_arrays,
                                          schema=pa.schema(out_fields))

    @staticmethod
    def _arr(a, n):
        if isinstance(a, pa.ChunkedArray):
            a = a.combine_chunks()
        if isinstance(a, pa.Scalar):
            a = pa.array([a.as_py()] * n, type=a.type)
        return a

    @staticmethod
    def _global_agg(col: pa.ChunkedArray, fname: str, opts):
        fn = {"sum": pc.sum, "min": pc.min, "max": pc.max, "mean": pc.mean,
              "count": pc.count, "first": lambda c, options=None:
                  c[0] if len(c) else None,
              "last": lambda c, options=None: c[-1] if len(c) else None,
              }[fname]
        if fname in ("first", "last"):
            vals = col.drop_null() if opts is not None and \
                getattr(opts, "skip_nulls", False) else col
            return fn(vals)
        return fn(col, options=opts) if opts is not None else fn(col)

    def describe(self):
        return (f"CpuAggregateExec[keys={self.key_names}, "
                f"aggs={[n for _, n in self.aggs]}]")


class CpuSortExec(HostNode):
    def __init__(self, orders, child: HostNode):
        """orders: (bound-or-unbound expr, ascending, nulls_first) tuples."""
        super().__init__(child)
        self.orders = [(e.bind(child.output_schema), asc, nf)
                       for e, asc, nf in orders]

    @property
    def output_schema(self) -> t.StructType:
        return self.child.output_schema

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        tbl = self._table(ctx)
        rb = HostBatch.from_table(tbl).rb
        sort_cols, keys = [], []
        for i, (e, asc, nf) in enumerate(self.orders):
            sort_cols.append(CpuAggregateExec._arr(e.eval_cpu(rb), rb.num_rows))
            keys.append((f"_s{i}", "ascending" if asc else "descending",
                         "at_start" if nf else "at_end"))
        work = pa.table({f"_s{i}": c for i, c in enumerate(sort_cols)})
        idx = pc.sort_indices(
            work, sort_keys=[(n, d) for n, d, _ in keys],
            null_placement=keys[0][2] if keys else "at_start")
        out = pa.Table.from_batches([rb]).take(idx)
        yield HostBatch.from_table(out).rb

    def describe(self):
        return f"CpuSortExec[{len(self.orders)} keys]"


class CpuLimitExec(HostNode):
    def __init__(self, limit: int, child: HostNode):
        super().__init__(child)
        self.limit = limit

    @property
    def output_schema(self) -> t.StructType:
        return self.child.output_schema

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        remaining = self.limit
        for rb in self.child.execute(ctx):
            if remaining <= 0:
                return
            if rb.num_rows <= remaining:
                remaining -= rb.num_rows
                yield rb
            else:
                yield rb.slice(0, remaining)
                return


_PA_JOIN = {"inner": "inner", "left_outer": "left outer",
            "right_outer": "right outer", "full_outer": "full outer",
            "left_semi": "left semi", "left_anti": "left anti"}


class CpuJoinExec(HostNode):
    def __init__(self, join_type: str, left_keys, right_keys,
                 left: HostNode, right: HostNode):
        super().__init__(left, right)
        self.join_type = join_type
        self.left_keys = [k.bind(left.output_schema) for k in left_keys]
        self.right_keys = [k.bind(right.output_schema) for k in right_keys]

    @property
    def output_schema(self) -> t.StructType:
        lf = list(self.children[0].output_schema.fields)
        if self.join_type in ("left_semi", "left_anti"):
            return t.StructType(lf)
        return t.StructType(lf + list(self.children[1].output_schema.fields))

    def _side_table(self, ctx, side: int) -> pa.Table:
        rbs = [rb for rb in self.children[side].execute(ctx) if rb.num_rows > 0]
        schema = struct_to_schema(self.children[side].output_schema)
        if not rbs:
            return pa.Table.from_batches([], schema)
        return pa.Table.from_batches(rbs, rbs[0].schema)

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        lt = self._side_table(ctx, 0)
        rt = self._side_table(ctx, 1)
        if self.join_type == "cross":
            yield from self._cross(lt, rt)
            return
        lrb = HostBatch.from_table(lt).rb
        rrb = HostBatch.from_table(rt).rb
        lkeys = [f"_jk{i}" for i in range(len(self.left_keys))]
        lt2 = lt
        for name, e in zip(lkeys, self.left_keys):
            lt2 = lt2.append_column(name,
                                    CpuAggregateExec._arr(e.eval_cpu(lrb), lrb.num_rows))
        rt2 = rt
        for name, e in zip(lkeys, self.right_keys):
            rt2 = rt2.append_column(name,
                                    CpuAggregateExec._arr(e.eval_cpu(rrb), rrb.num_rows))
        # avoid output name collisions: suffix right columns on conflict
        out = lt2.join(rt2, keys=lkeys, join_type=_PA_JOIN[self.join_type],
                       left_suffix="", right_suffix="_r",
                       coalesce_keys=False)
        drop = [c for c in out.column_names if c.startswith("_jk")]
        out = out.drop_columns(drop)
        want = struct_to_schema(self.output_schema)
        out = out.rename_columns(want.names)
        out = out.cast(want)
        yield HostBatch.from_table(out).rb

    def _cross(self, lt: pa.Table, rt: pa.Table):
        import numpy as np
        nl, nr = lt.num_rows, rt.num_rows
        if nl == 0 or nr == 0:
            return
        li = np.repeat(np.arange(nl), nr)
        ri = np.tile(np.arange(nr), nl)
        lo = lt.take(li)
        ro = rt.take(ri)
        cols = list(lo.columns) + list(ro.columns)
        names = list(self.output_schema.names)
        yield HostBatch.from_table(
            pa.table(dict(zip(names, cols)))).rb

    def describe(self):
        return f"CpuJoinExec[{self.join_type}]"


class CpuUnionExec(HostNode):
    def __init__(self, *children: HostNode):
        super().__init__(*children)

    @property
    def output_schema(self) -> t.StructType:
        return self.children[0].output_schema

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        names = struct_to_schema(self.output_schema).names
        for c in self.children:
            for rb in c.execute(ctx):
                yield pa.RecordBatch.from_arrays(
                    list(rb.columns), schema=rb.schema.with_metadata(None)
                ).rename_columns(names)


class CpuRangeExec(HostNode):
    def __init__(self, start, end, step=1, name="id",
                 batch_rows: Optional[int] = None):
        super().__init__()
        self.start, self.end, self.step = start, end, step
        self.col_name = name
        self.batch_rows = batch_rows

    @property
    def output_schema(self) -> t.StructType:
        return t.StructType([t.StructField(self.col_name, t.LongType(), False)])

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        import numpy as np
        vals = np.arange(self.start, self.end, self.step, dtype=np.int64)
        chunk = self.batch_rows or ctx.conf.batch_size_rows
        for off in range(0, len(vals), chunk):
            yield pa.RecordBatch.from_arrays(
                [pa.array(vals[off:off + chunk])],
                schema=pa.schema([pa.field(self.col_name, pa.int64(), False)]))


class CpuExpandExec(HostNode):
    def __init__(self, projections, names, child: HostNode):
        super().__init__(child)
        self.projections = [[e.bind(child.output_schema) for e in p]
                            for p in projections]
        self.names = list(names)

    @property
    def output_schema(self) -> t.StructType:
        return t.StructType([t.StructField(n, e.dtype) for n, e in
                             zip(self.names, self.projections[0])])

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        for rb in self.child.execute(ctx):
            for proj in self.projections:
                yield _eval_named(proj, self.names, rb)
