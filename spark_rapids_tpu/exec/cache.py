"""Cached-plan materialization — the ParquetCachedBatchSerializer role.

Reference: df.cache() stores batches as compressed Parquet BYTES
(ParquetCachedBatchSerializer.scala:264, GpuInMemoryTableScanExec): the
columnar encode compresses on the accelerator side and cached data
re-decodes on demand, trading CPU-side decode for a fraction of the
memory of raw batches.

Here: the first materialization streams the child's host batches into an
in-memory zstd parquet buffer (one shot); replays decode from the buffer
through the standard host->device upload.  The logical node pins the
buffer on the LOGICAL plan object so every physical re-plan of the same
DataFrame reuses it (Spark's cache is also logical-plan-keyed)."""
from __future__ import annotations

import io as _io
from typing import Iterator, Optional

import pyarrow as pa
import pyarrow.parquet as pq

from .. import types as t
from ..columnar.host import schema_to_struct, struct_to_schema
from ..plan import logical as L


class LogicalCache(L.LogicalPlan):
    """Caches the child's result on first materialization."""

    def __init__(self, child: L.LogicalPlan):
        super().__init__(child)
        self._buffer: Optional[bytes] = None
        self._cached_schema: Optional[pa.Schema] = None

    def _resolve_schema(self):
        return self.child.schema

    def materialized(self) -> bool:
        return self._buffer is not None

    def cached_bytes(self) -> int:
        return len(self._buffer) if self._buffer is not None else 0

    def materialize(self, conf) -> None:
        if self._buffer is not None:
            return
        from ..plan.overrides import apply_overrides
        q = apply_overrides(self.child, conf)
        schema = struct_to_schema(self.schema)
        sink = _io.BytesIO()
        writer = pq.ParquetWriter(sink, schema, compression="zstd")
        try:
            for rb in q.execute_host_batches():
                if rb.num_rows:
                    writer.write_batch(rb.cast(schema)
                                       if rb.schema != schema else rb)
        finally:
            writer.close()
        self._buffer = sink.getvalue()
        self._cached_schema = schema

    def read_batches(self, batch_rows: int) -> Iterator[pa.RecordBatch]:
        assert self._buffer is not None, "cache not materialized"
        f = pq.ParquetFile(_io.BytesIO(self._buffer))
        for rb in f.iter_batches(batch_size=batch_rows):
            yield rb

    def describe(self):
        state = f"{self.cached_bytes()}B" if self.materialized() \
            else "cold"
        return f"Cache[{state}]"


class CachedHostScan:
    """Host exec over a LogicalCache: materializes lazily at EXECUTE time
    (never during plan conversion — explain stays side-effect free) and
    STREAMS batches from the compressed buffer (peak memory = one decoded
    batch, which is the cache's whole point)."""

    def __init__(self, lc: LogicalCache, conf):
        from .host_exec import HostNode
        self.children = []
        self._lc = lc
        self._conf = conf

    @property
    def output_schema(self):
        return self._lc.schema

    def execute(self, ctx) -> Iterator[pa.RecordBatch]:
        self._lc.materialize(ctx.conf)
        yield from self._lc.read_batches(ctx.conf.batch_size_rows)

    def describe(self):
        return f"CachedHostScan[{self._lc.describe()}]"

    def tree_string(self, indent: int = 0) -> str:
        return "  " * indent + self.describe()

    def name(self):
        return type(self).__name__

    def collect(self, ctx=None):
        from .host_exec import HostNode
        return HostNode.collect(self, ctx)
