"""Per-operator metrics instrumentation — the GpuMetric / GpuTaskMetrics
role.

Reference: GpuExec declares metric sets surfaced in the Spark UI
(GpuExec.scala:49-160: opTime, numOutputRows, ...), GpuTaskMetrics adds
semaphore-wait / spill / retry accumulators, and NVTX ranges mark
operator spans for nsys (NvtxWithMetrics.scala).

TPU shape: `instrument(root, ctx)` wraps every PlanNode/HostNode execute
stream with wall-time + row counters keyed `<ExecName>.op_time_ms` /
`.output_rows` in ctx.metrics (enabled at metrics level >= OPERATOR), and
`profile_trace(conf)` wraps a query in a jax-profiler trace (the
NVTX/CUPTI analogue — open the trace in XProf/perfetto) when
`spark.rapids.tpu.profile.path` is set."""
from __future__ import annotations

import time
from contextlib import contextmanager, nullcontext

from ..config import METRICS_LEVEL, PROFILE_PATH, TpuConf


def instrument(node, ctx) -> None:
    """Wrap the execute() of every node in the tree (device and host)
    with op-time and output-row metrics.  Idempotent per node object."""
    if getattr(node, "_metered", False):
        return
    node._metered = True
    name = type(node).__name__
    inner = node.execute

    def metered(c):
        t0 = time.perf_counter()
        rows = 0
        try:
            it = inner(c)
            while True:
                t1 = time.perf_counter()
                try:
                    out = next(it)
                except StopIteration:
                    return
                finally:
                    c.metrics[f"{name}.op_time_ms"] = c.metrics.get(
                        f"{name}.op_time_ms", 0.0) + \
                        (time.perf_counter() - t1) * 1000.0
                n = getattr(out, "num_rows", None)
                if n is not None:
                    try:
                        rows += int(n)
                    except Exception:       # lazy device count: skip sync
                        pass
                yield out
        finally:
            c.metrics[f"{name}.total_time_ms"] = c.metrics.get(
                f"{name}.total_time_ms", 0.0) + \
                (time.perf_counter() - t0) * 1000.0
            c.metrics[f"{name}.output_rows"] = c.metrics.get(
                f"{name}.output_rows", 0) + rows

    node.execute = metered
    for attr in ("children",):
        for c in getattr(node, attr, []):
            instrument(c, ctx)
    for attr in ("host_child", "device_child"):
        c = getattr(node, attr, None)
        if c is not None:
            instrument(c, ctx)


def should_instrument(conf: TpuConf) -> bool:
    return conf.get(METRICS_LEVEL) in ("MODERATE", "DEBUG")


@contextmanager
def profile_trace(conf: TpuConf):
    """jax profiler trace around a query when profile.path is set —
    the NVTX/nsys + built-in Profiler analogue (SURVEY §5 tracing)."""
    path = conf.get(PROFILE_PATH)
    if not path:
        with nullcontext():
            yield
        return
    import jax
    with jax.profiler.trace(path):
        yield
