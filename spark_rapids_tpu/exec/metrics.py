"""Per-operator metrics instrumentation — the GpuMetric / GpuTaskMetrics
role.

Reference: GpuExec declares metric sets surfaced in the Spark UI
(GpuExec.scala:49-160: opTime, numOutputRows, ...), GpuTaskMetrics adds
semaphore-wait / spill / retry accumulators, and NVTX ranges mark
operator spans for nsys (NvtxWithMetrics.scala).

TPU shape: `instrument(root, ctx)` assigns every PlanNode/HostNode a
STABLE node id (`<ExecName>#<preorder>` — two `HashAggregateExec`s in one
plan keep separate counters instead of merging by class name) and wraps
its execute stream with wall-time + row + batch counters, keyed both
per-node-id (`HashAggregateExec#3.op_time_ms`) and aggregated per class
(`HashAggregateExec.op_time_ms`, the pre-node-id compatible keys).
Row counts accumulate LAZILY — a device-scalar num_rows folds into the
running device sum instead of being skipped — and coerce in the one
batched fetch at query end (plan/overrides.py), so lazy-count operators
no longer silently under-report.  Each operator also reports one span
(cat=operator) to the query tracer (obs/tracer.py) when tracing is on.
`profile_trace(conf)` wraps a query in a jax-profiler trace (the
NVTX/CUPTI analogue — open the trace in XProf/perfetto) when
`spark.rapids.tpu.profile.path` is set."""
from __future__ import annotations

import re
import time
from contextlib import contextmanager, nullcontext

from ..config import METRICS_LEVEL, PROFILE_PATH, TpuConf
from ..obs.tracer import NULL_TRACER


def _child_nodes(node):
    for c in getattr(node, "children", []):
        yield c
    for attr in ("host_child", "device_child"):
        c = getattr(node, attr, None)
        if c is not None:
            yield c


def assign_node_ids(root) -> None:
    """Preorder `<ExecName>#<i>` ids over the physical tree (device and
    host nodes).  Stable for a given plan shape; idempotent."""
    if getattr(root, "_node_id", None) is not None:
        return
    i = 0
    stack = [root]
    while stack:
        n = stack.pop()
        if getattr(n, "_node_id", None) is None:
            n._node_id = f"{type(n).__name__}#{i}"
            n._node_preorder = i
            i += 1
        # preorder: children pushed reversed so left-most pops first
        stack.extend(reversed(list(_child_nodes(n))))


def node_id_range(root):
    """(lo, hi) preorder-index range of the nodes reachable under `root`
    in the CURRENT tree — the segment's plan-addressable span.  Nodes
    without an assigned preorder (split-seam leaves swapped in after
    id assignment) are skipped, so a split segment's range covers
    exactly the original plan nodes its program traces.  (None, None)
    when nothing under root carries an id."""
    lo = hi = None
    stack = [root]
    seen = set()
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        i = getattr(n, "_node_preorder", None)
        if i is not None:
            lo = i if lo is None else min(lo, i)
            hi = i if hi is None else max(hi, i)
        stack.extend(_child_nodes(n))
    return lo, hi


def plan_node_table(root) -> list:
    """[{id, name, parent}] rows for the profile's self-time computation
    (QueryProfile.operators) — requires assign_node_ids first."""
    out = []

    def walk(n, parent):
        nid = getattr(n, "_node_id", None)
        out.append({"id": nid, "name": type(n).__name__,
                    "parent": parent})
        for c in _child_nodes(n):
            walk(c, nid)
    walk(root, None)
    return out


def _bump(metrics: dict, key: str, v):
    metrics[key] = metrics.get(key, 0) + v


def instrument(node, ctx) -> None:
    """Wrap the execute() of every node in the tree (device and host)
    with op-time / row / batch metrics.  Idempotent per node object."""
    assign_node_ids(node)
    tr = getattr(ctx, "tracer", NULL_TRACER)
    if getattr(tr, "enabled", False):
        tr.meta.setdefault("plan_nodes", plan_node_table(node))
    _instrument_node(node, ctx)


def _instrument_node(node, ctx) -> None:
    if getattr(node, "_metered", False):
        return
    node._metered = True
    name = type(node).__name__
    nid = node._node_id
    inner = node.execute

    def metered(c):
        t0 = time.perf_counter()
        rows = 0
        batches = 0
        op_ms = 0.0
        try:
            it = inner(c)
            while True:
                t1 = time.perf_counter()
                try:
                    out = next(it)
                except StopIteration:
                    return
                finally:
                    op_ms += (time.perf_counter() - t1) * 1000.0
                batches += 1
                n = getattr(out, "num_rows", None)
                if n is not None:
                    # a lazy device count folds into the running (device)
                    # sum — no sync here, ONE batched fetch at query end
                    rows = rows + n
                yield out
        finally:
            total_ms = (time.perf_counter() - t0) * 1000.0
            m = c.metrics
            for key in (nid, name):     # per-node-id + class aggregate
                _bump(m, f"{key}.op_time_ms", op_ms)
                _bump(m, f"{key}.total_time_ms", total_ms)
                _bump(m, f"{key}.output_rows", rows)
                _bump(m, f"{key}.output_batches", batches)
            tr = getattr(c, "tracer", NULL_TRACER)
            tr.add_span(name, "operator",
                        t0, t0 + total_ms / 1e3, node=nid,
                        op_time_ms=round(op_ms, 3), output_batches=batches)

    node.execute = metered
    for c in _child_nodes(node):
        _instrument_node(c, ctx)


def should_instrument(conf: TpuConf) -> bool:
    return conf.get(METRICS_LEVEL) in ("MODERATE", "DEBUG")


#: operator CLASS-aggregate metric keys (no '#' — per-node-id detail
#: stays in the query dicts; the process registry aggregates by class)
_CLASS_METRIC_RE = re.compile(
    r"^(?P<op>[A-Za-z_]\w*)\.(?P<field>op_time_ms|output_rows|"
    r"output_batches)$")


def publish_registry(ctx) -> None:
    """Fold one finished query's operator/class metrics into the
    always-on process registry (obs/registry.py) — called by the
    instrumented scope AFTER lazy device row counts coerced, so every
    value is a host number and nothing here forces a device sync.
    The per-query ctx.metrics dict stays untouched (the compat view)."""
    from ..obs.registry import (COMPILES_TOTAL, OPERATOR_BATCHES,
                                OPERATOR_ROWS, OPERATOR_TIME_MS, REGISTRY)
    if not REGISTRY.enabled:
        return
    for key, v in list(ctx.metrics.items()):
        m = _CLASS_METRIC_RE.match(key)
        if not m or not isinstance(v, (int, float)):
            continue
        op, field = m.group("op"), m.group("field")
        if field == "output_rows":
            OPERATOR_ROWS.inc(int(v), op=op)
        elif field == "output_batches":
            OPERATOR_BATCHES.inc(int(v), op=op)
        else:
            OPERATOR_TIME_MS.inc(float(v), op=op)
    hits = ctx.metrics.get("compile_cache_hits", 0)
    misses = ctx.metrics.get("compile_cache_misses", 0)
    if hits:
        COMPILES_TOTAL.inc(int(hits), outcome="hit")
    if misses:
        COMPILES_TOTAL.inc(int(misses), outcome="miss")
    # wall-decomposition plane: one observation per finished query per
    # nonzero overhead category (brackets in exec/compiled.py; dispatch
    # and pad_waste populate on profiled runs, seam is always-on)
    from ..obs.registry import OVERHEAD_MS
    for cat, key in (("dispatch", "overhead.dispatch_ms"),
                     ("seam", "overhead.seam_ms"),
                     ("pad_waste", "overhead.pad_waste_ms")):
        v = ctx.metrics.get(key)
        if isinstance(v, (int, float)) and v > 0:
            OVERHEAD_MS.observe(float(v), category=cat)


def finish_memattr(ctx) -> None:
    """Query-end half of the memory-attribution plane (obs/memattr.py),
    called from the instrumented scope after lazy metrics coerced:

      * fold the recorder's measured working set + timeline into the
        query metrics / tracer meta (the `memory.hbm_*` keys
        QueryProfile.hbm() and the history feed read);
      * the residual-leak check — ALWAYS on, one counter read: naked
        (directly reserved, non-Spillable) budget bytes still live at
        query end are a leak, flagged in the profile and counted in
        tpu_hbm_residual_bytes."""
    m = ctx.metrics
    rec = getattr(ctx, "_memattr", None)
    if rec is not None:
        summ = rec.summary()
        peak = max(int(summ["query_peak_bytes"]),
                   int(m.get("exec_hbm_bytes", 0) or 0))
        if peak:
            m["memory.hbm_measured_working_set"] = peak
        if summ["skipped"]:
            m["memory.hbm_census_skipped"] = summ["skipped"]
        if summ["events"] > 1:           # beyond the start marker
            m["memory.hbm_timeline_events"] = summ["events"]
        tr = getattr(ctx, "tracer", NULL_TRACER)
        if getattr(tr, "enabled", False):
            tr.meta["hbm_timeline"] = rec.timeline()
            tr.meta["hbm_summary"] = summ
    b = getattr(ctx, "_budget", None)
    if b is not None:
        resid = int(getattr(b, "naked_live", 0) or 0)
        if resid > 0:
            from ..obs.registry import HBM_RESIDUAL
            HBM_RESIDUAL.inc(resid)
            m["memory.residual_naked_bytes"] = resid
            getattr(ctx, "tracer", NULL_TRACER).instant(
                "hbm_leak", "runtime", bytes=resid)


def record_history(pq, ctx, wall_ms: float) -> None:
    """Feed one completed query into the persistent performance-history
    store (obs/history.py) — called at query end from
    PhysicalQuery.collect, INSIDE the crash-capture scope so the
    `history` chaos site's fatal kind produces a classified dump while
    its ioerror kind skips the entry with the query unaffected.  The
    disabled path (spark.rapids.tpu.history.dir unset) is one cached
    conf check."""
    from ..obs.history import get_store
    store = get_store(ctx.conf)
    if store is None:
        return
    store.record_query(pq, ctx, wall_ms)


@contextmanager
def profile_trace(conf: TpuConf):
    """jax profiler trace around a query when profile.path is set —
    the NVTX/nsys + built-in Profiler analogue (SURVEY §5 tracing)."""
    path = conf.get(PROFILE_PATH)
    if not path:
        with nullcontext():
            yield
        return
    import jax
    with jax.profiler.trace(path):
        yield
