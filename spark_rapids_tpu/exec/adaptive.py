"""Adaptive (runtime-statistics) execution: the engine's AQE analogue.

Reference: the plugin's AQE integration re-applies overrides per query
stage with real sizes in hand (GpuOverrides.scala:496-564,
GpuCustomShuffleReaderExec.scala:37), and
GpuShuffledSymmetricHashJoinExec.scala:354 probes both join inputs'
sizes at runtime to pick the build side.  Spark can do this because a
shuffle stage fully materializes before the next stage is planned.

This engine's plans are single-process pipelines, so the same two
runtime decisions attach directly to the operators that need them:

- `AdaptiveShuffledJoinExec` materializes BOTH join inputs as spillable
  stages (exactly what completed map stages are), measures real bytes,
  and builds the hash table on the smaller side — mirroring the join
  type when that swaps the inputs and restoring the original column
  order on output.
- `plan_coalesced_reads` groups a materialized exchange's partitions to
  an advisory byte target using the shuffle manager's real per-partition
  sizes (the GpuAQEShuffleRead / coalesced CustomShuffleReader role).
"""
from __future__ import annotations

from typing import Iterator, List, Sequence

from .. import types as t
from ..columnar.device import DeviceBatch
from ..plan import expressions as E
from ..runtime.memory import Spillable
from .join import HashJoinExec
from .plan import ExecContext, PlanNode

_MIRROR = {"inner": "inner", "left_outer": "right_outer",
           "right_outer": "left_outer", "full_outer": "full_outer"}


class _ReplayStage(PlanNode):
    """A completed, spillable 'stage' the re-planned join replays."""

    def __init__(self, batches: List[Spillable], schema: t.StructType,
                 source: PlanNode = None):
        super().__init__()
        self.batches = batches
        self._schema = schema
        self._source = source      # statistics delegate (keys_unique)

    @property
    def output_schema(self) -> t.StructType:
        return self._schema

    def keys_unique(self, names):
        # replay preserves exactly the source's rows
        return self._source is not None and self._source.keys_unique(names)

    def column_range(self, name):
        return None if self._source is None \
            else self._source.column_range(name)

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        for sp in self.batches:
            yield sp.get()

    def describe(self):
        return f"ReplayStage[{len(self.batches)} batches]"


class _BloomFilterStage(PlanNode):
    """Probe-side runtime filter: drop rows whose join key is DEFINITELY
    absent from the build side (ops/bloom.py).  Only wrapped around
    joins where unmatched probe rows never reach the output."""

    def __init__(self, child: PlanNode, bits, key_cols_fn, k: int,
                 key_exprs=None):
        super().__init__(child)
        self.bits = bits
        self.key_cols_fn = key_cols_fn
        self.k = k
        self.key_exprs = list(key_exprs or [])

    @property
    def output_schema(self) -> t.StructType:
        return self.child.output_schema

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        from ..ops.bloom import bloom_might_contain
        from ..ops.filter import compact_batch
        import jax.numpy as jnp
        for db in self.child.execute(ctx):
            if db.thin is not None and self.key_exprs:
                # a THIN probe stream: the bloom probe needs dense key
                # columns — materialize exactly those; payload lanes
                # stay live (the wrapped join composes them)
                from ..columnar.lanes import materialize_refs
                db = materialize_refs(db, self.key_exprs, ctx.conf)
            mask = bloom_might_contain(self.bits, self.key_cols_fn(db),
                                       db, self.k) & db.row_mask()
            if db.thin is not None:
                # preserve the lanes: compose the bloom verdict into the
                # selection vector instead of compacting (a compaction is
                # the row-gather pass late materialization exists to skip)
                ctx.bump("bloom_filtered_rows",
                         jnp.int64(db.num_rows) -
                         jnp.sum(mask, dtype=jnp.int64))
                yield DeviceBatch(list(db.columns),
                                  jnp.sum(mask, dtype=jnp.int32),
                                  db.names, db.origin_file, sel=mask,
                                  thin=db.thin)
                continue
            out = compact_batch(db, mask, ctx.conf)
            # lazy metric: accumulate on device, coerced ONCE at query end
            # (PhysicalQuery._instrumented) instead of a sync per batch
            ctx.bump("bloom_filtered_rows",
                     jnp.int64(db.num_rows) - jnp.int64(out.num_rows))
            yield out

    def describe(self):
        return f"BloomFilterStage[k={self.k}]"


class AdaptiveShuffledJoinExec(PlanNode):
    """Equi-join whose build side is chosen from measured input sizes.

    Output schema and semantics are identical to
    HashJoinExec(join_type, ...) — the mirror swap is invisible outside
    (columns are restored to left-then-right order)."""

    def __init__(self, join_type: str, left_keys: Sequence[E.Expression],
                 right_keys: Sequence[E.Expression],
                 left: PlanNode, right: PlanNode):
        super().__init__(left, right)
        self.join_type = join_type
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.lazy_sel = False      # forwarded to the inner HashJoinExec
        # late-materialization allowance (plan/overrides.py
        # _negotiate_thin), forwarded to the inner HashJoinExec; the
        # mirror swap is invisible (thin state remaps through select)
        self.thin_payload = None

    @property
    def left(self) -> PlanNode:
        return self.children[0]

    @property
    def right(self) -> PlanNode:
        return self.children[1]

    @property
    def output_schema(self) -> t.StructType:
        lf = list(self.left.output_schema.fields)
        if self.join_type in ("left_semi", "left_anti"):
            return t.StructType(lf)
        return t.StructType(lf + list(self.right.output_schema.fields))

    @staticmethod
    def _side_unique(keys, side) -> bool:
        from .join import key_ref_names
        kn = key_ref_names(keys)
        return kn is not None and side.keys_unique(kn)

    def keys_unique(self, names):
        from .join import join_keys_unique
        return join_keys_unique(self.join_type, self.left, self.right,
                                self.left_keys, self.right_keys, names)

    def column_range(self, name):
        from .join import join_column_range
        return join_column_range(self.join_type, self.left, self.right,
                                 name)

    def _materialize(self, node: PlanNode, ctx: ExecContext
                     ) -> List[Spillable]:
        # no per-batch row-count sync: empty batches are padding-only and
        # byte sizing below uses capacity-based nbytes (host-known)
        return [Spillable(db, ctx.budget) for db in node.execute(ctx)
                if not (isinstance(db.num_rows, int) and db.num_rows == 0)]

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        left_stage: List[Spillable] = []
        right_stage: List[Spillable] = []
        # Fuse upstream filters (HashJoinExec._peel_filters): stages hold
        # the RAW child batches and the predicates ride into the join as
        # probe/build masks — no mask compaction on either input.  Byte
        # sizes below are therefore PRE-filter sizes; the build-side
        # choice only shifts when filters are both selective and skewed
        # between sides, and correctness never depends on it.
        left_src, left_conds = HashJoinExec._peel_filters(self.left)
        right_src, right_conds = HashJoinExec._peel_filters(self.right)
        try:
            left_stage = self._materialize(left_src, ctx)
            right_stage = self._materialize(right_src, ctx)
            lbytes = sum(sp._nbytes for sp in left_stage)
            rbytes = sum(sp._nbytes for sp in right_stage)
            ctx.metrics["adaptive_left_bytes"] = lbytes
            ctx.metrics["adaptive_right_bytes"] = rbytes
            swap = (self.join_type in _MIRROR) and lbytes < rbytes
            if self.join_type in _MIRROR:
                # A UNIQUE-keyed build side unlocks the sync-free aligned
                # probe (exec/join.py) — worth more than raw size unless
                # the unique side is dramatically bigger (8x guard).
                run_u = self._side_unique(self.right_keys, self.right)
                lun_u = self._side_unique(self.left_keys, self.left)
                if run_u != lun_u:
                    if lun_u and lbytes <= 8 * max(rbytes, 1):
                        swap = True
                    elif run_u and rbytes <= 8 * max(lbytes, 1):
                        swap = False
            if swap:
                ctx.bump("adaptive_join_mirrored")
                jt = _MIRROR[self.join_type]
                join = HashJoinExec(
                    jt, self.right_keys, self.left_keys,
                    _ReplayStage(right_stage,
                                 self.right.output_schema, self.right),
                    _ReplayStage(left_stage, self.left.output_schema,
                                 self.left),
                    probe_conds=right_conds, build_conds=left_conds)
                join.lazy_sel = self.lazy_sel
                join.thin_payload = self.thin_payload
                self._maybe_bloom(join, jt, left_stage,
                                  max(rbytes, 1), lbytes, ctx)
                n_r = len(self.right.output_schema.fields)
                n_l = len(self.left.output_schema.fields)
                # mirrored output is right-cols ++ left-cols; restore
                perm = list(range(n_r, n_r + n_l)) + list(range(n_r))
                for db in join.execute(ctx):
                    yield db.select(perm)
            else:
                join = HashJoinExec(
                    self.join_type, self.left_keys, self.right_keys,
                    _ReplayStage(left_stage, self.left.output_schema,
                                 self.left),
                    _ReplayStage(right_stage,
                                 self.right.output_schema, self.right),
                    probe_conds=left_conds, build_conds=right_conds)
                join.lazy_sel = self.lazy_sel
                join.thin_payload = self.thin_payload
                self._maybe_bloom(join, self.join_type, right_stage,
                                  max(lbytes, 1), rbytes, ctx)
                yield from join.execute(ctx)
        finally:
            for sp in left_stage + right_stage:
                sp.close()

    def _maybe_bloom(self, join: HashJoinExec, effective_jt: str,
                     build_stage: List[Spillable], probe_bytes: int,
                     build_bytes: int, ctx: ExecContext) -> None:
        """Install a probe-side bloom runtime filter when profitable.

        Safe only where unmatched PROBE rows never reach the output
        (inner/left_semi: dropped anyway; right_outer: output = matched
        probe + all build rows).  left/full outer must keep unmatched
        probe rows null-extended, anti must OUTPUT them — never
        filtered."""
        from ..config import RUNTIME_FILTER_ENABLED, RUNTIME_FILTER_RATIO
        if effective_jt not in ("inner", "right_outer", "left_semi"):
            return
        if not ctx.conf.get(RUNTIME_FILTER_ENABLED):
            return
        if probe_bytes < build_bytes * ctx.conf.get(RUNTIME_FILTER_RATIO):
            return
        from .join import key_ref_names
        build_rows = sum(sp.num_rows for sp in build_stage)
        rn = key_ref_names(join.right_keys)
        if rn is not None and len(rn) == 1 and \
                key_ref_names(join.left_keys) is not None and \
                build_rows <= 2 * ctx.conf.batch_size_rows:
            # (sub-partitioned builds never make one dense table, so the
            # skip only applies on the single-batch path)
            rng = join.right.column_range(rn[0])
            if rng is not None and HashJoinExec._span_fits(
                    int(rng[1]) - int(rng[0]) + 1, max(build_rows, 1)):
                # the join will probe a dense direct-address table (two
                # gathers per batch) — a bloom pass costs a full probe
                # compaction, more than it can save there
                return
        from ..config import RUNTIME_FILTER_FPP
        from ..ops.bloom import (bloom_build, optimal_hashes,
                                 optimal_slots)
        m = optimal_slots(build_rows, fpp=ctx.conf.get(RUNTIME_FILTER_FPP))
        k = optimal_hashes(build_rows, m)
        raw_pos = join._raw_key_positions()
        bits = None
        for sp in build_stage:
            bb = sp.get()
            # fused build filters must mask insertion, else the bloom
            # keeps the keys the filter was meant to remove
            live = None
            if join.build_conds:
                live = join._conds_mask(join.build_conds, bb,
                                        bb.row_mask(), ctx)
            bits = bloom_build(
                join._key_cols(bb, join.right_keys, raw_pos, ctx),
                bb, m, k, bits, live=live)

        def probe_keys(db):
            return join._key_cols(db, join.left_keys, raw_pos, ctx)

        # the probe child was just constructed by execute(); wrapping it
        # here keeps key binding (done in HashJoinExec.__init__) intact
        join.children[0] = _BloomFilterStage(
            join.children[0], bits, probe_keys, k,
            key_exprs=join.left_keys)
        ctx.metrics["bloom_filter_slots"] = m

    def describe(self):
        return f"AdaptiveShuffledJoinExec[{self.join_type}]"


def plan_coalesced_reads(exchange, ctx: ExecContext,
                         advisory_bytes: int) -> List[List]:
    """Group a materialized exchange's partitions so each reduce group is
    ~advisory_bytes, from REAL map-output sizes (order preserved: range
    partitions stay contiguous).

    SKEWED partitions — stored bytes above skewedPartitionFactor x the
    median AND the advisory size — split into multiple independent
    sub-read units instead of coalescing (the reference's
    GpuCustomShuffleReaderExec skew reads, which slice one hot
    partition's map outputs across several reduce tasks; a join above
    streams probe batches, so each sub-read joins against the full
    build side exactly as Spark's skew-join sub-tasks do).

    Read units are partition ids, or (partition, block_lo, block_hi)
    map-block slices for split partitions."""
    import statistics
    from ..config import ADAPTIVE_SKEW_FACTOR
    from ..shuffle.manager import get_shuffle_manager
    sid = exchange.materialize(ctx)
    mgr = get_shuffle_manager()
    sizes = mgr.partition_sizes(sid)
    n = exchange.partitioning.num_partitions
    factor = float(ctx.conf.get(ADAPTIVE_SKEW_FACTOR))
    nonzero = sorted(b for b in sizes.values() if b) or [0]
    median = statistics.median(nonzero)
    skew_threshold = max(advisory_bytes, factor * median) \
        if factor > 0 else float("inf")

    groups: List[List] = []
    cur: List = []
    cur_bytes = 0
    skew_splits = 0
    for p in range(n):
        b = sizes.get(p, 0)
        if b > skew_threshold:
            blocks = mgr.block_sizes(sid, p)
            if len(blocks) > 1:
                if cur:
                    groups.append(cur)
                    cur, cur_bytes = [], 0
                nsub = 0
                lo = 0
                acc = 0
                for i, bb in enumerate(blocks):
                    if acc and acc + bb > advisory_bytes:
                        groups.append([(p, lo, i)])
                        nsub += 1
                        lo, acc = i, 0
                    acc += bb
                groups.append([(p, lo, len(blocks))])
                nsub += 1
                if nsub > 1:        # an actual split, not a solo group
                    skew_splits += 1
                continue
            # single stored block: nothing to slice — solo group below
        if cur and cur_bytes + b > advisory_bytes:
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(p)
        cur_bytes += b
    if cur:
        groups.append(cur)
    ctx.metrics["adaptive_coalesced_groups"] = len(groups)
    if skew_splits:
        ctx.metrics["adaptive_skew_split_partitions"] = skew_splits
        # always-on plane: skew mitigation engaged (the reduce-side
        # counterpart of the mesh exchange's exchange_skew_split)
        ctx.tracer.instant("shuffle_skew_split", "shuffle",
                           partitions=skew_splits)
    return groups
