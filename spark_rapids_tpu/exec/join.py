"""Join exec nodes over the sorted-hash probe kernels (ops/join.py).

Reference execs: GpuShuffledHashJoinExec (GpuShuffledHashJoinExec.scala:107),
GpuBroadcastHashJoinExecBase, GpuHashJoin gather machinery
(org/.../execution/GpuHashJoin.scala:104).  Output schema is
left columns ++ right columns (Spark layout); the build side is fully
materialized (concat of the build stream), probes stream batch-by-batch —
the same shape as the reference's build-then-stream iterator.
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp
import pyarrow as pa

from .. import types as t
from ..columnar.device import DeviceBatch, DeviceColumn, bucket_capacity
from ..ops import join as J
from ..ops.batch_ops import concat_batches, ensure_unique_dict, \
    remap_codes_into
from ..ops.filter import compact_batch, gather_batch
from ..plan import expressions as E
from .evaluator import evaluate_projection
from .plan import ExecContext, PlanNode


def _null_columns(schema: t.StructType, capacity: int) -> List[DeviceColumn]:
    cols = []
    for f in schema.fields:
        dt = f.data_type
        np_dt = jnp.int64 if isinstance(dt, t.DoubleType) \
            else t.physical_np_dtype(dt)
        cols.append(DeviceColumn(jnp.zeros((capacity,), np_dt),
                                 jnp.zeros((capacity,), bool), dt))
    return cols


def key_ref_names(exprs) -> Optional[List[str]]:
    """Column names when every key expression is a plain (possibly
    aliased) column reference, else None.  Shared by HashJoinExec and
    AdaptiveShuffledJoinExec so the aligned-path legality rule cannot
    drift between them."""
    names = []
    for e in exprs:
        inner = e.children[0] if isinstance(e, E.Alias) else e
        if not isinstance(inner, E.ColumnRef):
            return None
        names.append(inner.name)
    return names


def join_keys_unique(join_type: str, left, right, left_keys, right_keys,
                     names) -> bool:
    """Shared statistics-propagation rule for equi-join operators
    (HashJoinExec and the adaptive planner wrap the same semantics):
    semi/anti keep a subset of left rows; otherwise a side's columns stay
    unique iff that side was unique AND the other side's join keys are
    unique (each row matched at most once)."""
    def side_unique(keys, side):
        kn = key_ref_names(keys)
        return kn is not None and side.keys_unique(kn)

    if join_type in (J.LEFT_SEMI, J.LEFT_ANTI):
        return left.keys_unique(names)
    left_names = set(left.output_schema.names)
    if all(n in left_names for n in names):
        return left.keys_unique(names) and side_unique(right_keys, right)
    right_names = set(right.output_schema.names)
    if all(n in right_names for n in names):
        return right.keys_unique(names) and side_unique(left_keys, left)
    return False


def join_column_range(join_type: str, left, right, name):
    """Shared value-range propagation: joins gather existing rows, so a
    column's range only narrows (outer-join nulls are not values)."""
    if name in left.output_schema.names:
        return left.column_range(name)
    if join_type not in (J.LEFT_SEMI, J.LEFT_ANTI) and \
            name in right.output_schema.names:
        return right.column_range(name)
    return None


def _join_partition_ids(key_cols: List[DeviceColumn], db: DeviceBatch,
                        num_buckets: int, salt: int = 0) -> jax.Array:
    """Bucket ids from join-key columns; value-stable across sides and
    batches (reuses the agg fallback's lane-normalized hash).  `salt`
    decorrelates recursive re-partitions of a skewed bucket — the same
    hash would map the bucket onto itself."""
    from .plan import _agg_partition_ids
    kb = DeviceBatch(list(key_cols), db.num_rows,
                     [f"_k{i}" for i in range(len(key_cols))])
    return _agg_partition_ids(kb, len(key_cols), num_buckets, salt)


class HashJoinExec(PlanNode):
    """Equi-join: inner / left|right|full outer / left semi / left anti.

    The RIGHT side is the build side (callers swap inputs to choose, as the
    reference's GpuJoinUtils.getGpuBuildSide does)."""

    def __init__(self, join_type: str, left_keys: Sequence[E.Expression],
                 right_keys: Sequence[E.Expression],
                 left: PlanNode, right: PlanNode,
                 probe_conds: Optional[List[E.Expression]] = None,
                 build_conds: Optional[List[E.Expression]] = None):
        super().__init__(left, right)
        self.join_type = join_type
        self.left_keys = [e.bind(left.output_schema) for e in left_keys]
        self.right_keys = [e.bind(right.output_schema) for e in right_keys]
        assert len(self.left_keys) == len(self.right_keys)
        # pre-fused filter predicates (see _peel_filters): evaluated as
        # masks on raw input batches instead of upstream compactions
        # lazy_sel: a mask-aware parent (negotiated by the overrides
        # post-pass) lets this join emit a selection vector instead of
        # compacting its output
        self.lazy_sel = False
        # LATE MATERIALIZATION (columnar/lanes.py): output column names
        # the parent pipeline allows to ride as row-id lanes instead of
        # gathered payloads.  None = disabled; set by the overrides
        # legality pass (_negotiate_thin) only when every consumer up to
        # the pipeline sink handles thin batches.
        self.thin_payload = None
        self.probe_conds = list(probe_conds or [])
        self.build_conds = list(build_conds or [])
        if join_type not in (INNER_TYPES := {J.INNER, J.LEFT_OUTER,
                                             J.RIGHT_OUTER, J.FULL_OUTER,
                                             J.LEFT_SEMI, J.LEFT_ANTI}):
            raise ValueError(f"unsupported join type {join_type}")

    @property
    def left(self) -> PlanNode:
        return self.children[0]

    @property
    def right(self) -> PlanNode:
        return self.children[1]

    @property
    def output_schema(self) -> t.StructType:
        lf = list(self.left.output_schema.fields)
        if self.join_type in (J.LEFT_SEMI, J.LEFT_ANTI):
            return t.StructType(lf)
        rf = list(self.right.output_schema.fields)
        return t.StructType(lf + rf)

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _plain_ref(e: E.Expression):
        inner = e.children[0] if isinstance(e, E.Alias) else e
        return inner if isinstance(inner, E.ColumnRef) else None

    def _raw_key_positions(self) -> List[bool]:
        """Key positions where BOTH sides are plain column references: those
        keys stay on their raw storage lanes (for DOUBLE that is the
        bit-exact int64 lane — projecting would force the lossy native-f64
        compute representation, the round-1 ADVICE.md defect).  Both sides
        must agree so build/probe lane encodings match."""
        out = []
        for le, re_ in zip(self.left_keys, self.right_keys):
            out.append(self._plain_ref(le) is not None and
                       self._plain_ref(re_) is not None)
        return out

    def _key_cols(self, db: DeviceBatch, exprs, raw_pos, ctx
                  ) -> List[DeviceColumn]:
        cols: List[Optional[DeviceColumn]] = [None] * len(exprs)
        proj_exprs, proj_slots = [], []
        for i, (e, raw) in enumerate(zip(exprs, raw_pos)):
            if raw:
                cols[i] = db.column_by_name(self._plain_ref(e).name)
            else:
                proj_exprs.append(e)
                proj_slots.append(i)
        if proj_exprs:
            kb = evaluate_projection(
                proj_exprs, [f"_k{i}" for i in proj_slots], db, ctx.conf)
            for slot, c in zip(proj_slots, kb.columns):
                cols[slot] = c
        return cols

    def keys_unique(self, names: Sequence[str]) -> bool:
        return join_keys_unique(self.join_type, self.left, self.right,
                                self.left_keys, self.right_keys, names)

    def _build_unique(self) -> bool:
        names = key_ref_names(self.right_keys)
        return names is not None and self.right.keys_unique(names)

    def _probe_unique(self) -> bool:
        names = key_ref_names(self.left_keys)
        return names is not None and self.left.keys_unique(names)

    def column_range(self, name: str):
        return join_column_range(self.join_type, self.left, self.right,
                                 name)

    def _range_pack_spec(self):
        """([(lo, stride)] per key column, total span) when the composite
        key can fold into ONE injective int64 lane from exact
        column-range statistics (min/max over BOTH sides), else None.
        Packed lane values lie in [0, total) — a ready-made dense domain.
        Gives multi-column joins the exact single-lane probe paths (no
        composite-hash collisions, no sizing sync)."""
        ln = key_ref_names(self.left_keys)
        rn = key_ref_names(self.right_keys)
        if ln is None or rn is None or len(ln) < 2:
            return None
        spans = []
        for l, r in zip(ln, rn):
            lr = self.left.column_range(l)
            rr = self.right.column_range(r)
            if lr is None or rr is None:
                return None
            lo = min(lr[0], rr[0])
            hi = max(lr[1], rr[1])
            spans.append((lo, hi - lo + 1))
        total = 1
        for _lo, span in spans:
            total *= span
            if total >= (1 << 62):
                return None
        spec = []
        stride = 1
        for lo, span in reversed(spans):
            spec.append((lo, stride))
            stride *= span
        spec.reverse()
        return spec, total

    @staticmethod
    def _span_fits(span: int, build_capacity: int) -> bool:
        """Direct-address-table sizing policy, shared by the single-key
        and packed-composite-key dense gates."""
        return span <= max(16 * build_capacity, 1 << 20) and \
            span <= (1 << 26)

    def _dense_domain(self, build_keys, build_capacity: int):
        """(lo, hi) covering every valid BUILD key, for single-key joins
        whose span is bounded enough for a direct-address table:
        dictionary size for strings (codes are dense by construction),
        exact scan statistics for integer-lane types.  None otherwise."""
        if len(self.right_keys) != 1:
            return None
        c = build_keys[0]
        if isinstance(c.dtype, t.StringType):
            if c.dictionary is None:
                return None
            span = max(len(c.dictionary), 1)
            lo, hi = 0, span - 1
        else:
            rn = key_ref_names(self.right_keys)
            if rn is None or key_ref_names(self.left_keys) is None:
                return None
            rng = self.right.column_range(rn[0])
            if rng is None:
                return None
            lo, hi = int(rng[0]), int(rng[1])
            span = hi - lo + 1
        if not self._span_fits(span, build_capacity):
            return None
        return lo, hi

    @staticmethod
    def _packed_lane(key_cols, spec) -> jax.Array:
        """Fold per-column int64 canonical lanes into the packed lane."""
        packed = None
        for c, (lo, stride) in zip(key_cols, spec):
            lane = c.data.astype(jnp.int64)
            part = (lane - jnp.int64(lo)) * jnp.int64(stride)
            packed = part if packed is None else packed + part
        return packed

    @staticmethod
    def _peel_filters(node: PlanNode):
        """Peel the chain of FilterExec children a join can fuse; returns
        (batch source node, conditions outermost-last).  Mirrors
        HashAggregateExec._strip_filters: the predicates become probe /
        build liveness masks instead of upstream mask compactions (a TPU
        compaction is an argsort + row gathers — far costlier than a
        fused mask lane)."""
        from .plan import FilterExec
        conds: List[E.Expression] = []
        while isinstance(node, FilterExec):
            conds.append(node.condition)
            node = node.child
        conds.reverse()
        return node, conds

    @staticmethod
    def _conds_mask(conds, db: DeviceBatch, base, ctx: ExecContext):
        """AND the fused predicates into a row mask over `db`."""
        from .evaluator import compute_predicate
        for c in conds:
            base = base & compute_predicate(c, db, ctx.conf)
        return base

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        right_src, peeled = self._peel_filters(self.right)
        build_conds = list(self.build_conds) + peeled
        left_src, peeled = self._peel_filters(self.left)
        probe_conds = list(self.probe_conds) + peeled
        # ---- build (right side), fully materialized ----
        # No per-batch row-count sync: empty batches are harmless (padding
        # only) and the sub-partition gate sizes by capacity, which bounds
        # rows from above without a D2H round trip.
        right_batches = [db for db in right_src.execute(ctx)
                         if db.capacity > 0 and not
                         (isinstance(db.num_rows, int) and db.num_rows == 0)]
        if not right_batches:
            yield from self._empty_build_output(left_src, probe_conds, ctx)
            return

        from ..config import HASH_SUBPARTITION_FALLBACK
        from . import ooc as O
        build_rows_bound = sum(b.capacity for b in right_batches)
        if ctx.conf.get(HASH_SUBPARTITION_FALLBACK):
            # Oversized build side: re-hash-partition BOTH sides into
            # independent sub-joins (GpuSubPartitionHashJoin.scala:32) —
            # equal keys hash to the same bucket on both sides, so the
            # union of bucket joins is the join.  The gate sizes by
            # BYTES against the out-of-core resident window (measured
            # row width from the batches — wide payload rows used to
            # blow past the row count before it tripped), with the
            # legacy 2-target-batch row gate kept as the floor and the
            # escalated/forced context tripping unconditionally.
            policy = O.ooc_policy(ctx)
            rows_trip = build_rows_bound > 2 * ctx.conf.batch_size_rows
            bytes_trip = policy.bytes_trip(
                sum(b.nbytes() for b in right_batches))
            if rows_trip or bytes_trip or policy.force:
                build_rows = sum(int(b.num_rows) for b in right_batches)
                build_bytes = sum(O.batch_bytes(b) for b in right_batches)
                if build_rows > 2 * ctx.conf.batch_size_rows or \
                        policy.bytes_trip(build_bytes) or policy.force:
                    yield from self._sub_partition_join(
                        right_batches, left_src, build_conds, probe_conds,
                        ctx, policy)
                    return
                right_batches = [b for b in right_batches
                                 if int(b.num_rows)]
                if not right_batches:
                    yield from self._empty_build_output(
                        left_src, probe_conds, ctx)
                    return

        build_batch = concat_batches(right_batches, ctx.conf)
        yield from self._join_stream(build_batch, left_src.execute(ctx),
                                     ctx, build_conds, probe_conds)

    def _sub_partition_join(self, right_batches, left_src, build_conds,
                            probe_conds, ctx: ExecContext, policy=None
                            ) -> Iterator[DeviceBatch]:
        """Budget-sized partitioned-spill join (the out-of-core tier):
        both sides hash-scatter into budget-registered spillable
        buckets; the partition count derives from measured build BYTES
        vs the resident window (exec/ooc.py), and a bucket whose build
        side still exceeds the window re-partitions recursively with a
        re-salted hash (bounded depth) so key skew cannot OOM it —
        past the depth bound the split-retry ladder owns the rest."""
        from ..runtime.memory import Spillable
        from . import ooc as O
        conf = ctx.conf
        if policy is None:
            policy = O.ooc_policy(ctx)
        build_rows = sum(int(b.num_rows) for b in right_batches)
        build_bytes = sum(O.batch_bytes(b) for b in right_batches)
        # legacy row-derived fan-out floors the byte-derived count so
        # budget-less configurations keep their old partition sizing
        rows_k = 1 << max(1, (build_rows // conf.batch_size_rows)
                          .bit_length() - 1)
        rows_k = min(rows_k, 32)
        k = O.partition_count(build_bytes, policy, rows_k=rows_k)
        ctx.bump("join_subpartition_fallbacks")
        O.record_election(
            ctx, "join",
            "bytes" if policy.bytes_trip(build_bytes) else
            ("forced" if policy.force and
             build_rows <= 2 * conf.batch_size_rows else "rows"))

        raw_pos = self._raw_key_positions()

        def scatter(db, exprs, conds, buckets, nparts, salt) -> int:
            if db.thin is not None:
                # key/condition columns must be dense before bucketing;
                # remaining deferred columns resolve inside the bucket
                # compaction (compact_thin — one composed gather)
                from ..columnar.lanes import materialize_refs
                db = materialize_refs(db, list(exprs) + list(conds),
                                      ctx.conf)
            keys = self._key_cols(db, exprs, raw_pos, ctx)
            ids = _join_partition_ids(keys, db, nparts, salt)
            # fused filters apply here — bucket batches are post-filter,
            # so the bucket joins run with no conds
            live = self._conds_mask(conds, db, db.row_mask(), ctx)
            scattered = 0
            for p in range(nparts):
                part = compact_batch(db, (ids == p) & live, ctx.conf)
                from ..ops.batch_ops import shrink_to_rows
                part = shrink_to_rows(part, int(part.num_rows), ctx.conf)
                if int(part.num_rows):
                    sp = Spillable(part, ctx.budget)
                    # live-row-scaled size rides the handle: bucket
                    # recursion must size by actual rows, not the
                    # min-bucket capacity padding of many tiny slices
                    sp.live_nbytes = O.batch_bytes(part)
                    buckets[p].append(sp)
                    scattered += sp.live_nbytes
            return scattered

        def process(bl, pl, depth):
            """Join one (build, probe) bucket pair, re-partitioning
            recursively while its build side exceeds the window."""
            if not bl and not pl:
                return
            bucket_bytes = sum(getattr(sp, "live_nbytes", sp.nbytes)
                               for sp in bl)
            if bl and policy.bytes_trip(bucket_bytes) and \
                    depth < policy.max_depth and \
                    sum(sp.num_rows for sp in bl) > 1:
                # skewed bucket: re-salted recursive re-partition
                O.record_recursion(ctx, "join")
                k2 = O.partition_count(bucket_bytes, policy)
                sub_b = [[] for _ in range(k2)]
                sub_p = [[] for _ in range(k2)]
                try:
                    sbytes = 0
                    for sp in bl:
                        b = sp.get()
                        sp.close()
                        sbytes += scatter(b, self.right_keys, (), sub_b,
                                          k2, depth + 1)
                    for sp in pl:
                        b = sp.get()
                        sp.close()
                        sbytes += scatter(b, self.left_keys, (), sub_p,
                                          k2, depth + 1)
                    O.record_partitions(ctx, "join", k2, sbytes)
                    for p in range(k2):
                        if not sub_b[p] and not sub_p[p]:
                            continue
                        O.fire(ctx, "join", bucket=p, k=k2,
                               depth=depth + 1)
                        yield from process(sub_b[p], sub_p[p], depth + 1)
                finally:
                    for part in sub_b + sub_p:
                        for sp in part:
                            sp.close()
                return

            def probes():
                for sp in pl:
                    b = sp.get()
                    sp.close()
                    yield b
            if not bl:
                if self.join_type in (J.INNER, J.LEFT_SEMI,
                                      J.RIGHT_OUTER):
                    # nothing to emit: release without re-uploading
                    for sp in pl:
                        sp.close()
                    return
                # empty build bucket: the empty-build rule decides
                yield from self._empty_build_stream(probes(), ctx)
                return
            bbs = [sp.get() for sp in bl]
            build_batch = concat_batches(bbs, ctx.conf) \
                if len(bbs) > 1 else bbs[0]
            for sp in bl:
                sp.close()
            yield from self._join_stream(build_batch, probes(), ctx)

        build_parts = [[] for _ in range(k)]
        probe_parts = [[] for _ in range(k)]
        try:
            sbytes = 0
            for db in right_batches:
                sbytes += scatter(db, self.right_keys, build_conds,
                                  build_parts, k, 0)
            for db in left_src.execute(ctx):
                if int(db.num_rows) == 0:
                    continue
                sbytes += scatter(db, self.left_keys, probe_conds,
                                  probe_parts, k, 0)
            O.record_partitions(ctx, "join", k, sbytes)
            for p in range(k):
                bl, pl = build_parts[p], probe_parts[p]
                if not bl and not pl:
                    continue
                O.fire(ctx, "join", bucket=p, k=k, depth=0)
                yield from process(bl, pl, 0)
        finally:
            # early generator abandonment (e.g. LIMIT above the join) must
            # not leak registered spillables / disk spill files; close is
            # idempotent by contract (runtime/memory.py), so handles the
            # bucket loop already consumed release nothing twice
            for part in build_parts + probe_parts:
                for sp in part:
                    sp.close()

    # -- late materialization helpers --------------------------------------

    def _thin_transparent(self) -> bool:
        """Whether this join can carry a THIN probe stream through (pass
        lanes along / compose them) instead of materializing on entry."""
        return self.thin_payload is not None and self.join_type in (
            J.INNER, J.LEFT_OUTER, J.LEFT_SEMI, J.LEFT_ANTI)

    def _defer_right(self) -> List[int]:
        """Right-side column indices this join defers behind a build
        row-id lane (inner/left-outer only: their null-extension falls
        out of the -1 lane; right/full outer emit a dense build tail)."""
        if self.thin_payload is None or \
                self.join_type not in (J.INNER, J.LEFT_OUTER):
            return []
        return [j for j, f in enumerate(self.right.output_schema.fields)
                if f.name in self.thin_payload]

    def _prep_probe(self, pb: DeviceBatch, probe_conds,
                    ctx: ExecContext) -> DeviceBatch:
        """Normalize an incoming probe batch for this join: a thin batch
        materializes fully unless this join is thin-transparent; a
        transparent join still forces early materialization of exactly
        the deferred columns its keys/conditions reference, plus any
        pending column the parent pipeline disallowed."""
        if pb.thin is None:
            return pb
        from ..columnar.lanes import materialize_batch, materialize_refs
        if not self._thin_transparent():
            return materialize_batch(pb, ctx.conf)
        pb = materialize_refs(pb, list(self.left_keys) + list(probe_conds),
                              ctx.conf)
        if pb.thin is not None:
            allowed = self.thin_payload
            bad = [p for p in pb.thin.pending
                   if pb.names[p] not in allowed]
            if bad:
                ctx.bump("join_thin_early_materialized", len(bad))
                pb = materialize_batch(pb, ctx.conf, bad)
        return pb

    @staticmethod
    def _make_thin(out_capacity: int, probe_thin, build_batch, build_lane,
                   defer_right, nleft: int, probe_sources=None):
        """ThinState for a join output: probe-side lane sources ride
        through (pass-through, or pre-composed through the pair
        expansion), the build side appends one new source addressed by
        `build_lane`.  None when nothing ends up pending."""
        from ..columnar.lanes import LaneSource, ThinState
        sources = list(probe_sources if probe_sources is not None
                       else (probe_thin.sources if probe_thin else []))
        pending = dict(probe_thin.pending) if probe_thin else {}
        if defer_right:
            ord_b = len(sources)
            sources.append(LaneSource(build_batch, build_lane))
            for j in defer_right:
                pending[nleft + j] = (ord_b, j)
        if not pending:
            return None
        return ThinState(out_capacity, sources, pending)

    def _join_stream(self, build_batch: DeviceBatch, probe_iter,
                     ctx: ExecContext, build_conds=(), probe_conds=()
                     ) -> Iterator[DeviceBatch]:
        raw_pos = self._raw_key_positions()
        build_keys = self._key_cols(build_batch, self.right_keys, raw_pos,
                                    ctx)
        # fused build-side filters: rows failing them never match and
        # never surface as outer-unmatched
        build_pre = self._conds_mask(build_conds, build_batch,
                                     build_batch.row_mask(), ctx)
        # String build keys: dedupe their dictionaries ONCE; probe batches
        # remap into the build code space (-1 for strings the build side
        # never saw), so the build sort below happens once per join, not
        # once per probe batch.
        has_str = [isinstance(c.dtype, t.StringType) for c in build_keys]
        for i, s in enumerate(has_str):
            if s:
                build_keys[i] = ensure_unique_dict(build_keys[i])
        # Composite keys with exact range statistics fold into one
        # injective int64 lane — single-lane probe paths apply.
        pack_and_span = self._range_pack_spec() if all(raw_pos) else None
        pack, pack_span = pack_and_span if pack_and_span is not None \
            else (None, None)
        build_lanes = None if pack is None \
            else [self._packed_lane(build_keys, pack)]
        # Dense key domain (packed-lane span / dictionary size / scan
        # stats): probes become direct-address gathers — no search, and
        # a unique build side needs no sort either (ops/join.py).
        if pack is not None:
            domain = (0, pack_span - 1) if self._span_fits(
                pack_span, build_batch.capacity) else None
        else:
            domain = self._dense_domain(build_keys, build_batch.capacity)
        # Pallas hash-probe tier: replaces the sorted-build + merge-rank
        # search always, and the dense direct-address tables under the
        # denseReplace policy (span-sized offs sorts dominate the dense
        # build past ~4x the build rows; below it its one-gather probes
        # win).  Single-exact-lane legality finishes inside BuildTable.
        from ..config import JOIN_MATCHED_VIA_PRESENCE
        from ..ops.pallas import count_fallback, elect_join
        dense_span = None if domain is None \
            else int(domain[1]) - int(domain[0]) + 1
        via_presence = ctx.conf.get(JOIN_MATCHED_VIA_PRESENCE)
        matched_only = self.join_type in (J.LEFT_SEMI, J.LEFT_ANTI)
        if matched_only and domain is not None and via_presence:
            # semi/anti over a dense domain: the probe needs a PRESENCE
            # bitmap only (ops/join.py BuildTable.present — one bool
            # scatter), which beats both the hash table and the sorted
            # offs table regardless of span; skip the kernel election
            pallas_tier = None
            from ..ops.pallas import kernel_tier
            if kernel_tier(ctx.conf).join:
                count_fallback("hash_probe_join", "dense_matched")
        else:
            pallas_tier = elect_join(ctx.conf, build_batch.capacity,
                                     dense_span=dense_span)
        if pallas_tier is not None:
            domain = None               # the hash table takes the join
            ctx.bump("join_pallas_hash")
        unique = domain is not None and self._build_unique()
        if domain is not None:
            ctx.bump("join_dense_domain")
        from ..config import (JOIN_DENSE_BUILD_VIA_SORT,
                              JOIN_MATCHED_VIA_MERGE)
        build = J.BuildTable(build_batch, build_keys, build_lanes,
                             domain=domain, unique=unique,
                             extra_valid=build_pre if build_conds else None,
                             dense_via_sort=ctx.conf.get(
                                 JOIN_DENSE_BUILD_VIA_SORT),
                             matched_via_merge=ctx.conf.get(
                                 JOIN_MATCHED_VIA_MERGE),
                             matched_via_presence=via_presence,
                             pallas_tier=pallas_tier)
        out_names = list(self.output_schema.names)
        # Sync-free probe-aligned path: a build side whose keys are unique
        # (exact plan statistics — dimension scans, group-by outputs) makes
        # every probe row match at most once, so join output rides the
        # probe's own static capacity and NO host round trip sizes it.
        # Single-lane only: the sorted lane is exact there (no composite-
        # hash collisions), so the one verified slot IS the unique match.
        aligned = all(raw_pos) and len(build.lanes) == 1 \
            and self._build_unique()
        if aligned:
            ctx.bump("join_aligned_fastpath")

        build_matched_acc = jnp.zeros((build_batch.capacity,), bool)

        # late materialization: right-side columns in `defer_right` ride
        # as a build row-id lane instead of being gathered per probe
        # batch; a thin probe stream passes its lanes through
        transparent = self._thin_transparent()
        defer_right = self._defer_right()
        defer_set = frozenset(defer_right)
        nleft = len(self.left.output_schema.names)
        nright = len(self.right.output_schema.fields) \
            if self.join_type not in (J.LEFT_SEMI, J.LEFT_ANTI) else 0
        if defer_right:
            from ..obs.registry import DEFERRED_GATHERS
            from ..columnar.lanes import deferred_column
            mat_right = [j for j in range(nright) if j not in defer_set]
            right_placeholders = {
                j: deferred_column(build_batch.columns[j])
                for j in defer_right}

        def right_out_cols(gathered):
            """Interleave gathered (materialized) right columns with the
            deferred placeholders, in schema order."""
            if not defer_right:
                return list(gathered)
            it = iter(gathered)
            return [right_placeholders[j] if j in defer_set else next(it)
                    for j in range(nright)]

        for pb in probe_iter:
            if isinstance(pb.num_rows, int) and pb.num_rows == 0:
                continue
            pb = self._prep_probe(pb, probe_conds, ctx)
            probe_keys = self._key_cols(pb, self.left_keys, raw_pos, ctx)
            for i, s in enumerate(has_str):
                if s:
                    probe_keys[i] = remap_codes_into(
                        probe_keys[i], build_keys[i].dictionary)
            probe_lanes = [self._packed_lane(probe_keys, pack)] \
                if pack is not None else J.key_cols_lanes(probe_keys)
            # fused probe-side filters: failing rows are dead for every
            # join type (they don't match, and don't surface as outer
            # unmatched rows either)
            pre = self._conds_mask(probe_conds, pb, pb.row_mask(), ctx)
            probe_valid = pre
            for c in probe_keys:
                probe_valid = probe_valid & c.validity

            if self.join_type in (J.LEFT_SEMI, J.LEFT_ANTI):
                # matched flag only — no pair expansion; single-lane keys
                # (exact ranges) need no host sync and no uniqueness
                if len(probe_lanes) == 1 and len(build.lanes) == 1:
                    matched = J.probe_matched_lazy(build, probe_lanes,
                                                   probe_valid)
                else:
                    lo, counts, cum, total = J.probe_counts(
                        build, probe_lanes, probe_valid)
                    if total == 0:
                        matched = jnp.zeros((pb.capacity,), bool)
                    else:
                        out_cap = bucket_capacity(total, ctx.conf)
                        _, _, _, matched, _ = J.expand_pairs(
                            build, probe_lanes, probe_valid, lo, counts,
                            cum, out_cap, total)
                keep = matched if self.join_type == J.LEFT_SEMI \
                    else pre & ~matched
                if self.lazy_sel or (transparent and pb.thin is not None):
                    # mask-aware parent (aggregation live mask / another
                    # join's probe liveness) or a thin stream: skip the
                    # compaction — row gathers are the dominant device
                    # cost; thin lanes stay output-aligned
                    yield DeviceBatch(list(pb.columns),
                                      jnp.sum(keep, dtype=jnp.int32),
                                      out_names, pb.origin_file, sel=keep,
                                      thin=pb.thin)
                    continue
                out = compact_batch(pb, keep, ctx.conf)
                yield DeviceBatch(out.columns, out.num_rows, out_names)
                continue

            if aligned:
                build_idx, ok = J.probe_aligned(build, probe_lanes,
                                                probe_valid)
                # a masked probe's live rows are NOT a prefix: gather with
                # every position live; sel excludes dead rows downstream
                out_rows = pb.capacity if pb.sel is not None else pb.num_rows
                build_lane = jnp.where(ok, build_idx,
                                       jnp.int32(-1)).astype(jnp.int32)
                if defer_right:
                    # deferred right columns ride the lane; only the
                    # early-needed ones are gathered per probe batch
                    ctx.bump("join_deferred_gathers", len(defer_right))
                    DEFERRED_GATHERS.inc(len(defer_right))
                    rg_cols = right_out_cols(
                        gather_batch(build_batch.select(mat_right),
                                     build_lane, out_rows,
                                     null_out_of_bounds=True).columns
                        if mat_right else [])
                else:
                    rg_cols = gather_batch(build_batch, build_lane,
                                           out_rows,
                                           null_out_of_bounds=True).columns
                thin = self._make_thin(pb.capacity, pb.thin, build_batch,
                                       build_lane, defer_right, nleft) \
                    if (defer_right or pb.thin is not None) else None
                if self.join_type in (J.RIGHT_OUTER, J.FULL_OUTER):
                    if build.matched_via_merge:
                        from ..ops.segments import matched_flags
                        hit = matched_flags(build_idx, ok,
                                            build_batch.capacity)
                    else:
                        hit = jnp.zeros(
                            (build_batch.capacity,), jnp.int32) \
                            .at[jnp.where(ok, build_idx, 0)] \
                            .max(ok.astype(jnp.int32)) > 0
                    build_matched_acc = build_matched_acc | hit
                if self.join_type == J.LEFT_OUTER:
                    # all (filter-surviving) probe rows survive; unmatched
                    # rows carry null right columns (the -1 gather/lane)
                    out = DeviceBatch(list(pb.columns) + rg_cols,
                                      pb.num_rows, out_names, thin=thin)
                    if not probe_conds:
                        # a masked probe's liveness must survive verbatim
                        yield out if pb.sel is None else DeviceBatch(
                            out.columns, pb.num_rows, out_names,
                            sel=pb.sel, thin=thin)
                    elif self.lazy_sel or thin is not None:
                        yield DeviceBatch(out.columns,
                                          jnp.sum(pre, dtype=jnp.int32),
                                          out_names, sel=pre, thin=thin)
                    else:
                        yield compact_batch(out, pre, ctx.conf)
                else:   # inner / right_outer / full_outer matched part
                    pairs = DeviceBatch(list(pb.columns) + rg_cols,
                                        pb.num_rows, out_names, thin=thin)
                    keep = ok & pre
                    if self.join_type == J.INNER and \
                            (self.lazy_sel or thin is not None):
                        yield DeviceBatch(pairs.columns,
                                          jnp.sum(keep, dtype=jnp.int32),
                                          out_names, sel=keep, thin=thin)
                    else:
                        yield compact_batch(pairs, keep, ctx.conf)
                    if self.join_type == J.FULL_OUTER:
                        unmatched = pre & ~ok
                        right_nulls = _null_columns(
                            self.right.output_schema, pb.capacity)
                        padded = DeviceBatch(
                            list(pb.columns) + right_nulls, pb.num_rows,
                            out_names)
                        yield compact_batch(padded, unmatched, ctx.conf)
                continue

            lo, counts, cum, total = J.probe_counts(build, probe_lanes,
                                                    probe_valid)
            go_thin = defer_right or (transparent and pb.thin is not None)
            if total > 0:
                out_cap = bucket_capacity(total, ctx.conf)
                probe_idx, build_idx, ok, probe_matched, build_matched = \
                    J.expand_pairs(build, probe_lanes, probe_valid, lo,
                                   counts, cum, out_cap, total)
                build_matched_acc = build_matched_acc | build_matched
                if go_thin:
                    # thin pair expansion: gather only materialized
                    # columns; upstream probe lanes COMPOSE through
                    # probe_idx (one int32 take per source) and the
                    # deferred right columns ride the new build lane
                    from ..columnar.lanes import LaneSource
                    pend_l = pb.thin.pending if pb.thin is not None else {}
                    mat_l = [i for i in range(len(pb.columns))
                             if i not in pend_l]
                    lg = gather_batch(pb.select(mat_l), probe_idx, total)
                    safe_p = jnp.clip(probe_idx, 0,
                                      max(pb.capacity - 1, 0))
                    probe_sources = []
                    if pb.thin is not None:
                        for s in pb.thin.sources:
                            comp = jnp.take(s.lane, safe_p)
                            probe_sources.append(LaneSource(
                                s.batch,
                                jnp.where(ok, comp, jnp.int32(-1))))
                    build_lane = jnp.where(ok, build_idx, jnp.int32(-1))
                    if defer_right:
                        gathered = gather_batch(
                            build_batch.select(mat_right), build_lane,
                            total, null_out_of_bounds=True).columns \
                            if mat_right else []
                        rg_cols = right_out_cols(gathered)
                        ctx.bump("join_deferred_gathers", len(defer_right))
                        DEFERRED_GATHERS.inc(len(defer_right))
                    else:
                        rg_cols = gather_batch(
                            build_batch, build_lane, total,
                            null_out_of_bounds=True).columns
                    left_cols = []
                    lgi = iter(lg.columns)
                    for i in range(nleft):
                        left_cols.append(pb.columns[i] if i in pend_l
                                         else next(lgi))
                    thin = self._make_thin(out_cap, pb.thin, build_batch,
                                           build_lane, defer_right, nleft,
                                           probe_sources=probe_sources)
                    yield DeviceBatch(left_cols + rg_cols,
                                      jnp.sum(ok, dtype=jnp.int32),
                                      out_names, sel=ok, thin=thin)
                else:
                    lg = gather_batch(pb, probe_idx, total)
                    rg = gather_batch(build_batch, build_idx, total)
                    pairs = DeviceBatch(lg.columns + rg.columns, total,
                                        out_names)
                    pairs = compact_batch(pairs, ok, ctx.conf)
                    yield pairs
            else:
                probe_matched = jnp.zeros((pb.capacity,), bool)

            if self.join_type in (J.LEFT_OUTER, J.FULL_OUTER):
                unmatched = pre & ~probe_matched
                left_cols = list(pb.columns)
                if go_thin and self.join_type == J.LEFT_OUTER:
                    # unmatched probe rows: deferred right columns keep a
                    # -1 (null) lane, upstream lanes pass through
                    null_lane = jnp.full((pb.capacity,), -1, jnp.int32)
                    rn_cols = right_out_cols(_null_columns(
                        t.StructType([
                            f for j, f in enumerate(
                                self.right.output_schema.fields)
                            if j not in defer_set]),
                        pb.capacity)) if defer_right else _null_columns(
                        self.right.output_schema, pb.capacity)
                    thin = self._make_thin(pb.capacity, pb.thin,
                                           build_batch, null_lane,
                                           defer_right, nleft)
                    yield DeviceBatch(left_cols + rn_cols,
                                      jnp.sum(unmatched, dtype=jnp.int32),
                                      out_names, sel=unmatched, thin=thin)
                else:
                    right_nulls = _null_columns(self.right.output_schema,
                                                pb.capacity)
                    padded = DeviceBatch(left_cols + right_nulls,
                                         pb.num_rows, out_names)
                    yield compact_batch(padded, unmatched, ctx.conf)

        if self.join_type in (J.RIGHT_OUTER, J.FULL_OUTER):
            unmatched = build_pre & ~build_matched_acc
            left_nulls = _null_columns(self.left.output_schema,
                                       build_batch.capacity)
            padded = DeviceBatch(left_nulls + list(build_batch.columns),
                                 build_batch.num_rows, out_names)
            yield compact_batch(padded, unmatched, ctx.conf)

    def _empty_build_output(self, left_src, probe_conds, ctx
                            ) -> Iterator[DeviceBatch]:
        # top level: inner/semi/right-outer need not execute the probe
        # subtree at all (the pre-sub-partition short-circuit)
        if self.join_type in (J.INNER, J.LEFT_SEMI, J.RIGHT_OUTER):
            return
        yield from self._empty_build_stream(left_src.execute(ctx), ctx,
                                            probe_conds)

    def _empty_build_stream(self, probe_iter, ctx, probe_conds=()
                            ) -> Iterator[DeviceBatch]:
        """Empty build side: inner/semi/right produce nothing; left outer
        and anti pass probe rows through (right side null)."""
        if self.join_type in (J.INNER, J.LEFT_SEMI, J.RIGHT_OUTER):
            for _ in probe_iter:     # drain (sub-partition spill cleanup)
                pass
            return
        out_names = list(self.output_schema.names)
        for pb in probe_iter:
            if int(pb.num_rows) == 0:
                continue
            if pb.thin is not None and not self._thin_transparent():
                from ..columnar.lanes import materialize_batch
                pb = materialize_batch(pb, ctx.conf)
            if probe_conds:
                pb = compact_batch(
                    pb, self._conds_mask(probe_conds, pb, pb.row_mask(),
                                         ctx), ctx.conf)
            if self.join_type == J.LEFT_ANTI:
                yield DeviceBatch(pb.columns, pb.num_rows, out_names,
                                  sel=pb.sel, thin=pb.thin)
            else:   # left/full outer
                right_nulls = _null_columns(self.right.output_schema,
                                            pb.capacity)
                yield DeviceBatch(list(pb.columns) + right_nulls,
                                  pb.num_rows, out_names, sel=pb.sel,
                                  thin=pb.thin)

    def describe(self):
        return (f"HashJoinExec[{self.join_type}, "
                f"keys={len(self.left_keys)}]")


class CrossJoinExec(PlanNode):
    """GpuCartesianProductExec analogue: every (probe, build) pair."""

    def __init__(self, left: PlanNode, right: PlanNode):
        super().__init__(left, right)

    @property
    def output_schema(self) -> t.StructType:
        return t.StructType(list(self.children[0].output_schema.fields) +
                            list(self.children[1].output_schema.fields))

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        out_names = list(self.output_schema.names)
        if self.children[1].static_row_count() == 1:
            # scalar-subquery cross join (the HAVING-against-total shape):
            # exactly one build row broadcasts onto every probe row with
            # zero host syncs
            build = None
            for db in self.children[1].execute(ctx):
                build = db if build is None else build
            for pb in self.children[0].execute(ctx):
                idx0 = jnp.zeros((pb.capacity,), jnp.int32)
                rg = gather_batch(build, idx0, pb.num_rows)
                yield DeviceBatch(list(pb.columns) + rg.columns,
                                  pb.num_rows, out_names)
            return
        right_batches = [db for db in self.children[1].execute(ctx)
                         if int(db.num_rows) > 0]
        if not right_batches:
            return
        build = concat_batches(right_batches, ctx.conf)
        nb = int(build.num_rows)
        for pb in self.children[0].execute(ctx):
            npr = int(pb.num_rows)
            if npr == 0:
                continue
            total = npr * nb
            out_cap = bucket_capacity(total, ctx.conf)
            i = jnp.arange(out_cap, dtype=jnp.int32)
            probe_idx = i // nb
            build_idx = i % nb
            lg = gather_batch(pb, probe_idx, total)
            rg = gather_batch(build, build_idx, total)
            yield DeviceBatch(lg.columns + rg.columns, total, out_names)
