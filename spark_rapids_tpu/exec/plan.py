"""Physical plan nodes producing streams of device batches.

The reference's operator contract is `GpuExec.internalDoExecuteColumnar():
RDD[ColumnarBatch]` (GpuExec.scala:365) — each exec pulls an iterator of
batches from its child and pushes transformed batches downstream.  The TPU
analogue keeps the pull-iterator shape (it is what enables out-of-core
execution) but each operator's device work is one cached jit program per
row-bucket (exec/evaluator.py), not a sequence of library kernel launches.

Nodes here are *physical*: expressions arrive already bound to the child's
schema (plan/overrides.py does the tagging/conversion from a logical tree).
"""
from __future__ import annotations

import dataclasses
from typing import Iterable, Iterator, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import pyarrow as pa

from .. import types as t
from ..config import TpuConf, DEFAULT_CONF
from ..columnar.device import DeviceBatch, to_device, to_host, empty_device_batch
from ..columnar.host import HostBatch, schema_to_struct
from ..ops.batch_ops import concat_batches, shrink_to_rows
from ..ops.filter import compact_batch
from ..plan import expressions as E
from ..plan.aggregates import AggregateFunction
from .aggregate import HashAggregate
from .evaluator import evaluate_projection


@dataclasses.dataclass
class ExecContext:
    """Per-query execution state threaded through the plan."""
    conf: TpuConf = DEFAULT_CONF
    metrics: dict = dataclasses.field(default_factory=dict)

    def bump(self, name: str, n: int = 1):
        self.metrics[name] = self.metrics.get(name, 0) + n


class PlanNode:
    """Base physical operator. Children first, Spark-style."""

    def __init__(self, *children: "PlanNode"):
        self.children = list(children)

    @property
    def child(self) -> "PlanNode":
        return self.children[0]

    @property
    def output_schema(self) -> t.StructType:
        raise NotImplementedError

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        raise NotImplementedError

    def name(self) -> str:
        return type(self).__name__

    def tree_string(self, indent: int = 0) -> str:
        lines = ["  " * indent + self.describe()]
        for c in self.children:
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)

    def describe(self) -> str:
        return self.name()

    # -- helpers -----------------------------------------------------------
    def collect(self, ctx: Optional[ExecContext] = None) -> pa.Table:
        """Run the plan and bring results back to host (GpuBringBackToHost)."""
        ctx = ctx or ExecContext()
        hbs = [to_host(db) for db in self.execute(ctx)
               if int(db.num_rows) > 0]
        schema = None
        batches = []
        for hb in hbs:
            schema = schema or hb.rb.schema
            batches.append(hb.rb)
        if not batches:
            from ..columnar.host import struct_to_schema
            return pa.Table.from_batches([], struct_to_schema(self.output_schema))
        return pa.Table.from_batches(batches, schema)


class HostScanExec(PlanNode):
    """Leaf: uploads host Arrow batches to device (HostColumnarToGpu role)."""

    def __init__(self, batches: Sequence[HostBatch],
                 schema: Optional[t.StructType] = None):
        super().__init__()
        self.batches = list(batches)
        self._schema = schema or (self.batches[0].schema if self.batches
                                  else t.StructType([]))

    @classmethod
    def from_table(cls, table: pa.Table, max_rows: Optional[int] = None
                   ) -> "HostScanExec":
        rbs = table.to_batches(max_chunksize=max_rows) if max_rows \
            else table.combine_chunks().to_batches()
        return cls([HostBatch(rb) for rb in rbs],
                   schema_to_struct(table.schema))

    @property
    def output_schema(self) -> t.StructType:
        return self._schema

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        for hb in self.batches:
            ctx.bump("scanned_rows", hb.num_rows)
            yield to_device(hb, ctx.conf)

    def describe(self):
        return f"HostScanExec[{len(self.batches)} batches]"


class ProjectExec(PlanNode):
    """GpuProjectExec: one fused XLA program per row bucket
    (reference basicPhysicalOperators.scala:350)."""

    def __init__(self, exprs: Sequence[E.Expression], names: Sequence[str],
                 child: PlanNode):
        super().__init__(child)
        self.exprs = [e.bind(child.output_schema) for e in exprs]
        self.names = list(names)

    @property
    def output_schema(self) -> t.StructType:
        return t.StructType([t.StructField(n, e.dtype)
                             for n, e in zip(self.names, self.exprs)])

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        for db in self.child.execute(ctx):
            yield evaluate_projection(self.exprs, self.names, db, ctx.conf)

    def describe(self):
        return f"ProjectExec[{', '.join(self.names)}]"


class FilterExec(PlanNode):
    """GpuFilterExec: predicate eval fused into one program, then stable
    mask compaction (ops/filter.py) instead of cuDF apply_boolean_mask."""

    def __init__(self, condition: E.Expression, child: PlanNode):
        super().__init__(child)
        self.condition = condition.bind(child.output_schema)

    @property
    def output_schema(self) -> t.StructType:
        return self.child.output_schema

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        from .evaluator import compute_predicate
        for db in self.child.execute(ctx):
            keep = compute_predicate(self.condition, db, ctx.conf)
            # lazy row count: downstream device ops keep running sync-free
            yield compact_batch(db, keep, ctx.conf)

    def describe(self):
        return f"FilterExec[{self.condition!r}]"


class HashAggregateExec(PlanNode):
    """GpuHashAggregateExec (GpuAggregateExec.scala:1711): streaming partial
    aggregation per batch, concat+merge regroup, final projection."""

    def __init__(self, key_exprs: Sequence[E.Expression],
                 key_names: Sequence[str],
                 aggs: Sequence[Tuple[AggregateFunction, str]],
                 child: PlanNode):
        super().__init__(child)
        schema = child.output_schema
        self.key_exprs = [e.bind(schema) for e in key_exprs]
        self.key_names = list(key_names)
        self.aggs = [(fn.bind(schema), name) for fn, name in aggs]
        from .aggregate import check_agg_buffers_supported
        check_agg_buffers_supported(self.aggs)

    @property
    def output_schema(self) -> t.StructType:
        fields = []
        for n, e in zip(self.key_names, self.key_exprs):
            fields.append(t.StructField(n, e.dtype))
        for fn, n in self.aggs:
            fields.append(t.StructField(n, fn.dtype))
        return t.StructType(fields)

    def _strip_filters(self, can_fuse: bool):
        """Peel the chain of FilterExec children this aggregate can fuse;
        returns (batch source node, conditions outermost-last)."""
        source: PlanNode = self.child
        conds: List[E.Expression] = []
        if can_fuse:
            while isinstance(source, FilterExec):
                conds.append(source.condition)
                source = source.child
            conds.reverse()
        return source, conds

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        agg = HashAggregate(self.key_exprs, self.key_names, self.aggs,
                            ctx.conf)
        # Fuse a chain of upstream filters into the map-side program: the
        # predicates become the groupby's live-mask, so filter + projections
        # + update aggregation run as ONE dispatch with no compaction
        # (TPU row gathers cost far more than masked reduction lanes).
        source, conds = self._strip_filters(agg.can_fuse_filter())
        partials: List[DeviceBatch] = []
        seen = False
        for db in source.execute(ctx):
            if isinstance(db.num_rows, int) and db.num_rows == 0:
                continue
            seen = True
            partials.append(agg.partial_fused(db, conds)
                            if agg.can_fuse_filter() else agg.partial(db))
            # Bound the pending set: merge when the partials would overflow
            # one target batch (the reference's tryMergeAggregatedBatches).
            if len(partials) > 1 and \
                    sum(int(p.num_rows) for p in partials) > ctx.conf.batch_size_rows:
                partials = [agg.merge(partials)]
        if not seen:
            if self.key_exprs:
                return  # grouped agg over empty input -> no rows
            # global agg over empty input still emits one row (e.g. COUNT=0)
            empty = empty_device_batch(self.child.output_schema, ctx.conf)
            partials = [agg.partial(empty)]
        merged = agg.merge(partials) if len(partials) > 1 else partials[0]
        yield agg.final(merged)

    def collect_device(self, ctx: Optional[ExecContext] = None):
        """Dispatch a global (no-key) aggregation fully async: returns
        (outs, finalize) where `outs` is the list of (scalar, valid) device
        buffers and `finalize(fetched)` turns their host values into the
        result table.  No host sync happens inside this call — callers can
        pipeline many queries and batch all fetches into one D2H round trip
        (the concurrent-GpuSemaphore-tasks analogue for a chip behind a
        high-latency link)."""
        if self.key_exprs:
            raise ValueError("collect_device is for global aggregations")
        ctx = ctx or ExecContext()
        agg = HashAggregate(self.key_exprs, self.key_names, self.aggs,
                            ctx.conf)
        source, conds = self._strip_filters(agg.can_fuse_filter())
        raw = []
        for db in source.execute(ctx):
            if isinstance(db.num_rows, int) and db.num_rows == 0:
                continue
            raw.append(agg.partial_fused(db, conds, raw=True))
        if not raw:
            empty = empty_device_batch(source.output_schema, ctx.conf)
            raw.append(agg.partial_fused(empty, conds, raw=True))
        return agg.merge_raw(raw), agg.finalize_fetched

    def collect(self, ctx: Optional[ExecContext] = None) -> pa.Table:
        """Global (no-key) aggregations finish on host from raw buffer
        scalars: N fused partial dispatches + at most one merge dispatch +
        ONE D2H fetch — no 1-row device batches, no device final
        projection."""
        if self.key_exprs:
            return super().collect(ctx)
        import jax
        outs, finalize = self.collect_device(ctx)
        return finalize(jax.device_get(list(outs)))

    def describe(self):
        return (f"HashAggregateExec[keys={self.key_names}, "
                f"aggs={[n for _, n in self.aggs]}]")


class LocalLimitExec(PlanNode):
    """Per-stream limit (GpuLocalLimitExec, limit.scala)."""

    def __init__(self, limit: int, child: PlanNode):
        super().__init__(child)
        self.limit = limit

    @property
    def output_schema(self) -> t.StructType:
        return self.child.output_schema

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        remaining = self.limit
        for db in self.child.execute(ctx):
            if remaining <= 0:
                return
            n = int(db.num_rows)
            if n <= remaining:
                remaining -= n
                yield db
            else:
                yield shrink_to_rows(_truncate(db, remaining), remaining,
                                     ctx.conf)
                return

    def describe(self):
        return f"{self.name()}[{self.limit}]"


class GlobalLimitExec(LocalLimitExec):
    """Same device semantics as local limit; the global cut happens after
    the single-partition exchange inserted by the planner."""


def _truncate(db: DeviceBatch, rows: int) -> DeviceBatch:
    from ..columnar.device import DeviceColumn
    live = jnp.arange(db.capacity, dtype=jnp.int32) < jnp.int32(rows)
    cols = [DeviceColumn(c.data, c.validity & live, c.dtype, c.dictionary,
                         c.data_hi) for c in db.columns]
    return DeviceBatch(cols, rows, db.names)


class UnionExec(PlanNode):
    """GpuUnionExec: concatenation of children streams (schema-aligned)."""

    def __init__(self, *children: PlanNode):
        super().__init__(*children)

    @property
    def output_schema(self) -> t.StructType:
        return self.children[0].output_schema

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        names = list(self.output_schema.names)
        for c in self.children:
            for db in c.execute(ctx):
                yield DeviceBatch(db.columns, db.num_rows, names)


class CoalesceBatchesExec(PlanNode):
    """GpuCoalesceBatches (GpuCoalesceBatches.scala:697): concatenate small
    batches until the target row goal so downstream programs run on full
    buckets."""

    def __init__(self, child: PlanNode, target_rows: Optional[int] = None,
                 require_single: bool = False):
        super().__init__(child)
        self.target_rows = target_rows
        self.require_single = require_single

    @property
    def output_schema(self) -> t.StructType:
        return self.child.output_schema

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        target = self.target_rows or ctx.conf.batch_size_rows
        pending: List[DeviceBatch] = []
        rows = 0
        for db in self.child.execute(ctx):
            n = int(db.num_rows)   # coalesce sizes batches -> sync point
            if n == 0:
                continue
            if not self.require_single and rows and rows + n > target:
                yield concat_batches(pending, ctx.conf)
                pending, rows = [], 0
            pending.append(db)
            rows += n
        if pending:
            yield concat_batches(pending, ctx.conf)

    def describe(self):
        goal = "RequireSingleBatch" if self.require_single \
            else f"target={self.target_rows or 'conf'}"
        return f"CoalesceBatchesExec[{goal}]"


class SortExec(PlanNode):
    """GpuSortExec (GpuSortExec.scala:86): sorts by SortOrder keys.

    global_sort concatenates the input stream (the single-partition case or
    post-range-exchange per-partition totals); local sort orders each batch
    independently (enough for sort-merge structures and windows).  The
    out-of-core merge path of the reference (GpuOutOfCoreSortIterator:281)
    maps to sorting coalesced sub-runs and merging via concat+resort —
    TPU sort is one fused lexsort, so resorting merged runs is cheaper than
    an N-way merge with its data-dependent control flow."""

    def __init__(self, keys, child: PlanNode, global_sort: bool = True):
        from ..ops.sort import SortKey
        super().__init__(child)
        self.keys = [k if isinstance(k, SortKey) else SortKey(*k)
                     for k in keys]
        self.global_sort = global_sort

    @property
    def output_schema(self) -> t.StructType:
        return self.child.output_schema

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        from ..ops.sort import sort_batch
        if not self.global_sort:
            for db in self.child.execute(ctx):
                yield sort_batch(db, self.keys, ctx.conf)
            return
        batches = [db for db in self.child.execute(ctx)
                   if int(db.num_rows) > 0]
        if not batches:
            return
        merged = concat_batches(batches, ctx.conf)
        yield sort_batch(merged, self.keys, ctx.conf)

    def describe(self):
        scope = "global" if self.global_sort else "local"
        return f"SortExec[{scope}, {self.keys}]"


class TopNExec(PlanNode):
    """GpuTopN (limit.scala): sort + limit without materializing the full
    sorted output — each batch keeps only its top-N prefix, pending rows
    are re-sorted together and cut once more at the end."""

    def __init__(self, limit: int, keys, child: PlanNode):
        from ..ops.sort import SortKey
        super().__init__(child)
        self.limit = limit
        self.keys = [k if isinstance(k, SortKey) else SortKey(*k)
                     for k in keys]

    @property
    def output_schema(self) -> t.StructType:
        return self.child.output_schema

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        from ..ops.sort import sort_batch
        pending: Optional[DeviceBatch] = None
        for db in self.child.execute(ctx):
            if int(db.num_rows) == 0:
                continue
            batch = db if pending is None \
                else concat_batches([pending, db], ctx.conf)
            s = sort_batch(batch, self.keys, ctx.conf)
            n = min(self.limit, int(s.num_rows))
            pending = shrink_to_rows(_truncate(s, n), n, ctx.conf)
        if pending is not None:
            yield pending

    def describe(self):
        return f"TopNExec[{self.limit}, {self.keys}]"


class RangeExec(PlanNode):
    """GpuRangeExec (basicPhysicalOperators.scala:838): generates id ranges
    directly on device with iota."""

    def __init__(self, start: int, end: int, step: int = 1,
                 name: str = "id", batch_rows: Optional[int] = None):
        super().__init__()
        assert step != 0
        self.start, self.end, self.step = start, end, step
        self.col_name = name
        self.batch_rows = batch_rows

    @property
    def output_schema(self) -> t.StructType:
        return t.StructType([t.StructField(self.col_name, t.LongType())])

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        from ..columnar.device import DeviceColumn, bucket_capacity
        total = max(0, -(-(self.end - self.start) // self.step))
        chunk = self.batch_rows or ctx.conf.batch_size_rows
        emitted = 0
        while emitted < total:
            n = min(chunk, total - emitted)
            cap = bucket_capacity(n, ctx.conf)
            base = self.start + emitted * self.step
            data = jnp.int64(base) + jnp.arange(cap, dtype=jnp.int64) * self.step
            live = jnp.arange(cap, dtype=jnp.int32) < jnp.int32(n)
            yield DeviceBatch(
                [DeviceColumn(data, live, t.LongType())], n, [self.col_name])
            emitted += n
        if total == 0:
            return

    def describe(self):
        return f"RangeExec[{self.start},{self.end},{self.step}]"


class ExpandExec(PlanNode):
    """GpuExpandExec (GpuExpandExec.scala:70): N projections per input batch
    (rollup/cube/grouping sets lowering)."""

    def __init__(self, projections: Sequence[Sequence[E.Expression]],
                 names: Sequence[str], child: PlanNode):
        super().__init__(child)
        self.projections = [[e.bind(child.output_schema) for e in p]
                            for p in projections]
        self.names = list(names)

    @property
    def output_schema(self) -> t.StructType:
        return t.StructType([t.StructField(n, e.dtype) for n, e in
                             zip(self.names, self.projections[0])])

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        for db in self.child.execute(ctx):
            for proj in self.projections:
                yield evaluate_projection(proj, self.names, db, ctx.conf)
