"""Physical plan nodes producing streams of device batches.

The reference's operator contract is `GpuExec.internalDoExecuteColumnar():
RDD[ColumnarBatch]` (GpuExec.scala:365) — each exec pulls an iterator of
batches from its child and pushes transformed batches downstream.  The TPU
analogue keeps the pull-iterator shape (it is what enables out-of-core
execution) but each operator's device work is one cached jit program per
row-bucket (exec/evaluator.py), not a sequence of library kernel launches.

Nodes here are *physical*: expressions arrive already bound to the child's
schema (plan/overrides.py does the tagging/conversion from a logical tree).
"""
from __future__ import annotations

import dataclasses
import threading
import time
from contextlib import contextmanager
from typing import Iterable, Iterator, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import pyarrow as pa

from .. import types as t
from ..config import TpuConf, DEFAULT_CONF
from ..columnar.device import DeviceBatch, to_device, empty_device_batch
from ..columnar.host import HostBatch, schema_to_struct
from ..ops.batch_ops import concat_batches, shrink_to_rows
from ..ops.filter import compact_batch
from ..plan import expressions as E
from ..plan.aggregates import AggregateFunction
from .aggregate import HashAggregate
from .evaluator import evaluate_projection


_BUDGET_INIT_LOCK = threading.Lock()


class QueryDeadlineExceeded(RuntimeError):
    """The query ran past its per-query deadline (serving.deadlineMs /
    submit(deadline_ms=...)) and a cooperative cancellation checkpoint
    cancelled it.  Classified 'query': the ticket fails cleanly, every
    reservation its budget held is released (DeviceCensus shows zero
    residual), and the hosting worker keeps serving."""


class InjectedDeadlineExceeded(QueryDeadlineExceeded):
    """Chaos-harness form (`deadline:timeout:...`, runtime/faults.py):
    a synthetic deadline expiry at the Nth checkpoint."""


class QueryCancelled(QueryDeadlineExceeded):
    """Cooperative cancellation (ExecContext.cancel event set) — the
    graceful-drain / client-abandoned form of the same checkpoint
    contract."""


#: the executing thread's context, for cancellation checkpoints at
#: conf-less brackets (exchange rounds, spill sweeps) — registered for
#: the duration of a deadline-armed execute (cancel_scope)
_TLS_CTX = threading.local()


@contextmanager
def cancel_scope(ctx: "ExecContext"):
    """Register `ctx` as the executing thread's active context so
    conf-less brackets (parallel/exchange.py rounds, runtime/memory.py
    spill sweeps) can reach its cancellation checkpoint."""
    prev = getattr(_TLS_CTX, "ctx", None)
    _TLS_CTX.ctx = ctx
    try:
        yield ctx
    finally:
        _TLS_CTX.ctx = prev


def checkpoint_active(bracket: str = "") -> None:
    """Fire the active context's cancellation checkpoint (no-op when no
    deadline-armed query runs on this thread)."""
    ctx = getattr(_TLS_CTX, "ctx", None)
    if ctx is not None:
        ctx.checkpoint(bracket)


@dataclasses.dataclass
class ExecContext:
    """Per-query execution state threaded through the plan."""
    conf: TpuConf = DEFAULT_CONF
    metrics: dict = dataclasses.field(default_factory=dict)
    _budget: object = None
    # query-lifecycle span tracer (obs/tracer.py); NULL when tracing is
    # off so record calls cost one no-op method dispatch
    tracer: object = None
    # out-of-core escalation flag (exec/ooc.py): set by the query-level
    # OOM ladder / proactive election / serving admission; every
    # eligible hash join and aggregation then runs spill-partitioned
    ooc_force: bool = False
    # cooperative cancellation (serving deadlines / graceful drain):
    # absolute time.monotonic() deadline (0 = none) and an optional
    # threading.Event — checkpoint() raises past either
    deadline: float = 0.0
    cancel: object = None

    def __post_init__(self):
        if self.tracer is None:
            from ..obs.tracer import NULL_TRACER
            self.tracer = NULL_TRACER

    def arm_deadline(self, deadline_ms: float,
                     started: Optional[float] = None) -> None:
        """Arm the per-query deadline `deadline_ms` milliseconds after
        `started` (time.monotonic(); now when None)."""
        if deadline_ms and deadline_ms > 0:
            base = time.monotonic() if started is None else started
            self.deadline = base + float(deadline_ms) / 1e3

    def checkpoint(self, bracket: str = "") -> None:
        """Cooperative cancellation checkpoint — called at the seam /
        per-batch / OOC-pass / exchange-round / spill brackets.  Fires
        the `deadline` chaos site when armed, then raises
        QueryCancelled / QueryDeadlineExceeded when the cancel event is
        set or the deadline has passed.  The disabled path is two
        attribute checks."""
        from ..runtime.faults import get_injector
        inj = get_injector(self.conf)
        if inj.enabled:
            inj.fire("deadline", bracket=bracket or "?")
        if self.cancel is not None and self.cancel.is_set():
            self.bump("deadline_checkpoints_cancelled")
            raise QueryCancelled(
                f"query cancelled at the {bracket or '?'} checkpoint")
        if self.deadline and time.monotonic() > self.deadline:
            self.bump("deadline_checkpoints_cancelled")
            raise QueryDeadlineExceeded(
                f"query deadline exceeded at the {bracket or '?'} "
                f"checkpoint (serving.deadlineMs)")

    @property
    def budget(self):
        """Lazy per-query HBM budget (runtime/memory.py) — the
        RapidsBufferCatalog role for batches operators hold.  Guarded:
        a racing first touch from shuffle/scan worker threads must not
        create two disjoint budgets."""
        if self._budget is None:
            with _BUDGET_INIT_LOCK:
                if self._budget is None:
                    from ..runtime.memory import MemoryBudget
                    self._budget = MemoryBudget(self.conf)
        return self._budget

    def bump(self, name: str, n: int = 1):
        self.metrics[name] = self.metrics.get(name, 0) + n


class PlanNode:
    """Base physical operator. Children first, Spark-style."""

    def __init__(self, *children: "PlanNode"):
        self.children = list(children)

    @property
    def child(self) -> "PlanNode":
        return self.children[0]

    @property
    def output_schema(self) -> t.StructType:
        raise NotImplementedError

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        raise NotImplementedError

    def name(self) -> str:
        return type(self).__name__

    # -- static statistics (the CBO/AQE-statistics analogue) ---------------
    def keys_unique(self, names: Sequence[str]) -> bool:
        """True if no two live rows can carry equal NON-NULL values in the
        named column tuple.  Drives the sync-free probe-aligned join path
        (ops/join.py probe_aligned): a unique build side makes join output
        size a static fact.  Conservative default: unknown -> False.
        Sources of truth: exact scan statistics (HostScanExec), group-by
        structure, and uniqueness-preserving operators (filter/sort/limit
        keep a subset of rows; joins with unique build sides repeat each
        probe row at most once)."""
        return False

    def static_row_count(self) -> Optional[int]:
        """Exact output row count when statically known (global aggregates
        emit exactly one row), else None.  Lets cross joins against scalar
        subqueries run without a host sync."""
        return None

    def column_range(self, name: str) -> Optional[Tuple[int, int]]:
        """Exact (min, max) of a column's integer-lane values when known
        from scan statistics, else None.  Value-preserving operators
        delegate; values only ever narrow (filter/limit keep subsets,
        joins gather existing rows).  Lets multi-column join keys pack
        into ONE injective int64 lane (exec/join.py), unlocking the
        sync-free aligned/semi probe paths for composite keys."""
        return None

    def row_upper_bound(self) -> Optional[int]:
        """Static UPPER bound on output rows (a limit/top-N cap, a
        single-row global aggregate), else None.  Drives the result-fetch
        head size: over a high-latency low-bandwidth link the collect
        path ships `bound` rows instead of the padded bucket capacity
        (columnar.device.to_host fetch_rows)."""
        return self.static_row_count()

    def tree_string(self, indent: int = 0) -> str:
        lines = ["  " * indent + self.describe()]
        for c in self.children:
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)

    def describe(self) -> str:
        return self.name()

    # -- helpers -----------------------------------------------------------
    def collect(self, ctx: Optional[ExecContext] = None) -> pa.Table:
        """Run the plan and bring results back to host (GpuBringBackToHost).

        Transfer policy per batch: fetch_result_batch ships the live-row
        prefix, not the padded capacity — static counts/bounds in one
        exactly-sized trip, unknown counts via a speculative
        count+head-prefix trip (columnar.device.fetch_result_batch)."""
        ctx = ctx or ExecContext()
        import time as _time
        from ..columnar.device import fetch_result_batch
        from ..runtime.retry import retry_io
        bound = self.row_upper_bound()
        hbs = []
        for db in self.execute(ctx):
            ctx.checkpoint("batch")
            if isinstance(db.num_rows, int) and db.num_rows == 0:
                continue
            t0 = _time.perf_counter()
            with ctx.tracer.span("fetch", "transition"):
                hb = retry_io(ctx.conf, "d2h",
                              lambda: fetch_result_batch(db, bound,
                                                         ctx.conf))
            # always-on result-fetch bracket: the tail host sync every
            # query pays (overhead plane, obs/profile.wall_breakdown)
            ctx.metrics["overhead.fetch_ms"] = ctx.metrics.get(
                "overhead.fetch_ms", 0.0) \
                + (_time.perf_counter() - t0) * 1e3
            ctx.bump("d2h_rows", hb.num_rows)
            ctx.tracer.add_bytes("d2h_bytes", hb.rb.nbytes)
            hbs.append(hb)
        schema = None
        batches = []
        for hb in hbs:
            if hb.num_rows > 0:
                schema = schema or hb.rb.schema
                batches.append(hb.rb)
        if not batches:
            from ..columnar.host import struct_to_schema
            return pa.Table.from_batches([], struct_to_schema(self.output_schema))
        return pa.Table.from_batches(batches, schema)


class HostScanExec(PlanNode):
    """Leaf: uploads host Arrow batches to device (HostColumnarToGpu role)."""

    def __init__(self, batches: Sequence[HostBatch],
                 schema: Optional[t.StructType] = None,
                 source_table: Optional[pa.Table] = None):
        super().__init__()
        self.batches = list(batches)
        self._schema = schema or (self.batches[0].schema if self.batches
                                  else t.StructType([]))
        self._source_table = source_table
        # whole-plan compilation hooks (exec/compiled.py): uploaded-once
        # device batches, and tracer stand-ins installed during jit trace
        self._device_cache = None
        self._trace_batches = None
        # columns approved for FOR-narrowed encoded upload by the
        # _negotiate_encoded legality pass (plan/overrides.py); None =
        # un-negotiated, lanes stay full width
        self.encoded_cols = None

    @classmethod
    def from_table(cls, table: pa.Table, max_rows: Optional[int] = None
                   ) -> "HostScanExec":
        rbs = table.to_batches(max_chunksize=max_rows) if max_rows \
            else table.combine_chunks().to_batches()
        return cls([HostBatch(rb) for rb in rbs],
                   schema_to_struct(table.schema), source_table=table)

    def keys_unique(self, names: Sequence[str]) -> bool:
        """Exact scan-time distinctness statistics (the role Delta/Iceberg
        table stats play for the reference's planner), cached per source
        table so repeated queries over the same data pay once."""
        tbl = self._source_table
        if tbl is None or not names or \
                any(n not in tbl.schema.names for n in names):
            return False
        return _table_keys_unique(tbl, tuple(names))

    def column_range(self, name: str) -> Optional[Tuple[int, int]]:
        tbl = self._source_table
        if tbl is None or name not in tbl.schema.names:
            return None
        return _table_column_range(tbl, name)

    @property
    def output_schema(self) -> t.StructType:
        return self._schema

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        if self._trace_batches is not None:   # under whole-plan tracing
            yield from self._trace_batches
            return
        from ..runtime.retry import retry_io
        for hb in self.batches:
            ctx.bump("scanned_rows", hb.num_rows)
            with ctx.tracer.span("upload", "transition"):
                db = retry_io(ctx.conf, "h2d",
                              lambda: to_device(hb, ctx.conf,
                                                encoded_cols=self.encoded_cols))
            ctx.bump("h2d_rows", hb.num_rows)
            ctx.tracer.add_bytes("h2d_bytes", hb.rb.nbytes)
            yield db

    def describe(self):
        return f"HostScanExec[{len(self.batches)} batches]"


_UNIQUE_STAT_CACHE: dict = {}


def _table_keys_unique(tbl: pa.Table, names: tuple) -> bool:
    """No two rows share equal fully-non-null values in `names` (rows with
    any null key are excluded — null join keys never match).

    Cached per (table identity, key tuple) via weakref: stats die with
    the table instead of pinning gigabytes of dropped inputs, and id()
    reuse after GC cannot alias a stale entry (the finalizer removes it)."""
    import weakref
    key = (id(tbl), names)
    hit = _UNIQUE_STAT_CACHE.get(key)
    if hit is not None and hit[0]() is tbl:
        return hit[1]
    import pyarrow.compute as pc
    sub = tbl.select(list(names)).drop_null()
    if sub.num_rows == 0:
        uniq = True
    elif len(names) == 1:
        uniq = pc.count_distinct(sub.column(0)).as_py() == sub.num_rows
    else:
        uniq = sub.group_by(list(names)).aggregate([]).num_rows \
            == sub.num_rows
    try:
        ref = weakref.ref(tbl, lambda _r, k=key:
                          _UNIQUE_STAT_CACHE.pop(k, None))
    except TypeError:        # weakref-unsupported object: don't cache
        return uniq
    if len(_UNIQUE_STAT_CACHE) > 1024:
        _UNIQUE_STAT_CACHE.clear()
    _UNIQUE_STAT_CACHE[key] = (ref, uniq)
    return uniq


_RANGE_STAT_CACHE: dict = {}


def _table_column_range(tbl: pa.Table, name: str):
    """Exact (min, max) of the column's canonical int64 lane (ints/dates
    as-is, bool as 0/1, narrow decimals as unscaled), or None for types
    without a single integer lane.  Weakref-cached like the uniqueness
    stats."""
    import weakref
    key = (id(tbl), name)
    hit = _RANGE_STAT_CACHE.get(key)
    if hit is not None and hit[0]() is tbl:
        return hit[1]
    import pyarrow.compute as pc
    col = tbl.column(name)
    typ = col.type
    rng = None
    try:
        if pa.types.is_integer(typ) or pa.types.is_date(typ) or \
                pa.types.is_boolean(typ):
            mm = pc.min_max(col)
            lo, hi = mm["min"].as_py(), mm["max"].as_py()
            if lo is not None:
                if pa.types.is_boolean(typ):
                    lo, hi = int(lo), int(hi)
                elif pa.types.is_date(typ):
                    import datetime as _dt
                    epoch = _dt.date(1970, 1, 1)
                    lo, hi = (lo - epoch).days, (hi - epoch).days
                rng = (int(lo), int(hi))
        elif pa.types.is_decimal(typ) and typ.precision <= 18:
            mm = pc.min_max(col)
            lo, hi = mm["min"].as_py(), mm["max"].as_py()
            if lo is not None:
                s = typ.scale
                rng = (int(lo.scaleb(s)), int(hi.scaleb(s)))
    except Exception:                            # noqa: BLE001
        rng = None
    try:
        ref = weakref.ref(tbl, lambda _r, k=key:
                          _RANGE_STAT_CACHE.pop(k, None))
    except TypeError:
        return rng
    if len(_RANGE_STAT_CACHE) > 4096:
        _RANGE_STAT_CACHE.clear()
    _RANGE_STAT_CACHE[key] = (ref, rng)
    return rng


class ProjectExec(PlanNode):
    """GpuProjectExec: one fused XLA program per row bucket
    (reference basicPhysicalOperators.scala:350)."""

    def __init__(self, exprs: Sequence[E.Expression], names: Sequence[str],
                 child: PlanNode):
        super().__init__(child)
        self.exprs = [e.bind(child.output_schema) for e in exprs]
        self.names = list(names)

    def keys_unique(self, names: Sequence[str]) -> bool:
        # renames/pass-throughs delegate to the child's columns; the
        # plain-reference rule is the shared join helper so the aligned-
        # path legality cannot drift between project and join
        from .join import key_ref_names
        mapped = []
        for n in names:
            if n not in self.names:
                return False
            ref = key_ref_names([self.exprs[self.names.index(n)]])
            if ref is None:
                return False
            mapped.extend(ref)
        return self.child.keys_unique(mapped)

    def static_row_count(self):
        return self.child.static_row_count()   # projection keeps rows

    def row_upper_bound(self):
        return self.child.row_upper_bound()

    def column_range(self, name):
        from .join import key_ref_names
        if name not in self.names:
            return None
        ref = key_ref_names([self.exprs[self.names.index(name)]])
        return None if ref is None else self.child.column_range(ref[0])

    @property
    def output_schema(self) -> t.StructType:
        return t.StructType([t.StructField(n, e.dtype)
                             for n, e in zip(self.names, self.exprs)])

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        from .evaluator import project_batch
        for db in self.child.execute(ctx):
            # thin-aware: plain refs to deferred columns pass through as
            # lanes (project_batch); computed exprs materialize their refs
            yield project_batch(self.exprs, self.names, db, ctx.conf)

    def describe(self):
        return f"ProjectExec[{', '.join(self.names)}]"


class FilterExec(PlanNode):
    """GpuFilterExec: predicate eval fused into one program, then stable
    mask compaction (ops/filter.py) instead of cuDF apply_boolean_mask."""

    def __init__(self, condition: E.Expression, child: PlanNode):
        super().__init__(child)
        self.condition = condition.bind(child.output_schema)

    @property
    def output_schema(self) -> t.StructType:
        return self.child.output_schema

    def keys_unique(self, names):
        return self.child.keys_unique(names)   # subset of rows

    def column_range(self, name):
        return self.child.column_range(name)   # subset of values

    def row_upper_bound(self):
        return self.child.row_upper_bound()    # filter only shrinks

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        from .evaluator import compute_predicate
        for db in self.child.execute(ctx):
            if db.thin is not None:
                # thin input: referenced deferred columns materialize
                # early (just those); the mask then COMPOSES into the
                # selection vector instead of compacting, so the lanes
                # stay live to the pipeline sink
                from ..columnar.lanes import materialize_refs
                db = materialize_refs(db, [self.condition], ctx.conf)
                if db.thin is not None and db.sel is not None and \
                        any(c.offsets is not None for c in db.columns):
                    # ragged+sel forces an internal prefix compaction in
                    # compute_predicate whose row order would desync
                    # from the lanes — resolve them first
                    from ..ops.batch_ops import ensure_prefix
                    db = ensure_prefix(db, ctx.conf)
                keep = compute_predicate(self.condition, db, ctx.conf)
                if db.thin is not None:
                    yield DeviceBatch(list(db.columns),
                                      jnp.sum(keep, dtype=jnp.int32),
                                      db.names, db.origin_file, sel=keep,
                                      thin=db.thin)
                    continue
            else:
                keep = compute_predicate(self.condition, db, ctx.conf)
            # lazy row count: downstream device ops keep running sync-free
            yield compact_batch(db, keep, ctx.conf)

    def describe(self):
        return f"FilterExec[{self.condition!r}]"


def sample_hash_u32(idx_u32, seed: int):
    """Murmur3 finalizer over the global live-row index mixed with the
    seed.  Pure uint32 lattice ops, so numpy (CPU path) and jnp (device
    path) produce bit-identical hashes — both engines keep exactly the
    same rows for a given seed."""
    h = idx_u32 ^ ((seed * 0x9E3779B9) & 0xFFFFFFFF)
    h = h ^ (h >> 16)
    h = h * 0x85EBCA6B
    h = h ^ (h >> 13)
    h = h * 0xC2B2AE35
    h = h ^ (h >> 16)
    return h


def sample_threshold(fraction: float) -> int:
    """uint32 keep-threshold for a Bernoulli fraction (callers special-
    case fraction >= 1.0: everything is kept, no compare)."""
    return min(int(round(fraction * 2.0 ** 32)), 2 ** 32 - 1)


class SampleExec(PlanNode):
    """GpuSampleExec (basicPhysicalOperators.scala:838): Bernoulli
    row sampling without replacement.  The keep decision is a counter-
    based hash of the row's global live position — no RNG state, so the
    result is deterministic per seed, independent of batch boundaries,
    and identical to the CPU fallback's (CpuSampleExec shares
    sample_hash_u32)."""

    def __init__(self, fraction: float, seed: int, child: PlanNode):
        super().__init__(child)
        self.fraction = float(fraction)
        self.seed = int(seed)

    @property
    def output_schema(self) -> t.StructType:
        return self.child.output_schema

    def keys_unique(self, names):
        return self.child.keys_unique(names)   # subset of rows

    def column_range(self, name):
        return self.child.column_range(name)   # subset of values

    def row_upper_bound(self):
        return self.child.row_upper_bound()    # sampling only shrinks

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        from ..ops.filter import compact_batch
        from ..ops.kernels import live_mask
        threshold = sample_threshold(self.fraction)
        offset = jnp.int64(0)
        for db in self.child.execute(ctx):
            if isinstance(db.num_rows, int) and db.num_rows == 0:
                continue
            if self.fraction >= 1.0:
                yield db
                offset = offset + jnp.asarray(db.num_rows, jnp.int64)
                continue
            cap = db.capacity
            if db.sel is not None:
                # lazy selection: live rows are sel-True, their global
                # position is the running count of earlier True lanes
                live = db.sel
                pos = jnp.cumsum(live.astype(jnp.int64)) - 1
            else:
                live = live_mask(cap, jnp.asarray(db.num_rows))
                pos = jnp.arange(cap, dtype=jnp.int64)
            idx32 = (offset + pos).astype(jnp.uint32)
            keep = live & (sample_hash_u32(idx32, self.seed)
                           < jnp.uint32(threshold))
            offset = offset + jnp.asarray(db.num_rows, jnp.int64)
            yield compact_batch(db, keep, ctx.conf)

    def describe(self):
        return f"SampleExec[{self.fraction}, seed={self.seed}]"


class HashAggregateExec(PlanNode):
    """GpuHashAggregateExec (GpuAggregateExec.scala:1711): streaming partial
    aggregation per batch, concat+merge regroup, final projection."""

    def __init__(self, key_exprs: Sequence[E.Expression],
                 key_names: Sequence[str],
                 aggs: Sequence[Tuple[AggregateFunction, str]],
                 child: PlanNode):
        super().__init__(child)
        schema = child.output_schema
        self.key_exprs = [e.bind(schema) for e in key_exprs]
        self.key_names = list(key_names)
        self.aggs = [(fn.bind(schema), name) for fn, name in aggs]
        from .aggregate import check_agg_buffers_supported
        check_agg_buffers_supported(self.aggs)

    @property
    def output_schema(self) -> t.StructType:
        fields = []
        for n, e in zip(self.key_names, self.key_exprs):
            fields.append(t.StructField(n, e.dtype))
        for fn, n in self.aggs:
            fields.append(t.StructField(n, fn.dtype))
        return t.StructType(fields)

    def keys_unique(self, names: Sequence[str]) -> bool:
        # the group-key tuple is unique by construction; any superset of a
        # unique tuple is unique.  A global aggregate has exactly one row.
        if not self.key_exprs:
            return True
        return set(self.key_names) <= set(names)

    def column_range(self, name):
        from .join import key_ref_names
        if name in self.key_names:
            # group-key columns pass values through unchanged
            e = self.key_exprs[self.key_names.index(name)]
            ref = key_ref_names([e])
            return None if ref is None else self.child.column_range(ref[0])
        # Min/Max aggregate outputs select existing values -> the child
        # column's range bounds them
        from ..plan.aggregates import Max, Min
        for fn, out_name in self.aggs:
            if out_name == name and isinstance(fn, (Min, Max)):
                ref = key_ref_names([fn.child])
                if ref is not None:
                    return self.child.column_range(ref[0])
        return None

    def static_row_count(self) -> Optional[int]:
        return 1 if not self.key_exprs else None

    def row_upper_bound(self):
        if not self.key_exprs:
            return 1
        # bounded key domains bound the group count (dense-domain shapes:
        # every key has exact range stats)
        ranges = self._key_ranges()
        if any(r is None for r in ranges):
            return None
        prod = 1
        for lo, hi in ranges:
            prod *= (hi - lo + 2)              # +1 span, +1 null slot
            if prod > (1 << 22):
                return None
        return prod

    def _strip_filters(self, can_fuse: bool):
        """Peel the chain of FilterExec children this aggregate can fuse;
        returns (batch source node, conditions outermost-last)."""
        source: PlanNode = self.child
        conds: List[E.Expression] = []
        if can_fuse:
            while isinstance(source, FilterExec):
                conds.append(source.condition)
                source = source.child
            conds.reverse()
        return source, conds

    def _key_ranges(self):
        """Exact (lo, hi) per group key from plan statistics (plain
        column refs only) — unlocks packed-lane group-by sorts."""
        from .join import key_ref_names
        out = []
        for e in self.key_exprs:
            ref = key_ref_names([e])
            out.append(None if ref is None
                       else self.child.column_range(ref[0]))
        return out

    def _input_ranges(self, agg) -> dict:
        """id(input expr) -> exact (lo, hi) for plain column refs with
        scan statistics — feeds the int32 gather narrowing."""
        from .join import key_ref_names
        out = {}
        for e in agg.input_exprs:
            ref = key_ref_names([e])
            if ref is not None:
                rng = self.child.column_range(ref[0])
                if rng is not None:
                    out[id(e)] = rng
        return out

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        from ..config import AGG_FALLBACK_PARTITIONS
        from . import ooc as O
        from .ooc_agg import OutOfCoreAggregator
        agg = HashAggregate(self.key_exprs, self.key_names, self.aggs,
                            ctx.conf, key_ranges=self._key_ranges())
        agg._input_ranges_by_expr = self._input_ranges(agg)
        # Fuse upstream filters into the map side for EVERY aggregation:
        # the predicates become the groupby's live-mask, so filter +
        # projections + update aggregation run with no mask compaction
        # (TPU row gathers — one argsort + per-column gathers — cost far
        # more than masked reduction lanes; ~3s at an 8M bucket).  Keys
        # the single-program fuse can't take (host dictionary work) still
        # skip the compact: the mask evaluates as its own program.
        source, conds = self._strip_filters(True)
        policy = O.ooc_policy(ctx)
        partials: List[DeviceBatch] = []
        partial_bytes = 0
        oocagg: "OutOfCoreAggregator | None" = None
        seen = False

        def start_ooc(mode: str) -> OutOfCoreAggregator:
            k = max(ctx.conf.get(AGG_FALLBACK_PARTITIONS),
                    O.partition_count(partial_bytes, policy))
            ctx.bump("agg_repartition_fallbacks")
            O.record_election(ctx, "agg", mode)
            return OutOfCoreAggregator(agg, len(self.key_names), ctx,
                                       policy, k)

        for db in source.execute(ctx):
            if isinstance(db.num_rows, int) and db.num_rows == 0:
                continue
            seen = True
            if db.thin is not None:
                # aggregation is a pipeline SINK: deferred columns the
                # keys/inputs/fused conds reference materialize here with
                # one composed gather per lane source; unreferenced ones
                # stay zero-capacity placeholders no program reads
                from ..columnar.lanes import materialize_refs
                db = materialize_refs(
                    db, list(conds) + list(self.key_exprs) +
                    list(agg.input_exprs), ctx.conf)
            if agg.can_fuse_filter(db):
                p = agg.partial_fused(db, conds)
            else:
                live = None
                if conds:
                    from .evaluator import compute_predicate
                    live = db.row_mask()
                    for c in conds:
                        live = live & compute_predicate(c, db, ctx.conf)
                p = agg.partial(db, live)
            if oocagg is not None:
                oocagg.add(p)
                continue
            partials.append(p)
            partial_bytes += O.batch_bytes(p)
            # OOC byte gate / forced context: the accumulated partial
            # working set exceeds the resident window (or the query is
            # escalated/forced out-of-core) — spill-partition by key NOW
            # instead of betting the merge below still reduces; key-
            # disjoint buckets make the union exact (exec/ooc_agg.py)
            if self.key_exprs and \
                    (policy.force or policy.bytes_trip(partial_bytes)):
                oocagg = start_ooc(
                    "forced" if policy.force else "bytes")
                for q in partials:
                    oocagg.add(q)
                partials = []
                continue
            # Bound the pending set: merge when the partials would overflow
            # one target batch (the reference's tryMergeAggregatedBatches).
            # Capacity is a host fact, so the gate never syncs; it bounds
            # rows from above (merging slightly early is harmless).
            if len(partials) > 1 and \
                    sum(p.capacity for p in partials) > ctx.conf.batch_size_rows:
                merged = agg.merge(partials)
                if self.key_exprs and \
                        isinstance(merged.num_rows, int) and \
                        merged.num_rows > ctx.conf.batch_size_rows:
                    # High-cardinality fallback (GpuAggregateExec.scala:711
                    # repartition-based path): merging no longer reduces, so
                    # hash-split the merged partials into independently
                    # mergeable buckets held as spillables.
                    oocagg = start_ooc("rows")
                    oocagg.add(merged)
                    partials = []
                else:
                    partials = [merged]
                    partial_bytes = O.batch_bytes(merged)
        if oocagg is not None:
            # results() owns the cleanup sweep (idempotent closes), so a
            # LIMIT above this aggregation leaks no spill files
            yield from oocagg.results()
            return
        if not seen:
            if self.key_exprs:
                return  # grouped agg over empty input -> no rows
            # global agg over empty input still emits one row (e.g. COUNT=0)
            empty = empty_device_batch(self.child.output_schema, ctx.conf)
            partials = [agg.partial(empty)]
        merged = agg.merge(partials) if len(partials) > 1 else partials[0]
        yield agg.final(merged)

    def collect_device(self, ctx: Optional[ExecContext] = None):
        """Dispatch a global (no-key) aggregation fully async: returns
        (outs, finalize) where `outs` is the list of (scalar, valid) device
        buffers and `finalize(fetched)` turns their host values into the
        result table.  No host sync happens inside this call — callers can
        pipeline many queries and batch all fetches into one D2H round trip
        (the concurrent-GpuSemaphore-tasks analogue for a chip behind a
        high-latency link)."""
        if self.key_exprs:
            raise ValueError("collect_device is for global aggregations")
        ctx = ctx or ExecContext()
        agg = HashAggregate(self.key_exprs, self.key_names, self.aggs,
                            ctx.conf)
        source, conds = self._strip_filters(True)
        raw = []
        for db in source.execute(ctx):
            if isinstance(db.num_rows, int) and db.num_rows == 0:
                continue
            if db.thin is not None:
                # same sink rule as execute(): deferred columns the
                # fused conds/inputs reference materialize here
                from ..columnar.lanes import materialize_refs
                db = materialize_refs(db, list(conds) +
                                      list(agg.input_exprs), ctx.conf)
            raw.append(agg.partial_fused(db, conds, raw=True))
        if not raw:
            empty = empty_device_batch(source.output_schema, ctx.conf)
            raw.append(agg.partial_fused(empty, conds, raw=True))
        return agg.merge_raw(raw), agg.finalize_fetched

    def collect(self, ctx: Optional[ExecContext] = None) -> pa.Table:
        """Global (no-key) aggregations finish on host from raw buffer
        scalars: N fused partial dispatches + at most one merge dispatch +
        ONE D2H fetch — no 1-row device batches, no device final
        projection."""
        if self.key_exprs:
            return super().collect(ctx)
        import jax
        outs, finalize = self.collect_device(ctx)
        return finalize(jax.device_get(list(outs)))

    def describe(self):
        return (f"HashAggregateExec[keys={self.key_names}, "
                f"aggs={[n for _, n in self.aggs]}]")


_AGG_PART_CACHE = {}


def _agg_partition_ids(pb: DeviceBatch, nkeys: int, num_buckets: int,
                       salt: int = 0):
    """Deterministic bucket id per row from the leading `nkeys` columns.

    Unlike shuffle HashPartitioning this need not be Spark-exact — it only
    must map equal keys to equal buckets across batches: string columns
    hash their dictionary VALUES through a host crc32 table (per-batch
    codes are not stable), other lanes fold to uint32.  `salt` decorrelates
    recursive re-scatters (same hash would map a bucket onto itself).
    crc32 tables pad to power-of-two sizes so per-batch dictionary growth
    does not churn the jit cache."""
    import jax

    tables = {}
    for i, c in enumerate(pb.columns[:nkeys]):
        if c.dictionary is not None:
            tables[i] = _dict_crc_table(c.dictionary)
    dtypes = tuple(c.dtype for c in pb.columns[:nkeys])
    sig = ("aggpart", pb.capacity, num_buckets, nkeys, salt,
           tuple(d.simple_string for d in dtypes),
           tuple((str(c.data.dtype), c.data_hi is not None,
                  i in tables and int(tables[i].shape[0]))
                 for i, c in enumerate(pb.columns[:nkeys])))
    fn = _AGG_PART_CACHE.get(sig)
    if fn is None:
        capacity = pb.capacity

        salt_c = jnp.uint32((salt * 0x9E3779B9) & 0xFFFFFFFF)

        def run(datas, valids, his, tabs):
            h = jnp.full((capacity,), 17, jnp.uint32)
            for i in range(nkeys):
                d = datas[i]
                if i in tabs:
                    tab = tabs[i]
                    lane = tab[jnp.clip(d, 0, tab.shape[0] - 1)]
                elif isinstance(dtypes[i], (t.DoubleType, t.FloatType)):
                    # DOUBLE has two storage lanes (int64 bit patterns /
                    # native f64); hash a lane-independent value derivation
                    # so spilled-and-reuploaded batches bucket identically
                    from ..ops.kernels import compute_view
                    f = compute_view(d, dtypes[i]).astype(jnp.float64)
                    isnan = jnp.isnan(f)
                    isinf = jnp.isinf(f)
                    safe = jnp.where(isnan | isinf, 0.0, f)
                    ip = jnp.floor(safe)
                    fr = ((safe - ip) * jnp.float64(1 << 30)) \
                        .astype(jnp.uint32)
                    ii = jnp.clip(ip, -2.0**62, 2.0**62).astype(jnp.int64)
                    lane = ((ii ^ (ii >> 32)).astype(jnp.uint32)
                            * jnp.uint32(31)) ^ fr
                    lane = jnp.where(isnan, jnp.uint32(0xA5A5A5A5), lane)
                    lane = jnp.where(isinf & (f > 0),
                                     jnp.uint32(0x77777777), lane)
                    lane = jnp.where(isinf & (f < 0),
                                     jnp.uint32(0x33333333), lane)
                else:
                    # equal values -> equal lanes is all bucketing needs
                    x = d.astype(jnp.int64)
                    lane = (x ^ (x >> 32)).astype(jnp.uint32)
                lane = jnp.where(valids[i], lane, jnp.uint32(0x9E3779B9))
                # XOR-salt each lane: an additive salt would only rotate
                # bucket labels, leaving re-scatter groupings unchanged
                h = h * jnp.uint32(2654435761) + (lane ^ salt_c)
                if his[i] is not None:
                    hx = his[i]
                    h = h * jnp.uint32(31) + \
                        ((hx ^ (hx >> 32)).astype(jnp.uint32))
            # avalanche so the low bits (the modulo) see every input bit
            h = h ^ (h >> 16)
            h = h * jnp.uint32(0x7FEB352D)
            h = h ^ (h >> 15)
            return (h % jnp.uint32(num_buckets)).astype(jnp.int32)

        fn = jax.jit(run)
        if len(_AGG_PART_CACHE) > 512:
            _AGG_PART_CACHE.clear()
        _AGG_PART_CACHE[sig] = fn
    return fn(tuple(c.data for c in pb.columns[:nkeys]),
              tuple(c.validity for c in pb.columns[:nkeys]),
              tuple(c.data_hi for c in pb.columns[:nkeys]), tables)


_CRC_TABLE_CACHE = {}


def _dict_crc_table(dictionary):
    """crc32-of-value table for a string dictionary, padded to a power of
    two (stable jit signatures) and cached by dictionary identity (the
    same pa.Array flows through every batch sharing the dictionary)."""
    import zlib
    import numpy as np
    key = id(dictionary)
    hit = _CRC_TABLE_CACHE.get(key)
    if hit is not None and hit[0] is dictionary:
        return hit[1]
    ent = [zlib.crc32(s.encode("utf-8")) if s is not None else 0
           for s in dictionary.to_pylist()] or [0]
    padded = 1 << (len(ent) - 1).bit_length()
    ent += [0] * (padded - len(ent))
    tab = jnp.asarray(np.asarray(ent, np.uint32))
    import jax
    if isinstance(tab, jax.core.Tracer):
        return tab               # whole-plan tracing: never cache tracers
    if len(_CRC_TABLE_CACHE) > 512:
        _CRC_TABLE_CACHE.clear()
    # pin the dictionary so its id stays valid while cached
    _CRC_TABLE_CACHE[key] = (dictionary, tab)
    return tab


class LocalLimitExec(PlanNode):
    """Per-stream limit (GpuLocalLimitExec, limit.scala)."""

    def __init__(self, limit: int, child: PlanNode):
        super().__init__(child)
        self.limit = limit

    @property
    def output_schema(self) -> t.StructType:
        return self.child.output_schema

    def keys_unique(self, names):
        return self.child.keys_unique(names)   # prefix of rows

    def column_range(self, name):
        return self.child.column_range(name)

    def row_upper_bound(self):
        child = self.child.row_upper_bound()
        return self.limit if child is None else min(self.limit, child)

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        # Never peek ahead: pulling a second batch before emitting would
        # compute an entire extra upstream batch even when the first one
        # already satisfies the limit.  A lazy count costs one scalar
        # sync; the payoff is the capacity slice (shrink_to_capacity), so
        # a tiny LIMIT never ships a full-capacity batch to host.
        from ..ops.batch_ops import ensure_prefix, shrink_to_capacity
        remaining = self.limit
        for db in self.child.execute(ctx):
            if remaining <= 0:
                return
            db = ensure_prefix(db, ctx.conf)   # limit cuts a PREFIX
            n = int(db.num_rows)
            if n == 0:
                continue
            if n < remaining:
                remaining -= n
                yield db
            else:
                yield shrink_to_capacity(_truncate(db, remaining),
                                         remaining, ctx.conf)
                return

    def describe(self):
        return f"{self.name()}[{self.limit}]"


class GlobalLimitExec(LocalLimitExec):
    """Same device semantics as local limit; the global cut happens after
    the single-partition exchange inserted by the planner."""


def _truncate(db: DeviceBatch, rows: int) -> DeviceBatch:
    from ..columnar.device import DeviceColumn
    live = jnp.arange(db.capacity, dtype=jnp.int32) < jnp.int32(rows)
    cols = [DeviceColumn(c.data, c.validity & live, c.dtype, c.dictionary,
                         c.data_hi) for c in db.columns]
    return DeviceBatch(cols, rows, db.names, db.origin_file)


class UnionExec(PlanNode):
    """GpuUnionExec: concatenation of children streams (schema-aligned)."""

    def __init__(self, *children: PlanNode):
        super().__init__(*children)

    @property
    def output_schema(self) -> t.StructType:
        return self.children[0].output_schema

    def column_range(self, name):
        rngs = [c.column_range(name) for c in self.children]
        if any(r is None for r in rngs):
            return None
        return (min(r[0] for r in rngs), max(r[1] for r in rngs))

    def row_upper_bound(self):
        bounds = [c.row_upper_bound() for c in self.children]
        if any(b is None for b in bounds):
            return None
        return sum(bounds)

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        names = list(self.output_schema.names)
        for c in self.children:
            for db in c.execute(ctx):
                yield DeviceBatch(db.columns, db.num_rows, names)


class CoalesceBatchesExec(PlanNode):
    """GpuCoalesceBatches (GpuCoalesceBatches.scala:697): concatenate small
    batches until the target row goal so downstream programs run on full
    buckets."""

    def __init__(self, child: PlanNode, target_rows: Optional[int] = None,
                 require_single: bool = False):
        super().__init__(child)
        self.target_rows = target_rows
        self.require_single = require_single

    @property
    def output_schema(self) -> t.StructType:
        return self.child.output_schema

    def keys_unique(self, names):
        return self.child.keys_unique(names)   # same rows, repacked

    def static_row_count(self):
        return self.child.static_row_count()

    def column_range(self, name):
        return self.child.column_range(name)

    def row_upper_bound(self):
        return self.child.row_upper_bound()

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        target = self.target_rows or ctx.conf.batch_size_rows
        pending: List[DeviceBatch] = []
        rows = 0
        for db in self.child.execute(ctx):
            n = int(db.num_rows)   # coalesce sizes batches -> sync point
            if n == 0:
                continue
            if not self.require_single and rows and rows + n > target:
                yield concat_batches(pending, ctx.conf)
                pending, rows = [], 0
            pending.append(db)
            rows += n
        if pending:
            yield concat_batches(pending, ctx.conf)

    def describe(self):
        goal = "RequireSingleBatch" if self.require_single \
            else f"target={self.target_rows or 'conf'}"
        return f"CoalesceBatchesExec[{goal}]"


class SortExec(PlanNode):
    """GpuSortExec (GpuSortExec.scala:86): sorts by SortOrder keys.

    global_sort runs through the out-of-core sorter (exec/ooc_sort.py):
    under an HBM budget the input accumulates as spillable sorted runs
    merged by capstone-bounded concat+resort passes (the
    GpuOutOfCoreSortIterator role); with no budget it degenerates to one
    concat+lexsort.  Local sort orders each batch independently (enough
    for sort-merge structures and windows)."""

    def __init__(self, keys, child: PlanNode, global_sort: bool = True):
        from ..ops.sort import SortKey
        super().__init__(child)
        self.keys = [k if isinstance(k, SortKey) else SortKey(*k)
                     for k in keys]
        self.global_sort = global_sort

    @property
    def output_schema(self) -> t.StructType:
        return self.child.output_schema

    def keys_unique(self, names):
        return self.child.keys_unique(names)   # permutation of rows

    def static_row_count(self):
        return self.child.static_row_count()

    def row_upper_bound(self):
        return self.child.row_upper_bound()

    def column_range(self, name):
        return self.child.column_range(name)

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        from ..ops.sort import sort_batch
        if not self.global_sort:
            for db in self.child.execute(ctx):
                yield sort_batch(db, self.keys, ctx.conf)
            return
        # Single-batch input sorts directly with zero host syncs (the
        # dominant case once upstream operators keep lazy row counts);
        # the out-of-core path engages from the second batch on.
        it = self.child.execute(ctx)
        first = next(it, None)
        if first is None:
            return
        second = next(it, None)
        if second is None:
            yield sort_batch(first, self.keys, ctx.conf)
            return
        from .ooc_sort import OutOfCoreSorter
        sorter = OutOfCoreSorter(self.keys, ctx)
        sorter.add(first)
        sorter.add(second)
        for db in it:
            sorter.add(db)
        yield from sorter.results()

    def describe(self):
        scope = "global" if self.global_sort else "local"
        return f"SortExec[{scope}, {self.keys}]"


class TopNExec(PlanNode):
    """GpuTopN (limit.scala): sort + limit without materializing the full
    sorted output — each batch keeps only its top-N prefix, pending rows
    are re-sorted together and cut once more at the end."""

    def __init__(self, limit: int, keys, child: PlanNode):
        from ..ops.sort import SortKey
        super().__init__(child)
        self.limit = limit
        self.keys = [k if isinstance(k, SortKey) else SortKey(*k)
                     for k in keys]

    @property
    def output_schema(self) -> t.StructType:
        return self.child.output_schema

    def keys_unique(self, names):
        return self.child.keys_unique(names)   # prefix of a permutation

    def column_range(self, name):
        return self.child.column_range(name)

    def row_upper_bound(self):
        child = self.child.row_upper_bound()
        return self.limit if child is None else min(self.limit, child)

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        from ..ops.sort import sort_batch
        pending: Optional[DeviceBatch] = None
        from ..ops.batch_ops import shrink_to_capacity
        for db in self.child.execute(ctx):
            if isinstance(db.num_rows, int) and db.num_rows == 0:
                continue
            batch = db if pending is None \
                else concat_batches([pending, db], ctx.conf)
            s = sort_batch(batch, self.keys, ctx.conf)
            # lazy cut + static capacity shrink: live rows <= limit by
            # construction, so the bucket slice needs no row-count sync
            nl = jnp.minimum(jnp.int32(self.limit), jnp.int32(s.num_rows))
            pending = shrink_to_capacity(_truncate(s, nl), self.limit,
                                         ctx.conf)
        if pending is not None:
            yield pending

    def describe(self):
        return f"TopNExec[{self.limit}, {self.keys}]"


class RangeExec(PlanNode):
    """GpuRangeExec (basicPhysicalOperators.scala:838): generates id ranges
    directly on device with iota."""

    def __init__(self, start: int, end: int, step: int = 1,
                 name: str = "id", batch_rows: Optional[int] = None):
        super().__init__()
        assert step != 0
        self.start, self.end, self.step = start, end, step
        self.col_name = name
        self.batch_rows = batch_rows

    @property
    def output_schema(self) -> t.StructType:
        return t.StructType([t.StructField(self.col_name, t.LongType())])

    def keys_unique(self, names):
        return list(names) == [self.col_name]   # iota never repeats

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        from ..columnar.device import DeviceColumn, bucket_capacity
        total = max(0, -(-(self.end - self.start) // self.step))
        chunk = self.batch_rows or ctx.conf.batch_size_rows
        emitted = 0
        while emitted < total:
            n = min(chunk, total - emitted)
            cap = bucket_capacity(n, ctx.conf)
            base = self.start + emitted * self.step
            data = jnp.int64(base) + jnp.arange(cap, dtype=jnp.int64) * self.step
            live = jnp.arange(cap, dtype=jnp.int32) < jnp.int32(n)
            yield DeviceBatch(
                [DeviceColumn(data, live, t.LongType())], n, [self.col_name])
            emitted += n
        if total == 0:
            return

    def describe(self):
        return f"RangeExec[{self.start},{self.end},{self.step}]"


class ExpandExec(PlanNode):
    """GpuExpandExec (GpuExpandExec.scala:70): N projections per input batch
    (rollup/cube/grouping sets lowering)."""

    def __init__(self, projections: Sequence[Sequence[E.Expression]],
                 names: Sequence[str], child: PlanNode):
        super().__init__(child)
        self.projections = [[e.bind(child.output_schema) for e in p]
                            for p in projections]
        self.names = list(names)

    @property
    def output_schema(self) -> t.StructType:
        return t.StructType([t.StructField(n, e.dtype) for n, e in
                             zip(self.names, self.projections[0])])

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        for db in self.child.execute(ctx):
            for proj in self.projections:
                yield evaluate_projection(proj, self.names, db, ctx.conf)
