"""Exchange execs: shuffle write/read + broadcast.

Reference: GpuShuffleExchangeExecBase.scala:167 (device partition split ->
serialize -> shuffle write), GpuShuffleCoalesceExec.scala:43 (reduce side:
concat host payloads to target batch size, ONE upload), and
GpuBroadcastExchangeExec.scala:352.

Single-process realization: ShuffleExchangeExec materializes the child
through the in-process ShuffleManager keyed by partition; downstream
ShuffleReadExec streams any subset of partitions.  The two halves are
separate plan nodes exactly so a runtime scheduler (runtime/) can run map
and reduce stages as independent task sets — the same stage split Spark
performs at every exchange.
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple, Union

#: one reduce-read work unit: a whole partition id, or a (partition,
#: block_lo, block_hi) slice of a skew-split partition's map blocks
ReadUnit = Union[int, Tuple[int, int, int]]

import numpy as np
import pyarrow as pa

from .. import types as t
from ..columnar.device import DeviceBatch, to_device, to_host
from ..columnar.host import HostBatch, struct_to_schema
from ..shuffle.manager import ShuffleManager, get_shuffle_manager
from ..shuffle.partition import Partitioning, SinglePartitioning
from .plan import ExecContext, PlanNode


class ShuffleExchangeExec(PlanNode):
    """Map side: partition every child batch and write to the shuffle
    store.  `materialize(ctx)` runs the whole map stage; execute() yields
    the read-back stream of all partitions (for single-process plans that
    consume the exchange inline)."""

    def __init__(self, partitioning: Partitioning, child: PlanNode):
        super().__init__(child)
        self.partitioning = partitioning
        if hasattr(partitioning, "bind"):
            partitioning.bind(child.output_schema)
        self.shuffle_id: Optional[int] = None

    @property
    def output_schema(self) -> t.StructType:
        return self.child.output_schema

    def materialize(self, ctx: ExecContext) -> int:
        """Run the map stage; returns the shuffle id."""
        if self.shuffle_id is not None:
            return self.shuffle_id
        from ..config import SHUFFLE_COMPRESSION
        from ..runtime.retry import retry_io
        mgr = get_shuffle_manager()
        sid = mgr.new_shuffle()
        n = self.partitioning.num_partitions
        codec = str(ctx.conf.get(SHUFFLE_COMPRESSION)).lower()
        for db in self.child.execute(ctx):
            if db.sel is not None or db.thin is not None:
                # exchange is a pipeline SINK: partition ids must align
                # row-for-row with the serialized prefix, so lazy
                # selection vectors compact and deferred columns resolve
                # (one composed gather per lane source) before splitting
                from ..ops.batch_ops import ensure_prefix
                db = ensure_prefix(db, ctx.conf)
            if int(db.num_rows) == 0:
                continue
            ids = self.partitioning.partition_ids(db, ctx.conf)
            with ctx.tracer.span("shuffle_fetch", "transition",
                                 node=getattr(self, "_node_id", None)):
                hb = retry_io(ctx.conf, "d2h", lambda: to_host(db))
            ctx.tracer.add_bytes("d2h_bytes", hb.rb.nbytes)
            with ctx.tracer.span("shuffle_write", "shuffle",
                                 node=getattr(self, "_node_id", None)):
                # write_batch is transactional (nothing published until
                # every slice serialized) so the retry cannot duplicate
                nbytes = retry_io(
                    ctx.conf, "shuffle_write",
                    lambda: mgr.write_batch(sid, hb, ids, n, codec))
            ctx.bump("shuffle_rows_written", int(db.num_rows))
            ctx.bump("shuffle_bytes_written", nbytes)
            ctx.tracer.add_bytes("shuffle_bytes_written", nbytes)
        self.shuffle_id = sid
        return sid

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        sid = self.materialize(ctx)
        from ..config import (ADAPTIVE_ADVISORY_PARTITION_BYTES,
                              ADAPTIVE_ENABLED)
        if ctx.conf.get(ADAPTIVE_ENABLED):
            # AQE analogue: one reduce group per ~advisory bytes from
            # REAL map-output sizes (GpuAQEShuffleRead role) instead of
            # one group per partition
            from .adaptive import plan_coalesced_reads
            groups = plan_coalesced_reads(
                self, ctx,
                int(ctx.conf.get(ADAPTIVE_ADVISORY_PARTITION_BYTES)))
        else:
            groups = [[p] for p in
                      range(self.partitioning.num_partitions)]
        for group in groups:
            reader = ShuffleReadExec(self, group)
            reader.shuffle_id = sid
            yield from reader.execute(ctx)

    def describe(self):
        return (f"ShuffleExchangeExec[{type(self.partitioning).__name__}"
                f"({self.partitioning.num_partitions})]")


class ShuffleReadExec(PlanNode):
    """Reduce side (GpuShuffleCoalesceExec role): read partition payloads,
    concatenate on HOST up to the batch row target, upload once per
    coalesced group."""

    def __init__(self, exchange: ShuffleExchangeExec,
                 partitions: Sequence[ReadUnit]):
        super().__init__(exchange)
        self.exchange = exchange
        self.partitions = list(partitions)
        self.shuffle_id: Optional[int] = None

    @property
    def output_schema(self) -> t.StructType:
        return self.exchange.output_schema

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        sid = self.shuffle_id if self.shuffle_id is not None \
            else self.exchange.materialize(ctx)
        from ..runtime.retry import retry_io
        mgr = get_shuffle_manager()
        target = ctx.conf.batch_size_rows
        pending: List[pa.RecordBatch] = []
        rows = 0
        for unit in self.partitions:
            # a unit is a whole partition id or a (partition, block_lo,
            # block_hi) skew sub-read (plan_coalesced_reads)
            with ctx.tracer.span("shuffle_read", "shuffle",
                                 node=getattr(self, "_node_id", None)):
                if isinstance(unit, tuple):
                    p, lo, hi = unit
                    rbs = retry_io(
                        ctx.conf, "shuffle_fetch",
                        lambda: mgr.read_partition(sid, p,
                                                   block_range=(lo, hi)))
                    nbytes = sum(mgr.block_sizes(sid, p)[lo:hi])
                else:
                    rbs = retry_io(
                        ctx.conf, "shuffle_fetch",
                        lambda: mgr.read_partition(sid, unit))
                    nbytes = sum(mgr.block_sizes(sid, unit))
            ctx.bump("shuffle_bytes_read", nbytes)
            ctx.tracer.add_bytes("shuffle_bytes_read", nbytes)
            for rb in rbs:
                if rb.num_rows == 0:
                    continue
                if rows and rows + rb.num_rows > target:
                    yield self._upload(pending, ctx)
                    pending, rows = [], 0
                pending.append(rb)
                rows += rb.num_rows
        if pending:
            yield self._upload(pending, ctx)

    def _upload(self, rbs: List[pa.RecordBatch], ctx) -> DeviceBatch:
        from ..runtime.retry import retry_io
        if len(rbs) == 1 and rbs[0].num_rows:
            # one payload (AQE-coalesced group, skew sub-read): upload
            # it directly — the Table round trip below would copy every
            # column through combine_chunks for nothing
            hb = HostBatch(rbs[0])
        else:
            tbl = pa.Table.from_batches(rbs).combine_chunks()
            hb = HostBatch(tbl.to_batches()[0] if tbl.num_rows else
                           pa.RecordBatch.from_pydict(
                               {n: [] for n in tbl.schema.names},
                               schema=tbl.schema))
        ctx.bump("shuffle_rows_read", hb.num_rows)
        ctx.tracer.add_bytes("h2d_bytes", hb.rb.nbytes)
        with ctx.tracer.span("upload", "transition",
                             node=getattr(self, "_node_id", None)):
            return retry_io(ctx.conf, "h2d",
                            lambda: to_device(hb, ctx.conf))

    def describe(self):
        return f"ShuffleReadExec[{len(self.partitions)} parts]"


class PartitionReadExec(PlanNode):
    """Reduce-task view of ONE partition of an exchange — the unit the
    runtime scheduler assigns to a task."""

    def __init__(self, exchange: ShuffleExchangeExec, partition: int):
        super().__init__(exchange)
        self.exchange = exchange
        self.partition = partition

    @property
    def output_schema(self) -> t.StructType:
        return self.exchange.output_schema

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        reader = ShuffleReadExec(self.exchange, [self.partition])
        yield from reader.execute(ctx)


class BroadcastExchangeExec(PlanNode):
    """GpuBroadcastExchangeExec analogue: materializes the child once and
    replays the host copy to every consumer (single-process: a cache; the
    mesh path broadcasts via replicated sharding in parallel/mesh.py)."""

    def __init__(self, child: PlanNode):
        super().__init__(child)
        self._cached: Optional[pa.Table] = None

    @property
    def output_schema(self) -> t.StructType:
        return self.child.output_schema

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        if self._cached is None:
            hbs = [to_host(db).rb for db in self.child.execute(ctx)
                   if int(db.num_rows) > 0]
            schema = struct_to_schema(self.output_schema)
            self._cached = pa.Table.from_batches(hbs, schema) if hbs \
                else pa.Table.from_batches([], schema)
        tbl = self._cached.combine_chunks()
        for rb in tbl.to_batches():
            yield to_device(HostBatch(rb), ctx.conf)
