"""Hash-aggregate execution: partial -> merge -> final over device batches.

The GpuHashAggregateExec analogue (reference GpuAggregateExec.scala:1711,
call stack SURVEY §3.3): per input batch, project the aggregate inputs and
run the update groupby (partial); accumulated partials are concatenated and
re-grouped with the merge ops; the final projection evaluates each
aggregate's result expression over the merged buffers.

TPU-first deltas from the reference:
  * partial aggregation is sort+segment (ops/groupby.py), not hash tables;
  * merge is concat+regroup in one jit rather than cuDF concatenate+groupby;
  * string group keys ride as unified dictionary codes, so regrouping
    across batches is plain int comparison.
"""
from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from .. import types as t
from ..config import TpuConf
from ..columnar.device import DeviceBatch, DeviceColumn
from ..ops import groupby as G
from ..ops.batch_ops import concat_batches, shrink_to_rows, unify_dictionaries, \
    remap_string_column
from ..plan import expressions as E
from ..plan.aggregates import AggregateFunction
from .evaluator import evaluate_projection

_GROUPBY_CACHE = {}
_REDUCE_CACHE = {}


def _ensure_unique_dict(col: DeviceColumn) -> DeviceColumn:
    """Group keys compare by code, which requires a duplicate-free dict."""
    d = col.dictionary
    if d is None:
        return col
    unified, remaps = unify_dictionaries([d])
    if len(unified) == len(d):
        return col
    return remap_string_column(col, remaps[0], unified)


def _run_groupby(key_cols: List[DeviceColumn], agg_cols: List[DeviceColumn],
                 specs: List[G.AggSpec], num_rows: int, capacity: int):
    key_cols = [_ensure_unique_dict(c) for c in key_cols]
    info = tuple((c.dtype, True, str(c.data.dtype)) for c in key_cols)
    sig = (info, tuple((s.kind, s.input_idx, s.dtype) for s in specs),
           capacity, tuple(str(c.data.dtype) for c in agg_cols))
    fn = _GROUPBY_CACHE.get(sig)
    if fn is None:
        fn = jax.jit(G.groupby_trace(list(info), list(specs), capacity,
                                     capacity))
        _GROUPBY_CACHE[sig] = fn
    out_keys, outs, num_groups = fn(
        tuple(c.data for c in key_cols),
        tuple(c.validity for c in key_cols),
        tuple(c.data for c in agg_cols),
        tuple(c.validity for c in agg_cols),
        jnp.int32(num_rows))
    return key_cols, out_keys, outs, int(num_groups)


def _run_reduce(agg_cols: List[DeviceColumn], specs: List[G.AggSpec],
                num_rows: int, capacity: int):
    sig = (tuple((s.kind, s.input_idx, s.dtype) for s in specs), capacity,
           tuple(str(c.data.dtype) for c in agg_cols))
    fn = _REDUCE_CACHE.get(sig)
    if fn is None:
        fn = jax.jit(G.reduce_trace(list(specs), capacity))
        _REDUCE_CACHE[sig] = fn
    return fn(tuple(c.data for c in agg_cols),
              tuple(c.validity for c in agg_cols), jnp.int32(num_rows))


def _storage_zeros(dt: t.DataType, capacity: int):
    if isinstance(dt, t.DoubleType):
        return jnp.zeros((capacity,), jnp.float64)
    return jnp.zeros((capacity,), t.physical_np_dtype(dt))


class HashAggregate:
    """Bound group-by aggregation over a stream of device batches."""

    def __init__(self, key_exprs: Sequence[E.Expression],
                 key_names: Sequence[str],
                 aggs: Sequence[Tuple[AggregateFunction, str]],
                 conf: TpuConf):
        self.key_exprs = list(key_exprs)
        self.key_names = list(key_names)
        self.aggs = list(aggs)
        self.conf = conf
        # flatten buffers
        self.update_specs: List[G.AggSpec] = []
        self.merge_specs: List[G.AggSpec] = []
        self.input_exprs: List[Optional[E.Expression]] = []
        self.buffer_slices: List[Tuple[int, int]] = []
        for fn, _name in self.aggs:
            start = len(self.update_specs)
            ins = fn.inputs()
            for (kind, bdt), (mkind, mdt), inp in zip(
                    fn.update_ops(), fn.merge_ops(), ins):
                idx = -1
                if inp is not None:
                    idx = len(self.input_exprs)
                    self.input_exprs.append(inp)
                self.update_specs.append(G.AggSpec(kind, idx, bdt))
            self.buffer_slices.append((start, len(self.update_specs)))
        # merge specs operate on buffer columns positionally
        mi = 0
        for (fn, _name) in self.aggs:
            for (mkind, mdt) in fn.merge_ops():
                self.merge_specs.append(G.AggSpec(mkind, mi, mdt))
                mi += 1

    # ---- phases ----

    def partial(self, db: DeviceBatch) -> DeviceBatch:
        """One input batch -> (keys + buffer columns) partial result."""
        key_batch = evaluate_projection(self.key_exprs, self.key_names, db,
                                        self.conf) if self.key_exprs else None
        agg_in = evaluate_projection(
            [e for e in self.input_exprs],
            [f"_in{i}" for i in range(len(self.input_exprs))], db, self.conf) \
            if self.input_exprs else None
        agg_cols = agg_in.columns if agg_in is not None else []
        if not self.key_exprs:
            outs = _run_reduce(agg_cols, self.update_specs, db.num_rows,
                               db.capacity)
            return self._reduce_outs_to_batch(outs)
        key_cols, out_keys, outs, n_groups = _run_groupby(
            key_batch.columns, agg_cols, self.update_specs, db.num_rows,
            db.capacity)
        return self._groupby_outs_to_batch(key_cols, out_keys, outs, n_groups)

    def merge(self, partials: List[DeviceBatch]) -> DeviceBatch:
        merged = concat_batches(partials, self.conf)
        nkeys = len(self.key_exprs)
        key_cols = merged.columns[:nkeys]
        buf_cols = merged.columns[nkeys:]
        if not self.key_exprs:
            outs = _run_reduce(buf_cols, self.merge_specs, merged.num_rows,
                               merged.capacity)
            return self._reduce_outs_to_batch(outs)
        key_cols, out_keys, outs, n_groups = _run_groupby(
            key_cols, buf_cols, self.merge_specs, merged.num_rows,
            merged.capacity)
        return self._groupby_outs_to_batch(key_cols, out_keys, outs, n_groups)

    def final(self, merged: DeviceBatch) -> DeviceBatch:
        """Evaluate result expressions over (keys + buffers)."""
        nkeys = len(self.key_exprs)
        schema = merged.schema
        out_exprs: List[E.Expression] = []
        out_names: List[str] = []
        for i, name in enumerate(self.key_names):
            out_exprs.append(E.ColumnRef(name).bind(schema))
            out_names.append(name)
        for (fn, name), (start, end) in zip(self.aggs, self.buffer_slices):
            refs = [E.ColumnRef(f"_buf{j}").bind(schema)
                    for j in range(start, end)]
            expr = fn.evaluate(refs)
            from ..plan.aggregates import _resolved
            out_exprs.append(_resolved(expr) if expr.dtype is None else expr)
            out_names.append(name)
        return evaluate_projection(out_exprs, out_names, merged, self.conf)

    def execute(self, batches: Iterable[DeviceBatch]) -> DeviceBatch:
        partials = [self.partial(db) for db in batches]
        if not partials:
            raise ValueError("aggregation over zero batches")
        merged = self.merge(partials) if len(partials) > 1 else partials[0]
        return self.final(merged)

    # ---- plumbing ----

    def _buffer_names(self):
        return [f"_buf{i}" for i in range(len(self.update_specs))]

    def _groupby_outs_to_batch(self, key_cols, out_keys, outs, n_groups):
        cols = []
        for (kd, kv), kc in zip(out_keys, key_cols):
            cols.append(DeviceColumn(kd, kv, kc.dtype, kc.dictionary,
                                     kc.data_hi))
        # update and merge specs share buffer dtypes positionally
        for (data, valid), spec in zip(outs, self.update_specs):
            cols.append(DeviceColumn(data.astype(_storage_zeros(
                spec.dtype, 1).dtype), valid, spec.dtype))
        db = DeviceBatch(cols, n_groups, self.key_names + self._buffer_names())
        return shrink_to_rows(db, n_groups, self.conf)

    def _reduce_outs_to_batch(self, outs) -> DeviceBatch:
        from ..columnar.device import bucket_capacity
        cap = bucket_capacity(1, self.conf)
        cols = []
        for (data, valid), spec in zip(outs, self.update_specs):
            d = jnp.zeros((cap,), _storage_zeros(spec.dtype, 1).dtype
                          ).at[0].set(data.astype(_storage_zeros(
                              spec.dtype, 1).dtype))
            v = jnp.zeros((cap,), bool).at[0].set(valid)
            cols.append(DeviceColumn(d, v, spec.dtype))
        return DeviceBatch(cols, 1, self._buffer_names())
