"""Hash-aggregate execution: partial -> merge -> final over device batches.

The GpuHashAggregateExec analogue (reference GpuAggregateExec.scala:1711,
call stack SURVEY §3.3): per input batch, project the aggregate inputs and
run the update groupby (partial); accumulated partials are concatenated and
re-grouped with the merge ops; the final projection evaluates each
aggregate's result expression over the merged buffers.

TPU-first deltas from the reference:
  * partial aggregation is sort+segment (ops/groupby.py), not hash tables;
  * merge is concat+regroup in one jit rather than cuDF concatenate+groupby;
  * string group keys ride as unified dictionary codes, so regrouping
    across batches is plain int comparison.
"""
from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from .. import types as t
from ..config import TpuConf
from ..columnar.device import DeviceBatch, DeviceColumn
from ..ops import groupby as G
from ..ops.batch_ops import concat_batches, ensure_unique_dict, \
    shrink_to_rows
from ..plan import expressions as E
from ..plan.aggregates import AggregateFunction
from .evaluator import evaluate_projection

_GROUPBY_CACHE = {}
_REDUCE_CACHE = {}


_DENSE_DOMAIN_MAX = 4096
_DICT_UNIQUE_CACHE: dict = {}


def _dict_unique(d: pa.Array) -> bool:
    """Duplicate-free dictionary (code equality == value equality), cached
    by identity."""
    import pyarrow.compute as pc
    key = id(d)
    hit = _DICT_UNIQUE_CACHE.get(key)
    if hit is not None and hit[0] is d:
        return hit[1]
    u = len(pc.unique(d.cast(pa.string()))) == len(d)
    if len(_DICT_UNIQUE_CACHE) > 1024:
        _DICT_UNIQUE_CACHE.clear()
    _DICT_UNIQUE_CACHE[key] = (d, u)
    return u


def _dense_domains(key_cols, conf=None) -> "Optional[List[int]]":
    """Static per-key domain sizes when ALL keys are bounded (dictionary
    codes / booleans) and the bucket product stays small — the dense
    no-sort groupby's eligibility (ops/groupby.py dense_groupby_trace).

    The size/budget check runs FIRST: a high-cardinality dictionary must
    bail out before any O(unique) host work."""
    from ..config import DENSE_AGG_DOMAIN_MAX
    limit = conf.get(DENSE_AGG_DOMAIN_MAX) if conf is not None \
        else _DENSE_DOMAIN_MAX
    sizes = []
    total = 1
    for c in key_cols:
        if c.dictionary is not None:
            sizes.append(max(len(c.dictionary), 1))
        elif isinstance(c.dtype, t.BooleanType):
            sizes.append(2)
        else:
            return None
        total *= sizes[-1] + 1
        if total > limit:
            return None
    return sizes


_PACK_BUDGET = 1 << 62


def _key_pack_spec(key_cols: List[DeviceColumn],
                   key_ranges) -> "Optional[tuple]":
    """Per-key (lo, span) for keys with exact static bounds — plan range
    statistics (exec layer) or deduped dictionary domains — greedily
    until the span product budget; None unless >=2 keys pack (one packed
    lane must actually replace lanes to pay for itself)."""
    spec: List[Optional[Tuple[int, int]]] = []
    total = 1
    packed = 0
    for i, c in enumerate(key_cols):
        rng = key_ranges[i] if key_ranges is not None else None
        entry = None
        if isinstance(c.dtype, t.StringType):
            if c.dictionary is not None:
                # pow2-quantized span: the jit signature must not churn
                # with every per-batch dictionary size (a span only
                # needs to be >= the real domain)
                span = max(len(c.dictionary), 1) + 1
                entry = (0, 1 << (span - 1).bit_length())
        elif isinstance(c.dtype, t.DoubleType) or \
                isinstance(c.dtype, t.FloatType):
            entry = None
        elif rng is not None:
            lo, hi = int(rng[0]), int(rng[1])
            entry = (lo, hi - lo + 2)
        elif isinstance(c.dtype, t.BooleanType):
            entry = (0, 3)
        if entry is not None and total * entry[1] <= _PACK_BUDGET:
            total *= entry[1]
            packed += 1
            spec.append(entry)
        else:
            spec.append(None)
    # all keys covered -> the scatter-free single-sort-lane group-by
    # (ops/groupby.py packed_groupby_trace); a partial pack must replace
    # >=2 lanes to pay for itself
    if packed == len(key_cols) and packed >= 1:
        return tuple(spec)
    return tuple(spec) if packed >= 2 else None


def _fused_pack_spec(key_exprs, key_ranges) -> "Optional[tuple]":
    """Pack spec for the fused map-side path: plan ranges only (string
    dictionaries are per-batch host values there)."""
    spec: List[Optional[Tuple[int, int]]] = []
    total = 1
    packed = 0
    for e, rng in zip(key_exprs, key_ranges or []):
        entry = None
        if rng is not None and not isinstance(
                e.dtype, (t.DoubleType, t.FloatType, t.StringType)):
            lo, hi = int(rng[0]), int(rng[1])
            entry = (lo, hi - lo + 2)
        if entry is not None and total * entry[1] <= _PACK_BUDGET:
            total *= entry[1]
            packed += 1
            spec.append(entry)
        else:
            spec.append(None)
    if packed == len(key_exprs) and packed >= 1:
        return tuple(spec)
    return tuple(spec) if packed >= 2 else None


def holistic_pack_spec(key_cols, key_exprs, child):
    """Pack spec for the holistic (sorted_segments) aggregation execs:
    plan range stats via plain column refs + dictionary/bool domains —
    folds every key into ONE sort lane when all are bounded
    (ops/percentile.py sorted_segments packed path)."""
    from .join import key_ref_names
    ranges = []
    for e in key_exprs:
        ref = key_ref_names([e])
        ranges.append(None if ref is None
                      else child.column_range(ref[0]))
    return _key_pack_spec(key_cols, ranges)


def _seg_knobs(conf):
    """(scatter_free, max_sort_operands, dense_via_sort) statics for the
    group-by trace builders — part of every jit cache key they shape."""
    from ..config import (DENSE_AGG_VIA_SORT, MAX_SORT_OPERANDS,
                          SEG_SCATTER_FREE)
    if conf is None:
        return True, 2, False
    return (conf.get(SEG_SCATTER_FREE), conf.get(MAX_SORT_OPERANDS),
            conf.get(DENSE_AGG_VIA_SORT))


def _domains_as_pack(domains):
    """Dense key domains (codes in [0, size)) as a packed-lane spec:
    slot 0 stays the null slot, codes shift up by one."""
    return tuple((0, size + 1) for size in domains)


def _run_groupby(key_cols: List[DeviceColumn], agg_cols: List[DeviceColumn],
                 specs: List[G.AggSpec], live, capacity: int,
                 key_ranges=None, conf=None):
    key_cols = [ensure_unique_dict(c) for c in key_cols]
    if conf is not None and any(c.dictionary is not None for c in key_cols):
        # dictionary group keys aggregate UNDECODED (codes hash/pack/
        # accumulate directly) — count the encoded dispatch so a
        # regression back to decoded keys is visible in the plane
        from ..ops.encodings import count_dispatch, encoding_policy
        if encoding_policy(conf).enabled:
            count_dispatch("groupby_codes")
    info = tuple((c.dtype, True, str(c.data.dtype)) for c in key_cols)
    scatter_free, max_ops, dense_sort = _seg_knobs(conf)
    domains = _dense_domains(key_cols, conf)
    if domains is not None and dense_sort:
        # flip knob: run the bounded domain through the packed
        # single-sort-lane kernel instead of the no-sort bucket scatters
        pack, domains = _domains_as_pack(domains), None
    else:
        pack = None if domains is not None \
            else _key_pack_spec(key_cols, key_ranges)
    # Pallas block-accumulate segmented aggregation (ops/pallas/segagg):
    # any fully-bounded key tuple — dense domains or a complete pack —
    # whose span product fits the block accumulator aggregates with no
    # sort, no scatter and no row permutation at all
    pallas_interp = None
    full_pack = pack if (pack is not None and
                         all(s is not None for s in pack)) else \
        (_domains_as_pack(domains) if domains is not None else None)
    if full_pack is not None and conf is not None:
        total = 1
        for _lo, span in full_pack:
            total *= int(span)
        from ..ops.pallas import elect_segagg
        has_float_sum = any(s.kind == G.SUM and t.is_floating(s.dtype)
                            for s in specs)
        ptier = elect_segagg(conf, total, has_float_sum)
        if ptier is not None:
            pack, domains = full_pack, None
            pallas_interp = ptier.interpret
    sig = (info, tuple((s.kind, s.input_idx, s.dtype) for s in specs),
           capacity, tuple(str(c.data.dtype) for c in agg_cols),
           tuple(domains) if domains else None, pack, scatter_free,
           max_ops, pallas_interp)
    fn = _GROUPBY_CACHE.get(sig)
    if fn is None:
        if pallas_interp is not None:
            from ..ops.pallas.segagg import pallas_groupby_trace
            fn = jax.jit(pallas_groupby_trace(pack, list(info),
                                              list(specs), capacity,
                                              capacity, pallas_interp))
        elif domains is not None:
            fn = jax.jit(G.dense_groupby_trace(list(domains), list(specs),
                                               capacity))
        else:
            fn = jax.jit(G.groupby_trace(list(info), list(specs), capacity,
                                         capacity, pack_spec=pack,
                                         scatter_free=scatter_free,
                                         max_sort_operands=max_ops))
        _GROUPBY_CACHE[sig] = fn
    out_keys, outs, num_groups = fn(
        tuple(c.data for c in key_cols),
        tuple(c.validity for c in key_cols),
        tuple(c.data for c in agg_cols),
        tuple(c.validity for c in agg_cols),
        live)
    # concrete (eager) group counts coerce to host as before — shrinking
    # to the real bucket keeps downstream sorts small; under whole-plan
    # tracing the count is a Tracer and must stay on device
    if not isinstance(num_groups, jax.core.Tracer):
        num_groups = int(num_groups)
    return key_cols, out_keys, outs, num_groups


def _run_reduce(agg_cols: List[DeviceColumn], specs: List[G.AggSpec],
                live, capacity: int):
    sig = (tuple((s.kind, s.input_idx, s.dtype) for s in specs), capacity,
           tuple(str(c.data.dtype) for c in agg_cols))
    fn = _REDUCE_CACHE.get(sig)
    if fn is None:
        fn = jax.jit(G.reduce_trace(list(specs), capacity))
        _REDUCE_CACHE[sig] = fn
    return fn(tuple(c.data for c in agg_cols),
              tuple(c.validity for c in agg_cols), live)


def check_agg_buffers_supported(aggs) -> None:
    """Decimal buffers ride the single int64 unscaled lane (sums whose
    true value exceeds int64 null out — ops/decimal.py module docs).  Only
    two-lane 128-bit HOST inputs are rejected; plan-time tagging does this
    too (aggregates.py unsupported_reasons) — fail fast for direct API
    users."""
    for fn, _name in aggs:
        child = getattr(fn, "child", None)
        if child is not None and E._consumes_wide_host(child):
            raise NotImplementedError(
                f"128-bit host decimal input to {fn.name} not supported "
                "on device")


def _storage_zeros(dt: t.DataType, capacity: int):
    if isinstance(dt, t.DoubleType):
        return jnp.zeros((capacity,), jnp.float64)
    return jnp.zeros((capacity,), t.physical_np_dtype(dt))


class HashAggregate:
    """Bound group-by aggregation over a stream of device batches."""

    def __init__(self, key_exprs: Sequence[E.Expression],
                 key_names: Sequence[str],
                 aggs: Sequence[Tuple[AggregateFunction, str]],
                 conf: TpuConf, key_ranges=None, input_ranges=None):
        self.key_exprs = list(key_exprs)
        self.key_names = list(key_names)
        self.aggs = list(aggs)
        self.conf = conf
        # exact (lo, hi) per key from plan statistics (or None) — lets
        # the group-by pack bounded keys into one sort lane
        self.key_ranges = list(key_ranges) if key_ranges is not None \
            else [None] * len(self.key_exprs)
        # exact (lo, hi) per INPUT expression (plain column refs with
        # scan stats): an int64 lane whose range fits int32 gathers as
        # ONE u32 lane instead of a pair — the permutation gather is the
        # dominant group-by cost at big buckets (~390ms for one 8M int64
        # lane), so halving its width is material
        self._input_ranges_by_expr = input_ranges or {}
        check_agg_buffers_supported(self.aggs)
        # flatten buffers
        self.update_specs: List[G.AggSpec] = []
        self.merge_specs: List[G.AggSpec] = []
        self.input_exprs: List[Optional[E.Expression]] = []
        self.buffer_slices: List[Tuple[int, int]] = []
        for fn, _name in self.aggs:
            start = len(self.update_specs)
            ins = fn.inputs()
            for (kind, bdt), (mkind, mdt), inp in zip(
                    fn.update_ops(), fn.merge_ops(), ins):
                idx = -1
                if inp is not None:
                    idx = len(self.input_exprs)
                    self.input_exprs.append(inp)
                self.update_specs.append(G.AggSpec(kind, idx, bdt))
            self.buffer_slices.append((start, len(self.update_specs)))
        # merge specs operate on buffer columns positionally
        mi = 0
        for (fn, _name) in self.aggs:
            for (mkind, mdt) in fn.merge_ops():
                self.merge_specs.append(G.AggSpec(mkind, mi, mdt))
                mi += 1

    # ---- phases ----

    _I32_LO, _I32_HI = -(1 << 31), (1 << 31) - 1

    def _narrow_cols(self, agg_cols):
        """Cast int64 agg-input lanes with an int32-fitting known range
        down to int32 (exact; sums re-widen inside the kernel);
        spark.rapids.tpu.sql.agg.inputNarrowing gates it."""
        from ..config import AGG_INPUT_NARROWING
        if not self.conf.get(AGG_INPUT_NARROWING):
            return list(agg_cols)
        out = []
        for c, e in zip(agg_cols, self.input_exprs):
            rng = self._input_ranges_by_expr.get(id(e))
            if rng is not None and c.data.dtype == jnp.int64 and \
                    self._I32_LO <= rng[0] and rng[1] <= self._I32_HI:
                out.append(DeviceColumn(c.data.astype(jnp.int32),
                                        c.validity, c.dtype,
                                        c.dictionary))
            else:
                out.append(c)
        return out

    def partial(self, db: DeviceBatch, live=None) -> DeviceBatch:
        """One input batch -> (keys + buffer columns) partial result.

        `live` (optional bool mask) lets an upstream filter fuse into the
        aggregation: filtered rows simply never contribute — no compaction
        (= no TPU row gather) between filter and agg."""
        key_batch = evaluate_projection(self.key_exprs, self.key_names, db,
                                        self.conf) if self.key_exprs else None
        agg_in = evaluate_projection(
            [e for e in self.input_exprs],
            [f"_in{i}" for i in range(len(self.input_exprs))], db, self.conf) \
            if self.input_exprs else None
        agg_cols = self._narrow_cols(agg_in.columns) \
            if agg_in is not None else []
        if live is None:
            live = db.row_mask()
        if not self.key_exprs:
            outs = _run_reduce(agg_cols, self.update_specs, live, db.capacity)
            return self._reduce_outs_to_batch(outs)
        key_cols, out_keys, outs, n_groups = _run_groupby(
            key_batch.columns, agg_cols, self.update_specs, live,
            db.capacity, key_ranges=self.key_ranges, conf=self.conf)
        return self._groupby_outs_to_batch(key_cols, out_keys, outs, n_groups)

    def can_fuse_filter(self, db: "Optional[DeviceBatch]" = None) -> bool:
        """Whether the whole map side (filter mask + projections + update
        groupby) can run as ONE traced program.

        Non-string keys always fuse.  String keys fuse when the batch is
        in hand and every string key is a plain column reference with a
        duplicate-free dictionary whose domain is small: the DENSE
        bounded-domain groupby (ops/groupby.py) then needs no host-side
        dictionary work inside the trace."""
        if not any(isinstance(e.dtype, t.StringType) for e in self.key_exprs):
            return True
        if db is None:
            return False
        return self._fused_dense_domains(db) is not None

    def _fused_dense_domains(self, db: DeviceBatch):
        """Static dense-groupby domain sizes for the fused path, or None.

        Sizes/budget check first; the O(unique) duplicate check only ever
        runs on dictionaries already under the (small) domain budget."""
        from ..config import DENSE_AGG_DOMAIN_MAX
        limit = self.conf.get(DENSE_AGG_DOMAIN_MAX)
        sizes = []
        dicts = []
        total = 1
        for e in self.key_exprs:
            inner = e.children[0] if isinstance(e, E.Alias) else e
            if isinstance(e.dtype, t.BooleanType):
                sizes.append(2)
                dicts.append(None)
            elif isinstance(e.dtype, t.StringType):
                if not isinstance(inner, E.ColumnRef):
                    return None
                try:
                    c = db.column_by_name(inner.name)
                except ValueError:
                    return None
                if c.dictionary is None:
                    return None
                sizes.append(max(len(c.dictionary), 1))
                dicts.append(c.dictionary)
            else:
                return None
            total *= sizes[-1] + 1
            if total > limit:
                return None
        for d in dicts:
            if d is not None and not _dict_unique(d):
                return None
        return sizes

    def partial_fused(self, db: DeviceBatch, conds: Sequence[E.Expression],
                      raw: bool = False):
        """Filter + key/input projection + update groupby in ONE program.

        The whole map-side of an aggregation (predicate, projections,
        sort-segment reduce) is a single XLA program per row bucket: one
        dispatch, full fusion, no intermediate HBM round-trips.  The
        reference runs these as separate cuDF kernel launches
        (GpuFilterExec -> projections -> Table.groupBy); on TPU the fused
        form is both lower-latency and lets XLA share subexpressions."""
        from .evaluator import (_JIT_CACHE, _batch_meta, _build_inputs,
                                _jit_key, _num_rows_scalar, _prepare)
        from ..ops.kernels import live_mask, valid_or_true
        if db.sel is not None and any(c.offsets is not None
                                      for c in db.columns):
            # ragged kernels assume prefix liveness (see evaluator)
            from ..ops.batch_ops import ensure_prefix
            db = ensure_prefix(db, self.conf)
        exprs_all = list(conds) + self.key_exprs + self.input_exprs
        pctx, hostvals, aux = _prepare(exprs_all, db, self.conf)
        spec_sig = tuple((s.kind, s.input_idx, str(s.dtype))
                         for s in self.update_specs)
        scatter_free, max_ops, dense_sort = _seg_knobs(self.conf)
        dense_domains = self._fused_dense_domains(db) \
            if any(isinstance(e.dtype, (t.StringType, t.BooleanType))
                   for e in self.key_exprs) else None
        pack = None
        if dense_domains is not None and dense_sort:
            pack, dense_domains = _domains_as_pack(dense_domains), None
        elif dense_domains is None:
            pack = _fused_pack_spec(self.key_exprs, self.key_ranges)
        # Pallas block-accumulate election, mirroring _run_groupby
        pallas_interp = None
        full_pack = pack if (pack is not None and self.key_exprs and
                             all(s is not None for s in pack)) else \
            (_domains_as_pack(dense_domains)
             if dense_domains is not None else None)
        if full_pack is not None:
            total = 1
            for _lo, span in full_pack:
                total *= int(span)
            from ..ops.pallas import elect_segagg
            has_float_sum = any(
                s.kind == G.SUM and t.is_floating(s.dtype)
                for s in self.update_specs)
            ptier = elect_segagg(self.conf, total, has_float_sum)
            if ptier is not None:
                pack, dense_domains = full_pack, None
                pallas_interp = ptier.interpret
        has_sel = db.sel is not None
        from ..config import AGG_INPUT_NARROWING
        _narrow_on = self.conf.get(AGG_INPUT_NARROWING)
        narrow = tuple(
            _narrow_on
            and (rng := self._input_ranges_by_expr.get(id(e))) is not None
            and self._I32_LO <= rng[0] and rng[1] <= self._I32_HI
            for e in self.input_exprs)
        key = _jit_key(exprs_all, db, aux, self.conf,
                       ("fpartial", spec_sig, len(conds),
                        len(self.key_exprs),
                        tuple(dense_domains) if dense_domains else None,
                        pack, has_sel, narrow, scatter_free, max_ops,
                        pallas_interp))
        fn = _JIT_CACHE.get(key)
        if fn is None:
            capacity = db.capacity
            node_slots = dict(pctx.node_slots)
            node_info = dict(pctx.node_info)
            conf = self.conf
            conds_t = tuple(conds)
            keys_t = tuple(self.key_exprs)
            ins_t = tuple(self.input_exprs)
            specs = list(self.update_specs)
            meta = _batch_meta(db)

            def run(col_data, col_valid, num_rows, aux_arrs, *sel_opt):
                inputs, raw = _build_inputs(meta, col_data, col_valid)
                ctx = E.EvalCtx(capacity, num_rows, inputs, aux_arrs,
                                node_slots, conf, raw,
                                node_info=node_info)
                # lazy join output: liveness is the selection vector
                live = sel_opt[0] if sel_opt \
                    else live_mask(capacity, num_rows)
                for c in conds_t:
                    dv = c.eval_dev(ctx)
                    k = dv.data.astype(bool)
                    if dv.validity is not None:
                        k = k & dv.validity
                    live = live & k
                agg_data, agg_valid = [], []
                for i, e in enumerate(ins_t):
                    dv = e.eval_dev(ctx)
                    d = dv.data
                    if narrow[i] and d.dtype == jnp.int64:
                        # range-proven int32 fit: halve the permutation
                        # gather width (sums re-widen in the kernel)
                        d = d.astype(jnp.int32)
                    agg_data.append(d)
                    agg_valid.append(valid_or_true(dv.validity, capacity))
                if not keys_t:
                    red = G.reduce_trace(specs, capacity)
                    return (None,
                            red(tuple(agg_data), tuple(agg_valid), live),
                            None)
                kds, kvs, kinfo = [], [], []
                for e in keys_t:
                    dv = e.eval_dev(ctx)
                    kds.append(dv.data)
                    kvs.append(valid_or_true(dv.validity, capacity))
                    kinfo.append((e.dtype, True, str(dv.data.dtype)))
                if pallas_interp is not None:
                    from ..ops.pallas.segagg import pallas_groupby_trace
                    gb = pallas_groupby_trace(pack, kinfo, specs,
                                              capacity, capacity,
                                              pallas_interp)
                elif dense_domains is not None:
                    gb = G.dense_groupby_trace(list(dense_domains), specs,
                                               capacity)
                else:
                    gb = G.groupby_trace(kinfo, specs, capacity, capacity,
                                         pack_spec=pack,
                                         scatter_free=scatter_free,
                                         max_sort_operands=max_ops)
                return gb(tuple(kds), tuple(kvs), tuple(agg_data),
                          tuple(agg_valid), live)

            fn = jax.jit(run)
            _JIT_CACHE[key] = fn

        from .evaluator import _col_lanes
        extra = (db.sel,) if has_sel else ()
        out_keys, outs, ng = fn(_col_lanes(db),
                                tuple(c.validity for c in db.columns),
                                _num_rows_scalar(db.num_rows), aux, *extra)
        if not self.key_exprs:
            return outs if raw else self._reduce_outs_to_batch(outs)
        nconds = len(conds)
        key_cols = []
        for i, e in enumerate(self.key_exprs):
            hv = hostvals[nconds + i]
            key_cols.append(DeviceColumn(
                jnp.zeros((0,)), jnp.zeros((0,), bool), e.dtype,
                hv.dictionary))
        if not isinstance(ng, jax.core.Tracer):
            ng = int(ng)
        return self._groupby_outs_to_batch(key_cols, out_keys, outs, ng)

    def merge_raw(self, partial_outs: List[List]) -> List:
        """Merge per-batch global-agg scalar outputs into final buffer
        scalars — one tiny jit over stacked scalars, no 1-row batches."""
        if len(partial_outs) == 1:
            return partial_outs[0]
        k = len(partial_outs)
        sig = (k, tuple((s.kind, s.input_idx, str(s.dtype))
                        for s in self.merge_specs))
        fn = _REDUCE_CACHE.get(sig)
        if fn is None:
            red = G.reduce_trace(self.merge_specs, k)

            def run(stacks, valids):
                return red(stacks, valids, jnp.ones((k,), bool))

            fn = jax.jit(run)
            _REDUCE_CACHE[sig] = fn
        stacks = tuple(jnp.stack([p[i][0] for p in partial_outs])
                       for i in range(len(self.update_specs)))
        valids = tuple(jnp.stack([p[i][1] for p in partial_outs])
                       for i in range(len(self.update_specs)))
        return list(fn(stacks, valids))

    def final_host(self, outs) -> pa.Table:
        """Finish a global aggregation on host: one D2H fetch of the buffer
        scalars, then the result expressions run via their CPU kernels on a
        1-row Arrow batch (cheaper than dispatching a device program for a
        single row)."""
        fetched = jax.device_get([(d, v) for d, v in outs])
        return self.finalize_fetched(fetched)

    def finalize_fetched(self, fetched) -> pa.Table:
        """Host-side tail of final_host, split out so pipelined callers
        (bench, concurrent-task executor) can batch many queries' D2H
        fetches into one transfer before finalizing each."""
        from ..columnar.host import dtype_to_arrow
        arrays = []
        for (d, v), spec in zip(fetched, self.update_specs):
            val = d.item() if bool(v) else None
            if val is not None and isinstance(spec.dtype, t.DecimalType):
                import decimal as pydec
                val = pydec.Decimal(val).scaleb(-spec.dtype.scale)
            arrays.append(pa.array([val], dtype_to_arrow(spec.dtype)))
        names = self._buffer_names()
        rb = pa.RecordBatch.from_arrays(arrays, names)
        schema = t.StructType([t.StructField(n, s.dtype)
                               for n, s in zip(names, self.update_specs)])
        out_arrays, out_names = [], []
        for (fn, name), (start, end) in zip(self.aggs, self.buffer_slices):
            refs = [E.ColumnRef(f"_buf{j}").bind(schema)
                    for j in range(start, end)]
            expr = fn.evaluate(refs)
            from ..plan.aggregates import _deep_resolved
            expr = _deep_resolved(expr)
            out_arrays.append(expr.eval_cpu(rb))
            out_names.append(name)
        return pa.Table.from_arrays(out_arrays, out_names)

    def merge(self, partials: List[DeviceBatch]) -> DeviceBatch:
        merged = concat_batches(partials, self.conf)
        nkeys = len(self.key_exprs)
        key_cols = merged.columns[:nkeys]
        buf_cols = merged.columns[nkeys:]
        if not self.key_exprs:
            outs = _run_reduce(buf_cols, self.merge_specs, merged.row_mask(),
                               merged.capacity)
            return self._reduce_outs_to_batch(outs)
        key_cols, out_keys, outs, n_groups = _run_groupby(
            key_cols, buf_cols, self.merge_specs, merged.row_mask(),
            merged.capacity, key_ranges=self.key_ranges, conf=self.conf)
        return self._groupby_outs_to_batch(key_cols, out_keys, outs, n_groups)

    def final(self, merged: DeviceBatch) -> DeviceBatch:
        """Evaluate result expressions over (keys + buffers)."""
        nkeys = len(self.key_exprs)
        schema = merged.schema
        out_exprs: List[E.Expression] = []
        out_names: List[str] = []
        for i, name in enumerate(self.key_names):
            out_exprs.append(E.ColumnRef(name).bind(schema))
            out_names.append(name)
        for (fn, name), (start, end) in zip(self.aggs, self.buffer_slices):
            refs = [E.ColumnRef(f"_buf{j}").bind(schema)
                    for j in range(start, end)]
            expr = fn.evaluate(refs)
            from ..plan.aggregates import _deep_resolved
            out_exprs.append(_deep_resolved(expr))
            out_names.append(name)
        return evaluate_projection(out_exprs, out_names, merged, self.conf)

    def execute(self, batches: Iterable[DeviceBatch]) -> DeviceBatch:
        partials = [self.partial(db) for db in batches]
        if not partials:
            raise ValueError("aggregation over zero batches")
        merged = self.merge(partials) if len(partials) > 1 else partials[0]
        return self.final(merged)

    # ---- plumbing ----

    def _buffer_names(self):
        return [f"_buf{i}" for i in range(len(self.update_specs))]

    def _static_group_bound(self, key_cols) -> "Optional[int]":
        """Upper bound on group count from key-domain sizes (dictionary
        lengths, bool), when every key has a bounded domain.  +1 per key
        for the null group.  Lets the output shrink to a tiny bucket with
        NO host sync — the group count itself can stay on device."""
        bound = 1
        for kc in key_cols:
            if kc.dictionary is not None:
                bound *= len(kc.dictionary) + 1
            elif isinstance(kc.dtype, t.BooleanType):
                bound *= 3
            else:
                return None
            if bound > (1 << 22):
                return None
        return bound

    def _groupby_outs_to_batch(self, key_cols, out_keys, outs, n_groups):
        cols = []
        for (kd, kv), kc in zip(out_keys, key_cols):
            cols.append(DeviceColumn(kd, kv, kc.dtype, kc.dictionary,
                                     kc.data_hi))
        # update and merge specs share buffer dtypes positionally
        for (data, valid), spec in zip(outs, self.update_specs):
            cols.append(DeviceColumn(data.astype(_storage_zeros(
                spec.dtype, 1).dtype), valid, spec.dtype))
        db = DeviceBatch(cols, n_groups, self.key_names + self._buffer_names())
        if isinstance(n_groups, int):
            return shrink_to_rows(db, n_groups, self.conf)
        # lazy group count: shrink by the static key-domain bound instead
        # of syncing (whole-plan tracing / tunnel-latency paths)
        bound = self._static_group_bound(key_cols)
        if bound is not None:
            from ..ops.batch_ops import shrink_to_capacity
            return shrink_to_capacity(db, bound, self.conf)
        return db

    def _reduce_outs_to_batch(self, outs) -> DeviceBatch:
        from ..columnar.device import bucket_capacity
        cap = bucket_capacity(1, self.conf)
        cols = []
        for (data, valid), spec in zip(outs, self.update_specs):
            # row 0 by concatenation, not `.at[0].set` — the 1-element
            # scatter that lowers to would be the only scatter left in a
            # global-aggregation program
            sdt = _storage_zeros(spec.dtype, 1).dtype
            d = jnp.concatenate([data.astype(sdt)[None],
                                 jnp.zeros((cap - 1,), sdt)])
            v = jnp.concatenate([valid[None], jnp.zeros((cap - 1,), bool)])
            cols.append(DeviceColumn(d, v, spec.dtype))
        return DeviceBatch(cols, 1, self._buffer_names())
