"""Device collect_list / collect_set aggregation exec.

Role of the reference's collect aggregations (GpuAggregateExec.scala +
cuDF collect_list/collect_set ops; windowed forms in
GpuWindowExpression.scala): a group-by whose aggregates are ALL collect
functions runs fully on device via the sort-segment collect kernel
(ops/percentile.py collect_trace), emitting RAGGED result columns over
the values+offsets device layout.  Mixed collect+other aggregations are
tagged to the CPU path by AggregateMeta, like the percentile family.

Collect is holistic (a group's list spans every input batch), so the
exec concatenates the child stream first — the same partial/final
collapse the reference performs when it concatenates partial collect
buffers before the final pass."""
from __future__ import annotations

from typing import Iterator, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import types as t
from ..columnar.device import DeviceBatch, DeviceColumn
from ..ops import percentile as P
from ..ops.batch_ops import concat_batches, ensure_unique_dict
from ..plan import expressions as E
from ..plan.aggregates import CollectList, CollectSet
from .evaluator import evaluate_projection
from .plan import ExecContext, PlanNode

_TRACE_CACHE: dict = {}


class CollectAggregateExec(PlanNode):
    def __init__(self, key_exprs: Sequence[E.Expression],
                 key_names: Sequence[str],
                 aggs: Sequence[Tuple[CollectList, str]],
                 child: PlanNode):
        super().__init__(child)
        schema = child.output_schema
        self.key_exprs = [e.bind(schema) for e in key_exprs]
        self.key_names = list(key_names)
        self.aggs = [(fn.bind(schema), name) for fn, name in aggs]
        assert all(isinstance(fn, CollectList) for fn, _ in self.aggs)

    @property
    def output_schema(self) -> t.StructType:
        fields = [t.StructField(n, e.dtype)
                  for n, e in zip(self.key_names, self.key_exprs)]
        for fn, n in self.aggs:
            fields.append(t.StructField(n, fn.dtype))
        return t.StructType(fields)

    def keys_unique(self, names):
        if not self.key_exprs:
            return True
        return set(self.key_names) <= set(names)

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        conf = ctx.conf
        batches = [db for db in self.child.execute(ctx)
                   if int(db.num_rows) > 0]
        if not batches:
            if not self.key_exprs:
                yield self._empty_row(conf)
            return
        merged = concat_batches(batches, conf)

        val_exprs: List[E.Expression] = []
        val_map: List[int] = []     # agg i -> (col j, distinct)
        fps = {}
        for fn, _name in self.aggs:
            fp = (repr(fn.child), isinstance(fn, CollectSet))
            if fp not in fps:
                fps[fp] = len(val_exprs)
                val_exprs.append(fn.child)  # already bound
            val_map.append(fps[fp])

        nk = len(self.key_exprs)
        proj = evaluate_projection(
            self.key_exprs + val_exprs,
            [f"_k{i}" for i in range(nk)] +
            [f"_v{j}" for j in range(len(val_exprs))], merged, conf)
        key_cols = [ensure_unique_dict(c) for c in proj.columns[:nk]]
        # value dictionaries must be duplicate-free too: collect_set
        # dedupes by CODE (same reason as exec/distinct.py)
        val_cols = [ensure_unique_dict(c) if c.dictionary is not None
                    else c for c in proj.columns[nk:]]
        live = merged.row_mask()
        capacity = merged.capacity
        info = tuple((c.dtype, True, str(c.data.dtype)) for c in key_cols)
        from .aggregate import _seg_knobs, holistic_pack_spec
        pack = holistic_pack_spec(key_cols, self.key_exprs, self.child)
        _sf, max_ops, _ds = _seg_knobs(ctx.conf)

        results = [None] * len(self.aggs)
        out_keys = n_groups = None
        group_live = None
        flavors = list(fps)          # (child repr, distinct) per val col
        for j, vcol in enumerate(val_cols):
            distinct = flavors[j][1]
            sig = ("collect", info, capacity, distinct,
                   str(vcol.data.dtype), pack, max_ops)
            fn = _TRACE_CACHE.get(sig)
            if fn is None:
                fn = jax.jit(P.collect_trace(
                    list(info), capacity, capacity, distinct,
                    vcol.dtype, pack_spec=pack,
                    max_sort_operands=max_ops), static_argnums=())
                _TRACE_CACHE[sig] = fn
            ok, values, offs, ev, ng, _gl = fn(
                tuple(c.data for c in key_cols),
                tuple(c.validity for c in key_cols),
                vcol.data, vcol.validity, live)
            if out_keys is None:
                out_keys, n_groups = ok, int(ng)
                group_live = _gl
            for i, jj in enumerate(val_map):
                if jj == j:
                    results[i] = (values, offs, ev, vcol)

        cols = []
        for (kd, kv), kc in zip(out_keys, key_cols):
            cols.append(DeviceColumn(kd, kv, kc.dtype, kc.dictionary,
                                     kc.data_hi))
        for (values, offs, ev, vcol), (fn_, _n) in zip(results, self.aggs):
            cols.append(DeviceColumn(
                values, group_live, fn_.dtype,
                vcol.dictionary, offsets=offs, elem_valid=ev))
        n_out = max(n_groups, 1) if not self.key_exprs else n_groups
        db = DeviceBatch(cols, n_out,
                         self.key_names + [n for _f, n in self.aggs])
        yield db

    def _empty_row(self, conf) -> DeviceBatch:
        from ..columnar.device import bucket_capacity
        cap = bucket_capacity(1, conf)
        cols = []
        for fn, _n in self.aggs:
            cols.append(DeviceColumn(
                jnp.zeros((cap,), t.physical_np_dtype(
                    fn.dtype.element_type)),
                jnp.ones((cap,), bool), fn.dtype, None,
                offsets=jnp.zeros((cap + 1,), jnp.int32),
                elem_valid=jnp.zeros((cap,), bool)))
        return DeviceBatch(cols, 1, [n for _f, n in self.aggs])

    def describe(self):
        return (f"CollectAggregateExec[keys={self.key_names}, "
                f"{[n for _f, n in self.aggs]}]")
