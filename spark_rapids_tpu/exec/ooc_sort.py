"""Out-of-core sort: spillable sorted runs + capstone k-way merge.

Reference: GpuOutOfCoreSortIterator (GpuSortExec.scala:281) — sort each
batch, split into chunks, keep a spillable pending set, N-way merge.

TPU-first shape: TPU sort is ONE fused lexsort, so "merging" loaded chunks
is a concat + resort (cheaper than data-dependent k-way merge control
flow).  What makes it out-of-core is the *emission rule*: after resorting
the loaded window, only rows ≤ the smallest **capstone** (the last — i.e.
largest — row of each run's currently-loaded chunk) can be emitted,
because every unloaded row of run i is ≥ run i's capstone.  The capstone
position is found by tracking its concat index through the sort
permutation — no device key comparisons, one scalar D2H per capstone.

Memory: held state (runs, pending set) lives in budget-registered
Spillables (runtime/memory.py) that demote to host/disk under pressure;
the merge window is R+1 transient batches.
"""
from __future__ import annotations

from collections import deque
from typing import Iterator, List, Optional, Sequence

import jax.numpy as jnp

from ..columnar.device import DeviceBatch
from ..config import TpuConf
from ..ops.batch_ops import concat_batches
from ..ops.sort import (SortKey, permute_batch, sort_batch,
                        sort_permutation)
from ..runtime.memory import MemoryBudget, Spillable
from ..runtime.retry import slice_batch, with_split_retry
from .plan import ExecContext


def _row_bytes(db: DeviceBatch) -> int:
    """Approximate bytes per logical row at full occupancy."""
    return max(1, db.nbytes() // max(db.capacity, 1))


class OutOfCoreSorter:
    """Accumulates input batches into sorted runs, then streams the merged
    order in bounded chunks."""

    def __init__(self, keys: Sequence[SortKey], ctx: ExecContext):
        self.keys = list(keys)
        self.ctx = ctx
        self.conf: TpuConf = ctx.conf
        self.budget: MemoryBudget = ctx.budget
        self._pending: List[DeviceBatch] = []
        self._pending_rows = 0
        self._runs: List[deque] = []      # deques of Spillable chunks
        self._window_rows: Optional[int] = None
        self._merge_pending: Optional[Spillable] = None

    # -- phase 1: build sorted runs ---------------------------------------
    def _resolve_window(self, db: DeviceBatch) -> int:
        if self._window_rows is None:
            from ..config import OOC_SORT_WINDOW_ROWS
            from . import ooc as O
            forced = self.conf.get(OOC_SORT_WINDOW_ROWS)
            policy = O.ooc_policy(self.ctx)
            if forced:
                self._window_rows = forced
            elif policy.window is not None:
                # the shared out-of-core resident window (exec/ooc.py:
                # ooc.residentFraction x the HBM budget), in rows of
                # the measured width
                self._window_rows = max(
                    self.conf.batch_size_rows // 8,
                    policy.window // _row_bytes(db))
            else:
                self._window_rows = 1 << 62      # unlimited: single run
        return self._window_rows

    def add(self, db: DeviceBatch):
        if db.thin is not None:
            # sort sink: resolve deferred columns before run building
            # (runs slice/spill column lanes directly)
            from ..ops.batch_ops import ensure_prefix
            db = ensure_prefix(db, self.conf)
        n = int(db.num_rows)
        if n == 0:
            return
        window = self._resolve_window(db)
        self._pending.append(db)
        self._pending_rows += n
        if self._pending_rows >= window:
            self._close_run()

    def _close_run(self):
        if not self._pending:
            return
        batches, self._pending = self._pending, []
        self._pending_rows = 0
        merged = concat_batches(batches, self.conf) if len(batches) > 1 \
            else batches[0]
        chunk_rows = self.conf.batch_size_rows
        # Each with_split_retry output is sorted INDEPENDENTLY (OOM halves
        # are not ordered relative to each other), so each one must open
        # its own run — the capstone merge relies on within-run order.
        for s in with_split_retry(
                self.budget, self.conf, merged,
                lambda b: sort_batch(b, self.keys, self.conf)):
            run = deque()
            rows = int(s.num_rows)
            for off in range(0, rows, chunk_rows):
                hi = min(off + chunk_rows, rows)
                chunk = slice_batch(s, off, hi, self.conf) \
                    if (off, hi) != (0, rows) else s
                run.append(Spillable(chunk, self.budget))
            if run:
                self._runs.append(run)
                self.ctx.bump("sort_runs")

    # -- phase 2: merge ----------------------------------------------------
    def results(self) -> Iterator[DeviceBatch]:
        self._close_run()
        if not self._runs:
            return
        try:
            if len(self._runs) == 1:
                for sp in self._runs[0]:
                    yield sp.get()
                    sp.close()
                return
            yield from self._merge()
        finally:
            # early abandonment (e.g. LIMIT above the sort) must release
            # every still-registered chunk and the pending set
            for run in self._runs:
                for sp in run:
                    sp.close()
            self._runs = []
            if self._merge_pending is not None:
                self._merge_pending.close()
                self._merge_pending = None

    def _merge(self) -> Iterator[DeviceBatch]:
        from . import ooc as O
        runs = self._runs
        O.record_election(self.ctx, "sort", "bytes")
        passno = 0
        while True:
            # one merge pass = one out-of-core window: publish the run
            # state to the flight recorder, then give the chaos harness
            # its shot MID-SPILL (the `ooc` site) — recoverable kinds
            # must come back bit-identical, fatal dumps embed the state
            O.fire(self.ctx, "sort", merge_pass=passno,
                   runs=sum(1 for r in runs if r),
                   chunks=sum(len(r) for r in runs))
            passno += 1
            window: List[DeviceBatch] = []
            if self._merge_pending is not None:
                window.append(self._merge_pending.get())
                self._merge_pending.close()
                self._merge_pending = None
            # load the next chunk of every non-empty run; remember each
            # loaded chunk's last-row concat index (the capstone)
            offset = sum(int(b.num_rows) for b in window)
            capstones = []                     # (concat_idx, run_idx)
            for ri, run in enumerate(runs):
                if not run:
                    continue
                sp = run.popleft()
                b = sp.get()
                sp.close()
                window.append(b)
                rows = int(b.num_rows)
                capstones.append((offset + rows - 1, ri))
                offset += rows
            if not window:
                return
            merged = concat_batches(window, self.conf) \
                if len(window) > 1 else window[0]
            total = int(merged.num_rows)
            perm = sort_permutation(merged, self.keys, self.conf)
            inv = jnp.zeros((merged.capacity,), jnp.int32).at[perm].set(
                jnp.arange(merged.capacity, dtype=jnp.int32))
            # emit rows up to the smallest capstone of runs that still
            # have unloaded chunks; runs now empty constrain nothing
            active = [ci for ci, ri in capstones if runs[ri]]
            if active:
                cut = min(int(inv[ci]) for ci in active) + 1
            else:
                cut = total
            s = permute_batch(merged, perm)
            yield slice_batch(s, 0, cut, self.conf) if cut < total else \
                DeviceBatch(s.columns, total, list(s.names))
            self.ctx.bump("sort_merge_passes")
            if cut < total:
                self._merge_pending = Spillable(
                    slice_batch(s, cut, total, self.conf), self.budget)
            elif not any(runs):
                return
