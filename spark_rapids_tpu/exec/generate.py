"""Device explode/posexplode over ragged columns (GpuGenerateExec role).

Reference: GpuGenerateExec.scala:829 runs explode on GPU via cuDF LIST
explode.  TPU-native over the values+offsets layout (ops/ragged.py) the
output is almost free: the exploded rows ARE the values lane — parent
columns gather through the per-value row-id lane, `pos` is
`arange - offsets[row]`, and the output's static capacity is the values
lane's own bucket, so the whole operator is sync-free (whole-plan
traceable).

Like Spark's GenerateExec.requiredChildOutput, the exploded ARRAY input
column is pruned from the output — the overrides meta places this exec
only when the parent operator provably never reads it (re-expanding each
row's array per element would be quadratic in values).

`outer` explode additionally emits the rows whose array is null/empty
with a null element (and null pos), as a second compacted batch.
"""
from __future__ import annotations

from typing import Iterator, List

import jax
import jax.numpy as jnp

from .. import types as t
from ..columnar.device import DeviceBatch, DeviceColumn
from ..ops import ragged as R
from ..ops.filter import compact_batch, gather_batch
from .plan import ExecContext, PlanNode


class GenerateExec(PlanNode):
    """explode/posexplode(col): child columns (minus the array input)
    ++ [pos,] col."""

    def __init__(self, generator, output_names: List[str], child: PlanNode):
        super().__init__(child)
        self.generator = generator.bind(child.output_schema)
        gen_fields = self.generator.output_fields()
        self.output_names = list(output_names) or \
            [f.name for f in gen_fields]
        self._gen_fields = gen_fields
        self._arr_name = self.generator.child.name

    @property
    def output_schema(self) -> t.StructType:
        fields = [f for f in self.child.output_schema.fields
                  if f.name != self._arr_name]
        for f, n in zip(self._gen_fields, self.output_names):
            fields.append(t.StructField(n, f.data_type, f.nullable))
        return t.StructType(fields)

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        gen = self.generator
        out_names = list(self.output_schema.names)
        for db in self.child.execute(ctx):
            col = db.column_by_name(self._arr_name)
            keep_idx = [i for i, n in enumerate(db.names)
                        if n != self._arr_name]
            parent_src = db.select(keep_idx)

            vcap = col.value_capacity
            rid = R.row_ids(col.offsets, vcap)
            live = R.value_live(col.offsets, vcap, db.num_rows)
            n_out = col.offsets[jnp.int32(db.num_rows)]

            safe_rid = jnp.clip(rid, 0, db.capacity - 1)
            parent = gather_batch(parent_src,
                                  jnp.where(live, safe_rid, -1),
                                  n_out, null_out_of_bounds=True)
            out_cols = list(parent.columns)
            if gen.pos:
                pos = jnp.arange(vcap, dtype=jnp.int32) - \
                    jnp.take(col.offsets, safe_rid)
                out_cols.append(DeviceColumn(pos, live, t.INT))
            out_cols.append(DeviceColumn(col.data, col.elem_valid & live,
                                         gen.child.dtype.element_type,
                                         col.dictionary))
            yield DeviceBatch(out_cols, n_out, out_names)

            if gen.outer:
                # rows with null/empty arrays emit once with null col/pos
                lens = col.offsets[1:] - col.offsets[:-1]
                empty = db.row_mask() & ((lens == 0) | ~col.validity)
                base = compact_batch(parent_src, empty, ctx.conf)
                extra = list(base.columns)
                cap = base.capacity
                if gen.pos:
                    extra.append(DeviceColumn(
                        jnp.zeros((cap,), jnp.int32),
                        jnp.zeros((cap,), bool), t.INT))
                extra.append(DeviceColumn(
                    jnp.zeros((cap,), col.data.dtype),
                    jnp.zeros((cap,), bool),
                    gen.child.dtype.element_type, col.dictionary))
                yield DeviceBatch(extra, base.num_rows, out_names)

    def describe(self):
        return f"GenerateExec[{self.generator!r}]"
