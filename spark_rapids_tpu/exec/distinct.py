"""Device count(DISTINCT) aggregation exec (ops/distinct.py runner).

Routing mirrors the percentile exec: an aggregation whose functions are
ALL CountDistinct runs here (one sorted program per distinct input
expression); mixing with streaming aggregates tags to the CPU path.
This is the device rewrite of the reference's per-key dedupe plan.
"""
from __future__ import annotations

from typing import Iterator, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import types as t
from ..columnar.device import DeviceBatch, DeviceColumn
from ..ops.batch_ops import (concat_batches, ensure_unique_dict,
                             shrink_to_rows)
from ..ops.distinct import distinct_count_trace
from ..plan import expressions as E
from ..plan.aggregates import CountDistinct
from .evaluator import evaluate_projection
from .plan import ExecContext, PlanNode

_TRACE_CACHE: dict = {}


class DistinctAggregateExec(PlanNode):
    def __init__(self, key_exprs: Sequence[E.Expression],
                 key_names: Sequence[str],
                 aggs: Sequence[Tuple[CountDistinct, str]],
                 child: PlanNode):
        super().__init__(child)
        schema = child.output_schema
        self.key_exprs = [e.bind(schema) for e in key_exprs]
        self.key_names = list(key_names)
        self.aggs = [(fn.bind(schema), name) for fn, name in aggs]
        assert all(isinstance(fn, CountDistinct) for fn, _ in self.aggs)

    @property
    def output_schema(self) -> t.StructType:
        fields = [t.StructField(n, e.dtype)
                  for n, e in zip(self.key_names, self.key_exprs)]
        for _fn, n in self.aggs:
            fields.append(t.StructField(n, t.LONG, False))
        return t.StructType(fields)

    def keys_unique(self, names) -> bool:
        # one output row per group-key tuple
        if not self.key_exprs:
            return True
        return set(self.key_names) <= set(names)

    def static_row_count(self):
        return 1 if not self.key_exprs else None

    def column_range(self, name):
        from .join import key_ref_names
        if name not in self.key_names:
            return None
        ref = key_ref_names([self.key_exprs[self.key_names.index(name)]])
        return None if ref is None else self.child.column_range(ref[0])

    def execute(self, ctx: ExecContext) -> Iterator[DeviceBatch]:
        conf = ctx.conf
        # no per-batch sync: statically-empty batches are dropped, lazy
        # counts flow through (padding rows are masked by `live` below)
        batches = [db for db in self.child.execute(ctx)
                   if not (isinstance(db.num_rows, int)
                           and db.num_rows == 0)]
        if not batches:
            if not self.key_exprs:
                yield self._zero_row(conf)
            return
        merged = concat_batches(batches, conf)

        val_exprs: List[E.Expression] = []
        val_of: List[int] = []
        fps = {}
        for fn, _name in self.aggs:
            fp = repr(fn.child)
            if fp not in fps:
                fps[fp] = len(val_exprs)
                val_exprs.append(fn.child)
            val_of.append(fps[fp])

        nk = len(self.key_exprs)
        proj = evaluate_projection(
            self.key_exprs + val_exprs,
            [f"_k{i}" for i in range(nk)] +
            [f"_v{j}" for j in range(len(val_exprs))], merged, conf)
        key_cols = [ensure_unique_dict(c) for c in proj.columns[:nk]]
        val_cols = [ensure_unique_dict(c) if c.dictionary is not None
                    else c for c in proj.columns[nk:]]
        live = merged.row_mask()
        capacity = merged.capacity

        info = tuple((c.dtype, True, str(c.data.dtype)) for c in key_cols)
        from .aggregate import _seg_knobs, holistic_pack_spec
        from .join import key_ref_names
        pack = holistic_pack_spec(key_cols, self.key_exprs, self.child)
        scatter_free, max_ops, _ds = _seg_knobs(conf)
        results: List = [None] * len(self.aggs)
        out_keys = n_groups = None
        for j, vcol in enumerate(val_cols):
            # exact value bounds (dictionary size / scan range stats)
            # let the value lane ride the packed key sort — the whole
            # count-distinct order becomes ONE 2-operand sort
            if vcol.dictionary is not None:
                val_range = (0, max(len(vcol.dictionary) - 1, 0))
            else:
                ref = key_ref_names([val_exprs[j]])
                val_range = None if ref is None \
                    else self.child.column_range(ref[0])
                if val_range is not None and not isinstance(
                        vcol.dtype, (t.DoubleType, t.FloatType)):
                    val_range = (int(val_range[0]), int(val_range[1]))
                else:
                    val_range = None
            sig = (info, capacity, vcol.dtype.simple_string,
                   str(vcol.data.dtype), pack, val_range, scatter_free,
                   max_ops)
            fn = _TRACE_CACHE.get(sig)
            if fn is None:
                fn = jax.jit(distinct_count_trace(
                    list(info), capacity, capacity, pack_spec=pack,
                    val_range=val_range, scatter_free=scatter_free,
                    max_sort_operands=max_ops)(vcol.dtype))
                _TRACE_CACHE[sig] = fn
            ok, (cnt, valid), ng = fn(
                tuple(c.data for c in key_cols),
                tuple(c.validity for c in key_cols),
                vcol.data, vcol.validity, live)
            if out_keys is None:
                out_keys = ok
                n_groups = ng if isinstance(ng, jax.core.Tracer) else int(ng)
            for i, jj in enumerate(val_of):
                if jj == j:
                    results[i] = (cnt, valid)

        cols = []
        for (kd, kv), kc in zip(out_keys, key_cols):
            cols.append(DeviceColumn(kd, kv, kc.dtype, kc.dictionary,
                                     kc.data_hi))
        for cnt, valid in results:
            # count(DISTINCT) is never null: 0 for empty groups
            cols.append(DeviceColumn(
                cnt, jnp.ones(cnt.shape, bool), t.LONG))
        names = self.key_names + [n for _f, n in self.aggs]
        if isinstance(n_groups, int):
            n_out = max(n_groups, 1) if not self.key_exprs else n_groups
            yield shrink_to_rows(DeviceBatch(cols, n_out, names), n_out,
                                 conf)
            return
        n_out = jnp.maximum(n_groups, 1) if not self.key_exprs else n_groups
        yield DeviceBatch(cols, n_out, names)

    def _zero_row(self, conf) -> DeviceBatch:
        from ..columnar.device import bucket_capacity
        cap = bucket_capacity(1, conf)
        cols = [DeviceColumn(jnp.zeros((cap,), jnp.int64),
                             jnp.ones((cap,), bool), t.LONG)
                for _ in self.aggs]
        return DeviceBatch(cols, 1, [n for _f, n in self.aggs])

    def describe(self):
        return (f"DistinctAggregateExec[keys={self.key_names}, "
                f"{[n for _f, n in self.aggs]}]")
