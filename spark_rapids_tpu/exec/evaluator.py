"""Compile-and-run machinery turning expression trees into XLA programs.

The reference evaluates each GpuExpression as a sequence of cuDF kernel
launches (GpuExpressions.scala columnarEval); here an operator's whole
expression list traces into ONE jit-compiled XLA program per
(operator, row-bucket) pair — XLA fuses the elementwise pipeline, which is
the TPU-idiomatic replacement for both columnarEval and the cudf AST
compiler (reference AstUtil.scala / GpuTieredProject common-subexpression
tiers: XLA's CSE does the tier work for free on the traced graph).

Jit caching: keyed on (identity of the bound expression list, capacity,
input physical signature, aux signature).  Batches flowing through the same
physical operator share bound trees, so steady-state execution hits the
cache; the bounded row-bucket set bounds total compiles.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as t
from ..config import TpuConf
from ..columnar.device import DeviceBatch, DeviceColumn
from ..ops.kernels import compute_view, storage_view, live_mask
from ..plan.expressions import DevVal, EvalCtx, Expression, PrepCtx

_JIT_CACHE = {}


def _input_sig(db: DeviceBatch):
    return tuple((str(c.data.dtype), c.data_hi is not None) for c in db.columns)


_AUX_DEVICE_CACHE = {}
_SMALL_AUX_CACHE = {}
_SCALAR_CACHE = {}


def _upload_aux(a) -> jax.Array:
    """Device copy of a host aux array, cached by content.

    Aux arrays (literal values, dictionary rank tables) repeat identically
    across batches and re-planned queries; uploading them per call costs a
    host->device transfer each — material when the chip sits behind a
    high-latency link.  Tiny scalars (e.g. monotonically_increasing_id's
    per-batch base) churn a DIFFERENT value every batch — they get their
    own small cache so they cannot evict the big shared uploads."""
    if isinstance(a, (jax.Array, jax.core.Tracer)):
        # already on device, or a lifted-literal tracer of the enclosing
        # whole-plan trace: pass through as a positional jit ARGUMENT
        # (caching a tracer would leak it into later eager calls)
        return a
    a = np.asarray(a)
    key = (a.dtype.str, a.shape, a.tobytes())
    cache = _SMALL_AUX_CACHE if a.nbytes <= 16 else _AUX_DEVICE_CACHE
    buf = cache.get(key)
    if buf is None:
        buf = jnp.asarray(a)
        if isinstance(buf, jax.core.Tracer):
            # under whole-plan tracing the "upload" is a traced constant —
            # caching it would leak the tracer into later eager calls
            return buf
        if len(cache) > 4096:
            cache.clear()
        cache[key] = buf
    return buf


def _num_rows_scalar(num_rows) -> jax.Array:
    if not isinstance(num_rows, int):
        return num_rows.astype(jnp.int32)
    buf = _SCALAR_CACHE.get(num_rows)
    if buf is None:
        buf = jnp.int32(num_rows)
        if isinstance(buf, jax.core.Tracer):
            return buf           # whole-plan tracing: never cache tracers
        if len(_SCALAR_CACHE) > 4096:
            _SCALAR_CACHE.clear()
        _SCALAR_CACHE[num_rows] = buf
    return buf


def _lift_enabled(conf: TpuConf) -> bool:
    from ..config import COMPILE_CONST_LIFT
    return bool(conf.get(COMPILE_CONST_LIFT))


def _prepare(exprs: Sequence[Expression], db: DeviceBatch, conf: TpuConf):
    dicts = {n: c.dictionary for n, c in zip(db.names, db.columns)}
    pctx = PrepCtx(conf, dicts, batch=db, lift_literals=_lift_enabled(conf))
    hostvals = [e.prepare(pctx) for e in exprs]
    aux = tuple(_upload_aux(a) for a in pctx.aux)
    return pctx, hostvals, aux


def _batch_meta(db: DeviceBatch):
    """(name, logical dtype, dictionary) per column — all a traced closure
    needs from the batch.  Capturing `db` itself would pin its device
    buffers in the jit cache for process lifetime."""
    return [(n, c.dtype, c.dictionary) for n, c in zip(db.names, db.columns)]


def _col_lanes(db: DeviceBatch):
    """Per-column jit argument: the data lane, (data, hi) for two-lane
    wide-decimal host columns, or (data, offsets, elem_valid) for ragged
    ARRAY columns (pytrees — jit handles the nesting)."""
    out = []
    for c in db.columns:
        if c.offsets is not None:
            out.append((c.data, c.offsets, c.elem_valid))
        elif c.data_hi is not None:
            out.append((c.data, c.data_hi))
        else:
            out.append(c.data)
    return tuple(out)


def _build_inputs(meta, col_data, col_valid):
    import numpy as _np
    from .. import types as t
    inputs = {}
    raw = {}
    for (name, dtype, dictionary), d, v in zip(meta, col_data, col_valid):
        hi = offsets = elem_valid = None
        if isinstance(d, tuple):
            if len(d) == 3:
                d, offsets, elem_valid = d
            else:
                d, hi = d
        view = d if offsets is not None else compute_view(d, dtype)
        narrow = None
        if offsets is None and hi is None and \
                not isinstance(dtype, (t.StringType, t.DoubleType,
                                       t.BooleanType, t.NullType)):
            # FOR-narrowed lane (value-preserving, ops/encodings.py):
            # expose the full-width view for generic consumers — the
            # widen is a fused convert, DCE'd when every consumer stays
            # narrow — and the narrow lane for encoded-aware ones
            phys = _np.dtype(t.physical_np_dtype(dtype))
            lane = _np.dtype(view.dtype)
            if lane.kind == "i" and phys.kind == "i" and \
                    lane.itemsize < phys.itemsize:
                narrow = view
                view = view.astype(phys)
        inputs[name] = DevVal(view, v, dtype, dictionary, hi,
                              offsets=offsets, elem_valid=elem_valid,
                              narrow=narrow)
        raw[name] = d          # storage lane (f64-bits stay int64)
    return inputs, raw


def _expr_fp(e) -> str:
    fp = e.__dict__.get("_fp_cache")
    if fp is None:
        fp = e.fingerprint()
        e.__dict__["_fp_cache"] = fp
    return fp


def _expr_canon_fp(e) -> str:
    fp = e.__dict__.get("_canon_fp_cache")
    if fp is None:
        fp = e.canonical_fingerprint()
        e.__dict__["_canon_fp_cache"] = fp
    return fp


def _jit_key(exprs, db, aux, conf, tag):
    # keyed on expression STRUCTURE (fingerprint), not object identity:
    # re-planned queries (every bench iteration, every AQE re-plan) must hit
    # the compiled program, not re-trace it.  Batch layout (column names,
    # logical dtypes) is part of the key — ColumnRefs resolve positionally
    # at trace time, so same-shaped batches with different layouts must not
    # share a program.  Under constant lifting the CANONICAL fingerprint
    # erases lifted literal values (they are runtime aux arguments), so
    # literal-only-different expressions share one program.
    fp = _expr_canon_fp if _lift_enabled(conf) else _expr_fp
    return (tag, tuple(fp(e) for e in exprs), db.capacity,
            tuple(db.names),
            tuple(c.dtype.simple_string for c in db.columns),
            _input_sig(db), tuple((a.shape, str(a.dtype)) for a in aux),
            conf.ansi)


def evaluate_projection(exprs: Sequence[Expression], names: Sequence[str],
                        db: DeviceBatch, conf: TpuConf) -> DeviceBatch:
    """Project `db` through bound expressions -> new DeviceBatch."""
    if db.thin is not None:
        # late materialization: referenced deferred columns resolve here
        # (ONE composed gather per lane source); unreferenced ones stay
        # zero-capacity placeholders the traced program never reads
        from ..columnar.lanes import materialize_refs
        db = materialize_refs(db, exprs, conf)
    if db.sel is not None and any(c.offsets is not None
                                  for c in db.columns):
        # ragged kernels bound live VALUES by offsets[num_rows] — a
        # prefix assumption a selection vector violates; materialize
        from ..ops.batch_ops import ensure_prefix
        db = ensure_prefix(db, conf)
    pctx, hostvals, aux = _prepare(exprs, db, conf)
    has_sel = db.sel is not None
    key = _jit_key(exprs, db, aux, conf, ("project", has_sel))
    fn = _JIT_CACHE.get(key)
    if fn is None:
        capacity = db.capacity
        node_slots = dict(pctx.node_slots)
        node_info = dict(pctx.node_info)
        exprs_t = tuple(exprs)
        meta = _batch_meta(db)

        def run(col_data, col_valid, num_rows, aux_arrs, *sel_opt):
            inputs, raw = _build_inputs(meta, col_data, col_valid)
            ctx = EvalCtx(capacity, num_rows, inputs, aux_arrs, node_slots,
                          conf, raw, node_info=node_info)
            # a selection vector replaces prefix liveness (lazy join
            # output: live rows are sel-True, not a front prefix)
            live = sel_opt[0] if sel_opt else live_mask(capacity, num_rows)
            outs = []
            for e in exprs_t:
                dv = e.eval_dev(ctx)
                if dv.offsets is not None:
                    data = dv.data
                elif dv.narrow is not None:
                    # FOR-narrowed lane rides through the projection
                    # un-widened (the decode stays sunk downstream)
                    data = dv.narrow
                else:
                    data = storage_view(dv.data, e.dtype)
                valid = dv.validity if dv.validity is not None \
                    else jnp.ones((capacity,), bool)
                # two-lane wide decimals keep their hi lane through the
                # projection (dropping it would corrupt |values| >= 2^63);
                # ragged (ARRAY) results keep offsets + element validity
                outs.append((data, valid & live, dv.hi, dv.offsets,
                             dv.elem_valid))
            return outs

        fn = jax.jit(run)
        _JIT_CACHE[key] = fn

    col_data = _col_lanes(db)
    col_valid = tuple(c.validity for c in db.columns)
    extra = (db.sel,) if has_sel else ()
    outs = fn(col_data, col_valid, _num_rows_scalar(db.num_rows), aux,
              *extra)
    cols = []
    for (data, valid, hi, offsets, ev), e, hv in zip(outs, exprs, hostvals):
        cols.append(DeviceColumn(data, valid, e.dtype, hv.dictionary,
                                 hi, offsets=offsets, elem_valid=ev))
    return DeviceBatch(cols, db.num_rows, list(names), db.origin_file,
                       sel=db.sel)


def project_batch(exprs: Sequence[Expression], names: Sequence[str],
                  db: DeviceBatch, conf: TpuConf) -> DeviceBatch:
    """ProjectExec entry point: evaluate_projection, except deferred
    columns referenced ONLY as plain pass-through refs STAY THIN — the
    placeholder and its lane bookkeeping move to the output position, so
    a projection between two joins doesn't force the materialization the
    join chain deferred.  Computed expressions still materialize exactly
    the columns they reference (early materialization)."""
    if db.thin is None:
        return evaluate_projection(exprs, names, db, conf)
    if any(c.offsets is not None for c in db.columns):
        # ragged lanes can force an internal prefix compaction whose
        # row order would desync from pass-through lanes — stay dense
        return evaluate_projection(exprs, names, db, conf)
    from ..columnar.lanes import (ThinState, materialize_refs,
                                  passthrough_positions)
    pass_map = passthrough_positions(db, exprs)
    eval_idx = [i for i in range(len(exprs)) if i not in pass_map]
    db = materialize_refs(db, [exprs[i] for i in eval_idx], conf)
    ts = db.thin
    if ts is not None and pass_map:
        # a computed expr may have materialized a pass-through column too
        pass_map = {oi: p for oi, p in pass_map.items() if p in ts.pending}
    if ts is None or not pass_map:
        return evaluate_projection(exprs, names, db, conf)
    cols: List[Optional[DeviceColumn]] = [None] * len(exprs)
    if eval_idx:
        ev = evaluate_projection([exprs[i] for i in eval_idx],
                                 [names[i] for i in eval_idx], db, conf)
        for i, c in zip(eval_idx, ev.columns):
            cols[i] = c
    used: List = []
    src_map: Dict[int, int] = {}
    new_pending: Dict[int, Tuple[int, int]] = {}
    for oi, p in pass_map.items():
        s, c = ts.pending[p]
        if s not in src_map:
            src_map[s] = len(used)
            used.append(ts.sources[s])
        cols[oi] = db.columns[p]          # the zero-capacity placeholder
        new_pending[oi] = (src_map[s], c)
    thin = ThinState(ts.capacity, used, new_pending)
    return DeviceBatch(cols, db.num_rows, list(names), db.origin_file,
                       sel=db.sel, thin=thin)


def compute_predicate(cond: Expression, db: DeviceBatch,
                      conf: TpuConf) -> jax.Array:
    """Evaluate a boolean expression -> keep-mask (False for null/padding)."""
    if db.thin is not None:
        from ..columnar.lanes import materialize_refs
        db = materialize_refs(db, [cond], conf)
    if db.sel is not None and any(c.offsets is not None
                                  for c in db.columns):
        from ..ops.batch_ops import ensure_prefix
        db = ensure_prefix(db, conf)
    pctx, _, aux = _prepare([cond], db, conf)
    has_sel = db.sel is not None
    key = _jit_key([cond], db, aux, conf, ("predicate", has_sel))
    fn = _JIT_CACHE.get(key)
    if fn is None:
        capacity = db.capacity
        node_slots = dict(pctx.node_slots)
        node_info = dict(pctx.node_info)
        meta = _batch_meta(db)

        def run(col_data, col_valid, num_rows, aux_arrs, *sel_opt):
            inputs, raw = _build_inputs(meta, col_data, col_valid)
            ctx = EvalCtx(capacity, num_rows, inputs, aux_arrs, node_slots,
                          conf, raw, node_info=node_info)
            dv = cond.eval_dev(ctx)
            keep = dv.data
            if dv.validity is not None:
                keep = keep & dv.validity
            live = sel_opt[0] if sel_opt else live_mask(capacity, num_rows)
            return keep & live

        fn = jax.jit(run)
        _JIT_CACHE[key] = fn
    extra = (db.sel,) if has_sel else ()
    return fn(_col_lanes(db), tuple(c.validity for c in db.columns),
              _num_rows_scalar(db.num_rows), aux, *extra)


def apply_filter(cond: Expression, db: DeviceBatch, conf: TpuConf) -> DeviceBatch:
    from ..ops.filter import compact_batch
    return compact_batch(db, compute_predicate(cond, db, conf), conf)
