"""Compile-and-run machinery turning expression trees into XLA programs.

The reference evaluates each GpuExpression as a sequence of cuDF kernel
launches (GpuExpressions.scala columnarEval); here an operator's whole
expression list traces into ONE jit-compiled XLA program per
(operator, row-bucket) pair — XLA fuses the elementwise pipeline, which is
the TPU-idiomatic replacement for both columnarEval and the cudf AST
compiler (reference AstUtil.scala / GpuTieredProject common-subexpression
tiers: XLA's CSE does the tier work for free on the traced graph).

Jit caching: keyed on (identity of the bound expression list, capacity,
input physical signature, aux signature).  Batches flowing through the same
physical operator share bound trees, so steady-state execution hits the
cache; the bounded row-bucket set bounds total compiles.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as t
from ..config import TpuConf
from ..columnar.device import DeviceBatch, DeviceColumn
from ..ops.kernels import compute_view, storage_view, live_mask
from ..plan.expressions import DevVal, EvalCtx, Expression, PrepCtx

_JIT_CACHE = {}


def _input_sig(db: DeviceBatch):
    return tuple((str(c.data.dtype), c.data_hi is not None) for c in db.columns)


def _prepare(exprs: Sequence[Expression], db: DeviceBatch, conf: TpuConf):
    dicts = {n: c.dictionary for n, c in zip(db.names, db.columns)}
    pctx = PrepCtx(conf, dicts)
    hostvals = [e.prepare(pctx) for e in exprs]
    aux = tuple(jnp.asarray(a) for a in pctx.aux)
    return pctx, hostvals, aux


def _build_inputs(db: DeviceBatch, col_data, col_valid):
    inputs = {}
    for name, col, d, v in zip(db.names, db.columns, col_data, col_valid):
        inputs[name] = DevVal(compute_view(d, col.dtype), v, col.dtype,
                              col.dictionary)
    return inputs


def _jit_key(exprs, db, aux, conf, tag):
    return (tag, tuple(id(e) for e in exprs), db.capacity, _input_sig(db),
            tuple((a.shape, str(a.dtype)) for a in aux), conf.ansi)


def evaluate_projection(exprs: Sequence[Expression], names: Sequence[str],
                        db: DeviceBatch, conf: TpuConf) -> DeviceBatch:
    """Project `db` through bound expressions -> new DeviceBatch."""
    pctx, hostvals, aux = _prepare(exprs, db, conf)
    key = _jit_key(exprs, db, aux, conf, "project")
    fn = _JIT_CACHE.get(key)
    if fn is None:
        capacity = db.capacity
        node_slots = dict(pctx.node_slots)
        exprs_t = tuple(exprs)

        def run(col_data, col_valid, num_rows, aux_arrs):
            inputs = _build_inputs(db, col_data, col_valid)
            ctx = EvalCtx(capacity, num_rows, inputs, aux_arrs, node_slots, conf)
            live = live_mask(capacity, num_rows)
            outs = []
            for e in exprs_t:
                dv = e.eval_dev(ctx)
                data = storage_view(dv.data, e.dtype)
                valid = dv.validity if dv.validity is not None \
                    else jnp.ones((capacity,), bool)
                outs.append((data, valid & live))
            return outs

        fn = jax.jit(run)
        _JIT_CACHE[key] = fn

    col_data = tuple(c.data for c in db.columns)
    col_valid = tuple(c.validity for c in db.columns)
    outs = fn(col_data, col_valid, jnp.int32(db.num_rows), aux)
    cols = []
    for (data, valid), e, hv in zip(outs, exprs, hostvals):
        cols.append(DeviceColumn(data, valid, e.dtype, hv.dictionary))
    return DeviceBatch(cols, db.num_rows, list(names))


def compute_predicate(cond: Expression, db: DeviceBatch,
                      conf: TpuConf) -> jax.Array:
    """Evaluate a boolean expression -> keep-mask (False for null/padding)."""
    pctx, _, aux = _prepare([cond], db, conf)
    key = _jit_key([cond], db, aux, conf, "predicate")
    fn = _JIT_CACHE.get(key)
    if fn is None:
        capacity = db.capacity
        node_slots = dict(pctx.node_slots)

        def run(col_data, col_valid, num_rows, aux_arrs):
            inputs = _build_inputs(db, col_data, col_valid)
            ctx = EvalCtx(capacity, num_rows, inputs, aux_arrs, node_slots, conf)
            dv = cond.eval_dev(ctx)
            keep = dv.data
            if dv.validity is not None:
                keep = keep & dv.validity
            return keep & live_mask(capacity, num_rows)

        fn = jax.jit(run)
        _JIT_CACHE[key] = fn
    return fn(tuple(c.data for c in db.columns),
              tuple(c.validity for c in db.columns),
              jnp.int32(db.num_rows), aux)


_COMPACT_CACHE = {}


def compact_by_mask(db: DeviceBatch, keep: jax.Array) -> DeviceBatch:
    """Gather kept rows to the front (the cuDF apply_boolean_mask analogue).

    Stable partition via argsort of the negated mask; one scalar D2H sync
    fetches the surviving row count (the reference pays the same sync for
    row counts after filters).
    """
    key = (db.capacity, _input_sig(db))
    fn = _COMPACT_CACHE.get(key)
    if fn is None:
        def run(col_data, col_valid, col_hi, keep_mask):
            perm = jnp.argsort(~keep_mask, stable=True)
            count = jnp.sum(keep_mask, dtype=jnp.int32)
            out = []
            for d, v, h in zip(col_data, col_valid, col_hi):
                out.append((d[perm], v[perm] & keep_mask[perm],
                            None if h is None else h[perm]))
            return out, count

        fn = jax.jit(run)
        _COMPACT_CACHE[key] = fn
    outs, count = fn(tuple(c.data for c in db.columns),
                     tuple(c.validity for c in db.columns),
                     tuple(c.data_hi for c in db.columns), keep)
    cols = [DeviceColumn(d, v, c.dtype, c.dictionary, h)
            for (d, v, h), c in zip(outs, db.columns)]
    return DeviceBatch(cols, int(count), list(db.names))


def apply_filter(cond: Expression, db: DeviceBatch, conf: TpuConf) -> DeviceBatch:
    return compact_by_mask(db, compute_predicate(cond, db, conf))
