"""Pandas/Python exec family (reference org/.../execution/python/, 3073
LoC: GpuArrowEvalPythonExec.scala:352, GpuMapInPandasExec et al.).

The reference moves device batches GPU -> Arrow host stream -> a
separate Python worker process (GpuArrowWriter/Reader), throttled by
PythonWorkerSemaphore, then back.  This engine's host side is already
Python+Arrow, so the worker boundary is a forked OS process fed Arrow
IPC over a pipe — real process isolation (a crashing/leaking UDF cannot
take the engine down), the same wire format (Arrow IPC), and a
concurrency semaphore.  Fork start means user functions need not be
picklable (closures/lambdas ride the copied address space), matching
pyspark ergonomics.

Execs:
  * MapInPandasExec  — df.map_in_pandas(fn, schema): fn receives an
    iterator of pandas.DataFrames, yields DataFrames (the
    GpuMapInPandasExec contract).
  * ArrowEvalPythonExec — scalar pandas UDF projection: each UDF maps
    pandas.Series -> pandas.Series, appended to the child's columns
    (the GpuArrowEvalPythonExec contract).

Both are host-side operators (transitions move device batches to Arrow
exactly as the reference's GPU->JVM->worker hops do); the overrides
engine places them with per-operator fallback reasons like any other
exec.
"""
from __future__ import annotations

import io
import multiprocessing as mp
import os
import struct
import threading
from typing import Callable, Iterator, List, Optional, Sequence, Tuple

import pyarrow as pa

from .. import types as t
from ..columnar.host import struct_to_schema
from .host_exec import HostNode
from .plan import ExecContext

_SEM_LOCK = threading.Lock()
_WORKER_SEM: Optional[threading.Semaphore] = None


def _worker_permit(conf):
    """PythonWorkerSemaphore role: bound concurrent UDF workers."""
    global _WORKER_SEM
    from ..config import PYTHON_WORKER_CONCURRENCY
    with _SEM_LOCK:
        if _WORKER_SEM is None:
            _WORKER_SEM = threading.Semaphore(
                int(conf.get(PYTHON_WORKER_CONCURRENCY)))
    return _WORKER_SEM


def _send_ipc(conn, tbl: Optional[pa.RecordBatch], schema: pa.Schema):
    if tbl is None:
        conn.send_bytes(b"")
        return
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, schema) as w:
        w.write_batch(tbl)
    conn.send_bytes(sink.getvalue())


def _recv_ipc(conn) -> Optional[pa.Table]:
    data = conn.recv_bytes()
    if not data:
        return None
    with pa.ipc.open_stream(io.BytesIO(data)) as r:
        return r.read_all()


def _map_worker(conn, fn, out_schema_bytes):
    """Child process: Arrow IPC in -> fn over pandas -> Arrow IPC out."""
    try:
        out_schema = pa.ipc.read_schema(pa.py_buffer(out_schema_bytes))

        def batches():
            while True:
                tbl = _recv_ipc(conn)
                if tbl is None:
                    return
                yield tbl.to_pandas()

        for out_df in fn(batches()):
            out = pa.RecordBatch.from_pandas(out_df,
                                             schema=out_schema,
                                             preserve_index=False)
            _send_ipc(conn, out, out_schema)
        conn.send_bytes(b"")                   # end of stream
        err = None
    except BaseException as e:                 # noqa: BLE001
        try:
            conn.send_bytes(b"ERR:" + repr(e).encode())
        except Exception:                      # noqa: BLE001
            pass
        return
    finally:
        conn.close()


class PythonWorkerError(RuntimeError):
    pass


class MapInPandasExec(HostNode):
    """df.mapInPandas over a forked Arrow-IPC worker process."""

    def __init__(self, fn: Callable, schema: t.StructType, child: HostNode):
        super().__init__(child)
        self.fn = fn
        self._schema = schema

    @property
    def output_schema(self) -> t.StructType:
        return self._schema

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        out_schema = struct_to_schema(self._schema)
        schema_bytes = out_schema.serialize().to_pybytes()
        mp_ctx = mp.get_context("fork")
        parent, child_conn = mp_ctx.Pipe()
        sem = _worker_permit(ctx.conf)
        with sem:
            proc = mp_ctx.Process(target=_map_worker,
                                  args=(child_conn, self.fn,
                                        schema_bytes), daemon=True)
            proc.start()
            child_conn.close()
            ctx.bump("python_workers_started")

            feeder_done = threading.Event()

            def feed():
                try:
                    for rb in self.child.execute(ctx):
                        if rb.num_rows == 0:
                            continue
                        _send_ipc(parent, rb, rb.schema)
                    _send_ipc(parent, None, out_schema)
                except (BrokenPipeError, OSError):
                    pass
                finally:
                    feeder_done.set()

            feeder = threading.Thread(target=feed, daemon=True)
            feeder.start()
            try:
                while True:
                    data = parent.recv_bytes()
                    if data.startswith(b"ERR:"):
                        raise PythonWorkerError(
                            data[4:].decode(errors="replace"))
                    if not data:
                        break
                    with pa.ipc.open_stream(io.BytesIO(data)) as r:
                        for rb in r.read_all().to_batches():
                            yield rb
            except EOFError:
                raise PythonWorkerError(
                    f"python worker died (exit={proc.exitcode})")
            finally:
                feeder_done.wait(timeout=5)
                parent.close()
                proc.join(timeout=10)
                if proc.is_alive():
                    proc.terminate()

    def describe(self):
        return f"MapInPandasExec[{getattr(self.fn, '__name__', 'fn')}]"


class ArrowEvalPythonExec(HostNode):
    """Scalar pandas UDFs appended as projection outputs.

    udfs: [(fn, input column names, output name, output type)] — each fn
    maps pandas.Series... -> pandas.Series of the output type (the
    GpuArrowEvalPythonExec scalar-UDF contract)."""

    def __init__(self, udfs: Sequence[Tuple[Callable, Sequence[str], str,
                                            t.DataType]],
                 child: HostNode):
        super().__init__(child)
        self.udfs = list(udfs)

    @property
    def output_schema(self) -> t.StructType:
        fields = list(self.child.output_schema.fields)
        for _fn, _cols, name, dt in self.udfs:
            fields.append(t.StructField(name, dt, True))
        return t.StructType(fields)

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        child_names = list(self.child.output_schema.names)
        out_schema = struct_to_schema(self.output_schema)

        def apply(batches):
            import pandas as pd
            for df in batches:
                cols = {n: df[n] for n in df.columns}
                for fn, in_cols, name, _dt in self.udfs:
                    cols[name] = pd.Series(
                        fn(*[df[c] for c in in_cols]))
                yield pd.DataFrame(cols)

        inner = MapInPandasExec(apply, self.output_schema, self.child)
        yield from inner.execute(ctx)

    def describe(self):
        names = [n for _f, _c, n, _t in self.udfs]
        return f"ArrowEvalPythonExec[{', '.join(names)}]"


def _group_frames(table: pa.Table, key_names: Sequence[str]):
    """pandas.DataFrame per group, null keys grouped together (pyspark
    applyInPandas contract).  Host-side segmentation: this exec IS the
    host boundary (the worker speaks pandas), so the reference's
    device-side segmentation hop has nothing to win here."""
    df = table.to_pandas()
    if not key_names:
        yield df
        return
    for _key_vals, g in df.groupby(list(key_names), dropna=False,
                                   sort=True):
        yield g


class _GroupedPandasExec(HostNode):
    """Shared scaffold for the grouped pandas exec family: materialize
    the child, segment by keys, run `apply` over per-group frames in the
    worker."""

    _group_names: Sequence[str] = ()

    def _run_grouped(self, ctx: ExecContext, apply
                     ) -> Iterator[pa.RecordBatch]:
        table = self._table(ctx)
        if table.num_rows == 0:
            return
        source = _FrameSource(_group_frames(table, self._group_names),
                              self.child.output_schema)
        inner = MapInPandasExec(apply, self.output_schema, source)
        yield from inner.execute(ctx)


class FlatMapGroupsInPandasExec(_GroupedPandasExec):
    """groupBy(keys).applyInPandas(fn, schema) — fn maps each group's
    pandas.DataFrame to a result DataFrame (reference
    GpuFlatMapGroupsInPandasExec)."""

    def __init__(self, key_names: Sequence[str], fn: Callable,
                 schema: t.StructType, child: HostNode):
        super().__init__(child)
        self.key_names = list(key_names)
        self.fn = fn
        self._schema = schema

    @property
    def _group_names(self):
        return self.key_names

    @property
    def output_schema(self) -> t.StructType:
        return self._schema

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        user_fn = self.fn

        def apply(frames):
            for df in frames:
                yield user_fn(df.reset_index(drop=True))

        yield from self._run_grouped(ctx, apply)

    def describe(self):
        return (f"FlatMapGroupsInPandasExec[{self.key_names}, "
                f"{getattr(self.fn, '__name__', 'fn')}]")


class AggregateInPandasExec(_GroupedPandasExec):
    """groupBy(keys).agg(pandas UDAF): each agg fn maps the group's
    input Series to ONE scalar; output = key columns + one column per
    agg, one row per group (reference GpuAggregateInPandasExec).

    aggs: [(fn, input column names, output name, output type)]."""

    def __init__(self, key_names: Sequence[str],
                 aggs: Sequence[Tuple[Callable, Sequence[str], str,
                                      t.DataType]],
                 child: HostNode):
        super().__init__(child)
        self.key_names = list(key_names)
        self.aggs = list(aggs)

    @property
    def output_schema(self) -> t.StructType:
        schema = self.child.output_schema
        fields = [schema.fields[schema.field_index(n)]
                  for n in self.key_names]
        for _fn, _cols, name, dt in self.aggs:
            fields.append(t.StructField(name, dt, True))
        return t.StructType(fields)

    @property
    def _group_names(self):
        return self.key_names

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        key_names = list(self.key_names)
        aggs = list(self.aggs)

        def apply(frames):
            import pandas as pd
            for df in frames:
                row = {n: [df[n].iloc[0]] for n in key_names}
                for fn, in_cols, name, _dt in aggs:
                    row[name] = [fn(*[df[c] for c in in_cols])]
                yield pd.DataFrame(row)

        yield from self._run_grouped(ctx, apply)

    def describe(self):
        return (f"AggregateInPandasExec[{self.key_names}, "
                f"{[n for _f, _c, n, _t in self.aggs]}]")


class WindowInPandasExec(_GroupedPandasExec):
    """Pandas window UDFs over unbounded partition frames: each fn maps
    the partition's input Series to either a Series of the partition's
    length or one scalar (broadcast) — the two shapes the reference's
    GpuWindowInPandasExec supports for UNBOUNDED PRECEDING/FOLLOWING.

    windows: [(fn, input column names, output name, output type)];
    output = child columns + one per window fn, rows ordered by
    (partition keys, order keys)."""

    def __init__(self, partition_names: Sequence[str],
                 order_names: Sequence[str],
                 windows: Sequence[Tuple[Callable, Sequence[str], str,
                                         t.DataType]],
                 child: HostNode):
        super().__init__(child)
        self.partition_names = list(partition_names)
        self.order_names = list(order_names)
        self.windows = list(windows)

    @property
    def output_schema(self) -> t.StructType:
        fields = list(self.child.output_schema.fields)
        for _fn, _cols, name, dt in self.windows:
            fields.append(t.StructField(name, dt, True))
        return t.StructType(fields)

    @property
    def _group_names(self):
        return self.partition_names

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        order = list(self.order_names)
        windows = list(self.windows)

        def apply(frames):
            import pandas as pd
            for df in frames:
                if order:
                    df = df.sort_values(order, kind="stable")
                df = df.reset_index(drop=True)
                cols = {n: df[n] for n in df.columns}
                for fn, in_cols, name, _dt in windows:
                    out = fn(*[df[c] for c in in_cols])
                    if not isinstance(out, pd.Series):
                        out = pd.Series([out] * len(df))
                    cols[name] = out.reset_index(drop=True)
                yield pd.DataFrame(cols)

        yield from self._run_grouped(ctx, apply)

    def describe(self):
        return (f"WindowInPandasExec[{self.partition_names}, "
                f"{[n for _f, _c, n, _t in self.windows]}]")


class _FrameSource(HostNode):
    """Adapter: a python iterator of pandas group frames as a HostNode
    child for MapInPandasExec (each frame = one worker batch = one
    group)."""

    def __init__(self, frames, schema: t.StructType):
        super().__init__()
        self._frames = frames
        self._schema = schema

    @property
    def output_schema(self) -> t.StructType:
        return self._schema

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        arrow_schema = struct_to_schema(self._schema)
        for df in self._frames:
            yield pa.RecordBatch.from_pandas(df, schema=arrow_schema,
                                             preserve_index=False)


def _cogroup_worker(conn, fn, out_schema_bytes):
    """Child process for cogrouped pandas: PAIRS of Arrow IPC tables in
    (left then right per group; an empty-bytes frame ends the stream),
    fn(left_df, right_df) -> DataFrame, Arrow IPC out."""
    try:
        out_schema = pa.ipc.read_schema(pa.py_buffer(out_schema_bytes))
        while True:
            l_tbl = _recv_ipc(conn)
            if l_tbl is None:
                break
            r_tbl = _recv_ipc(conn)
            out_df = fn(l_tbl.to_pandas(), r_tbl.to_pandas())
            out = pa.RecordBatch.from_pandas(out_df, schema=out_schema,
                                             preserve_index=False)
            _send_ipc(conn, out, out_schema)
        conn.send_bytes(b"")                   # end of stream
    except BaseException as e:                 # noqa: BLE001
        try:
            conn.send_bytes(b"ERR:" + repr(e).encode())
        except Exception:                      # noqa: BLE001
            pass
        return
    finally:
        conn.close()


class FlatMapCoGroupsInPandasExec(HostNode):
    """cogroup(left, right).applyInPandas(fn, schema) — the reference's
    GpuFlatMapCoGroupsInPandasExec over the fork-worker: both sides
    materialize, group frames pair by SORTED key tuple (full outer over
    the key sets — a key on one side only pairs with an empty frame),
    and each pair round-trips the worker as two Arrow IPC messages."""

    def __init__(self, left_keys: Sequence[str],
                 right_keys: Sequence[str], fn: Callable,
                 schema: t.StructType, left: HostNode, right: HostNode):
        super().__init__(left, right)
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.fn = fn
        self._schema = schema

    @property
    def output_schema(self) -> t.StructType:
        return self._schema

    def _side_table(self, node, ctx) -> pa.Table:
        batches = list(node.execute(ctx))
        schema = struct_to_schema(node.output_schema)
        return pa.Table.from_batches(batches, schema) if batches \
            else pa.Table.from_batches([], schema)

    def execute(self, ctx: ExecContext) -> Iterator[pa.RecordBatch]:
        import multiprocessing as mp
        left_t = self._side_table(self.children[0], ctx)
        right_t = self._side_table(self.children[1], ctx)
        l_schema = struct_to_schema(self.children[0].output_schema)
        r_schema = struct_to_schema(self.children[1].output_schema)
        l_groups = {k: df for k, df in _keyed_frames(left_t,
                                                     self.left_keys)}
        r_groups = {k: df for k, df in _keyed_frames(right_t,
                                                     self.right_keys)}
        keys = sorted(set(l_groups) | set(r_groups),
                      key=lambda kt: tuple((v is None, v) for v in kt))
        if not keys:
            return
        l_empty = left_t.slice(0, 0).to_pandas()
        r_empty = right_t.slice(0, 0).to_pandas()
        out_schema = struct_to_schema(self.output_schema)

        ctxmp = mp.get_context("fork")
        parent, child = ctxmp.Pipe()
        proc = ctxmp.Process(
            target=_cogroup_worker,
            args=(child, self.fn, out_schema.serialize().to_pybytes()),
            daemon=True)
        with _worker_permit(ctx.conf):
            proc.start()
            child.close()
            try:
                for kt in keys:
                    ldf = l_groups.get(kt)
                    rdf = r_groups.get(kt)
                    _send_ipc(parent, pa.RecordBatch.from_pandas(
                        ldf if ldf is not None else l_empty,
                        schema=l_schema, preserve_index=False), l_schema)
                    _send_ipc(parent, pa.RecordBatch.from_pandas(
                        rdf if rdf is not None else r_empty,
                        schema=r_schema, preserve_index=False), r_schema)
                    out = _recv_worker_batch(parent)
                    if out is not None and out.num_rows:
                        yield out
                parent.send_bytes(b"")          # end of stream
            finally:
                parent.close()
                proc.join(timeout=30)
                if proc.is_alive():
                    proc.terminate()

    def describe(self):
        return (f"FlatMapCoGroupsInPandasExec[{self.left_keys}|"
                f"{self.right_keys}, "
                f"{getattr(self.fn, '__name__', 'fn')}]")


def _keyed_frames(table: pa.Table, key_names: Sequence[str]):
    """(key tuple, pandas frame) per group, null keys grouped (pyspark
    cogroup contract)."""
    df = table.to_pandas()
    if not key_names:
        yield (), df
        return
    import pandas as pd
    for key_vals, g in df.groupby(list(key_names), dropna=False,
                                  sort=True):
        if not isinstance(key_vals, tuple):
            key_vals = (key_vals,)
        norm = tuple(None if (v is None or v != v) else v
                     for v in key_vals)
        yield norm, g


def _recv_worker_batch(parent) -> Optional[pa.RecordBatch]:
    """One result frame from the worker (None = empty result); raises
    PythonWorkerError on an ERR frame."""
    buf = parent.recv_bytes()
    if buf.startswith(b"ERR:"):
        raise PythonWorkerError(buf[4:].decode())
    if not buf:
        return None
    tbl = pa.ipc.open_stream(pa.py_buffer(buf)).read_all()
    rbs = tbl.to_batches()
    return rbs[0] if rbs else None
