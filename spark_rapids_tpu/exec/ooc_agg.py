"""Spill-partitioned out-of-core group-by aggregation.

Reference: GpuAggregateExec.scala:711 — when merging accumulated
partials stops reducing, the reference re-partitions them by key hash
and merges each bucket independently.  This module is that fallback
grown into a first-class out-of-core tier (ROADMAP item 4):

  * partial aggregates hash-scatter by GROUP KEY into budget-registered
    `Spillable` buckets (`runtime/memory.py`) — key-disjoint partitions
    make the union of per-bucket results EXACT, with the same output
    contracts as the resident path (each bucket finishes on the
    existing sorted/segagg group-by tiers);
  * the bucket fan-out derives from measured partial BYTES vs the
    out-of-core resident window (`exec/ooc.py`), not just the legacy
    row gate, so a wide-row aggregation degrades before the budget
    OOMs rather than after;
  * a bucket that still exceeds the window re-scatters recursively
    with a re-salted hash (bounded by `sql.ooc.maxDepth`) so key skew
    cannot OOM one bucket; merges inside a bucket are rolling and
    retry-wrapped, holding at most two batches resident;
  * every partition pass fires the `ooc` chaos site after publishing
    its `ooc_state` instant, and the `tpu_ooc_*` families count
    elections/partitions/bytes/recursions (`docs/METRICS.md`).

`HashAggregateExec` (exec/plan.py) owns WHEN to elect this tier (row
gate, byte gate, forced/escalated context); this module owns the
bucket lifecycle, including the idempotent-close cleanup sweep that
early generator abandonment (a LIMIT above the aggregation) relies on.
"""
from __future__ import annotations

from typing import Iterator, List

from ..columnar.device import DeviceBatch
from ..ops.filter import compact_batch
from ..ops.batch_ops import shrink_to_rows
from . import ooc as O
from .plan import ExecContext


class OutOfCoreAggregator:
    """Bucket lifecycle of one spill-partitioned aggregation."""

    def __init__(self, agg, nkeys: int, ctx: ExecContext,
                 policy: "O.OocPolicy", k: int):
        self.agg = agg                       # exec.aggregate.HashAggregate
        self.nkeys = nkeys
        self.ctx = ctx
        self.policy = policy
        self.k = k
        self.buckets: List[list] = [[] for _ in range(k)]
        self._scattered = 0

    # -- scatter -----------------------------------------------------------
    def _scatter(self, pb: DeviceBatch, buckets, nparts: int,
                 salt: int) -> int:
        """Split a partial batch into hash buckets of its group keys
        (value-stable across batches: string keys hash dictionary
        VALUES, not per-batch codes).  Returns spillable bytes added."""
        from ..runtime.memory import Spillable
        from .plan import _agg_partition_ids
        ctx = self.ctx
        ids = _agg_partition_ids(pb, self.nkeys, nparts, salt)
        live = pb.row_mask()
        added = 0
        for p in range(nparts):
            part = compact_batch(pb, (ids == p) & live, ctx.conf)
            part = shrink_to_rows(part, int(part.num_rows), ctx.conf)
            if int(part.num_rows):
                sp = Spillable(part, ctx.budget)
                # live-row-scaled size: recursion decisions must not be
                # inflated by min-bucket capacity padding of tiny slices
                sp.live_nbytes = O.batch_bytes(part)
                buckets[p].append(sp)
                added += sp.live_nbytes
        return added

    def add(self, pb: DeviceBatch) -> None:
        """Scatter one partial into the top-level buckets."""
        self._scattered += self._scatter(pb, self.buckets, self.k, 0)

    # -- finalize ----------------------------------------------------------
    def results(self) -> Iterator[DeviceBatch]:
        ctx = self.ctx
        O.record_partitions(ctx, "agg", self.k, self._scattered)
        try:
            for p, blist in enumerate(self.buckets):
                if not blist:
                    continue
                O.fire(ctx, "agg", bucket=p, k=self.k, depth=0)
                yield from self._finalize(blist, 1)
        finally:
            # early abandonment / errors must release every registered
            # spillable (close is idempotent by contract)
            self.close()

    def _finalize(self, blist, depth: int) -> Iterator[DeviceBatch]:
        """Merge + finalize one bucket.  Oversized buckets re-scatter
        with a different hash salt (bounded depth); merges are rolling
        and retry-wrapped so the working set stays at two batches."""
        from ..config import AGG_FALLBACK_PARTITIONS
        from ..runtime.memory import Spillable
        from ..runtime.retry import with_retry
        ctx, conf, policy = self.ctx, self.ctx.conf, self.policy
        total = sum(sp.num_rows for sp in blist)
        total_bytes = sum(getattr(sp, "live_nbytes", sp.nbytes)
                          for sp in blist)
        # re-scatter only when the bucket's distinct-key bound (its row
        # count) exceeds what one merged batch can hold — the rolling
        # merge below keeps residency at TWO batches regardless of how
        # many spillable slices the bucket accumulated, so byte volume
        # alone never justifies the re-partition churn
        rows_trip = len(blist) > 1 and total > 2 * conf.batch_size_rows
        sub: List[list] = []
        acc = None
        try:
            if depth < policy.max_depth and rows_trip:
                k = conf.get(AGG_FALLBACK_PARTITIONS)
                if policy.bytes_trip(total_bytes):
                    O.record_recursion(ctx, "agg")
                    k = max(k, O.partition_count(total_bytes, policy))
                sub = [[] for _ in range(k)]
                added = 0
                for sp in blist:
                    b = sp.get()
                    sp.close()
                    added += self._scatter(b, sub, k, salt=depth)
                ctx.bump("agg_repartition_fallbacks")
                O.record_partitions(ctx, "agg", k, added)
                for p, sl in enumerate(sub):
                    if sl:
                        O.fire(ctx, "agg", bucket=p, k=k, depth=depth)
                        yield from self._finalize(sl, depth + 1)
                return
            acc = blist[0]
            for sp in blist[1:]:
                # both inputs stay REGISTERED during the merge attempt so
                # the retry's spill_all can actually demote them (the
                # reference's "inputs must be spillable" contract); get()
                # inside the attempt re-materializes after a spill
                a, b = acc, sp
                merged = with_retry(ctx.budget, conf,
                                    lambda: self.agg.merge([a.get(),
                                                            b.get()]))
                nxt = Spillable(merged, ctx.budget)
                a.close()
                b.close()
                acc = nxt
            out = acc.get()
            acc.close()
            yield self.agg.final(out)
        finally:
            # early abandonment / mid-merge failure: release everything
            # still registered (close is idempotent)
            for sp in blist:
                sp.close()
            for sl in sub:
                for sp in sl:
                    sp.close()
            if acc is not None:
                acc.close()

    def close(self) -> None:
        for blist in self.buckets:
            for sp in blist:
                sp.close()
