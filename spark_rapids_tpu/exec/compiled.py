"""Whole-plan XLA compilation: one jit program per query.

The reference dispatches one cuDF kernel launch per operator step; launch
latency is ~free on a locally attached GPU.  On TPU the idiomatic shape
is the opposite: **trace the entire physical plan once and hand XLA a
single program** — operators fuse (filter masks into projections into
segment-reductions), intermediate lanes never round-trip through HBM
twice, and a warm query is ONE dispatch + ONE result fetch regardless of
plan depth.  This is the "cudf AST compiled expressions" idea
(GpuExpressions.scala convertToAst / ast.CompiledExpression) taken to its
XLA-native conclusion: tracing IS the AST, for the whole plan rather than
one expression.

How it works:
  * Leaf `HostScanExec`s upload their batches once (cached on the node —
    the buffer-cache / spill-framework role for hot inputs).
  * `jax.jit(run)` traces `root.execute(ctx)` — the ordinary operator
    generators — over placeholder arrays standing in for every leaf lane.
    All sync-free paths (probe-aligned joins, lazy filters/limits,
    segment aggregations, single-batch sorts) trace cleanly because they
    never coerce a device value on host.
  * Output batch *structure* (schema, capacities, dictionaries) is
    recorded at trace time; the compiled call returns flat lanes that are
    re-wrapped as DeviceBatches / fetched in one `jax.device_get`.
  * Anything that genuinely needs a host decision (sized join expansion,
    out-of-core sort, retry machinery) raises a tracer-concretization
    error — the caller falls back to the eager batch-at-a-time engine,
    which remains the out-of-core/general path.

Compile cost is paid once per (plan shape, input bucket) and is
persisted by jax's compilation cache; warm latency is what the
benchmark measures (BASELINE.md).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import pyarrow as pa

from .. import types as t
from ..columnar.device import (DeviceBatch, DeviceColumn, bucket_capacity,
                               to_device)
from ..config import TpuConf
from .plan import ExecContext, HostScanExec, PlanNode


def _find_scans(root: PlanNode) -> List[PlanNode]:
    """Leaves whose batches become jit inputs: host scans (uploaded) and
    device-resident split seams (already on device)."""
    out = []
    seen = set()

    def walk(n: PlanNode):
        if id(n) in seen:
            return
        seen.add(id(n))
        if isinstance(n, (HostScanExec, DeviceResidentScanExec)):
            out.append(n)
        for c in n.children:
            walk(c)
    walk(root)
    return out


def _flatten_batch(db: DeviceBatch):
    """-> (arrays, spec) where spec rebuilds the batch from arrays."""
    arrays = []
    cols = []
    for c in db.columns:
        arrays.append(c.data)
        arrays.append(c.validity)
        if c.data_hi is not None:
            arrays.append(c.data_hi)
        if c.offsets is not None:              # ragged ARRAY lanes
            arrays.append(c.offsets)
            arrays.append(c.elem_valid)
        cols.append((c.dtype, c.dictionary, c.data_hi is not None,
                     c.offsets is not None))
    static_rows = db.num_rows if isinstance(db.num_rows, int) else None
    if static_rows is None:
        arrays.append(db.num_rows)
    # a lazy selection vector is part of the batch's liveness: dropping
    # it across a program boundary would turn sel-liveness into (wrong)
    # prefix-liveness
    has_sel = db.sel is not None
    if has_sel:
        arrays.append(db.sel)
    return arrays, (cols, list(db.names), static_rows, db.origin_file,
                    has_sel)


def _rebuild_batch(arrays, spec, i: int) -> Tuple[DeviceBatch, int]:
    cols_spec, names, static_rows, origin, has_sel = spec
    cols = []
    for dtype, dictionary, has_hi, has_off in cols_spec:
        data = arrays[i]
        valid = arrays[i + 1]
        i += 2
        hi = offsets = elem_valid = None
        if has_hi:
            hi = arrays[i]
            i += 1
        if has_off:
            offsets = arrays[i]
            elem_valid = arrays[i + 1]
            i += 2
        cols.append(DeviceColumn(data, valid, dtype, dictionary, hi,
                                 offsets=offsets, elem_valid=elem_valid))
    if static_rows is None:
        num_rows = arrays[i]
        i += 1
    else:
        num_rows = static_rows
    sel = None
    if has_sel:
        sel = arrays[i]
        i += 1
    return DeviceBatch(cols, num_rows, names, origin, sel=sel), i


def _shard_batch(db: DeviceBatch, mesh) -> DeviceBatch:
    """Place a batch's lanes row-sharded over the mesh (replicated when
    the capacity doesn't divide the mesh)."""
    from jax.sharding import NamedSharding, PartitionSpec
    from ..parallel.mesh import SHARD_AXIS
    n = mesh.devices.size
    spec = PartitionSpec(SHARD_AXIS) if db.capacity % n == 0 \
        else PartitionSpec()
    sh = NamedSharding(mesh, spec)
    rep = NamedSharding(mesh, PartitionSpec())
    cols = []
    for c in db.columns:
        if c.offsets is not None:
            # ragged columns: offsets (rows+1) and value lanes don't fit
            # the row sharding — replicate; GSPMD still partitions the
            # flat columns around them
            cols.append(DeviceColumn(
                jax.device_put(c.data, rep),
                jax.device_put(c.validity, rep),
                c.dtype, c.dictionary, None,
                offsets=jax.device_put(c.offsets, rep),
                elem_valid=jax.device_put(c.elem_valid, rep)))
            continue
        cols.append(DeviceColumn(
            jax.device_put(c.data, sh),
            jax.device_put(c.validity, sh),
            c.dtype, c.dictionary,
            None if c.data_hi is None
            else jax.device_put(c.data_hi, sh)))
    return DeviceBatch(cols, db.num_rows, db.names, db.origin_file)


_SCAN_UPLOAD_CACHE: Dict[object, tuple] = {}


def _shared_scan_upload(node: HostScanExec, conf: TpuConf
                        ) -> List[DeviceBatch]:
    """Upload a scan's batches once PER SOURCE TABLE (not per plan): every
    re-planned query over the same pyarrow table shares one device copy —
    the buffer-cache role for hot inputs (reference FileCache /
    spill-framework device tier).  Weakref-keyed so device memory is
    released with the table."""
    import weakref
    tbl = node._source_table
    if tbl is None:
        return [to_device(hb, conf) for hb in node.batches]
    key = (id(tbl), conf.batch_size_rows)
    hit = _SCAN_UPLOAD_CACHE.get(key)
    if hit is not None and hit[0]() is tbl:
        return hit[1]
    dbs = [to_device(hb, conf) for hb in node.batches]
    try:
        ref = weakref.ref(tbl, lambda _r, k=key:
                          _SCAN_UPLOAD_CACHE.pop(k, None))
    except TypeError:
        return dbs
    _SCAN_UPLOAD_CACHE[key] = (ref, dbs)
    return dbs


class CompiledPlan:
    """A traced-and-jitted device plan bound to its leaf scans.

    With `mesh`, leaf lanes are placed row-sharded over the mesh axis and
    the SAME whole-plan program runs SPMD: XLA's GSPMD partitioner keeps
    scans/filters/projections data-parallel per chip and inserts the
    cross-chip collectives (all-to-all/all-gather/psum over ICI) where
    sorts, group-bys and joins need global views — the
    annotate-shardings-and-let-XLA-insert-collectives recipe, playing the
    reference's shuffle-exchange fabric role (RapidsShuffleManager/UCX)."""

    def __init__(self, root: PlanNode, conf: TpuConf, mesh=None):
        self.root = root
        self.conf = conf
        self.mesh = mesh
        self._out_specs: Optional[list] = None
        self._compiled = None
        self._input_specs = None

    # -- leaves ------------------------------------------------------------
    def _leaf_batches(self, ctx: ExecContext
                      ) -> List[Tuple[HostScanExec, List[DeviceBatch]]]:
        pairs = []
        for node in _find_scans(self.root):
            if isinstance(node, DeviceResidentScanExec):
                pairs.append((node, node.batches))   # already on device
                continue
            cached = getattr(node, "_device_cache", None)
            if cached is None:
                from ..runtime.retry import retry_io
                with ctx.tracer.span("upload", "transition"):
                    cached = retry_io(
                        ctx.conf, "h2d",
                        lambda: _shared_scan_upload(node, ctx.conf))
                    if self.mesh is not None:
                        cached = [_shard_batch(db, self.mesh)
                                  for db in cached]
                ctx.tracer.add_bytes(
                    "h2d_bytes", sum(hb.rb.nbytes for hb in node.batches))
                node._device_cache = cached
            pairs.append((node, cached))
        return pairs

    def _flatten_inputs(self, pairs):
        flat_in: List[jax.Array] = []
        in_specs = []
        for node, dbs in pairs:
            node_specs = []
            for db in dbs:
                arrays, spec = _flatten_batch(db)
                flat_in.extend(arrays)
                node_specs.append(spec)
            in_specs.append((node, node_specs))
        return flat_in, in_specs

    def _make_runner(self, in_specs, ctx: ExecContext,
                     out_holder: Dict[str, list]):
        """The traced whole-plan function over flattened leaf lanes."""
        def run(flat):
            # rebuild leaf batches from traced arrays and install them
            i = 0
            for node, node_specs in in_specs:
                batches = []
                for spec in node_specs:
                    db, i = _rebuild_batch(flat, spec, i)
                    batches.append(db)
                node._trace_batches = batches
            try:
                trace_ctx = _trace_context(ctx)
                outs = list(self.root.execute(trace_ctx))
            finally:
                for node, _ in in_specs:
                    node._trace_batches = None
                # copy ONLY host numbers back: a traced metric value
                # escaping the jit would be a leaked tracer
                for k, v in trace_ctx.metrics.items():
                    if isinstance(v, (int, float)):
                        ctx.metrics[k] = v
            flat_out = []
            specs = []
            for db in outs:
                if db.thin is not None:
                    # the program boundary is a pipeline SINK: resolve
                    # deferred columns INSIDE the traced program (the
                    # composed gathers fuse into the whole-plan XLA
                    # program; the flat output layer carries no lanes)
                    from ..columnar.lanes import materialize_batch
                    db = materialize_batch(db, ctx.conf)
                arrays, spec = _flatten_batch(db)
                flat_out.extend(arrays)
                specs.append(spec)
            out_holder["specs"] = specs
            return flat_out
        return run

    def make_jaxpr(self, ctx: ExecContext):
        """Abstract-trace the whole-plan program and return its
        ClosedJaxpr — no compile, no execution.  Powers the suite-wide
        sort-operand lint (testing.py) and bench.py's per-query
        `sort_operand_max` / `scatter_op_count` metrics.  Raises the
        same tracer errors as execute() for host-decision plans."""
        pairs = self._leaf_batches(ctx)
        flat_in, in_specs = self._flatten_inputs(pairs)
        holder: Dict[str, list] = {}
        return jax.make_jaxpr(self._make_runner(in_specs, ctx, holder))(
            flat_in)

    # -- compile + run -----------------------------------------------------
    def execute(self, ctx: ExecContext) -> List[DeviceBatch]:
        """Run the whole plan as one XLA program; returns device batches.

        Raises jax tracer errors (ConcretizationTypeError & friends) when
        the plan needs host decisions — callers fall back to eager."""
        pairs = self._leaf_batches(ctx)
        flat_in, in_specs = self._flatten_inputs(pairs)

        if self._compiled is None:
            import time as _time
            from ..runtime.faults import get_injector
            # chaos site: a whole-plan compile failure — injected `oom`
            # exercises the eager-engine fallback, `fatal` the crash
            # capture (collect_with_fallback owns both ladders)
            get_injector(ctx.conf).fire("compile")
            self._input_specs = [(n, list(s)) for n, s in in_specs]
            out_holder: Dict[str, list] = {}
            t0 = _time.perf_counter()
            with ctx.tracer.span("trace+compile", "compile",
                                 root=self.root.name()):
                compiled = jax.jit(self._make_runner(in_specs, ctx,
                                                     out_holder))
                flat_res = compiled(flat_in)     # traces on first call
            ctx.metrics["compile_ms"] = ctx.metrics.get(
                "compile_ms", 0.0) + (_time.perf_counter() - t0) * 1000.0
            ctx.bump("compile_cache_misses")
            self._out_specs = out_holder["specs"]
            self._compiled = compiled
        else:
            ctx.bump("compile_cache_hits")
            with ctx.tracer.span("execute", "execute",
                                 root=self.root.name()):
                flat_res = self._compiled(flat_in)

        outs = []
        i = 0
        for spec in self._out_specs:
            db, i = _rebuild_batch(flat_res, spec, i)
            outs.append(db)
        return outs

    def collect(self, ctx: ExecContext) -> pa.Table:
        from ..columnar.device import fetch_result_batch
        from ..columnar.host import struct_to_schema
        from ..runtime.retry import retry_io
        outs = self.execute(ctx)
        bound = self.root.row_upper_bound()
        hbs = []
        for db in outs:
            with ctx.tracer.span("fetch", "transition"):
                hb = retry_io(ctx.conf, "d2h",
                              lambda: fetch_result_batch(db, bound,
                                                         ctx.conf))
            ctx.bump("d2h_rows", hb.num_rows)
            ctx.tracer.add_bytes("d2h_bytes", hb.rb.nbytes)
            hbs.append(hb)
        batches = [hb.rb for hb in hbs if hb.num_rows > 0]
        if not batches:
            return pa.Table.from_batches(
                [], struct_to_schema(self.root.output_schema))
        return pa.Table.from_batches(batches, batches[0].schema)


def _trace_context(ctx: ExecContext) -> ExecContext:
    """Execution context for use UNDER tracing: unlimited budget (XLA owns
    memory inside one program; spilling a tracer is meaningless), no
    runtime bloom filters (their sizing needs host row counts), and a
    PRIVATE metrics dict — device-scalar metrics recorded during tracing
    are tracers and must never escape the jit (host numbers are copied
    back by the caller)."""
    from ..config import (HBM_BUDGET_BYTES, RUNTIME_FILTER_ENABLED,
                          TEST_FAULTS, TEST_INJECT_RETRY_OOM)
    raw = dict(ctx.conf._raw)
    raw[HBM_BUDGET_BYTES.key] = 1 << 62
    raw[RUNTIME_FILTER_ENABLED.key] = False
    raw[TEST_INJECT_RETRY_OOM.key] = 0
    # fault injection under jit tracing would bake a synthetic failure
    # into the compiled program; chaos targets the runtime layers only
    raw[TEST_FAULTS.key] = ""
    return ExecContext(TpuConf(raw))


# errors that mean "this plan needs host decisions" — not bugs
_TRACE_FALLBACK_ERRORS = (
    jax.errors.ConcretizationTypeError,
    jax.errors.TracerArrayConversionError,
    jax.errors.TracerBoolConversionError,
    jax.errors.TracerIntegerConversionError,
    jax.errors.UnexpectedTracerError,
)


class DeviceResidentScanExec(PlanNode):
    """Leaf standing in for an already-computed subplan's device output
    (the split-plan seam).  Delegates plan statistics to the node it
    replaces, so downstream fast paths (unique-build joins, dense
    domains) survive the split."""

    def __init__(self, source: PlanNode):
        super().__init__()
        self._source = source
        self.batches: List[DeviceBatch] = []

    @property
    def output_schema(self):
        return self._source.output_schema

    def keys_unique(self, names):
        return self._source.keys_unique(names)

    def column_range(self, name):
        return self._source.column_range(name)

    def static_row_count(self):
        if len(self.batches) == 1 and \
                isinstance(self.batches[0].num_rows, int):
            return self.batches[0].num_rows
        return self._source.static_row_count()

    def execute(self, ctx: ExecContext):
        trace = getattr(self, "_trace_batches", None)
        yield from (trace if trace is not None else self.batches)

    def describe(self):
        return f"DeviceResidentScan[{self._source.describe()}]"


def _find_split_seams(root: PlanNode, conf=None) -> List[PlanNode]:
    """Innermost-first seam nodes where live row counts collapse but
    static bucket capacities do not:

      1. the input of the topmost aggregate (after its fused-filter
         chain) when it is real work (a join subtree, not a bare scan) —
         selective joins + fused filters typically leave a small
         fraction of the input bucket live;
      2. the topmost aggregate itself — millions of rows in, thousands
         of groups out.

    Each seam costs one host count sync and re-buckets everything above
    it to actual sizes."""
    from .plan import FilterExec, HashAggregateExec, HostScanExec

    def find_agg(n: PlanNode):
        for c in n.children:
            if isinstance(c, HashAggregateExec):
                return c
            found = find_agg(c)
            if found is not None:
                return found
        return None

    agg = None if isinstance(root, HashAggregateExec) else find_agg(root)
    if agg is None:
        return []
    # every seam costs one host count sync (a full tunnel RTT) and one
    # extra program dispatch; with sub-capacity inputs the padding the
    # seam would trim is worth less than the round trips (q11: 75 ms of
    # device work behind ~450 ms of seam/dispatch latency), so only
    # split when the subtree actually carries big buckets
    from ..config import DEFAULT_CONF, SEAM_SPLIT_MIN_ROWS
    min_rows = (conf or DEFAULT_CONF).get(SEAM_SPLIT_MIN_ROWS)
    if _max_leaf_capacity(agg, conf) < min_rows:
        return []
    seams: List[PlanNode] = []
    source = agg.child
    while isinstance(source, FilterExec):
        source = source.child
    if not isinstance(source, (HostScanExec, DeviceResidentScanExec)):
        seams.append(source)
    seams.append(agg)
    return seams


def _max_leaf_capacity(root: PlanNode, conf=None) -> int:
    """Largest leaf-scan bucket under `root` (host batch row counts
    rounded to their buckets under the SESSION conf; device-resident
    seam leaves report their batch capacities)."""
    from ..config import DEFAULT_CONF
    conf = conf or DEFAULT_CONF
    best = 0
    for node in _find_scans(root):
        if isinstance(node, DeviceResidentScanExec):
            best = max(best, *(db.capacity for db in node.batches), 0)
            continue
        for hb in node.batches:
            best = max(best, bucket_capacity(max(hb.num_rows, 1), conf))
    return best


def _slice_batch(db: DeviceBatch, cap: int, n: int) -> DeviceBatch:
    """Narrow a live-prefix batch to a smaller capacity bucket."""
    cols = []
    for c in db.columns:
        cols.append(DeviceColumn(
            c.data[:cap], c.validity[:cap], c.dtype, c.dictionary,
            None if c.data_hi is None else c.data_hi[:cap]))
    return DeviceBatch(cols, n, db.names, db.origin_file)


def _swap_child(root: PlanNode, old: PlanNode, new: PlanNode):
    """(parent, index) of `old` under `root`; caller mutates + restores."""
    for n in [root] + [d for d in _walk_nodes(root)]:
        for i, c in enumerate(n.children):
            if c is old:
                return n, i
    raise ValueError("split node not found under root")


def _walk_nodes(n: PlanNode):
    for c in n.children:
        yield c
        yield from _walk_nodes(c)


class SplitCompiledPlan:
    """Segmented whole-plan execution: the plan splits at seam nodes
    where the live row count collapses (join subtrees under aggregates,
    the aggregates themselves — _find_split_seams).  Each segment runs
    as one XLA program; at every seam ONE host sync reads the actual
    row count and the seam output re-buckets down (a device slice, no
    data transfer) before the next segment compiles over the smaller
    shapes.

    The reference never needs this: its kernels size outputs dynamically
    per launch.  Static-shape XLA programs otherwise carry the input-
    scale padding through every downstream operator (a TPC-H q3 tail —
    sort+limit over ~11k groups — was running at the 4M-row lineitem
    bucket, and its group-by over ~540k join survivors likewise)."""

    def __init__(self, root: PlanNode, seams: List[PlanNode],
                 conf: TpuConf):
        self.root = root
        self.conf = conf
        self.seams = list(seams)            # innermost-first
        self.leaves = [DeviceResidentScanExec(s) for s in self.seams]
        self._parent_idx = []
        scope = list(self.seams[1:]) + [root]
        for seam, leaf, upper in zip(self.seams, self.leaves, scope):
            self._parent_idx.append(_swap_child(upper, seam, leaf))
        # compiled programs per (segment, input-capacity key)
        self._programs: List[Dict[tuple, CompiledPlan]] = \
            [{} for _ in range(len(self.seams) + 1)]

    def _segment(self, i: int, key: tuple, ctx) -> CompiledPlan:
        progs = self._programs[i]
        plan = progs.get(key)
        if plan is None:
            seg_root = self.seams[i] if i < len(self.seams) else self.root
            plan = CompiledPlan(seg_root, ctx.conf)
            progs[key] = plan
        return plan

    @staticmethod
    def _shrink(outs: List[DeviceBatch], ctx) -> List[DeviceBatch]:
        sliced = []
        for db in outs:
            if db.sel is not None or db.thin is not None:
                # lazy-join seam output: the seam re-buckets anyway, so
                # materialize the selection vector / deferred lanes here
                from ..ops.batch_ops import ensure_prefix
                db = ensure_prefix(db, ctx.conf)
            if any(c.offsets is not None for c in db.columns):
                raise _SplitUnsupported()   # ragged seam output
            n = db.num_rows if isinstance(db.num_rows, int) \
                else int(db.num_rows)       # ONE host sync per batch
            cap = min(bucket_capacity(max(n, 1), ctx.conf), db.capacity)
            # num_rows stays a device scalar so segment traces are keyed
            # on the CAPACITY BUCKET only — a drifting row count
            # (growing table, streaming appends) reuses compiled
            # programs instead of recompiling per exact count
            sliced.append(_slice_batch(db, cap, jnp.int32(n)))
        return sliced

    def collect(self, ctx: ExecContext) -> pa.Table:
        mutated = []
        try:
            key: tuple = ()
            for i, (leaf, (parent, ci)) in enumerate(
                    zip(self.leaves, self._parent_idx)):
                seg = self._segment(i, key, ctx)
                outs = seg.execute(ctx)
                sliced = self._shrink(outs, ctx)
                leaf.batches = sliced
                parent.children[ci] = leaf
                mutated.append((parent, ci, self.seams[i]))
                key = tuple(db.capacity for db in sliced)
            out = self._segment(len(self.seams), key, ctx).collect(ctx)
        finally:
            for parent, ci, orig in mutated:
                parent.children[ci] = orig
        ctx.bump("whole_plan_split_queries")
        return out


class _SplitUnsupported(Exception):
    pass


def session_mesh(conf: TpuConf):
    """The SPMD execution mesh for this conf, or None (disabled /
    single device)."""
    from ..config import MESH_DEVICES, MESH_ENABLED
    if not conf.get(MESH_ENABLED):
        return None
    n = conf.get(MESH_DEVICES) or len(jax.devices())
    if n < 2 or len(jax.devices()) < n:
        return None
    from ..parallel.mesh import make_mesh
    return make_mesh(n)


def collect_with_fallback(root: PlanNode, ctx: ExecContext,
                          cache_on: Optional[object] = None
                          ) -> Optional[pa.Table]:
    """Try the whole-plan compiled path; None means 'use the eager engine'
    (host-decision plan, or device OOM — the eager engine has the OOC
    machinery)."""
    holder = cache_on if cache_on is not None else root
    plan = getattr(holder, "_compiled_plan", None)
    if plan is False:                    # previously failed to trace
        return None
    if plan is None:
        mesh = session_mesh(ctx.conf)
        seams = [] if mesh is not None \
            else _find_split_seams(root, ctx.conf)
        plan = SplitCompiledPlan(root, seams, ctx.conf) if seams \
            else CompiledPlan(root, ctx.conf, mesh=mesh)
    try:
        out = plan.collect(ctx)
    except _SplitUnsupported:
        # e.g. ragged aggregate output: retry as one program, with the
        # same fallback ladder (trace errors AND device OOM -> eager)
        plan = CompiledPlan(root, ctx.conf)
        try:
            out = plan.collect(ctx)
        except _TRACE_FALLBACK_ERRORS:
            holder._compiled_plan = False
            ctx.bump("whole_plan_fallbacks")
            return None
        except Exception as e:           # noqa: BLE001
            from ..runtime.memory import is_oom_error
            ctx.bump("whole_plan_fallbacks")
            if is_oom_error(e):
                # transient device OOM: run eager THIS time, but keep the
                # compiled path eligible — memory pressure passes, a
                # trace error never does
                return None
            holder._compiled_plan = False
            raise
        holder._compiled_plan = plan
        ctx.bump("whole_plan_compiled_queries")
        return out
    except _TRACE_FALLBACK_ERRORS as e:
        holder._compiled_plan = False
        ctx.bump("whole_plan_fallbacks")
        ctx.tracer.instant("whole_plan_fallback", "runtime",
                           reason=type(e).__name__)
        return None
    except Exception as e:               # noqa: BLE001
        from ..runtime.memory import is_oom_error
        ctx.bump("whole_plan_fallbacks")
        if is_oom_error(e):
            ctx.tracer.instant("whole_plan_fallback", "runtime",
                               reason="device_oom")
            return None                  # eager engine has spill/retry;
                                         # compiled stays eligible
        holder._compiled_plan = False
        raise
    holder._compiled_plan = plan
    ctx.bump("whole_plan_compiled_queries")
    return out
