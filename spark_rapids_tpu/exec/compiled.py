"""Whole-plan XLA compilation: one jit program per query.

The reference dispatches one cuDF kernel launch per operator step; launch
latency is ~free on a locally attached GPU.  On TPU the idiomatic shape
is the opposite: **trace the entire physical plan once and hand XLA a
single program** — operators fuse (filter masks into projections into
segment-reductions), intermediate lanes never round-trip through HBM
twice, and a warm query is ONE dispatch + ONE result fetch regardless of
plan depth.  This is the "cudf AST compiled expressions" idea
(GpuExpressions.scala convertToAst / ast.CompiledExpression) taken to its
XLA-native conclusion: tracing IS the AST, for the whole plan rather than
one expression.

How it works:
  * Leaf `HostScanExec`s upload their batches once (cached on the node —
    the buffer-cache / spill-framework role for hot inputs).
  * `jax.jit(run)` traces `root.execute(ctx)` — the ordinary operator
    generators — over placeholder arrays standing in for every leaf lane.
    All sync-free paths (probe-aligned joins, lazy filters/limits,
    segment aggregations, single-batch sorts) trace cleanly because they
    never coerce a device value on host.
  * Output batch *structure* (schema, capacities, dictionaries) is
    recorded at trace time; the compiled call returns flat lanes that are
    re-wrapped as DeviceBatches / fetched in one `jax.device_get`.
  * Anything that genuinely needs a host decision (sized join expansion,
    out-of-core sort, retry machinery) raises a tracer-concretization
    error — the caller falls back to the eager batch-at-a-time engine,
    which remains the out-of-core/general path.

Compile cost is paid once per (plan shape, input bucket) and is
persisted by jax's compilation cache; warm latency is what the
benchmark measures (BASELINE.md).
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import pyarrow as pa

from .. import types as t
from ..columnar.device import (DeviceBatch, DeviceColumn, bucket_capacity,
                               to_device)
from ..config import TpuConf
from .plan import ExecContext, HostScanExec, PlanNode


_DISPATCH_FLOOR: Dict[str, float] = {}
_DISPATCH_FLOOR_LOCK = threading.Lock()


def dispatch_floor_ms(backend: Optional[str] = None) -> float:
    """Measured per-backend floor of one compiled-program dispatch, in ms.

    Times a trivially small pre-compiled program (warm, synced) and keeps
    the best of a few repeats — everything below this floor is runtime
    plumbing (argument flattening, executable call, stream sync), not
    compute, so it is the irreducible per-dispatch tax the overhead
    attribution plane charges to the `dispatch` category.  Cached per
    backend for the process lifetime; the microbenchmark itself costs a
    few ms once, so it only runs lazily from profiled paths."""
    import time as _time
    b = backend or jax.default_backend()
    v = _DISPATCH_FLOOR.get(b)
    if v is not None:
        return v
    with _DISPATCH_FLOOR_LOCK:
        v = _DISPATCH_FLOOR.get(b)
        if v is not None:
            return v
        fn = jax.jit(lambda x: x + 1)
        x = jnp.zeros(8, jnp.int32)
        jax.block_until_ready(fn(x))          # compile outside the timing
        best = float("inf")
        for _ in range(5):
            t0 = _time.perf_counter()
            jax.block_until_ready(fn(x))
            best = min(best, _time.perf_counter() - t0)
        v = _DISPATCH_FLOOR[b] = best * 1e3
    return v


def _find_scans(root: PlanNode) -> List[PlanNode]:
    """Leaves whose batches become jit inputs: host scans (uploaded) and
    device-resident split seams (already on device)."""
    out = []
    seen = set()

    def walk(n: PlanNode):
        if id(n) in seen:
            return
        seen.add(id(n))
        if isinstance(n, (HostScanExec, DeviceResidentScanExec)):
            out.append(n)
        for c in n.children:
            walk(c)
    walk(root)
    return out


def _flatten_batch(db: DeviceBatch):
    """-> (arrays, spec) where spec rebuilds the batch from arrays."""
    arrays = []
    cols = []
    for c in db.columns:
        arrays.append(c.data)
        arrays.append(c.validity)
        if c.data_hi is not None:
            arrays.append(c.data_hi)
        if c.offsets is not None:              # ragged ARRAY lanes
            arrays.append(c.offsets)
            arrays.append(c.elem_valid)
        cols.append((c.dtype, c.dictionary, c.data_hi is not None,
                     c.offsets is not None))
    static_rows = db.num_rows if isinstance(db.num_rows, int) else None
    if static_rows is None:
        arrays.append(db.num_rows)
    # a lazy selection vector is part of the batch's liveness: dropping
    # it across a program boundary would turn sel-liveness into (wrong)
    # prefix-liveness
    has_sel = db.sel is not None
    if has_sel:
        arrays.append(db.sel)
    return arrays, (cols, list(db.names), static_rows, db.origin_file,
                    has_sel)


def _rebuild_batch(arrays, spec, i: int) -> Tuple[DeviceBatch, int]:
    cols_spec, names, static_rows, origin, has_sel = spec
    cols = []
    for dtype, dictionary, has_hi, has_off in cols_spec:
        data = arrays[i]
        valid = arrays[i + 1]
        i += 2
        hi = offsets = elem_valid = None
        if has_hi:
            hi = arrays[i]
            i += 1
        if has_off:
            offsets = arrays[i]
            elem_valid = arrays[i + 1]
            i += 2
        cols.append(DeviceColumn(data, valid, dtype, dictionary, hi,
                                 offsets=offsets, elem_valid=elem_valid))
    if static_rows is None:
        num_rows = arrays[i]
        i += 1
    else:
        num_rows = static_rows
    sel = None
    if has_sel:
        sel = arrays[i]
        i += 1
    return DeviceBatch(cols, num_rows, names, origin, sel=sel), i


def _shard_batch(db: DeviceBatch, mesh) -> DeviceBatch:
    """Place a batch's lanes row-sharded over the mesh (replicated when
    the capacity doesn't divide the mesh)."""
    from jax.sharding import NamedSharding, PartitionSpec
    from ..parallel.mesh import SHARD_AXIS
    n = mesh.devices.size
    spec = PartitionSpec(SHARD_AXIS) if db.capacity % n == 0 \
        else PartitionSpec()
    sh = NamedSharding(mesh, spec)
    rep = NamedSharding(mesh, PartitionSpec())
    cols = []
    for c in db.columns:
        if c.offsets is not None:
            # ragged columns: offsets (rows+1) and value lanes don't fit
            # the row sharding — replicate; GSPMD still partitions the
            # flat columns around them
            cols.append(DeviceColumn(
                jax.device_put(c.data, rep),
                jax.device_put(c.validity, rep),
                c.dtype, c.dictionary, None,
                offsets=jax.device_put(c.offsets, rep),
                elem_valid=jax.device_put(c.elem_valid, rep)))
            continue
        cols.append(DeviceColumn(
            jax.device_put(c.data, sh),
            jax.device_put(c.validity, sh),
            c.dtype, c.dictionary,
            None if c.data_hi is None
            else jax.device_put(c.data_hi, sh)))
    return DeviceBatch(cols, db.num_rows, db.names, db.origin_file)


#: key -> (weakref(table), device batches, nbytes); insertion order IS
#: the LRU order (hits re-insert).  Byte-capped: long multi-table
#: sessions evict cold uploads instead of pinning device memory per
#: table forever (tpu_scan_upload_evictions_total counts evictions).
_SCAN_UPLOAD_CACHE: Dict[object, tuple] = {}
_SCAN_UPLOAD_LOCK = threading.Lock()


def _shared_scan_upload(node: HostScanExec, conf: TpuConf
                        ) -> List[DeviceBatch]:
    """Upload a scan's batches once PER SOURCE TABLE (not per plan): every
    re-planned query over the same pyarrow table shares one device copy —
    the buffer-cache role for hot inputs (reference FileCache /
    spill-framework device tier).  Weakref-keyed so device memory is
    released with the table; LRU byte-capped by
    spark.rapids.tpu.sql.scan.uploadCacheBytes."""
    import weakref
    from ..config import SCAN_UPLOAD_CACHE_BYTES
    cap_bytes = conf.get(SCAN_UPLOAD_CACHE_BYTES)
    tbl = node._source_table
    enc_cols = getattr(node, "encoded_cols", None)
    if tbl is None or cap_bytes == 0:
        return [to_device(hb, conf, encoded_cols=enc_cols)
                for hb in node.batches]
    # the encoded-upload form (sorted dictionaries, FOR-narrowed lanes —
    # ops/encodings.py) changes lane dtypes and dictionary order: plans
    # negotiated differently must never share a device copy
    from ..ops.encodings import encoding_discriminant
    key = (id(tbl), conf.batch_size_rows, encoding_discriminant(conf),
           None if enc_cols is None else tuple(sorted(enc_cols)))
    with _SCAN_UPLOAD_LOCK:
        hit = _SCAN_UPLOAD_CACHE.pop(key, None)
        if hit is not None and hit[0]() is tbl:
            _SCAN_UPLOAD_CACHE[key] = hit          # re-insert: now MRU
            return hit[1]
    dbs = [to_device(hb, conf, encoded_cols=enc_cols)
           for hb in node.batches]
    try:
        ref = weakref.ref(tbl, lambda _r, k=key:
                          _SCAN_UPLOAD_CACHE.pop(k, None))
    except TypeError:
        return dbs
    nbytes = sum(db.nbytes() for db in dbs)
    with _SCAN_UPLOAD_LOCK:
        _SCAN_UPLOAD_CACHE[key] = (ref, dbs, nbytes)
        total = sum(e[2] for e in _SCAN_UPLOAD_CACHE.values())
        while total > cap_bytes and len(_SCAN_UPLOAD_CACHE) > 1:
            _k = next(iter(_SCAN_UPLOAD_CACHE))
            if _k == key:                          # never evict the new entry
                break
            total -= _SCAN_UPLOAD_CACHE.pop(_k)[2]
            from ..obs.registry import SCAN_UPLOAD_EVICTIONS
            SCAN_UPLOAD_EVICTIONS.inc()
    return dbs


# ---------------------------------------------------------------------------
# Constant-lifted canonical plan keys + the process-wide executable cache
# ---------------------------------------------------------------------------
# Two queries that differ only in literals (dashboard traffic, bench
# reruns, parameterized filters) trace byte-identical programs once the
# literal values are runtime arguments.  `plan_cache_key` canonicalizes
# the whole physical plan — node structure + canonical expression
# fingerprints (lifted literal values erased) + the flattened input
# signature + the session conf — and `_PLAN_EXEC_CACHE` maps that key to
# the compiled XLA executable, its output specs and the trace-time host
# metrics.  Identity anchors (source tables, input dictionaries) guard
# the host data the traced program baked in: a hit requires the SAME
# objects, so a structurally identical plan over different tables never
# reuses another table's dictionaries.

def _canon_fp(e) -> str:
    fp = e.__dict__.get("_canon_fp_cache")
    if fp is None:
        fp = e.canonical_fingerprint()
        e.__dict__["_canon_fp_cache"] = fp
    return fp


def _collect_lits(e, lift_ok: bool, out: list) -> None:
    """Preorder liftable-literal collection mirroring BOTH the canonical
    fingerprint and Literal._prepare's lift decision — slot order is the
    contract between the cache key and the runtime argument vector."""
    from ..plan.expressions import Literal
    if isinstance(e, Literal):
        if lift_ok and e.lift_type_ok():
            out.append(e)
        return
    child_ok = type(e).lifts_literal_children
    for c in e.children:
        _collect_lits(c, child_ok, out)


def _node_exprs(node) -> Optional[list]:
    """The bound expression trees a physical node evaluates VERBATIM
    (projection lists, filter predicates, aggregate/join key lanes) in a
    deterministic order — the trees whose canonical fingerprints may
    erase lifted literal values.  Aggregate INPUT expressions are not
    here: the aggregate machinery evaluates derived wrappings of them,
    so their literals stay value-keyed (_node_extras).  None marks a
    node class the canonical key does not understand (its plans keep
    per-holder caching only)."""
    from .adaptive import AdaptiveShuffledJoinExec
    from .collect import CollectAggregateExec
    from .distinct import DistinctAggregateExec
    from .exchange import BroadcastExchangeExec
    from .join import CrossJoinExec, HashJoinExec
    from .percentile import PercentileAggregateExec
    from .plan import (CoalesceBatchesExec, ExpandExec, FilterExec,
                       GlobalLimitExec, HashAggregateExec, LocalLimitExec,
                       ProjectExec, RangeExec, SampleExec, SortExec,
                       TopNExec, UnionExec)
    if isinstance(node, ProjectExec):
        return list(node.exprs)
    if isinstance(node, FilterExec):
        return [node.condition]
    if isinstance(node, (HashAggregateExec, CollectAggregateExec,
                         DistinctAggregateExec, PercentileAggregateExec)):
        return list(getattr(node, "key_exprs", ()) or ())
    if isinstance(node, (HashJoinExec, AdaptiveShuffledJoinExec)):
        return (list(node.left_keys) + list(node.right_keys)
                + list(getattr(node, "probe_conds", None) or ())
                + list(getattr(node, "build_conds", None) or ()))
    if isinstance(node, ExpandExec):
        return [e for p in node.projections for e in p]
    if isinstance(node, (HostScanExec, DeviceResidentScanExec, SortExec,
                         TopNExec, GlobalLimitExec, LocalLimitExec,
                         UnionExec, CoalesceBatchesExec, RangeExec,
                         SampleExec, CrossJoinExec, BroadcastExchangeExec)):
        return []
    return None


def _node_extras(node) -> tuple:
    """Non-expression structure that changes the traced program."""
    from .plan import (CoalesceBatchesExec, GlobalLimitExec,
                       LocalLimitExec, RangeExec, SampleExec, SortExec,
                       TopNExec)
    extras: list = []
    if isinstance(node, (SortExec, TopNExec)):
        extras.append(tuple(node.keys))
        extras.append(getattr(node, "global_sort", None))
        extras.append(getattr(node, "limit", None))
    if isinstance(node, (GlobalLimitExec, LocalLimitExec)):
        extras.append(node.limit)
    if isinstance(node, CoalesceBatchesExec):
        extras.append((node.target_rows,
                       getattr(node, "require_single", None)))
    if isinstance(node, RangeExec):
        extras.append((node.start, node.end, node.step, node.col_name,
                       node.batch_rows))
    if isinstance(node, SampleExec):
        extras.append((node.fraction, node.seed))
    jt = getattr(node, "join_type", None)
    if jt is not None:
        extras.append(("join", jt, getattr(node, "lazy_sel", None),
                       getattr(node, "thin_payload", None)))
    names = getattr(node, "names", None) or getattr(node, "key_names", None)
    if names is not None:
        extras.append(tuple(names))
    # aggregate functions: class + output name + every non-expression
    # parameter (ignore_nulls, percentage, ...) + FULL fingerprints of
    # the input trees — agg inputs are evaluated through derived
    # wrappings, so their literals stay value-keyed (never erased)
    from ..plan.expressions import Expression as _Expr
    agg_sig = []
    for fn, name in getattr(node, "aggs", ()) or ():
        params = tuple(sorted(
            (k, repr(v)) for k, v in fn.__dict__.items()
            if k != "_shims" and not isinstance(v, _Expr)))
        kids = tuple(c.fingerprint()
                     for c in (getattr(fn, "child", None),
                               getattr(fn, "child2", None))
                     if c is not None)
        agg_sig.append((type(fn).__name__, name, params, kids))
    if agg_sig:
        extras.append(tuple(agg_sig))
    return tuple(extras)


def collect_plan_literals(root: PlanNode) -> Optional[List[object]]:
    """Every liftable Literal of a physical plan in canonical preorder,
    or None when the plan contains a node class the canonical key does
    not cover (those plans skip the process-wide cache)."""
    out: list = []
    seen = set()

    def walk(node):
        if id(node) in seen:
            return True
        seen.add(id(node))
        exprs = _node_exprs(node)
        if exprs is None:
            return False
        for e in exprs:
            _collect_lits(e, True, out)
        return all(walk(c) for c in node.children)

    return out if walk(root) else None


def plan_structure_key(root: PlanNode, conf: TpuConf) -> Optional[tuple]:
    """Canonical structural key of a device plan (literal values erased
    for lifted positions), or None for uncovered plans."""
    parts: list = []
    seen: dict = {}

    def walk(node):
        if id(node) in seen:
            # shared subtree (a broadcast build reused twice): mark the
            # revisit positionally instead of re-walking it
            parts.append(("shared", seen[id(node)]))
            return True
        seen[id(node)] = len(seen)
        exprs = _node_exprs(node)
        if exprs is None:
            return False
        parts.append((type(node).__name__,
                      tuple(_canon_fp(e) for e in exprs),
                      _node_extras(node),
                      len(node.children)))
        return all(walk(c) for c in node.children)

    if not walk(root):
        return None
    conf_sig = tuple(sorted((k, str(v)) for k, v in conf._raw.items()))
    # kernel-tier discriminant: the RESOLVED Pallas tier (which depends
    # on backend AUTO rules, not just the raw conf strings already in
    # conf_sig) keys the executable, so cached programs compiled with
    # hand-written kernels can never cross-load into a sort-tier
    # session or vice versa (ops/pallas.tier_discriminant; None when
    # the tier is fully off)
    from ..ops.encodings import encoding_discriminant
    from ..ops.pallas import tier_discriminant
    # encoded-execution discriminant mirrors the kernel tier's: the
    # RESOLVED policy (AUTO rules included) keys the executable so
    # encoded-representation programs never cross-load into a decoded
    # session or vice versa; None when fully off keeps the key
    # byte-identical to pre-encoding builds
    enc = encoding_discriminant(conf)
    if enc is None:
        return (tuple(parts), conf_sig, jax.default_backend(),
                tier_discriminant(conf))
    return (tuple(parts), conf_sig, jax.default_backend(),
            tier_discriminant(conf), enc)


def _plan_anchors(root: PlanNode, pairs) -> Optional[list]:
    """Host objects the traced program specializes on: scan source
    tables and every input dictionary.  Returned as weakrefs paired with
    the live object id; a cache hit must present the SAME objects."""
    import weakref
    anchors = []
    objs = []
    for node, dbs in pairs:
        if isinstance(node, HostScanExec) and node._source_table is not None:
            objs.append(node._source_table)
        for db in dbs:
            for c in db.columns:
                if c.dictionary is not None:
                    objs.append(c.dictionary)
    try:
        for o in objs:
            anchors.append(weakref.ref(o))
    except TypeError:
        return None               # un-weakref-able anchor: don't cache
    return anchors


def _anchors_match(anchors, root: PlanNode, pairs) -> bool:
    cur = _plan_anchors(root, pairs)
    if cur is None or len(cur) != len(anchors):
        return False
    return all(a() is c() and a() is not None
               for a, c in zip(anchors, cur))


#: canonical plan key -> (compiled executable, out_specs, out layout,
#: host metrics, static cost, anchors).  Name ends in _CACHE so
#: testing.clear_compiled_caches() releases the pinned executables with
#: everything else.
_PLAN_EXEC_CACHE: Dict[tuple, tuple] = {}
_PLAN_EXEC_LOCK = threading.Lock()


def _plan_cache_get(key, root, pairs):
    with _PLAN_EXEC_LOCK:
        entry = _PLAN_EXEC_CACHE.pop(key, None)
        if entry is not None:
            _PLAN_EXEC_CACHE[key] = entry          # MRU
    if entry is None:
        return None
    if not _anchors_match(entry[-1], root, pairs):
        return None
    return entry


def _compiled_cost(compiled) -> Dict[str, float]:
    """Static XLA cost surface of one compiled executable: FLOPs and
    bytes accessed from `cost_analysis()`, peak temp / output /
    argument bytes from `memory_analysis()`.  Best-effort — backends
    and jax versions that expose neither yield {} rather than failing
    the compile path."""
    out: Dict[str, float] = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            if ca.get("flops"):
                out["flops"] = float(ca["flops"])
            if ca.get("bytes accessed"):
                out["bytes_accessed"] = float(ca["bytes accessed"])
    except Exception:                    # noqa: BLE001
        pass
    try:
        ma = compiled.memory_analysis()
        for attr, name in (("temp_size_in_bytes", "peak_temp_bytes"),
                           ("output_size_in_bytes", "output_bytes"),
                           ("argument_size_in_bytes", "argument_bytes"),
                           ("generated_code_size_in_bytes",
                            "generated_code_bytes")):
            v = getattr(ma, attr, None)
            if v:
                out[name] = float(v)
    except Exception:                    # noqa: BLE001
        pass
    return out


def analysis_hbm_bytes(cost: Optional[Dict[str, float]]) -> int:
    """The XLA memory_analysis() working set of one compiled program:
    arguments + output + temp scratch + generated code — what the
    program itself holds in HBM while it runs (0 when the backend
    exposed no analysis)."""
    c = cost or {}
    return int(sum(c.get(k) or 0.0
                   for k in ("argument_bytes", "output_bytes",
                             "peak_temp_bytes", "generated_code_bytes")))


def _plan_cache_put(key, entry: tuple, conf: TpuConf) -> None:
    from ..config import PLAN_CACHE_ENTRIES
    bound = conf.get(PLAN_CACHE_ENTRIES)
    with _PLAN_EXEC_LOCK:
        _PLAN_EXEC_CACHE[key] = entry
        while len(_PLAN_EXEC_CACHE) > bound:
            _PLAN_EXEC_CACHE.pop(next(iter(_PLAN_EXEC_CACHE)))


class CompiledPlan:
    """A traced-and-jitted device plan bound to its leaf scans.

    With `mesh`, leaf lanes are placed row-sharded over the mesh axis and
    the SAME whole-plan program runs SPMD: XLA's GSPMD partitioner keeps
    scans/filters/projections data-parallel per chip and inserts the
    cross-chip collectives (all-to-all/all-gather/psum over ICI) where
    sorts, group-bys and joins need global views — the
    annotate-shardings-and-let-XLA-insert-collectives recipe, playing the
    reference's shuffle-exchange fabric role (RapidsShuffleManager/UCX)."""

    def __init__(self, root: PlanNode, conf: TpuConf, mesh=None,
                 leaf_overrides: Optional[Dict[int, list]] = None):
        self.root = root
        self.conf = conf
        self.mesh = mesh
        self._out_specs: Optional[list] = None
        self._compiled = None
        self._input_specs = None
        self._out_layout = None        # [(shape, dtype str)] of flat outputs
        self._host_metrics: Dict[str, object] = {}
        #: static XLA cost surface (flops / bytes accessed / peak temp)
        #: captured at compile time for the attribution plane
        self._cost: Dict[str, float] = {}
        # background speculative compiles trace over PLACEHOLDER batches
        # (id(leaf) -> batches of ShapeDtypeStruct lanes) without touching
        # the shared plan tree; cleared after compile so execution reads
        # the real leaf state
        self._leaf_overrides = dict(leaf_overrides or {})
        from ..config import COMPILE_CONST_LIFT
        self._lift = bool(conf.get(COMPILE_CONST_LIFT))
        self._literals = (collect_plan_literals(root) or []) \
            if self._lift else []
        self._cache_key = None         # lazily built at first compile
        self._fresh = False            # compiled/adopted THIS collect

    # -- leaves ------------------------------------------------------------
    def _leaf_batches(self, ctx: ExecContext
                      ) -> List[Tuple[HostScanExec, List[DeviceBatch]]]:
        pairs = []
        for node in _find_scans(self.root):
            override = self._leaf_overrides.get(id(node))
            if override is not None:
                pairs.append((node, override))
                continue
            if isinstance(node, DeviceResidentScanExec):
                pairs.append((node, node.batches))   # already on device
                continue
            cached = getattr(node, "_device_cache", None)
            if cached is None:
                from ..runtime.retry import retry_io
                with ctx.tracer.span("upload", "transition"):
                    cached = retry_io(
                        ctx.conf, "h2d",
                        lambda: _shared_scan_upload(node, ctx.conf))
                    if self.mesh is not None:
                        cached = [_shard_batch(db, self.mesh)
                                  for db in cached]
                ctx.tracer.add_bytes(
                    "h2d_bytes", sum(hb.rb.nbytes for hb in node.batches))
                node._device_cache = cached
            pairs.append((node, cached))
        return pairs

    def _lift_values(self) -> list:
        """The lifted literal values as 0-d device scalars, in canonical
        slot order — the runtime-argument tail of the flat input vector."""
        import numpy as np
        from ..ops.kernels import compute_dtype
        return [jnp.asarray(np.asarray(l._physical_value(),
                                       dtype=compute_dtype(l.dtype)))
                for l in self._literals]

    def _flatten_inputs(self, pairs):
        flat_in: List[jax.Array] = []
        in_specs = []
        for node, dbs in pairs:
            node_specs = []
            for db in dbs:
                arrays, spec = _flatten_batch(db)
                flat_in.extend(arrays)
                node_specs.append(spec)
            in_specs.append((node, node_specs))
        # constant lifting: literal values ride as the flat tail, so the
        # compiled program (and its cache key) is literal-value-agnostic
        flat_in.extend(self._lift_values())
        return flat_in, in_specs

    def _make_runner(self, in_specs, ctx: ExecContext,
                     out_holder: Dict[str, list]):
        """The traced whole-plan function over flattened leaf lanes."""
        lit_ids = [id(l) for l in self._literals]

        def run(flat):
            from ..plan.expressions import set_literal_bindings
            base = len(flat) - len(lit_ids)
            if lit_ids:
                # Literal._prepare hands these traced scalars into the
                # aux channel — inner-program ARGUMENTS, so the lifted
                # values never bake into the XLA program as constants
                set_literal_bindings(
                    {lid: flat[base + k] for k, lid in enumerate(lit_ids)})
            # rebuild leaf batches from traced arrays and install them
            i = 0
            for node, node_specs in in_specs:
                batches = []
                for spec in node_specs:
                    db, i = _rebuild_batch(flat, spec, i)
                    batches.append(db)
                node._trace_batches = batches
            trace_ctx = _trace_context(ctx)
            try:
                outs = list(self.root.execute(trace_ctx))
            finally:
                if lit_ids:
                    set_literal_bindings(None)
                for node, _ in in_specs:
                    node._trace_batches = None
                # copy ONLY host numbers back: a traced metric value
                # escaping the jit would be a leaked tracer
                host_metrics = {k: v for k, v in trace_ctx.metrics.items()
                                if isinstance(v, (int, float))}
                out_holder["host_metrics"] = host_metrics
                ctx.metrics.update(host_metrics)
            flat_out = []
            specs = []
            for db in outs:
                if db.thin is not None:
                    # the program boundary is a pipeline SINK: resolve
                    # deferred columns INSIDE the traced program (the
                    # composed gathers fuse into the whole-plan XLA
                    # program; the flat output layer carries no lanes)
                    from ..columnar.lanes import materialize_batch
                    db = materialize_batch(db, ctx.conf)
                arrays, spec = _flatten_batch(db)
                flat_out.extend(arrays)
                specs.append(spec)
            out_holder["specs"] = specs
            out_holder["layout"] = [(tuple(x.shape), str(x.dtype))
                                    for x in flat_out]
            return flat_out
        return run

    def make_jaxpr(self, ctx: ExecContext):
        """Abstract-trace the whole-plan program and return its
        ClosedJaxpr — no compile, no execution.  Powers the suite-wide
        sort-operand lint (testing.py) and bench.py's per-query
        `sort_operand_max` / `scatter_op_count` metrics.  Raises the
        same tracer errors as execute() for host-decision plans."""
        pairs = self._leaf_batches(ctx)
        flat_in, in_specs = self._flatten_inputs(pairs)
        holder: Dict[str, list] = {}
        return jax.make_jaxpr(self._make_runner(in_specs, ctx, holder))(
            flat_in)

    # -- compile + run -----------------------------------------------------
    def _build_cache_key(self, flat_in, in_specs) -> Optional[tuple]:
        """Canonical process-wide cache key, or None when this plan is
        outside the cacheable envelope (mesh SPMD, uncovered node class,
        lifting off)."""
        if not self._lift or self.mesh is not None:
            return None
        skey = plan_structure_key(self.root, self.conf)
        if skey is None:
            return None
        spec_sig = []
        for node, node_specs in in_specs:
            per = []
            for cols, names, static_rows, origin, has_sel in node_specs:
                per.append((tuple((dt.simple_string, d is not None, hi, off)
                                  for dt, d, hi, off in cols),
                            tuple(names), static_rows, origin, has_sel))
            spec_sig.append((type(node).__name__, tuple(per)))
        input_sig = tuple((tuple(a.shape), str(a.dtype)) for a in flat_in)
        return (skey, tuple(spec_sig), input_sig)

    def _try_plan_cache(self, ctx: ExecContext, pairs, flat_in,
                        in_specs) -> bool:
        """Adopt a process-cached executable compiled from a canonically
        identical plan over the SAME host objects (tables/dictionaries).
        The python trace never re-runs: lifted literal values arrive
        through the flat argument tail."""
        self._cache_key = self._build_cache_key(flat_in, in_specs)
        if self._cache_key is None:
            return False
        entry = _plan_cache_get(self._cache_key, self.root, pairs)
        if entry is None:
            return False
        (self._compiled, self._out_specs, self._out_layout,
         self._host_metrics, self._cost, _anchors) = entry
        self._input_specs = [(n, list(s)) for n, s in in_specs]
        ctx.metrics.update(self._host_metrics)
        ctx.bump("compile_cache_hits")
        ctx.bump("whole_plan_structure_hits")
        from ..obs.registry import PLAN_CACHE
        PLAN_CACHE.inc(outcome="hit")
        self._fresh = True
        return True

    def aot_compile(self, ctx: ExecContext, flat_in=None, in_specs=None,
                    pairs=None) -> None:
        """Trace + AOT-compile the whole-plan program (no execution:
        jax.jit(...).lower().compile(), so placeholder-shape inputs work
        and the persistent cache serves cold starts).  Fires the
        `compile` chaos site; raises tracer errors for host-decision
        plans exactly as execute() used to."""
        import time as _time
        from ..runtime.faults import get_injector
        if flat_in is None:
            pairs = self._leaf_batches(ctx)
            flat_in, in_specs = self._flatten_inputs(pairs)
        # chaos site: a whole-plan compile failure — injected `oom`
        # exercises the eager-engine fallback, `fatal` the crash
        # capture (collect_with_fallback owns both ladders); background
        # segment compiles fire here too, on the service thread
        get_injector(ctx.conf).fire("compile")
        self._input_specs = [(n, list(s)) for n, s in in_specs]
        out_holder: Dict[str, list] = {}
        t0 = _time.perf_counter()
        with ctx.tracer.span("trace+compile", "compile",
                             root=self.root.name()):
            lowered = jax.jit(self._make_runner(in_specs, ctx,
                                                out_holder)).lower(flat_in)
            compiled = lowered.compile()
        ctx.metrics["compile_ms"] = ctx.metrics.get(
            "compile_ms", 0.0) + (_time.perf_counter() - t0) * 1000.0
        ctx.bump("compile_cache_misses")
        self._out_specs = out_holder["specs"]
        self._out_layout = out_holder["layout"]
        self._host_metrics = out_holder.get("host_metrics", {})
        self._compiled = compiled
        from ..config import PROFILE_COST_ANALYSIS
        self._cost = _compiled_cost(compiled) \
            if self.conf.get(PROFILE_COST_ANALYSIS) else {}
        self._fresh = True
        # placeholder leaves only exist to shape the lowering; execution
        # must read the real leaf state installed by the caller
        self._leaf_overrides = {}
        if self._cache_key is None:
            self._cache_key = self._build_cache_key(flat_in, in_specs)
        if self._cache_key is not None and pairs is not None:
            anchors = _plan_anchors(self.root, pairs)
            if anchors is not None:
                from ..obs.registry import PLAN_CACHE
                PLAN_CACHE.inc(outcome="miss")
                _plan_cache_put(self._cache_key,
                                (compiled, self._out_specs,
                                 self._out_layout, self._host_metrics,
                                 self._cost, anchors), self.conf)

    def ensure_compiled(self, ctx: ExecContext) -> None:
        """Compile (or adopt a cached executable) without executing —
        the hook the split-plan pipeline uses to order 'compile, then
        speculate downstream, then execute'."""
        if self._compiled is not None:
            return
        pairs = self._leaf_batches(ctx)
        flat_in, in_specs = self._flatten_inputs(pairs)
        if not self._try_plan_cache(ctx, pairs, flat_in, in_specs):
            self.aot_compile(ctx, flat_in, in_specs, pairs)

    def execute(self, ctx: ExecContext) -> List[DeviceBatch]:
        """Run the whole plan as one XLA program; returns device batches.

        Raises jax tracer errors (ConcretizationTypeError & friends) when
        the plan needs host decisions — callers fall back to eager.

        With `spark.rapids.tpu.profile.segments` on, the dispatch blocks
        until the outputs are ready and the measured device wall is
        attributed to this program's plan-node-id range (the
        attribution plane — tracer `segment` span, tpu_segment_*
        registry families, segment.* query metrics)."""
        import time as _time
        from ..config import PROFILE_SEGMENTS
        pairs = self._leaf_batches(ctx)
        flat_in, in_specs = self._flatten_inputs(pairs)

        if self._compiled is None:
            if not self._try_plan_cache(ctx, pairs, flat_in, in_specs):
                self.aot_compile(ctx, flat_in, in_specs, pairs)
        elif not self._fresh:
            ctx.bump("compile_cache_hits")
        self._fresh = False

        prof = bool(ctx.conf.get(PROFILE_SEGMENTS))
        mrec = None
        if prof:
            # memory-attribution bracket (obs/memattr.py): census the
            # query's budget before the dispatch so the segment's
            # measured working set covers resident batches + this
            # program's own footprint.  The `memattr` chaos site fires
            # on the census read: an injected ioerror skips THIS
            # sample (query bit-identical), fatal propagates to crash
            # capture with the partial timeline embedded.
            mrec = getattr(ctx, "_memattr", None)
            if mrec is not None:
                from ..obs.memattr import budget_census
                from ..runtime.faults import get_injector
                nid = getattr(self.root, "_node_id", None)
                try:
                    get_injector(ctx.conf).fire(
                        "memattr", segment=nid or self.root.name())
                    mrec.open_segment(nid or type(self.root).__name__,
                                      budget_census(ctx)["live"])
                except OSError:
                    mrec.skipped += 1
                    ctx.bump("memattr_census_skipped")
                    mrec = None
        t0 = _time.perf_counter()
        with ctx.tracer.span("execute", "execute",
                             root=self.root.name()):
            try:
                flat_res = self._compiled(flat_in)
            except TypeError:
                # AOT signature drift (a speculative lowering's avals
                # not matching the real inputs): recompile inline once
                self._compiled = None
                self._cache_key = None
                self.aot_compile(ctx, flat_in, in_specs, pairs)
                flat_res = self._compiled(flat_in)
            if prof:
                # the sync that turns dispatch wall into DEVICE wall;
                # profiling-only — the default path stays async
                jax.block_until_ready(flat_res)
        t1 = _time.perf_counter()
        # always-on program-execution wall (device wall when profiling
        # syncs, the dispatch floor otherwise): the performance-history
        # plane's per-structure measured-cost feed (obs/history.py)
        m = ctx.metrics
        m["exec_device_ms"] = m.get("exec_device_ms", 0.0) \
            + (t1 - t0) * 1e3
        # always-on dispatch count: the overhead plane (and the history
        # feed) multiplies it by the measured per-backend dispatch floor
        # when no profiled decomposition exists for this run
        m["exec_dispatches"] = m.get("exec_dispatches", 0) + 1
        # always-on measured working-set floor: the largest XLA
        # memory_analysis() footprint this query dispatched (args +
        # output + temp + code, captured at compile time — no conf
        # check, no sync).  The history plane records it so admission
        # can serve a MEASURED working set instead of the source-bytes
        # heuristic (obs/history.py ws_bytes, obs/estimator.py)
        if self._cost:
            ws = analysis_hbm_bytes(self._cost)
            if ws > m.get("exec_hbm_bytes", 0):
                m["exec_hbm_bytes"] = ws

        outs = []
        i = 0
        for spec in self._out_specs:
            db, i = _rebuild_batch(flat_res, spec, i)
            outs.append(db)
        if prof:
            self._record_segment(ctx, t0, t1, outs, mrec, pairs)
        return outs

    def _record_segment(self, ctx: ExecContext, t0: float, t1: float,
                        outs: List[DeviceBatch], mrec=None,
                        pairs=None) -> None:
        """Attribute one measured program execution to its plan segment:
        the root node id + the preorder node-id range the program covers
        in the CURRENT tree (split-seam leaves excluded), output rows
        and bytes, the compile-time static cost overlay, and — when the
        memory-attribution bracket is open — the segment's measured
        HBM working set (XLA memory_analysis bytes vs the budget peak
        delta across the dispatch window, obs/memattr.py)."""
        from ..obs.registry import (SEGMENT_DEVICE_MS, SEGMENT_HBM_PEAK,
                                    SEGMENT_ROWS)
        from .metrics import node_id_range
        dev_ms = (t1 - t0) * 1e3
        nid = getattr(self.root, "_node_id", None)
        lo, hi = node_id_range(self.root)
        rows = 0
        out_bytes = 0
        for db in outs:
            try:
                rows += int(db.num_rows)     # already synced: prof path
            except Exception:                # noqa: BLE001
                pass
            try:
                out_bytes += int(db.nbytes())
            except Exception:                # noqa: BLE001
                pass
        cls = type(self.root).__name__
        SEGMENT_DEVICE_MS.observe(dev_ms, segment=cls)
        if rows:
            SEGMENT_ROWS.inc(rows, segment=cls)
        # overhead decomposition (profiled runs only): the measured
        # per-backend dispatch floor bounds the host launch tax inside
        # this program's wall, and padded-minus-live INPUT rows price the
        # bucket-quantization tax at this segment's own per-row device
        # cost.  Pad waste is a slice of device compute, not an additive
        # wall category — wall_breakdown() subtracts it back out.
        from ..obs.registry import PAD_ROWS, PAD_WASTE_MS
        floor = dispatch_floor_ms()
        disp_ms = min(floor, dev_ms)
        pad_rows = 0
        cap_rows = 0
        for _leaf, dbs in (pairs or ()):
            for db in dbs:
                cap = int(db.capacity)
                cap_rows += cap
                try:
                    live = int(db.num_rows)  # concrete post-sync scalar
                except Exception:            # noqa: BLE001
                    live = cap
                pad_rows += max(cap - min(live, cap), 0)
        pad_ms = (dev_ms - disp_ms) * (pad_rows / cap_rows) \
            if cap_rows else 0.0
        if pad_rows:
            PAD_ROWS.inc(pad_rows, site="segment")
            PAD_WASTE_MS.observe(pad_ms, segment=cls)
        key = nid or cls
        m = ctx.metrics
        m["overhead.dispatch_floor_ms"] = floor
        for field, v in (("device_ms", dev_ms), ("rows", rows),
                         ("out_bytes", out_bytes), ("executions", 1),
                         ("dispatch_ms", disp_ms), ("pad_rows", pad_rows),
                         ("pad_waste_ms", pad_ms)):
            mk = f"segment.{key}.{field}"
            m[mk] = m.get(mk, 0) + v
        for field, v in (("overhead.dispatch_ms", disp_ms),
                         ("overhead.pad_rows", pad_rows),
                         ("overhead.pad_waste_ms", pad_ms)):
            m[field] = m.get(field, 0) + v
        attrs = {"device_ms": round(dev_ms, 3), "rows": rows,
                 "out_bytes": out_bytes,
                 "dispatch_ms": round(disp_ms, 4), "pad_rows": pad_rows,
                 "pad_waste_ms": round(pad_ms, 4)}
        if lo is not None:
            attrs["node_lo"], attrs["node_hi"] = lo, hi
        for k in ("flops", "bytes_accessed", "peak_temp_bytes"):
            v = (self._cost or {}).get(k)
            if v:
                m[f"segment.{key}.{k}"] = v
                attrs[k] = v
        if mrec is not None:
            from ..obs.memattr import budget_census
            analysis = analysis_hbm_bytes(self._cost)
            hbm = mrec.close_segment(key, analysis,
                                     budget_census(ctx)["live"])
            SEGMENT_HBM_PEAK.observe(hbm["hbm_peak_bytes"], segment=cls)
            for field, v in (("hbm_bytes", analysis),
                             ("hbm_peak_bytes", hbm["hbm_peak_bytes"]),
                             ("hbm_resident_pre", hbm["resident_pre"])):
                mk = f"segment.{key}.{field}"
                if v > m.get(mk, 0):         # max, not sum: a repeated
                    m[mk] = v                # dispatch reuses its HBM
            attrs["hbm_bytes"] = analysis
            attrs["hbm_peak_bytes"] = hbm["hbm_peak_bytes"]
        ctx.tracer.add_span("segment", "execute", t0, t1, node=nid,
                            **attrs)

    def collect(self, ctx: ExecContext) -> pa.Table:
        import time as _time
        from ..columnar.device import fetch_result_batch
        from ..columnar.host import struct_to_schema
        from ..runtime.retry import retry_io
        # cancellation checkpoint before the program dispatches: a
        # deadline that expired in the queue cancels without paying for
        # the whole dispatch (single-program plans have no seams)
        ctx.checkpoint("program")
        outs = self.execute(ctx)
        bound = self.root.row_upper_bound()
        hbs = []
        for db in outs:
            ctx.checkpoint("fetch")
            t0 = _time.perf_counter()
            with ctx.tracer.span("fetch", "transition"):
                hb = retry_io(ctx.conf, "d2h",
                              lambda: fetch_result_batch(db, bound,
                                                         ctx.conf))
            ctx.metrics["overhead.fetch_ms"] = ctx.metrics.get(
                "overhead.fetch_ms", 0.0) \
                + (_time.perf_counter() - t0) * 1e3
            ctx.bump("d2h_rows", hb.num_rows)
            ctx.tracer.add_bytes("d2h_bytes", hb.rb.nbytes)
            hbs.append(hb)
        batches = [hb.rb for hb in hbs if hb.num_rows > 0]
        if not batches:
            return pa.Table.from_batches(
                [], struct_to_schema(self.root.output_schema))
        return pa.Table.from_batches(batches, batches[0].schema)


def _trace_context(ctx: ExecContext) -> ExecContext:
    """Execution context for use UNDER tracing: unlimited budget (XLA owns
    memory inside one program; spilling a tracer is meaningless), no
    runtime bloom filters (their sizing needs host row counts), and a
    PRIVATE metrics dict — device-scalar metrics recorded during tracing
    are tracers and must never escape the jit (host numbers are copied
    back by the caller)."""
    from ..config import (HBM_BUDGET_BYTES, RUNTIME_FILTER_ENABLED,
                          TEST_FAULTS, TEST_INJECT_RETRY_OOM)
    raw = dict(ctx.conf._raw)
    raw[HBM_BUDGET_BYTES.key] = 1 << 62
    raw[RUNTIME_FILTER_ENABLED.key] = False
    raw[TEST_INJECT_RETRY_OOM.key] = 0
    # fault injection under jit tracing would bake a synthetic failure
    # into the compiled program; chaos targets the runtime layers only
    raw[TEST_FAULTS.key] = ""
    return ExecContext(TpuConf(raw))


# errors that mean "this plan needs host decisions" — not bugs
_TRACE_FALLBACK_ERRORS = (
    jax.errors.ConcretizationTypeError,
    jax.errors.TracerArrayConversionError,
    jax.errors.TracerBoolConversionError,
    jax.errors.TracerIntegerConversionError,
    jax.errors.UnexpectedTracerError,
)


class DeviceResidentScanExec(PlanNode):
    """Leaf standing in for an already-computed subplan's device output
    (the split-plan seam).  Delegates plan statistics to the node it
    replaces, so downstream fast paths (unique-build joins, dense
    domains) survive the split."""

    def __init__(self, source: PlanNode):
        super().__init__()
        self._source = source
        self.batches: List[DeviceBatch] = []

    @property
    def output_schema(self):
        return self._source.output_schema

    def keys_unique(self, names):
        return self._source.keys_unique(names)

    def column_range(self, name):
        return self._source.column_range(name)

    def static_row_count(self):
        if len(self.batches) == 1 and \
                isinstance(self.batches[0].num_rows, int):
            return self.batches[0].num_rows
        return self._source.static_row_count()

    def execute(self, ctx: ExecContext):
        trace = getattr(self, "_trace_batches", None)
        yield from (trace if trace is not None else self.batches)

    def describe(self):
        return f"DeviceResidentScan[{self._source.describe()}]"


def _find_split_seams(root: PlanNode, conf=None) -> List[PlanNode]:
    """Innermost-first seam nodes where live row counts collapse but
    static bucket capacities do not:

      1. the input of the topmost aggregate (after its fused-filter
         chain) when it is real work (a join subtree, not a bare scan) —
         selective joins + fused filters typically leave a small
         fraction of the input bucket live;
      2. the topmost aggregate itself — millions of rows in, thousands
         of groups out.

    Each seam costs one host count sync and re-buckets everything above
    it to actual sizes."""
    from .plan import FilterExec, HashAggregateExec, HostScanExec

    def find_agg(n: PlanNode):
        for c in n.children:
            if isinstance(c, HashAggregateExec):
                return c
            found = find_agg(c)
            if found is not None:
                return found
        return None

    agg = None if isinstance(root, HashAggregateExec) else find_agg(root)
    if agg is None:
        return []
    # every seam costs one host count sync (a full tunnel RTT) and one
    # extra program dispatch; with sub-capacity inputs the padding the
    # seam would trim is worth less than the round trips (q11: 75 ms of
    # device work behind ~450 ms of seam/dispatch latency), so only
    # split when the subtree actually carries big buckets.  Profiling
    # (`profile.segments`) overrides the floor: the attribution plane
    # wants the SAME seam boundaries the split compiler knows at every
    # scale, so whole-plan programs re-split at profile time and join
    # subtrees / aggregates time as separate segments.
    from ..config import DEFAULT_CONF, PROFILE_SEGMENTS, SEAM_SPLIT_MIN_ROWS
    c = conf or DEFAULT_CONF
    if not c.get(PROFILE_SEGMENTS):
        min_rows = c.get(SEAM_SPLIT_MIN_ROWS)
        if _max_leaf_capacity(agg, conf) < min_rows:
            return []
    seams: List[PlanNode] = []
    source = agg.child
    while isinstance(source, FilterExec):
        source = source.child
    if not isinstance(source, (HostScanExec, DeviceResidentScanExec)):
        seams.append(source)
    seams.append(agg)
    return seams


def _max_leaf_capacity(root: PlanNode, conf=None) -> int:
    """Largest leaf-scan bucket under `root` (host batch row counts
    rounded to their buckets under the SESSION conf; device-resident
    seam leaves report their batch capacities)."""
    from ..config import DEFAULT_CONF
    conf = conf or DEFAULT_CONF
    best = 0
    for node in _find_scans(root):
        if isinstance(node, DeviceResidentScanExec):
            best = max(best, *(db.capacity for db in node.batches), 0)
            continue
        for hb in node.batches:
            best = max(best, bucket_capacity(max(hb.num_rows, 1), conf))
    return best


def _slice_batch(db: DeviceBatch, cap: int, n: int) -> DeviceBatch:
    """Narrow a live-prefix batch to a smaller capacity bucket."""
    cols = []
    for c in db.columns:
        cols.append(DeviceColumn(
            c.data[:cap], c.validity[:cap], c.dtype, c.dictionary,
            None if c.data_hi is None else c.data_hi[:cap]))
    return DeviceBatch(cols, n, db.names, db.origin_file)


def _swap_child(root: PlanNode, old: PlanNode, new: PlanNode):
    """EVERY (parent, index) link to `old` under `root`; caller mutates
    + restores.  Plan-level CSE (plan/overrides._dedupe_agg_twins) can
    give a seam node several parents — a q15-class grouped view read
    both directly and under its MAX subquery — and ALL of them must see
    the seam leaf, else one consumer re-executes the whole collapsed
    subtree inside its own segment."""
    links = []
    seen = set()
    for n in [root] + [d for d in _walk_nodes(root)]:
        if id(n) in seen:
            continue
        seen.add(id(n))
        for i, c in enumerate(n.children):
            if c is old:
                links.append((n, i))
    if not links:
        raise ValueError("split node not found under root")
    return links


def _walk_nodes(n: PlanNode):
    for c in n.children:
        yield c
        yield from _walk_nodes(c)


class SplitCompiledPlan:
    """Segmented whole-plan execution: the plan splits at seam nodes
    where the live row count collapses (join subtrees under aggregates,
    the aggregates themselves — _find_split_seams).  Each segment runs
    as one XLA program; at every seam ONE host sync reads the actual
    row count and the seam output re-buckets down (a device slice, no
    data transfer) before the next segment compiles over the smaller
    shapes.

    The reference never needs this: its kernels size outputs dynamically
    per launch.  Static-shape XLA programs otherwise carry the input-
    scale padding through every downstream operator (a TPC-H q3 tail —
    sort+limit over ~11k groups — was running at the 4M-row lineitem
    bucket, and its group-by over ~540k join survivors likewise)."""

    def __init__(self, root: PlanNode, seams: List[PlanNode],
                 conf: TpuConf):
        self.root = root
        self.conf = conf
        self.seams = list(seams)            # innermost-first
        self.leaves = [DeviceResidentScanExec(s) for s in self.seams]
        self._parent_idx = []
        scope = list(self.seams[1:]) + [root]
        for seam, leaf, upper in zip(self.seams, self.leaves, scope):
            self._parent_idx.append(_swap_child(upper, seam, leaf))
        # compiled programs per (segment, input-capacity key)
        self._programs: List[Dict[tuple, CompiledPlan]] = \
            [{} for _ in range(len(self.seams) + 1)]

    # -- tree swaps ---------------------------------------------------------
    def _install_leaves(self) -> None:
        """Swap every seam for its DeviceResidentScanExec leaf UP FRONT
        (restored in collect's finally): background compiles of
        downstream segments must see the seam leaf in the tree before
        the main thread reaches it.  Segment i's own program roots AT
        seams[i], so the swap above it never changes what segment i
        traces.  A seam with several parents (shared subtree) swaps at
        every link."""
        for links, leaf in zip(self._parent_idx, self.leaves):
            for parent, ci in links:
                parent.children[ci] = leaf

    def _restore_leaves(self) -> None:
        for links, seam in zip(self._parent_idx, self.seams):
            for parent, ci in links:
                parent.children[ci] = seam

    def _segment(self, i: int, key: tuple, ctx) -> CompiledPlan:
        progs = self._programs[i]
        plan = progs.get(key)
        if plan is None and i > 0:
            # a background speculative compile may have this program
            # ready (or in flight — wait overlaps its tail); its
            # exception (injected compile faults included) re-raises
            # HERE, on the consuming thread
            from ..runtime.compile_service import (background_enabled,
                                                   get_service)
            if background_enabled(ctx.conf):
                task = get_service(ctx.conf).take((id(self), i, key))
                if task is not None:
                    try:
                        # the wait IS compile wall from the query's
                        # point of view (the background thread has no
                        # tracer): bracket it under the compile
                        # category so wall_breakdown() attributes it
                        with ctx.tracer.span("compile.wait", "compile",
                                             segment=i):
                            plan = task.wait()
                        progs[key] = plan
                        ctx.bump("compile_background_used")
                    except TimeoutError:
                        plan = None      # hung pool: compile inline
        if plan is None:
            seg_root = self.seams[i] if i < len(self.seams) else self.root
            plan = CompiledPlan(seg_root, ctx.conf)
            progs[key] = plan
        return plan

    # -- background speculation --------------------------------------------
    @staticmethod
    def _lane_dtypes(spec, layout) -> List[str]:
        """Per-column data-lane dtype strings of one output batch,
        recovered from the flat layout in _flatten_batch order."""
        cols_spec = spec[0]
        dts = []
        j = 0
        for _dt, _d, has_hi, has_off in cols_spec:
            dts.append(layout[j][1])
            j += 2                       # data + validity
            if has_hi:
                j += 1
            if has_off:
                j += 2
        return dts

    @staticmethod
    def _placeholder_batch(spec, lane_dtypes, cap: int) -> DeviceBatch:
        """A post-shrink-shaped stand-in batch of ShapeDtypeStruct lanes
        (capacity `cap`, dynamic row count, real dictionaries): enough
        for jit(...).lower() to trace the next segment without data."""
        import numpy as np
        cols_spec, names, _static, origin, _sel = spec
        cols = []
        for (dt, dictionary, has_hi, _off), lane_dt in zip(cols_spec,
                                                           lane_dtypes):
            cols.append(DeviceColumn(
                jax.ShapeDtypeStruct((cap,), np.dtype(lane_dt)),
                jax.ShapeDtypeStruct((cap,), np.dtype(bool)),
                dt, dictionary,
                jax.ShapeDtypeStruct((cap,), np.dtype(np.int64))
                if has_hi else None))
        return DeviceBatch(cols,
                           jax.ShapeDtypeStruct((), np.dtype(np.int32)),
                           list(names), origin)

    def _candidate_caps(self, i: int, cap_in: int, conf) -> List[int]:
        """Predicted post-shrink buckets for seam i's output: exact when
        plan statistics bound the row count, else the two structural
        guesses — full collapse (aggregates: thousands of groups from
        millions of rows) and no collapse."""
        from ..config import COMPILE_BG_SPECULATE
        seam = self.seams[i]
        cands: List[int] = []
        r = seam.static_row_count()
        if r is None:
            r = seam.row_upper_bound()
        if r is not None:
            cands.append(min(bucket_capacity(max(int(r), 1), conf),
                             cap_in))
        cands.append(min(bucket_capacity(1, conf), cap_in))
        cands.append(cap_in)
        out: List[int] = []
        for c in cands:
            if c not in out:
                out.append(c)
        return out[:int(conf.get(COMPILE_BG_SPECULATE))]

    def _speculate(self, i: int, seg: CompiledPlan, ctx) -> None:
        """AOT-compile candidate programs for segment i+1 on the compile
        service while segment i executes — the seam sync then usually
        finds the next program ready instead of paying its compile on
        the critical path."""
        nxt = i + 1
        if nxt > len(self.seams):
            return
        from ..runtime.compile_service import (background_enabled,
                                               get_service)
        if not background_enabled(ctx.conf):
            return
        specs, layout = seg._out_specs, seg._out_layout
        if not specs or layout is None or len(specs) != 1:
            return                       # multi-batch seams: no prediction
        spec = specs[0]
        if any(off for _dt, _d, _hi, off in spec[0]):
            return                       # ragged seam output never splits
        lane_dtypes = self._lane_dtypes(spec, layout)
        cap_in = layout[0][0][0] if layout[0][0] else 0
        if not cap_in:
            return
        service = get_service(ctx.conf)
        seg_root = self.seams[nxt] if nxt < len(self.seams) else self.root
        conf = ctx.conf
        for cap in self._candidate_caps(i, cap_in, conf):
            key = (cap,)
            if key in self._programs[nxt]:
                continue
            placeholder = [self._placeholder_batch(spec, lane_dtypes, cap)]
            plan = CompiledPlan(
                seg_root, conf,
                leaf_overrides={id(self.leaves[i]): placeholder})

            def thunk(plan=plan, conf=conf):
                plan.aot_compile(ExecContext(conf))
                return plan

            service.submit((id(self), nxt, key), thunk)

    @staticmethod
    def _shrink(outs: List[DeviceBatch], ctx) -> List[DeviceBatch]:
        sliced = []
        for db in outs:
            if db.sel is not None or db.thin is not None:
                # lazy-join seam output: the seam re-buckets anyway, so
                # materialize the selection vector / deferred lanes here
                from ..ops.batch_ops import ensure_prefix
                db = ensure_prefix(db, ctx.conf)
            if any(c.offsets is not None for c in db.columns):
                raise _SplitUnsupported()   # ragged seam output
            n = db.num_rows if isinstance(db.num_rows, int) \
                else int(db.num_rows)       # ONE host sync per batch
            cap = min(bucket_capacity(max(n, 1), ctx.conf), db.capacity)
            # num_rows stays a device scalar so segment traces are keyed
            # on the CAPACITY BUCKET only — a drifting row count
            # (growing table, streaming appends) reuses compiled
            # programs instead of recompiling per exact count
            sliced.append(_slice_batch(db, cap, jnp.int32(n)))
        return sliced

    def collect(self, ctx: ExecContext) -> pa.Table:
        import time as _time
        self._install_leaves()
        try:
            key: tuple = ()
            for i, leaf in enumerate(self.leaves):
                # seam bracket doubles as a cancellation checkpoint: a
                # deadline-armed query cancels between segments, never
                # mid-dispatch (the reservation picture stays clean)
                ctx.checkpoint("seam")
                seg = self._segment(i, key, ctx)
                # compile first, THEN speculate: the next segment's
                # placeholder shapes need this segment's traced output
                # specs (dtypes, dictionaries).  Its compiles overlap
                # this segment's device execution + seam sync below.
                seg.ensure_compiled(ctx)
                self._speculate(i, seg, ctx)
                outs = seg.execute(ctx)
                # the seam bracket (always-on: two clock reads around
                # host work the seam pays anyway): one host row-count
                # sync + re-bucket per batch, the dominant fixed cost of
                # split plans on small inputs — overhead.seam_* feeds
                # wall_breakdown(), the history plane, and the seam gate
                t0 = _time.perf_counter()
                sliced = self._shrink(outs, ctx)
                leaf.batches = sliced
                key = tuple(db.capacity for db in sliced)
                t1 = _time.perf_counter()
                rows = 0
                nbytes = 0
                for db in sliced:
                    try:
                        rows += int(db.num_rows)  # concrete post-sync
                        nbytes += int(db.nbytes())
                    except Exception:             # noqa: BLE001
                        pass
                m = ctx.metrics
                m["overhead.seam_ms"] = m.get(
                    "overhead.seam_ms", 0.0) + (t1 - t0) * 1e3
                m["overhead.seam_count"] = m.get(
                    "overhead.seam_count", 0) + 1
                m["overhead.seam_rows"] = m.get(
                    "overhead.seam_rows", 0) + rows
                m["overhead.seam_bytes"] = m.get(
                    "overhead.seam_bytes", 0) + nbytes
                ctx.tracer.add_span(
                    "seam", "transition", t0, t1, seam=i, rows=rows,
                    bytes=nbytes, seam_ms=round((t1 - t0) * 1e3, 4))
            out = self._segment(len(self.seams), key, ctx).collect(ctx)
        finally:
            self._restore_leaves()
        ctx.bump("whole_plan_split_queries")
        return out


class _SplitUnsupported(Exception):
    pass


def session_mesh(conf: TpuConf):
    """The SPMD execution mesh for this conf, or None (disabled /
    single device)."""
    from ..config import MESH_DEVICES, MESH_ENABLED
    if not conf.get(MESH_ENABLED):
        return None
    n = conf.get(MESH_DEVICES) or len(jax.devices())
    if n < 2 or len(jax.devices()) < n:
        return None
    from ..parallel.mesh import make_mesh
    return make_mesh(n)


def build_plan(root: PlanNode, ctx: ExecContext):
    """The whole-plan execution object for this root under this conf:
    a SplitCompiledPlan when row-collapse seams pay for themselves,
    else one CompiledPlan (mesh-sharded when SPMD is on)."""
    mesh = session_mesh(ctx.conf)
    seams = [] if mesh is not None \
        else _find_split_seams(root, ctx.conf)
    return SplitCompiledPlan(root, seams, ctx.conf) if seams \
        else CompiledPlan(root, ctx.conf, mesh=mesh)


def collect_with_fallback(root: PlanNode, ctx: ExecContext,
                          cache_on: Optional[object] = None
                          ) -> Optional[pa.Table]:
    """Try the whole-plan compiled path; None means 'use the eager engine'
    (host-decision plan, or device OOM — the eager engine has the OOC
    machinery)."""
    holder = cache_on if cache_on is not None else root
    plan = getattr(holder, "_compiled_plan", None)
    if plan is False:                    # previously failed to trace
        return None
    if plan is None:
        plan = build_plan(root, ctx)
    try:
        out = plan.collect(ctx)
    except _SplitUnsupported:
        # e.g. ragged aggregate output: retry as one program, with the
        # same fallback ladder (trace errors AND device OOM -> eager)
        plan = CompiledPlan(root, ctx.conf)
        try:
            out = plan.collect(ctx)
        except _TRACE_FALLBACK_ERRORS:
            holder._compiled_plan = False
            ctx.bump("whole_plan_fallbacks")
            return None
        except Exception as e:           # noqa: BLE001
            from ..runtime.memory import is_oom_error
            ctx.bump("whole_plan_fallbacks")
            if is_oom_error(e):
                # transient device OOM: run eager THIS time, but keep the
                # compiled path eligible — memory pressure passes, a
                # trace error never does
                return None
            holder._compiled_plan = False
            raise
        holder._compiled_plan = plan
        ctx.bump("whole_plan_compiled_queries")
        return out
    except _TRACE_FALLBACK_ERRORS as e:
        holder._compiled_plan = False
        ctx.bump("whole_plan_fallbacks")
        ctx.tracer.instant("whole_plan_fallback", "runtime",
                           reason=type(e).__name__)
        return None
    except Exception as e:               # noqa: BLE001
        from ..runtime.memory import is_oom_error
        ctx.bump("whole_plan_fallbacks")
        if is_oom_error(e):
            ctx.tracer.instant("whole_plan_fallback", "runtime",
                               reason="device_oom")
            return None                  # eager engine has spill/retry;
                                         # compiled stays eligible
        holder._compiled_plan = False
        raise
    holder._compiled_plan = plan
    ctx.bump("whole_plan_compiled_queries")
    return out


# ---------------------------------------------------------------------------
# Persistent compile cache: topology-safe on-disk AOT executables
# ---------------------------------------------------------------------------
# jax's compilation cache serializes every XLA executable to disk, so a
# fresh process REPLAYS warmed queries with zero XLA compiles (trace +
# deserialize only).  Two engine problems with using it raw:
#
#   1. XLA's cache key does NOT hash the device topology or XLA_FLAGS —
#      one directory shared between a 1-chip bench and the tests' forced
#      8-device CPU mesh can hand one topology's serialized executable
#      to the other's deserializer and crash it (the bench.py incident
#      that split `.jax_cache_bench` off by hand).  The engine scopes
#      entries under a `topo-<hash>` subdirectory instead, hashing
#      backend, device count/kinds, process count and XLA_FLAGS.
#   2. There was no counter proving "this run compiled nothing" — the
#      monitoring listener below publishes persistent hit/miss into the
#      always-on registry (tpu_compile_cache_persistent_*), which
#      bench.py reports per run.

_PERSIST_STATE = {"listener": False, "dir": None}


def topology_fingerprint() -> str:
    """Stable hash of everything that changes serialized-executable
    compatibility but is absent from XLA's own cache key."""
    import hashlib
    import json
    import os
    devs = jax.devices()
    try:
        nproc = jax.process_count()
    except Exception:                    # noqa: BLE001
        nproc = 1
    sig = json.dumps(
        [jax.default_backend(), len(devs),
         sorted({d.device_kind for d in devs}), nproc,
         os.environ.get("XLA_FLAGS", "")], sort_keys=True)
    return hashlib.sha256(sig.encode()).hexdigest()[:12]


def _install_persistent_listener() -> None:
    if _PERSIST_STATE["listener"]:
        return
    _PERSIST_STATE["listener"] = True
    from jax._src import monitoring
    from ..obs.registry import (COMPILE_PERSISTENT_HITS,
                                COMPILE_PERSISTENT_MISSES)

    def _cb(event, **_kw):
        # the request event fires before the lookup, the hit event after
        # it: count every request as a miss, then retract on the hit
        if event == "/jax/compilation_cache/compile_requests_use_cache":
            COMPILE_PERSISTENT_MISSES.add(1)
        elif event == "/jax/compilation_cache/cache_hits":
            COMPILE_PERSISTENT_HITS.inc()
            COMPILE_PERSISTENT_MISSES.add(-1)

    monitoring.register_event_listener(_cb)


def configure_persistent_cache(conf: TpuConf) -> Optional[str]:
    """Point jax's compilation cache at the conf'd engine cache dir,
    scoped by topology; idempotent per resulting path.  Returns the
    active topology-scoped path, or None when unset."""
    import os
    from ..config import COMPILE_CACHE_DIR
    base = str(conf.get(COMPILE_CACHE_DIR) or "")
    if not base:
        return None
    _install_persistent_listener()
    path = os.path.join(base, f"topo-{topology_fingerprint()}")
    if _PERSIST_STATE["dir"] == path:
        return path
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # cache EVERYTHING: the point is zero compiles on replay, and tiny
    # entries (scalar fetch programs) recompile as often as big ones
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    try:
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()                # drop the handle to any old dir
    except Exception:                    # noqa: BLE001
        pass
    _PERSIST_STATE["dir"] = path
    return path


def persistent_cache_stats() -> Dict[str, int]:
    """{'hits', 'misses'} of the persistent compile cache this process
    (the bench/CI proof counters)."""
    from ..obs.registry import (COMPILE_PERSISTENT_HITS,
                                COMPILE_PERSISTENT_MISSES)
    return {"hits": int(COMPILE_PERSISTENT_HITS.value() or 0),
            "misses": int(COMPILE_PERSISTENT_MISSES.value() or 0)}
