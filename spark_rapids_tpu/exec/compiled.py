"""Whole-plan XLA compilation: one jit program per query.

The reference dispatches one cuDF kernel launch per operator step; launch
latency is ~free on a locally attached GPU.  On TPU the idiomatic shape
is the opposite: **trace the entire physical plan once and hand XLA a
single program** — operators fuse (filter masks into projections into
segment-reductions), intermediate lanes never round-trip through HBM
twice, and a warm query is ONE dispatch + ONE result fetch regardless of
plan depth.  This is the "cudf AST compiled expressions" idea
(GpuExpressions.scala convertToAst / ast.CompiledExpression) taken to its
XLA-native conclusion: tracing IS the AST, for the whole plan rather than
one expression.

How it works:
  * Leaf `HostScanExec`s upload their batches once (cached on the node —
    the buffer-cache / spill-framework role for hot inputs).
  * `jax.jit(run)` traces `root.execute(ctx)` — the ordinary operator
    generators — over placeholder arrays standing in for every leaf lane.
    All sync-free paths (probe-aligned joins, lazy filters/limits,
    segment aggregations, single-batch sorts) trace cleanly because they
    never coerce a device value on host.
  * Output batch *structure* (schema, capacities, dictionaries) is
    recorded at trace time; the compiled call returns flat lanes that are
    re-wrapped as DeviceBatches / fetched in one `jax.device_get`.
  * Anything that genuinely needs a host decision (sized join expansion,
    out-of-core sort, retry machinery) raises a tracer-concretization
    error — the caller falls back to the eager batch-at-a-time engine,
    which remains the out-of-core/general path.

Compile cost is paid once per (plan shape, input bucket) and is
persisted by jax's compilation cache; warm latency is what the
benchmark measures (BASELINE.md).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax
import pyarrow as pa

from .. import types as t
from ..columnar.device import DeviceBatch, DeviceColumn, to_device
from ..config import TpuConf
from .plan import ExecContext, HostScanExec, PlanNode


def _find_scans(root: PlanNode) -> List[HostScanExec]:
    out = []
    seen = set()

    def walk(n: PlanNode):
        if id(n) in seen:
            return
        seen.add(id(n))
        if isinstance(n, HostScanExec):
            out.append(n)
        for c in n.children:
            walk(c)
    walk(root)
    return out


def _flatten_batch(db: DeviceBatch):
    """-> (arrays, spec) where spec rebuilds the batch from arrays."""
    arrays = []
    cols = []
    for c in db.columns:
        arrays.append(c.data)
        arrays.append(c.validity)
        if c.data_hi is not None:
            arrays.append(c.data_hi)
        if c.offsets is not None:              # ragged ARRAY lanes
            arrays.append(c.offsets)
            arrays.append(c.elem_valid)
        cols.append((c.dtype, c.dictionary, c.data_hi is not None,
                     c.offsets is not None))
    static_rows = db.num_rows if isinstance(db.num_rows, int) else None
    if static_rows is None:
        arrays.append(db.num_rows)
    return arrays, (cols, list(db.names), static_rows, db.origin_file)


def _rebuild_batch(arrays, spec, i: int) -> Tuple[DeviceBatch, int]:
    cols_spec, names, static_rows, origin = spec
    cols = []
    for dtype, dictionary, has_hi, has_off in cols_spec:
        data = arrays[i]
        valid = arrays[i + 1]
        i += 2
        hi = offsets = elem_valid = None
        if has_hi:
            hi = arrays[i]
            i += 1
        if has_off:
            offsets = arrays[i]
            elem_valid = arrays[i + 1]
            i += 2
        cols.append(DeviceColumn(data, valid, dtype, dictionary, hi,
                                 offsets=offsets, elem_valid=elem_valid))
    if static_rows is None:
        num_rows = arrays[i]
        i += 1
    else:
        num_rows = static_rows
    return DeviceBatch(cols, num_rows, names, origin), i


def _shard_batch(db: DeviceBatch, mesh) -> DeviceBatch:
    """Place a batch's lanes row-sharded over the mesh (replicated when
    the capacity doesn't divide the mesh)."""
    from jax.sharding import NamedSharding, PartitionSpec
    from ..parallel.mesh import SHARD_AXIS
    n = mesh.devices.size
    spec = PartitionSpec(SHARD_AXIS) if db.capacity % n == 0 \
        else PartitionSpec()
    sh = NamedSharding(mesh, spec)
    rep = NamedSharding(mesh, PartitionSpec())
    cols = []
    for c in db.columns:
        if c.offsets is not None:
            # ragged columns: offsets (rows+1) and value lanes don't fit
            # the row sharding — replicate; GSPMD still partitions the
            # flat columns around them
            cols.append(DeviceColumn(
                jax.device_put(c.data, rep),
                jax.device_put(c.validity, rep),
                c.dtype, c.dictionary, None,
                offsets=jax.device_put(c.offsets, rep),
                elem_valid=jax.device_put(c.elem_valid, rep)))
            continue
        cols.append(DeviceColumn(
            jax.device_put(c.data, sh),
            jax.device_put(c.validity, sh),
            c.dtype, c.dictionary,
            None if c.data_hi is None
            else jax.device_put(c.data_hi, sh)))
    return DeviceBatch(cols, db.num_rows, db.names, db.origin_file)


_SCAN_UPLOAD_CACHE: Dict[object, tuple] = {}


def _shared_scan_upload(node: HostScanExec, conf: TpuConf
                        ) -> List[DeviceBatch]:
    """Upload a scan's batches once PER SOURCE TABLE (not per plan): every
    re-planned query over the same pyarrow table shares one device copy —
    the buffer-cache role for hot inputs (reference FileCache /
    spill-framework device tier).  Weakref-keyed so device memory is
    released with the table."""
    import weakref
    tbl = node._source_table
    if tbl is None:
        return [to_device(hb, conf) for hb in node.batches]
    key = (id(tbl), conf.batch_size_rows)
    hit = _SCAN_UPLOAD_CACHE.get(key)
    if hit is not None and hit[0]() is tbl:
        return hit[1]
    dbs = [to_device(hb, conf) for hb in node.batches]
    try:
        ref = weakref.ref(tbl, lambda _r, k=key:
                          _SCAN_UPLOAD_CACHE.pop(k, None))
    except TypeError:
        return dbs
    _SCAN_UPLOAD_CACHE[key] = (ref, dbs)
    return dbs


class CompiledPlan:
    """A traced-and-jitted device plan bound to its leaf scans.

    With `mesh`, leaf lanes are placed row-sharded over the mesh axis and
    the SAME whole-plan program runs SPMD: XLA's GSPMD partitioner keeps
    scans/filters/projections data-parallel per chip and inserts the
    cross-chip collectives (all-to-all/all-gather/psum over ICI) where
    sorts, group-bys and joins need global views — the
    annotate-shardings-and-let-XLA-insert-collectives recipe, playing the
    reference's shuffle-exchange fabric role (RapidsShuffleManager/UCX)."""

    def __init__(self, root: PlanNode, conf: TpuConf, mesh=None):
        self.root = root
        self.conf = conf
        self.mesh = mesh
        self._out_specs: Optional[list] = None
        self._compiled = None
        self._input_specs = None

    # -- leaves ------------------------------------------------------------
    def _leaf_batches(self, ctx: ExecContext
                      ) -> List[Tuple[HostScanExec, List[DeviceBatch]]]:
        pairs = []
        for node in _find_scans(self.root):
            cached = getattr(node, "_device_cache", None)
            if cached is None:
                cached = _shared_scan_upload(node, ctx.conf)
                if self.mesh is not None:
                    cached = [_shard_batch(db, self.mesh) for db in cached]
                node._device_cache = cached
            pairs.append((node, cached))
        return pairs

    # -- compile + run -----------------------------------------------------
    def execute(self, ctx: ExecContext) -> List[DeviceBatch]:
        """Run the whole plan as one XLA program; returns device batches.

        Raises jax tracer errors (ConcretizationTypeError & friends) when
        the plan needs host decisions — callers fall back to eager."""
        pairs = self._leaf_batches(ctx)
        flat_in: List[jax.Array] = []
        in_specs = []
        for node, dbs in pairs:
            node_specs = []
            for db in dbs:
                arrays, spec = _flatten_batch(db)
                flat_in.extend(arrays)
                node_specs.append(spec)
            in_specs.append((node, node_specs))

        if self._compiled is None:
            self._input_specs = [(n, list(s)) for n, s in in_specs]
            out_holder: Dict[str, list] = {}

            def run(flat):
                # rebuild leaf batches from traced arrays and install them
                i = 0
                for node, node_specs in in_specs:
                    batches = []
                    for spec in node_specs:
                        db, i = _rebuild_batch(flat, spec, i)
                        batches.append(db)
                    node._trace_batches = batches
                try:
                    trace_ctx = _trace_context(ctx)
                    outs = list(self.root.execute(trace_ctx))
                finally:
                    for node, _ in in_specs:
                        node._trace_batches = None
                    # copy ONLY host numbers back: a traced metric value
                    # escaping the jit would be a leaked tracer
                    for k, v in trace_ctx.metrics.items():
                        if isinstance(v, (int, float)):
                            ctx.metrics[k] = v
                flat_out = []
                specs = []
                for db in outs:
                    arrays, spec = _flatten_batch(db)
                    flat_out.extend(arrays)
                    specs.append(spec)
                out_holder["specs"] = specs
                return flat_out

            compiled = jax.jit(run)
            flat_res = compiled(flat_in)         # traces on first call
            self._out_specs = out_holder["specs"]
            self._compiled = compiled
        else:
            flat_res = self._compiled(flat_in)

        outs = []
        i = 0
        for spec in self._out_specs:
            db, i = _rebuild_batch(flat_res, spec, i)
            outs.append(db)
        return outs

    def collect(self, ctx: ExecContext) -> pa.Table:
        from ..columnar.device import to_host
        from ..columnar.host import struct_to_schema
        outs = self.execute(ctx)
        hbs = [to_host(db) for db in outs]
        batches = [hb.rb for hb in hbs if hb.num_rows > 0]
        if not batches:
            return pa.Table.from_batches(
                [], struct_to_schema(self.root.output_schema))
        return pa.Table.from_batches(batches, batches[0].schema)


def _trace_context(ctx: ExecContext) -> ExecContext:
    """Execution context for use UNDER tracing: unlimited budget (XLA owns
    memory inside one program; spilling a tracer is meaningless), no
    runtime bloom filters (their sizing needs host row counts), and a
    PRIVATE metrics dict — device-scalar metrics recorded during tracing
    are tracers and must never escape the jit (host numbers are copied
    back by the caller)."""
    from ..config import (HBM_BUDGET_BYTES, RUNTIME_FILTER_ENABLED,
                          TEST_INJECT_RETRY_OOM)
    raw = dict(ctx.conf._raw)
    raw[HBM_BUDGET_BYTES.key] = 1 << 62
    raw[RUNTIME_FILTER_ENABLED.key] = False
    raw[TEST_INJECT_RETRY_OOM.key] = 0
    return ExecContext(TpuConf(raw))


# errors that mean "this plan needs host decisions" — not bugs
_TRACE_FALLBACK_ERRORS = (
    jax.errors.ConcretizationTypeError,
    jax.errors.TracerArrayConversionError,
    jax.errors.TracerBoolConversionError,
    jax.errors.TracerIntegerConversionError,
    jax.errors.UnexpectedTracerError,
)


def session_mesh(conf: TpuConf):
    """The SPMD execution mesh for this conf, or None (disabled /
    single device)."""
    from ..config import MESH_DEVICES, MESH_ENABLED
    if not conf.get(MESH_ENABLED):
        return None
    n = conf.get(MESH_DEVICES) or len(jax.devices())
    if n < 2 or len(jax.devices()) < n:
        return None
    from ..parallel.mesh import make_mesh
    return make_mesh(n)


def collect_with_fallback(root: PlanNode, ctx: ExecContext,
                          cache_on: Optional[object] = None
                          ) -> Optional[pa.Table]:
    """Try the whole-plan compiled path; None means 'use the eager engine'
    (host-decision plan, or device OOM — the eager engine has the OOC
    machinery)."""
    holder = cache_on if cache_on is not None else root
    plan = getattr(holder, "_compiled_plan", None)
    if plan is False:                    # previously failed to trace
        return None
    if plan is None:
        plan = CompiledPlan(root, ctx.conf, mesh=session_mesh(ctx.conf))
    try:
        out = plan.collect(ctx)
    except _TRACE_FALLBACK_ERRORS:
        holder._compiled_plan = False
        ctx.bump("whole_plan_fallbacks")
        return None
    except Exception as e:               # noqa: BLE001
        from ..runtime.memory import is_oom_error
        holder._compiled_plan = False
        ctx.bump("whole_plan_fallbacks")
        if is_oom_error(e):
            return None                  # eager engine has spill/retry
        raise
    holder._compiled_plan = plan
    ctx.bump("whole_plan_compiled_queries")
    return out
