"""Test utilities: the assert_gpu_and_cpu_are_equal analogue.

The reference's entire correctness strategy (SURVEY §4) is "same engine, two
backends, compare" (integration_tests asserts.py:579).  Here the two backends
are the device path (jit-traced eval_dev) and the per-expression CPU fallback
(eval_cpu over pyarrow) — which doubles as the production fallback engine, so
these asserts also exercise the CPU path users hit on unsupported operators.
"""
from __future__ import annotations

import math
from typing import Dict, Sequence

import numpy as np
import pyarrow as pa

from .columnar import HostBatch, to_device, to_host
from .config import TpuConf, DEFAULT_CONF
from .exec.evaluator import apply_filter, evaluate_projection
from .plan.expressions import Expression


def _values_equal(a, b, approx_float: bool) -> bool:
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, float) and isinstance(b, float):
        if math.isnan(a) or math.isnan(b):
            return math.isnan(a) and math.isnan(b)
        if approx_float:
            return math.isclose(a, b, rel_tol=1e-12, abs_tol=1e-300)
        return a == b
    return a == b


def assert_columns_equal(got: pa.Array, want: pa.Array, label: str = "",
                         approx_float: bool = False):
    gl, wl = got.to_pylist(), want.to_pylist()
    assert len(gl) == len(wl), f"{label}: row count {len(gl)} != {len(wl)}"
    for i, (g, w) in enumerate(zip(gl, wl)):
        assert _values_equal(g, w, approx_float), \
            f"{label}: row {i}: device={g!r} cpu={w!r}"


def assert_device_cpu_equal(exprs: Sequence[Expression], data: Dict,
                            conf: TpuConf = DEFAULT_CONF,
                            approx_float: bool = False):
    """Evaluate bound-able expressions on device and CPU; compare results."""
    hb = HostBatch.from_pydict(data) if not isinstance(data, HostBatch) else data
    schema = hb.schema
    bound = [e.bind(schema) for e in exprs]
    for e in bound:
        reasons = e.tree_unsupported(conf)
        assert not reasons, f"expression not device-supported: {reasons}"
    db = to_device(hb, conf)
    names = [f"c{i}" for i in range(len(bound))]
    out = to_host(evaluate_projection(bound, names, db, conf))
    for i, e in enumerate(bound):
        want = e.eval_cpu(hb.rb)
        assert_columns_equal(out.rb.column(i), want, label=e.fingerprint(),
                             approx_float=approx_float)
    return out


# ---------------------------------------------------------------------------
# jaxpr program lints: sort-operand budget and scatter census
# ---------------------------------------------------------------------------
# The two compile/runtime cliffs of this platform are directly visible in
# the emitted jaxpr: variadic `sort` equations whose operand count blows
# up XLA compile time, and `scatter*` equations whose outputs land in
# slow S(1)-space buffers (docs/PERF.md §1).  These walkers turn both
# into assertable numbers for tier-1 tests and bench.py.

_SCATTER_PRIMS = ("scatter", "scatter-add", "scatter-mul", "scatter-min",
                  "scatter-max")


def _iter_eqns(jaxpr):
    """Every equation of a (Closed)Jaxpr, recursing into sub-jaxprs
    (pjit bodies, scan/while/cond branches, custom call wrappers)."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in inner.eqns:
        yield eqn
        for v in eqn.params.values():
            vs = v if isinstance(v, (tuple, list)) else (v,)
            for sub in vs:
                if hasattr(sub, "eqns") or hasattr(sub, "jaxpr"):
                    yield from _iter_eqns(sub)


def jaxpr_sort_operands(jaxpr) -> int:
    """Largest operand count of any `sort` equation (0 when sort-free)."""
    return max((len(e.invars) for e in _iter_eqns(jaxpr)
                if e.primitive.name == "sort"), default=0)


def jaxpr_sort_operand_total(jaxpr) -> int:
    """TOTAL operands across every `sort` equation — the whole-program
    sort volume proxy.  The Pallas kernel tier exists to shrink this on
    the join/filter-heavy tail (each replaced merge-rank probe was two
    2-operand sorts over build+probe rows); its budget lint asserts the
    q3/q9/q15-class programs emit strictly fewer sort operands with the
    tier on."""
    return sum(len(e.invars) for e in _iter_eqns(jaxpr)
               if e.primitive.name == "sort")


def jaxpr_pallas_calls(jaxpr) -> int:
    """Number of `pallas_call` equations — the hand-written kernel
    dispatches actually embedded in the program (interpret-mode calls
    included: the primitive is the same, only its lowering differs).
    Note _iter_eqns recurses INTO kernel bodies via the equation's
    jaxpr param, so sorts/scatters inside a kernel would still be
    counted by the census walkers above."""
    return sum(1 for e in _iter_eqns(jaxpr)
               if e.primitive.name == "pallas_call")


def _gather_sizes(eqn):
    """(operand elems, output elems) of a gather equation."""
    import numpy as np
    op_shape = getattr(eqn.invars[0].aval, "shape", ())
    out = 0
    for ov in eqn.outvars:
        shape = getattr(ov.aval, "shape", ())
        out += int(np.prod(shape)) if shape else 1
    return (int(np.prod(op_shape)) if op_shape else 1), out


def jaxpr_decode_count(jaxpr) -> int:
    """Number of DECODE-signature gathers: gather equations whose
    operand is SMALLER than their output — a per-row lookup through a
    table below row count (dictionary remap/rank/membership tables,
    dense direct-address probes).  The encoded-execution layer
    (ops/encodings.py) exists to shrink the dictionary-decode share of
    these: its per-query budget lint asserts the q1/q3/q9-class
    programs emit strictly less decode VOLUME with the feature on."""
    return sum(1 for e in _iter_eqns(jaxpr)
               if e.primitive.name == "gather"
               and _gather_sizes(e)[0] < _gather_sizes(e)[1])


def jaxpr_decode_elems(jaxpr) -> int:
    """Total OUTPUT elements across decode-signature gathers — the
    decode-volume proxy (rows actually expanded through sub-row-count
    tables).  Code-space predicates and order-preserving dictionaries
    remove remap/rank tables outright, so volume strictly drops where
    the rewrites engage while invariant table-gathers (join
    direct-address probes) cancel in the on/off comparison."""
    total = 0
    for e in _iter_eqns(jaxpr):
        if e.primitive.name == "gather":
            osz, out = _gather_sizes(e)
            if osz < out:
                total += out
    return total


def jaxpr_scatter_count(jaxpr) -> int:
    """Number of scatter-family equations in the program."""
    return sum(1 for e in _iter_eqns(jaxpr)
               if e.primitive.name in _SCATTER_PRIMS)


def jaxpr_gather_count(jaxpr) -> int:
    """Number of `gather` equations in the program — the descriptor-
    driven row-gather passes that dominate join-pipeline device time
    (docs/PERF.md; each gathered lane moves at DMA rather than vector
    bandwidth).  Late materialization (columnar/lanes.py) exists to
    shrink this number: its per-query budget lint asserts the q3/q9/
    q15/q16-class programs emit FEWER gathers with the feature on."""
    return sum(1 for e in _iter_eqns(jaxpr)
               if e.primitive.name == "gather")


def jaxpr_gather_elems(jaxpr) -> int:
    """Total OUTPUT elements across every `gather` equation — the
    volume proxy for row-gather device cost (rows x lanes actually
    moved through descriptor DMA).  Late materialization shrinks this
    even where the equation COUNT ties (a deferred column's sink gather
    replaces a per-join gather 1:1 but the skipped re-gathers of chained
    probe payloads don't), so the per-query budget lint compares
    volume."""
    import numpy as np
    total = 0
    for e in _iter_eqns(jaxpr):
        if e.primitive.name == "gather":
            for ov in e.outvars:
                shape = getattr(ov.aval, "shape", ())
                total += int(np.prod(shape)) if shape else 1
    return total


def plan_program_stats(physical, ctx=None) -> Dict:
    """{'sort_operand_max', 'scatter_op_count'} for a PhysicalQuery's
    device plan traced as ONE whole-plan XLA program
    (exec.compiled.CompiledPlan.make_jaxpr) — the same program shape the
    TPU backend dispatches.  Raises jax tracer errors for plans that
    need host decisions (callers treat those as not-traceable)."""
    from .exec.compiled import CompiledPlan
    from .exec.plan import ExecContext
    ctx = ctx or ExecContext(physical.conf)
    jx = CompiledPlan(physical.root, physical.conf).make_jaxpr(ctx)
    return {"sort_operand_max": jaxpr_sort_operands(jx),
            "sort_operand_total": jaxpr_sort_operand_total(jx),
            "scatter_op_count": jaxpr_scatter_count(jx),
            "gather_op_count": jaxpr_gather_count(jx),
            "gather_out_elems": jaxpr_gather_elems(jx),
            "decode_op_count": jaxpr_decode_count(jx),
            "decode_out_elems": jaxpr_decode_elems(jx),
            "pallas_call_count": jaxpr_pallas_calls(jx)}


# ---------------------------------------------------------------------------
# Compiled-program cache hygiene
# ---------------------------------------------------------------------------
# Every engine module memoizes its jitted kernels in module-level *_CACHE
# dicts, which keep the XLA LoadedExecutables alive for the process
# lifetime.  A long-lived process that compiles many thousands of
# distinct programs (the full tier-1 suite now crosses ~8k with the
# TPC-DS tranche aboard) can exhaust the JIT's executable code space and
# crash inside XLA.  These helpers let harnesses bound that growth.

def compiled_cache_entries() -> int:
    """Total entries across every engine *_CACHE module dict."""
    import sys
    total = 0
    for name, mod in list(sys.modules.items()):
        if not name.startswith("spark_rapids_tpu"):
            continue
        for attr, val in list(vars(mod).items()):
            if attr.endswith("_CACHE") and isinstance(val, dict):
                total += len(val)
    return total


def clear_compiled_caches() -> int:
    """Drop every engine *_CACHE dict and jax's own jit caches, freeing
    the compiled executables they pin.  Returns the number of entries
    released.  Safe at any quiescent point: kernels recompile (or
    reload from the persistent cache) on next use."""
    import sys
    import jax
    released = 0
    for name, mod in list(sys.modules.items()):
        if not name.startswith("spark_rapids_tpu"):
            continue
        for attr, val in list(vars(mod).items()):
            if attr.endswith("_CACHE") and isinstance(val, dict):
                released += len(val)
                val.clear()
    jax.clear_caches()
    return released


def assert_filter_matches(cond: Expression, data: Dict,
                          conf: TpuConf = DEFAULT_CONF):
    """Device filter vs CPU mask-filter row-set comparison."""
    import pyarrow.compute as pc
    hb = HostBatch.from_pydict(data) if not isinstance(data, HostBatch) else data
    bound = cond.bind(hb.schema)
    reasons = bound.tree_unsupported(conf)
    assert not reasons, f"predicate not device-supported: {reasons}"
    db = to_device(hb, conf)
    got = to_host(apply_filter(bound, db, conf))
    mask = pc.fill_null(bound.eval_cpu(hb.rb), False)
    want = hb.rb.filter(mask)
    assert got.num_rows == want.num_rows, \
        f"filter row count {got.num_rows} != {want.num_rows}"
    for i in range(want.num_columns):
        assert_columns_equal(got.rb.column(i), want.column(i),
                             label=f"col {hb.rb.schema.names[i]}")
    return got
