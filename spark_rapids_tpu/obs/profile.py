"""QueryProfile: the offline profiling-tool aggregate over raw spans.

Reference: the RAPIDS Accelerator ships a profiling tool that replays
Spark event logs into per-SQL operator/time breakdowns (SURVEY §5).
`QueryProfile` is that aggregate for one query: the
compile/execute/transition/shuffle wall-time split, a per-node-id
operator table (two `HashAggregateExec`s stay two rows), the fallback
summary, data-movement counters and the memory high-water.  Build it
from a live ExecContext (`from_context`) or a written event log
(`from_event_log`); `scripts/profile_report.py` renders it from disk,
`bench.py` embeds `summary()` per query.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

from .tracer import EventLog, NULL_TRACER, QueryTracer, read_event_log

#: metric keys of the per-node-id operator counters (exec/metrics.py)
_NODE_METRIC_RE = re.compile(
    r"^(?P<name>\w+)#(?P<nid>\d+)\.(?P<field>op_time_ms|total_time_ms|"
    r"output_rows|output_batches)$")

#: metric keys of the per-segment attribution counters (exec/compiled.py
#: _record_segment; populated when spark.rapids.tpu.profile.segments on)
_SEGMENT_METRIC_RE = re.compile(
    r"^segment\.(?P<node>[\w#]+)\.(?P<field>device_ms|rows|out_bytes|"
    r"executions|flops|bytes_accessed|peak_temp_bytes|hbm_bytes|"
    r"hbm_peak_bytes|hbm_resident_pre|dispatch_ms|pad_rows|"
    r"pad_waste_ms)$")

#: span categories that are measured directly; "execute" is the residual
_SPLIT_CATS = ("compile", "transition", "shuffle")


def _union_ms(ivals: List[tuple]) -> float:
    """Total covered milliseconds of possibly-overlapping intervals."""
    if not ivals:
        return 0.0
    ivals = sorted(ivals)
    total, lo, hi = 0.0, ivals[0][0], ivals[0][1]
    for a, b in ivals[1:]:
        if a > hi:
            total += hi - lo
            lo, hi = a, b
        else:
            hi = max(hi, b)
    total += hi - lo
    return total * 1000.0


class QueryProfile:
    def __init__(self, spans, events, counters, metrics, meta,
                 registry=None, truncated=False):
        self.spans = list(spans)
        self.events = list(events)
        self.counters = dict(counters)
        self.metrics = dict(metrics or {})
        self.meta = dict(meta or {})
        #: metrics-plane snapshot from the event log's query_end record
        #: (PR 5); empty for live contexts and truncated logs
        self.registry = dict(registry or {})
        self.truncated = bool(truncated)

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_context(cls, ctx) -> "QueryProfile":
        """From a collected query's ExecContext (tracer may be NULL —
        the metrics-only tables still populate)."""
        tr = getattr(ctx, "tracer", NULL_TRACER)
        if isinstance(tr, QueryTracer):
            return cls(tr.spans, tr.events, tr.counters,
                       tr.metrics if tr.metrics is not None
                       else ctx.metrics, tr.meta)
        return cls([], [], {}, ctx.metrics, {})

    @classmethod
    def from_event_log(cls, path_or_log) -> "QueryProfile":
        log = path_or_log if isinstance(path_or_log, EventLog) \
            else read_event_log(path_or_log)
        return cls(log.spans, log.events, log.counters, log.metrics,
                   log.meta, registry=log.registry,
                   truncated=log.truncated)

    # -- aggregates --------------------------------------------------------
    def wall_ms(self) -> float:
        roots = [s for s in self.spans if s.cat == "query"]
        if roots:
            return sum(s.dur_ms for s in roots)
        if self.spans:
            return (max(s.t1 for s in self.spans) -
                    min(s.t0 for s in self.spans)) * 1000.0
        return 0.0

    def time_split(self) -> Dict[str, float]:
        """compile / execute / transition / shuffle / plan split.

        compile, transition and shuffle sum their spans' interval UNION
        clipped to the query span (nested same-cat spans never double
        count); execute is the residual query wall.  plan covers the
        wrap/tag/convert phases, which run before the query span."""
        roots = [s for s in self.spans if s.cat == "query"]
        q0 = min((s.t0 for s in roots), default=None)
        q1 = max((s.t1 for s in roots), default=None)
        out = {"wall_ms": round(self.wall_ms(), 3),
               "plan_ms": round(sum(s.dur_ms for s in self.spans
                                    if s.cat == "plan"), 3)}
        covered = []
        for cat in _SPLIT_CATS:
            ivals = []
            for s in self.spans:
                if s.cat != cat:
                    continue
                t0, t1 = s.t0, s.t1
                if q0 is not None:
                    t0, t1 = max(t0, q0), min(t1, q1)
                if t1 > t0:
                    ivals.append((t0, t1))
            out[f"{cat}_ms"] = round(_union_ms(ivals), 3)
            covered.extend(ivals)
        out["execute_ms"] = round(
            max(0.0, out["wall_ms"] - _union_ms(covered)), 3)
        return out

    def operators(self) -> List[Dict[str, Any]]:
        """Per-node-id operator table from the instrumented metrics,
        sorted by self time (total minus children) descending."""
        rows: Dict[str, Dict[str, Any]] = {}
        for k, v in self.metrics.items():
            m = _NODE_METRIC_RE.match(k)
            if not m:
                continue
            node = f"{m.group('name')}#{m.group('nid')}"
            row = rows.setdefault(node, {"node": node,
                                         "name": m.group("name"),
                                         "nid": int(m.group("nid"))})
            row[m.group("field")] = v
        children: Dict[Optional[str], List[str]] = {}
        for n in self.meta.get("plan_nodes", []):
            children.setdefault(n.get("parent"), []).append(n["id"])

        def measured_descendants_ms(node: str) -> float:
            """Totals of the nearest MEASURED descendants — skipping
            through unmeasured nodes (fused filters, pass-throughs whose
            metered execute never ran) so their children still subtract
            from this operator's self time."""
            total = 0.0
            stack = list(children.get(node, []))
            while stack:
                c = stack.pop()
                if c in rows:
                    total += float(rows[c].get("total_time_ms", 0.0))
                else:
                    stack.extend(children.get(c, []))
            return total

        for node, row in rows.items():
            total = float(row.get("total_time_ms", 0.0))
            sub = measured_descendants_ms(node) if children else 0.0
            row["self_time_ms"] = round(max(0.0, total - sub), 3)
        return sorted(rows.values(),
                      key=lambda r: (-r["self_time_ms"], r["nid"]))

    # -- the attribution plane (per-segment device time) -------------------
    def segments(self) -> List[Dict[str, Any]]:
        """Per-segment device-time attribution table: one row per
        compiled program segment ({node, device_ms, rows, out_bytes,
        executions, pct, node_lo/node_hi, static cost overlay}), sorted
        by device_ms descending.  Populated only from runs with
        `spark.rapids.tpu.profile.segments` on; merges the segment.*
        metrics with span-level node ranges."""
        rows: Dict[str, Dict[str, Any]] = {}
        for k, v in self.metrics.items():
            m = _SEGMENT_METRIC_RE.match(k)
            if not m or not isinstance(v, (int, float)):
                continue
            row = rows.setdefault(m.group("node"),
                                  {"node": m.group("node")})
            row[m.group("field")] = v
        from_metrics = set(rows)
        for s in self.spans:
            if s.name != "segment" or s.cat != "execute":
                continue
            node = s.node or "?"
            row = rows.setdefault(node, {"node": node})
            if node not in from_metrics:
                # span-only fallback (e.g. a metrics-stripped log):
                # accumulate the per-execution attrs
                row["device_ms"] = row.get("device_ms", 0.0) + \
                    float(s.attrs.get("device_ms", s.dur_ms))
                row["rows"] = row.get("rows", 0) + s.attrs.get("rows", 0)
                row["out_bytes"] = row.get("out_bytes", 0) + \
                    s.attrs.get("out_bytes", 0)
            if "node_lo" not in row and "node_lo" in s.attrs:
                row["node_lo"] = s.attrs["node_lo"]
                row["node_hi"] = s.attrs.get("node_hi")
        total = sum(float(r.get("device_ms", 0.0)) for r in rows.values())
        for r in rows.values():
            r["device_ms"] = round(float(r.get("device_ms", 0.0)), 3)
            r["pct"] = round(100.0 * r["device_ms"] / total, 1) \
                if total else 0.0
        return sorted(rows.values(), key=lambda r: -r["device_ms"])

    def attributed_device_pct(self) -> Optional[float]:
        """Fraction of the measured device wall (the union of
        cat=execute spans) covered by NAMED plan segments (`segment`
        spans carrying a node id) — the explain_analyze attribution
        bar.  None when the run carried no execute spans (eager path,
        or tracing off)."""
        ex = [(s.t0, s.t1) for s in self.spans if s.cat == "execute"]
        total = _union_ms(ex)
        if not total:
            return None
        seg = [(s.t0, s.t1) for s in self.spans
               if s.name == "segment" and s.cat == "execute"
               and (s.node or s.attrs.get("node_lo") is not None)]
        return min(1.0, _union_ms(seg) / total)

    def attributed_wall_pct(self) -> Optional[float]:
        """Fraction of the END-TO-END query wall covered by named
        wall-breakdown categories — the honest attribution bar.
        `attributed_device_pct` divides by the execute-span union only,
        so a fixed-overhead-tail query (q2/q16 class) can report 90%+
        while 99% of its wall is seams and dispatch; this one divides by
        the full query span.  None without a query span."""
        if not any(s.cat == "query" for s in self.spans):
            return None
        bd = self.wall_breakdown()
        return min(1.0, bd["attributed_pct"] / 100.0)

    # -- the overhead attribution plane (wall decomposition) ---------------
    def overheads(self) -> Dict[str, float]:
        """The overhead.* accumulators (exec brackets): seam_ms /
        seam_count / seam_rows / seam_bytes (always-on), dispatch_ms /
        dispatch_floor_ms / pad_rows / pad_waste_ms (profiled runs),
        host_prep_ms, fetch_ms."""
        out: Dict[str, float] = {}
        for k, v in self.metrics.items():
            if k.startswith("overhead.") and isinstance(v, (int, float)):
                out[k.removeprefix("overhead.")] = v
        return out

    def wall_breakdown(self) -> Dict[str, Any]:
        """Decompose the end-to-end query wall into named, summing
        categories (the fixed-overhead-tail view, ROADMAP item 1):

          device_compute_ms  measured wall inside compiled segments,
                             net of the per-dispatch floor
          dispatch_ms        measured per-backend dispatch floor x
                             program launches
          seam_ms            host sync + re-bucket at every
                             SplitCompiledPlan boundary
          compile_ms         trace+compile span union (in-wall)
          fetch_ms           d2h/h2d transition span union (seams
                             excluded — they have their own line)
          shuffle_ms         shuffle span union
          host_prep_ms       in-wall setup before execution
          unattributed_ms    the residual

        `pad_waste_ms`/`pad_rows` ride along as informational fields: the
        bucket-quantization tax is a SLICE of device_compute_ms, not an
        additive category.  `plan_ms` and `semaphore_wait_ms` happen
        before the query span opens and are reported as pre-wall lines.
        Works from a live context or an event log; dispatch/pad fields
        populate only on profiled (profile.segments) runs."""
        roots = [s for s in self.spans if s.cat == "query"]
        q0 = min((s.t0 for s in roots), default=None)
        q1 = max((s.t1 for s in roots), default=None)
        wall = self.wall_ms()
        ov = self.overheads()

        def cat_union(cat: str, exclude_name: Optional[str] = None
                      ) -> float:
            ivals = []
            for s in self.spans:
                if s.cat != cat or \
                        (exclude_name and s.name == exclude_name):
                    continue
                t0, t1 = s.t0, s.t1
                if q0 is not None:
                    t0, t1 = max(t0, q0), min(t1, q1)
                if t1 > t0:
                    ivals.append((t0, t1))
            return _union_ms(ivals)

        seg_dev = sum(float(r.get("device_ms", 0.0))
                      for r in self.segments())
        dispatch_ms = float(ov.get("dispatch_ms", 0.0))
        if seg_dev <= 0.0:
            # unprofiled run: exec_device_ms is the dispatch wall; the
            # measured floor x launch count bounds its overhead share
            seg_dev = float(self.metrics.get("exec_device_ms", 0.0))
            floor = float(ov.get("dispatch_floor_ms", 0.0))
            if not dispatch_ms and floor:
                dispatch_ms = floor * float(
                    self.metrics.get("exec_dispatches", 0))
        dispatch_ms = min(dispatch_ms, seg_dev)
        pad_ms = min(float(ov.get("pad_waste_ms", 0.0)),
                     max(seg_dev - dispatch_ms, 0.0))
        seam_ms = float(ov.get("seam_ms", 0.0))
        cats = {
            "device_compute_ms": max(seg_dev - dispatch_ms, 0.0),
            "dispatch_ms": dispatch_ms,
            "seam_ms": seam_ms,
            "compile_ms": cat_union("compile"),
            "fetch_ms": cat_union("transition", exclude_name="seam"),
            "shuffle_ms": cat_union("shuffle"),
            "host_prep_ms": float(ov.get("host_prep_ms", 0.0)),
        }
        named = sum(cats.values())
        out: Dict[str, Any] = {"wall_ms": round(wall, 3)}
        out.update({k: round(v, 3) for k, v in cats.items()})
        out["unattributed_ms"] = round(max(wall - named, 0.0), 3)
        out["attributed_pct"] = round(100.0 * min(named / wall, 1.0), 1) \
            if wall > 0 else 0.0
        out["pad_waste_ms"] = round(float(ov.get("pad_waste_ms", 0.0)), 3)
        for k in ("pad_rows", "seam_count", "seam_rows", "seam_bytes"):
            if ov.get(k):
                out[k] = int(ov[k])
        if ov.get("dispatch_floor_ms"):
            out["dispatch_floor_ms"] = round(
                float(ov["dispatch_floor_ms"]), 4)
        n_disp = self.metrics.get("exec_dispatches")
        if n_disp:
            out["dispatches"] = int(n_disp)
        # pre-wall lines: planning and the device-permit queue wait both
        # happen before the query span opens
        out["plan_ms"] = round(sum(s.dur_ms for s in self.spans
                                   if s.cat == "plan"), 3)
        sem = self.metrics.get("semaphore_wait_ms")
        if sem:
            out["semaphore_wait_ms"] = round(float(sem), 3)
        return out

    def mesh_timeline(self) -> Dict[str, Any]:
        """Per-query mesh/collective timeline from the exchange
        instants (parallel/exchange.py): one record per ragged exchange
        call (round schedule, quotas, wire bytes pre/post compress,
        per-device arrival counts, per-round staging vs collective ms)
        plus one-time dictionary gathers and skew-split events."""
        exchanges: List[Dict[str, Any]] = []
        skew: List[Dict[str, Any]] = []
        cur: Optional[Dict[str, Any]] = None
        org = min([s.t0 for s in self.spans] +
                  [e.t for e in self.events], default=0.0)
        for e in self.events:
            t_ms = round((e.t - org) * 1e3, 3)
            if e.name == "ici_exchange":
                cur = {"kind": "exchange", "t_ms": t_ms, **e.attrs,
                       "round_events": []}
                exchanges.append(cur)
            elif e.name == "exchange_round" and cur is not None:
                cur["round_events"].append({"t_ms": t_ms, **e.attrs})
            elif e.name == "exchange_timing" and cur is not None:
                stage = e.attrs.get("stage_ms") or []
                coll = e.attrs.get("collective_ms") or []
                for rec, sm, cm in zip(cur["round_events"], stage, coll):
                    rec["stage_ms"] = sm
                    rec["collective_ms"] = cm
                cur["stage_ms_total"] = round(sum(stage), 3)
                cur["collective_ms_total"] = round(sum(coll), 3)
            elif e.name == "ici_dict_gather":
                exchanges.append({"kind": "dict_gather", "t_ms": t_ms,
                                  **e.attrs})
            elif e.name == "exchange_skew_split":
                skew.append({"t_ms": t_ms, **e.attrs})
        return {"exchanges": exchanges, "skew_splits": skew}

    def fallbacks(self) -> List[str]:
        return list(self.meta.get("fallbacks", []))

    def compile_stats(self) -> Dict[str, Any]:
        return {
            "cache_misses": int(self.metrics.get("compile_cache_misses",
                                                 0)),
            "cache_hits": int(self.metrics.get("compile_cache_hits", 0)),
            "compile_ms": round(float(self.metrics.get("compile_ms",
                                                       0.0)), 3),
        }

    def data_movement(self) -> Dict[str, int]:
        keys = ("h2d_bytes", "d2h_bytes", "shuffle_bytes_written",
                "shuffle_bytes_read", "ici_exchange_bytes")
        out = {}
        for k in keys:
            v = self.counters.get(k, self.metrics.get(k, 0))
            if v:
                out[k] = int(v)
        for k in ("h2d_rows", "d2h_rows", "shuffle_rows_written",
                  "shuffle_rows_read", "scanned_rows"):
            v = self.metrics.get(k)
            if v:
                out[k] = int(v)
        return out

    def memory(self) -> Dict[str, Any]:
        out = {}
        for k, v in self.metrics.items():
            if k.startswith("memory."):
                out[k.removeprefix("memory.")] = v
        return out

    # -- the memory-attribution plane (obs/memattr.py) ---------------------
    def hbm(self) -> Dict[str, Any]:
        """The query's measured-HBM view: the measured working set
        (memattr query peak / XLA memory_analysis floor), the budget
        peak reservation, residual-leak bytes and the per-segment
        memory table — empty for runs without the plane armed."""
        mem = self.memory()
        out: Dict[str, Any] = {}
        mws = mem.get("hbm_measured_working_set") \
            or self.metrics.get("exec_hbm_bytes")
        if mws:
            out["measured_working_set_bytes"] = int(mws)
        if mem.get("peak_bytes"):
            out["peak_reservation_bytes"] = int(mem["peak_bytes"])
        if mem.get("residual_naked_bytes"):
            out["residual_naked_bytes"] = int(mem["residual_naked_bytes"])
        if mem.get("hbm_census_skipped"):
            out["census_skipped"] = int(mem["hbm_census_skipped"])
        segs = [{k: s[k] for k in ("node", "hbm_bytes", "hbm_peak_bytes",
                                   "hbm_resident_pre") if k in s}
                for s in self.segments() if s.get("hbm_peak_bytes")]
        if segs:
            out["segments"] = segs
        return out

    def hbm_timeline(self) -> List[Dict[str, Any]]:
        """The per-query HBM timeline (reserve/release/spill/OOM
        watermarks + segment brackets with node attribution) embedded
        in the event-log meta by the memattr recorder."""
        tl = self.meta.get("hbm_timeline")
        return list(tl) if isinstance(tl, list) else []

    def incidents(self) -> Dict[str, int]:
        """Instant-event histogram: oom_retry / batch_split / spill /
        whole_plan_fallback / semaphore_wait counts."""
        out: Dict[str, int] = {}
        for e in self.events:
            out[e.name] = out.get(e.name, 0) + 1
        return out

    # -- presentation ------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        out = {"time_split": self.time_split(),
               "wall_breakdown": self.wall_breakdown(),
               "operators": self.operators(),
               "compile": self.compile_stats(),
               "data_movement": self.data_movement(),
               "memory": self.memory(),
               "incidents": self.incidents(),
               "fallbacks": self.fallbacks()}
        segs = self.segments()
        if segs:
            out["segments"] = segs
            pct = self.attributed_device_pct()
            if pct is not None:
                out["attributed_device_pct"] = round(pct * 100, 1)
        wpct = self.attributed_wall_pct()
        if wpct is not None:
            out["attributed_wall_pct"] = round(wpct * 100, 1)
        mesh = self.mesh_timeline()
        if mesh["exchanges"] or mesh["skew_splits"]:
            out["mesh_timeline"] = mesh
        hbm = self.hbm()
        if hbm:
            out["hbm"] = hbm
        tl = self.hbm_timeline()
        if tl:
            out["hbm_timeline"] = tl
        if self.registry:
            out["registry"] = self.registry
        if self.truncated:
            out["truncated"] = True
        return out

    def summary(self, top_n: int = 5) -> Dict[str, Any]:
        """Compact per-query embedding for BENCH_*.json."""
        ops = self.operators()
        out = {"time_split": self.time_split(),
               "wall_breakdown": self.wall_breakdown(),
               "top_operators": [
                   {"node": o["node"],
                    "self_time_ms": o["self_time_ms"],
                    "output_rows": o.get("output_rows", 0)}
                   for o in ops[:top_n]],
               "compile": self.compile_stats(),
               "data_movement": self.data_movement(),
               "memory_peak_bytes": self.memory().get("peak_bytes", 0),
               "incidents": self.incidents(),
               "fallback_count": len(self.fallbacks())}
        segs = self.segments()
        if segs:
            # the segment-level attribution rides into the bench record
            # so profile_diff.py / check_regression.py can cite the
            # regressed SEGMENT, not just the query
            out["segments"] = [
                {k: s[k] for k in ("node", "device_ms", "pct", "rows")
                 if k in s} for s in segs[:top_n]]
            pct = self.attributed_device_pct()
            if pct is not None:
                out["attributed_device_pct"] = round(pct * 100, 1)
        hbm = self.hbm()
        if hbm.get("peak_reservation_bytes"):
            # per-query HBM fields bench.py lifts into BENCH records so
            # check_regression.py can gate HBM-peak regressions
            out["hbm_peak_bytes"] = max(
                hbm["peak_reservation_bytes"],
                hbm.get("measured_working_set_bytes", 0))
        elif hbm.get("measured_working_set_bytes"):
            out["hbm_peak_bytes"] = hbm["measured_working_set_bytes"]
        if hbm.get("measured_working_set_bytes"):
            out["hbm_measured_working_set"] = \
                hbm["measured_working_set_bytes"]
        return out

    def render(self) -> str:
        """The human report: time split, top operators, fallbacks,
        memory high-water — the profiling-tool output."""
        split = self.time_split()
        lines = ["== query profile =="
                 + (" (TRUNCATED log — prefix only)"
                    if self.truncated else ""),
                 f"wall              {split['wall_ms']:.1f} ms",
                 f"  plan (pre-wall) {split['plan_ms']:.1f} ms",
                 f"  compile         {split['compile_ms']:.1f} ms",
                 f"  execute         {split['execute_ms']:.1f} ms",
                 f"  transition      {split['transition_ms']:.1f} ms",
                 f"  shuffle         {split['shuffle_ms']:.1f} ms"]
        cs = self.compile_stats()
        lines.append(f"compile cache     {cs['cache_hits']} hits / "
                     f"{cs['cache_misses']} misses")
        bd = self.wall_breakdown()
        if bd["wall_ms"] > 0:
            lines.extend(render_wall_breakdown(bd))
        if self.meta.get("stitched"):
            # a supervisor-side STITCHED pool record: render the cross-
            # process story — admission -> grant -> each execute attempt
            # (worker-named), with worker_lost instants marking redrives
            lines.append("-- stitched serving record "
                         f"(tenant={self.meta.get('tenant')}, "
                         f"status={self.meta.get('status')}, "
                         f"redrives={self.meta.get('redrives', 0)}) --")
            losses = {(e.attrs or {}).get("attempt"): e.attrs or {}
                      for e in self.events if e.name == "worker_lost"}
            for s in sorted(self.spans, key=lambda s: s.t0):
                if s.cat not in ("serving", "execute"):
                    continue
                extra = ""
                if s.cat == "execute":
                    a = s.attrs or {}
                    if "lost" in a:
                        extra = f"  ! LOST ({a['lost']}) -> redrive"
                    elif a.get("device_us") is not None:
                        extra = f"  device_us={a['device_us']}"
                lines.append(f"  {s.name:<24} {s.dur_ms:>9.1f} ms"
                             f"{extra}")
            if losses:
                lines.append(f"  workers: "
                             f"{self.meta.get('workers')} "
                             f"(answered by {self.meta.get('worker')})")
            wp = self.meta.get("worker_profile") or {}
            if wp:
                hbm = wp.get("hbm") or {}
                lines.append(
                    f"  worker profile: {wp.get('worker')} "
                    f"pid={wp.get('pid')} "
                    f"device_us={wp.get('device_us')} "
                    f"hbm_live={hbm.get('live_bytes', 0)} "
                    f"hbm_peak={hbm.get('peak_bytes', 0)}")
        ops = self.operators()
        if ops:
            lines.append("-- top operators (self time) --")
            for o in ops[:10]:
                lines.append(
                    f"  {o['node']:<32} {o['self_time_ms']:>9.1f} ms  "
                    f"rows={o.get('output_rows', 0)} "
                    f"batches={o.get('output_batches', 0)}")
        segs = self.segments()
        if segs:
            pct = self.attributed_device_pct()
            hdr = "-- segments (measured device time) --"
            if pct is not None:
                hdr += f"  [{pct * 100:.1f}% of device wall attributed]"
            lines.append(hdr)
            for sg in segs[:10]:
                rng = ""
                if sg.get("node_lo") is not None:
                    rng = f" nodes #{sg['node_lo']}-#{sg.get('node_hi')}"
                cost = ""
                if sg.get("flops"):
                    cost = f" flops={sg['flops']:.3g}"
                lines.append(
                    f"  {sg['node']:<32} {sg['device_ms']:>9.1f} ms "
                    f"({sg['pct']:>5.1f}%) rows={sg.get('rows', 0)}"
                    f"{rng}{cost}")
        mesh = self.mesh_timeline()
        if mesh["exchanges"]:
            lines.append("-- mesh timeline --")
            for ex in mesh["exchanges"][:12]:
                if ex.get("kind") == "dict_gather":
                    lines.append(f"  dict_gather bytes={ex.get('bytes', 0)}")
                    continue
                lines.append(
                    f"  exchange rounds={ex.get('rounds', 0)} "
                    f"quota={ex.get('quota', 0)} "
                    f"bytes={ex.get('bytes', 0)} "
                    f"(pre={ex.get('bytes_pre_compress', 0)}) "
                    f"stage={ex.get('stage_ms_total', 0)}ms "
                    f"collective={ex.get('collective_ms_total', 0)}ms")
            if mesh["skew_splits"]:
                lines.append(f"  skew splits: {len(mesh['skew_splits'])}")
        hbm = self.hbm()
        if hbm:
            lines.append("-- hbm (memory attribution) --")
            if hbm.get("measured_working_set_bytes"):
                lines.append(f"  measured working set    "
                             f"{hbm['measured_working_set_bytes']} bytes")
            if hbm.get("peak_reservation_bytes"):
                lines.append(f"  peak budget reservation "
                             f"{hbm['peak_reservation_bytes']} bytes")
            if hbm.get("residual_naked_bytes"):
                lines.append(f"  ! RESIDUAL LEAK         "
                             f"{hbm['residual_naked_bytes']} bytes of "
                             f"naked reservations at query end")
            if hbm.get("census_skipped"):
                lines.append(f"  (census samples skipped: "
                             f"{hbm['census_skipped']})")
            for sg in hbm.get("segments", [])[:10]:
                lines.append(
                    f"  {sg['node']:<32} hbm_peak="
                    f"{sg.get('hbm_peak_bytes', 0)} "
                    f"analysis={sg.get('hbm_bytes', 0)} "
                    f"resident_pre={sg.get('hbm_resident_pre', 0)}")
            tl = self.hbm_timeline()
            if tl:
                by_ev: Dict[str, int] = {}
                for e in tl:
                    by_ev[e.get("ev", "?")] = by_ev.get(
                        e.get("ev", "?"), 0) + 1
                peak_ev = max(tl, key=lambda e: e.get("live", 0))
                lines.append(
                    f"  timeline: {len(tl)} events ("
                    + ", ".join(f"{k}={v}" for k, v in sorted(by_ev.items()))
                    + f"); watermark peak {peak_ev.get('live', 0)} bytes"
                    + (f" at t={peak_ev.get('t_ms', 0)}ms"
                       f" node={peak_ev.get('node')}"
                       if peak_ev.get("node") else ""))
        dm = self.data_movement()
        if dm:
            lines.append("-- data movement --")
            for k, v in dm.items():
                lines.append(f"  {k:<24} {v}")
        mem = self.memory()
        if mem:
            lines.append(f"memory high-water {mem.get('peak_bytes', 0)} "
                         f"bytes; spilled {mem.get('spilled_batches', 0)} "
                         f"batches / {mem.get('spilled_bytes', 0)} bytes")
        inc = self.incidents()
        if inc:
            lines.append("-- incidents --")
            for k, v in sorted(inc.items()):
                lines.append(f"  {k:<24} {v}")
        fb = self.fallbacks()
        lines.append(f"-- fallbacks ({len(fb)}) --")
        for r in fb:
            lines.append(f"  ! {r}")
        if self.registry:
            # the always-on plane's state at log-write time, largest
            # counters first (docs/METRICS.md catalog)
            lines.append("-- metrics registry (process, at log write) --")
            scalars = [(k, v) for k, v in self.registry.items()
                       if isinstance(v, (int, float))]
            for k, v in sorted(scalars, key=lambda kv: -abs(kv[1]))[:12]:
                lines.append(f"  {k:<52} {round(v, 3)}")
            if len(scalars) > 12:
                lines.append(f"  ... {len(scalars) - 12} more series")
        return "\n".join(lines)


#: wall-breakdown category -> report label, render order
_BREAKDOWN_LABELS = (
    ("device_compute_ms", "device compute"),
    ("dispatch_ms", "dispatch overhead"),
    ("seam_ms", "seam time"),
    ("compile_ms", "compile"),
    ("fetch_ms", "fetch/upload"),
    ("shuffle_ms", "shuffle"),
    ("host_prep_ms", "host prep"),
    ("unattributed_ms", "unattributed"),
)


def render_wall_breakdown(bd: Dict[str, Any]) -> List[str]:
    """Text lines for one wall_breakdown() dict — shared by
    QueryProfile.render() and EXPLAIN ANALYZE (obs/attribution.py)."""
    wall = bd.get("wall_ms") or 0.0
    lines = [f"-- wall breakdown (end-to-end, {wall:.1f} ms, "
             f"{bd.get('attributed_pct', 0.0):.1f}% attributed) --"]
    for key, label in _BREAKDOWN_LABELS:
        v = float(bd.get(key, 0.0))
        pct = 100.0 * v / wall if wall else 0.0
        extra = ""
        if key == "device_compute_ms" and bd.get("pad_waste_ms"):
            extra = (f"  [pad waste {bd['pad_waste_ms']:.2f} ms over "
                     f"{bd.get('pad_rows', 0)} pad rows]")
        elif key == "dispatch_ms" and bd.get("dispatch_floor_ms"):
            extra = (f"  [floor {bd['dispatch_floor_ms']:.3f} ms x "
                     f"{bd.get('dispatches', 0)} dispatches]")
        elif key == "seam_ms" and bd.get("seam_count"):
            extra = (f"  [{bd['seam_count']} seams, "
                     f"{bd.get('seam_rows', 0)} rows, "
                     f"{bd.get('seam_bytes', 0)} bytes re-bucketed]")
        lines.append(f"  {label:<18} {v:>9.2f} ms ({pct:>5.1f}%){extra}")
    pre = [f"plan {bd.get('plan_ms', 0.0):.1f} ms"]
    if bd.get("semaphore_wait_ms"):
        pre.append(f"queue wait {bd['semaphore_wait_ms']:.1f} ms")
    lines.append("  (pre-wall: " + ", ".join(pre) + ")")
    return lines
