"""Process-wide always-on metrics registry — the Spark metrics-sink role.

Reference (SURVEY §5): the plugin surfaces per-operator GPU metrics
through Spark's *always-on* metric sinks and the history server, not
just opt-in traces.  The query tracer (obs/tracer.py, OFF by default)
covers the per-query deep dive; this registry is the complement: one
process-wide `MetricsRegistry` that every runtime subsystem publishes
into unconditionally — visible between queries, across queries and at
crash time (runtime/failure.py embeds a snapshot in crash dumps).

Three metric kinds, Prometheus-shaped:

  * Counter   — monotonically increasing totals (`.inc`);
  * Gauge     — point-in-time levels (`.set`) and high-waters (`.max`);
  * Histogram — bounded log2-bucket distributions (`.observe`): bucket
    `i` counts values in (2^(i-1), 2^i], so a byte-skew or wait-time
    distribution costs at most `_MAX_BUCKET`+1 integers per series,
    never a per-observation list.

Series carry labels (query id, device index, operator class, ...).
Label cardinality is BOUNDED: past `max_series` distinct label sets per
metric, further sets collapse into one `~overflow` series, so a label
mistake (or a million query ids) cannot grow memory — the registry is
fixed-cost by construction, which is what lets it stay always-on.

Export lives in obs/export.py (JSONL heartbeat + Prometheus text
endpoint); `spark.rapids.tpu.metrics.enabled=false` turns every publish
call into one attribute check for A/B overhead runs.

Every family registered here must be documented in docs/METRICS.md —
scripts/check_docs.py lints `REGISTRY.family_names()` against it.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

#: log2 buckets 0..50: bucket 0 is (-inf, 1], bucket i is (2^(i-1), 2^i];
#: 2^50 covers a petabyte of bytes or ~35 years of milliseconds
_MAX_BUCKET = 50

#: label-set value a metric's series collapse into past max_series
OVERFLOW = "~overflow"


def bucket_index(v: float) -> int:
    """Log2 bucket of one observation (shared with tests: the
    independently-computed distributions use this same mapping)."""
    if v <= 1:
        return 0
    n = int(v) if float(v).is_integer() else int(v) + 1
    return min((n - 1).bit_length(), _MAX_BUCKET)


def bucket_le(i: int) -> int:
    """Inclusive upper bound of bucket `i` (the Prometheus `le`)."""
    return 1 << i if i else 1


class _HistogramState:
    __slots__ = ("count", "sum", "buckets")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.buckets: Dict[int, int] = {}


class Metric:
    """One metric family: a name + kind + label names, holding every
    labeled series.  Publish methods are self-locking and no-op when
    the owning registry is disabled."""

    def __init__(self, registry: "MetricsRegistry", name: str, kind: str,
                 help_: str, labelnames: Tuple[str, ...]):
        self._reg = registry
        self.name = name
        self.kind = kind
        self.help = help_
        self.labelnames = tuple(labelnames)
        self._series: Dict[tuple, Any] = {}
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, Any]) -> tuple:
        key = tuple(str(labels.get(n, "")) for n in self.labelnames)
        if key not in self._series and \
                len(self._series) >= self._reg.max_series:
            # bounded cardinality: late label sets share one series
            return tuple(OVERFLOW for _ in self.labelnames)
        return key

    # -- publish (each checks the registry's enabled flag first) ----------
    def inc(self, v: float = 1, **labels) -> None:
        if not self._reg.enabled:
            return
        with self._lock:
            k = self._key(labels)
            self._series[k] = self._series.get(k, 0) + v

    def set(self, v: float, **labels) -> None:
        if not self._reg.enabled:
            return
        with self._lock:
            self._series[self._key(labels)] = v

    def max(self, v: float, **labels) -> None:
        """High-water update: keep the larger of current and `v`."""
        if not self._reg.enabled:
            return
        with self._lock:
            k = self._key(labels)
            if v > self._series.get(k, float("-inf")):
                self._series[k] = v

    def add(self, v: float, **labels) -> None:
        """Gauge delta (active-count style: add(+1)/add(-1))."""
        if not self._reg.enabled:
            return
        with self._lock:
            k = self._key(labels)
            self._series[k] = self._series.get(k, 0) + v

    def observe(self, v: float, **labels) -> None:
        if not self._reg.enabled:
            return
        with self._lock:
            k = self._key(labels)
            st = self._series.get(k)
            if st is None:
                st = self._series[k] = _HistogramState()
            st.count += 1
            st.sum += float(v)
            i = bucket_index(v)
            st.buckets[i] = st.buckets.get(i, 0) + 1

    def set_histogram(self, count: int, sum_: float, buckets,
                      **labels) -> None:
        """Cumulative SET of one histogram series from a snapshot's
        `[[le, count], ...]` bucket list — the federation fold: a worker
        ships its full histogram state each heartbeat and set semantics
        make a dropped frame self-heal on the next one."""
        if not self._reg.enabled:
            return
        st = _HistogramState()
        st.count = int(count)
        st.sum = float(sum_)
        for le, c in buckets or ():
            i = int(le).bit_length() - 1 if int(le) > 1 else 0
            st.buckets[i] = int(c)
        with self._lock:
            self._series[self._key(labels)] = st

    # -- read -------------------------------------------------------------
    def value(self, **labels):
        """Current value of one series (0 / None when never published)."""
        key = tuple(str(labels.get(n, "")) for n in self.labelnames)
        with self._lock:
            v = self._series.get(key)
        if isinstance(v, _HistogramState):
            return {"count": v.count, "sum": v.sum,
                    "buckets": dict(v.buckets)}
        return 0 if v is None and self.kind == "counter" else v

    def series(self) -> List[dict]:
        with self._lock:
            items = list(self._series.items())
        out = []
        for key, v in items:
            labels = dict(zip(self.labelnames, key))
            if isinstance(v, _HistogramState):
                out.append({"labels": labels, "count": v.count,
                            "sum": v.sum,
                            "buckets": [[bucket_le(i), c] for i, c in
                                        sorted(v.buckets.items())]})
            else:
                out.append({"labels": labels, "value": v})
        return out


class MetricsRegistry:
    """The process-wide family registry (one global `REGISTRY` below;
    independent instances exist only for tests)."""

    def __init__(self, max_series: int = 64):
        self.enabled = True
        self.max_series = max_series
        self._families: Dict[str, Metric] = {}
        self._lock = threading.Lock()

    def _register(self, name: str, kind: str, help_: str,
                  labelnames: Tuple[str, ...]) -> Metric:
        with self._lock:
            m = self._families.get(name)
            if m is not None:
                if m.kind != kind or m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} re-registered with a different "
                        f"shape ({m.kind}{m.labelnames} vs "
                        f"{kind}{tuple(labelnames)})")
                return m
            m = Metric(self, name, kind, help_, tuple(labelnames))
            self._families[name] = m
            return m

    def counter(self, name: str, help_: str, labelnames=()) -> Metric:
        return self._register(name, "counter", help_, tuple(labelnames))

    def gauge(self, name: str, help_: str, labelnames=()) -> Metric:
        return self._register(name, "gauge", help_, tuple(labelnames))

    def histogram(self, name: str, help_: str, labelnames=()) -> Metric:
        return self._register(name, "histogram", help_, tuple(labelnames))

    def family_names(self) -> List[str]:
        with self._lock:
            return sorted(self._families)

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._families.get(name)

    def reset(self) -> None:
        """Drop every series (families stay registered) — test isolation
        for exact-distribution assertions."""
        with self._lock:
            fams = list(self._families.values())
        for m in fams:
            with m._lock:
                m._series.clear()

    # -- export -----------------------------------------------------------
    def snapshot(self) -> dict:
        """Structured snapshot: every family with its labeled series."""
        with self._lock:
            fams = list(self._families.values())
        return {"ts": time.time(),
                "enabled": self.enabled,
                "families": [{"name": m.name, "kind": m.kind,
                              "help": m.help,
                              "labels": list(m.labelnames),
                              "series": m.series()}
                             for m in fams if m.series()]}

    def flat(self) -> Dict[str, Any]:
        """Compact `name{a=b}` -> value view (heartbeat lines, bench
        embedding, event-log query_end records).  Histograms flatten to
        `.count` / `.sum` entries."""
        out: Dict[str, Any] = {}
        for fam in self.snapshot()["families"]:
            for s in fam["series"]:
                lbl = ",".join(f"{k}={v}" for k, v in s["labels"].items()
                               if v != "")
                key = f"{fam['name']}{{{lbl}}}" if lbl else fam["name"]
                if "value" in s:
                    out[key] = s["value"]
                else:
                    out[key + ".count"] = s["count"]
                    out[key + ".sum"] = round(s["sum"], 3)
        return out

    def prometheus_text(self) -> str:
        """Prometheus exposition text format (served by the stdlib HTTP
        endpoint, obs/export.py)."""
        lines: List[str] = []
        for fam in self.snapshot()["families"]:
            name = fam["name"]
            lines.append(f"# HELP {name} {fam['help']}")
            lines.append(f"# TYPE {name} {fam['kind']}")
            for s in fam["series"]:
                lbl = ",".join(f'{k}="{v}"'
                               for k, v in s["labels"].items())
                if "value" in s:
                    lines.append(f"{name}{{{lbl}}} {s['value']}"
                                 if lbl else f"{name} {s['value']}")
                    continue
                cum = 0
                for le, c in s["buckets"]:
                    cum += c
                    ls = (lbl + "," if lbl else "") + f'le="{le}"'
                    lines.append(f"{name}_bucket{{{ls}}} {cum}")
                ls = (lbl + "," if lbl else "") + 'le="+Inf"'
                lines.append(f"{name}_bucket{{{ls}}} {s['count']}")
                lines.append(f"{name}_sum{{{lbl}}} {s['sum']}"
                             if lbl else f"{name}_sum {s['sum']}")
                lines.append(f"{name}_count{{{lbl}}} {s['count']}"
                             if lbl else f"{name}_count {s['count']}")
        return "\n".join(lines) + "\n"


#: THE process-wide registry every subsystem publishes into
REGISTRY = MetricsRegistry()


# ---------------------------------------------------------------------------
# Metric catalog: central declarations so the full family set exists at
# import time (scripts/check_docs.py lints these names against
# docs/METRICS.md) and call sites share one handle per family.
# ---------------------------------------------------------------------------

QUERIES_TOTAL = REGISTRY.counter(
    "tpu_queries_total",
    "Completed query collects by terminal status and root plan kind.",
    ("status", "kind"))

ACTIVE_QUERIES = REGISTRY.gauge(
    "tpu_active_queries",
    "Queries currently inside their instrumented execution scope.")

QUERY_WALL_MS = REGISTRY.histogram(
    "tpu_query_wall_ms",
    "End-to-end wall milliseconds per query collect (log2 buckets).")

DATA_BYTES = REGISTRY.counter(
    "tpu_data_movement_bytes_total",
    "Bytes moved per channel (h2d, d2h, shuffle_write, shuffle_read, "
    "ici_exchange) — fed by every tracer byte-counter call site, "
    "tracing on or off.",
    ("channel",))

RUNTIME_EVENTS = REGISTRY.counter(
    "tpu_runtime_events_total",
    "Runtime incident instants (oom_retry, spill, batch_split, io_retry, "
    "semaphore_wait, fault_injected, ...) by event name and category.",
    ("event", "cat"))

HBM_LIVE_BYTES = REGISTRY.gauge(
    "tpu_hbm_live_bytes",
    "Device bytes currently admitted by the HBM budget, per device.",
    ("device",))

HBM_PEAK_BYTES = REGISTRY.gauge(
    "tpu_hbm_peak_bytes",
    "Process-lifetime high-water of budget-admitted device bytes, per "
    "device.",
    ("device",))

HOST_SPILL_LIVE_BYTES = REGISTRY.gauge(
    "tpu_host_spill_live_bytes",
    "Bytes currently resident in the host spill tier.")

SPILL_BATCHES = REGISTRY.counter(
    "tpu_spill_batches_total",
    "Batches demoted per tier (host = device->host, disk = host->disk).",
    ("tier",))

SPILL_BYTES = REGISTRY.counter(
    "tpu_spill_bytes_total",
    "Bytes demoted per tier (host = device->host, disk = host->disk).",
    ("tier",))

SPILL_MS = REGISTRY.histogram(
    "tpu_spill_ms",
    "Milliseconds spent moving one spillable between tiers (op = spill "
    "| to_disk | read), log2 buckets — the spill wait-time histogram.",
    ("op",))

OOM_RETRIES = REGISTRY.counter(
    "tpu_oom_retries_total",
    "OOM-retry ladder replays (spill-everything-and-replay rungs).")

BATCH_SPLITS = REGISTRY.counter(
    "tpu_batch_splits_total",
    "Batches halved by the split-and-retry rung.")

IO_RETRIES = REGISTRY.counter(
    "tpu_io_retries_total",
    "Transient host-IO retries by injection/retry site.",
    ("site",))

RELEASE_UNDERFLOWS = REGISTRY.counter(
    "tpu_release_underflows_total",
    "Budget double-releases clamped to zero (should stay 0).")

SEMAPHORE_WAIT_MS = REGISTRY.histogram(
    "tpu_semaphore_wait_ms",
    "Milliseconds blocked acquiring a concurrentTpuTasks device permit, "
    "log2 buckets, one observation per acquisition.")

SHUFFLE_BYTES = REGISTRY.counter(
    "tpu_shuffle_bytes_total",
    "Serialized shuffle bytes by direction (written / read).",
    ("direction",))

SHUFFLE_PARTITION_BYTES = REGISTRY.histogram(
    "tpu_shuffle_partition_bytes",
    "Serialized bytes of each shuffle partition slice written by one "
    "map-task call, log2 buckets — the per-partition byte-skew "
    "distribution.")

ICI_EXCHANGE_BYTES = REGISTRY.counter(
    "tpu_ici_exchange_bytes_total",
    "Total post-compression wire bytes the mesh ships through ragged "
    "all_to_all exchange rounds and one-time dictionary gathers, summed "
    "across devices (masked slots transit too) — emitted once per "
    "exchange, off the per-device hot path.")

EXCHANGE_WIRE_PRE = REGISTRY.counter(
    "tpu_exchange_wire_bytes_pre_compress_total",
    "Wire bytes the planned exchange rounds WOULD have shipped at the "
    "logical lane widths (flags as int8, full-width integers), summed "
    "across devices — the numerator baseline of the on-wire "
    "compression ratio (spark.rapids.tpu.exchange.compress.enabled).")

EXCHANGE_WIRE_POST = REGISTRY.counter(
    "tpu_exchange_wire_bytes_post_compress_total",
    "Wire bytes actually shipped after bit-packing flag lanes and "
    "frame-of-reference narrowing integer lanes, summed across devices "
    "— post/pre is the achieved on-wire compression ratio.")

EXCHANGE_ROUNDS = REGISTRY.histogram(
    "tpu_exchange_rounds",
    "all_to_all rounds per ragged exchange call (log2 buckets): the "
    "skew-aware quota scheduler's output — uniform exchanges land in "
    "bucket 1, a hot destination no longer inflates everyone's round "
    "count.")

OPERATOR_ROWS = REGISTRY.counter(
    "tpu_operator_output_rows_total",
    "Output rows per operator class (published at query end, after "
    "lazy device counts coerce).",
    ("op",))

OPERATOR_BATCHES = REGISTRY.counter(
    "tpu_operator_output_batches_total",
    "Output batches per operator class.",
    ("op",))

OPERATOR_TIME_MS = REGISTRY.counter(
    "tpu_operator_time_ms_total",
    "Operator wall milliseconds per operator class.",
    ("op",))

COMPILES_TOTAL = REGISTRY.counter(
    "tpu_compiles_total",
    "Whole-plan XLA compile-cache outcomes (hit / miss).",
    ("outcome",))

KERNEL_DISPATCH = REGISTRY.counter(
    "tpu_kernel_dispatch_total",
    "Operator dispatches onto the hand-written Pallas kernel tier "
    "(ops/pallas/), by kernel family (hash_probe_join, segagg, "
    "compact) and mode (compiled / interpret). Counted once per "
    "trace on the whole-plan path, once per batch eagerly.",
    ("kernel", "mode"))

KERNEL_FALLBACK = REGISTRY.counter(
    "tpu_kernel_fallback_total",
    "Dispatches that consulted the enabled Pallas kernel tier but fell "
    "back to the sort-based portable tier, by kernel family and reason "
    "(multi_lane, dense_domain, dense_matched, build_too_large, "
    "domain_too_large, float_exact, backend, oom). The 'oom' reason is "
    "the chaos-visible recovery rung: a kernel-site OOM sheds the query "
    "to the sort tier bit-identically instead of failing it.",
    ("kernel", "reason"))

ENCODED_DISPATCH = REGISTRY.counter(
    "tpu_encoded_dispatch_total",
    "Operator dispatches that stayed in the compressed domain "
    "(ops/encodings.py), by site (predicate_code, predicate_range, "
    "in_codes, predicate_narrow, arith_narrow, sort_codes, "
    "groupby_codes, narrow_upload, dict_sort_upload) and outcome "
    "(encoded = computed on codes/narrow lanes; decode = fell back to "
    "a rank-table/remap gather or full-width widen; oom_shed = a "
    "kernel-site chaos OOM shed the dispatch onto the decoded tier).",
    ("site", "outcome"))

DECODE_BYTES = REGISTRY.counter(
    "tpu_decode_bytes_total",
    "Bytes materialized by DECODING encoded columns (per-row rank/remap "
    "table gathers, full-width widens of FOR-narrowed lanes), by site — "
    "the volume the encoded-execution layer exists to shrink; counted "
    "at capacity scale when the decode is emitted into a program.",
    ("site",))

PLAN_CACHE = REGISTRY.counter(
    "tpu_plan_cache_total",
    "Process-wide whole-plan executable cache outcomes (canonical "
    "constant-lifted structure key, exec/compiled.py): hit = a query "
    "adopted another query's compiled program (literal-only variants, "
    "re-planned repeats); miss = a cacheable plan paid a fresh compile.",
    ("outcome",))

COMPILE_PERSISTENT_HITS = REGISTRY.counter(
    "tpu_compile_cache_persistent_hits_total",
    "XLA compiles served from the on-disk persistent compile cache "
    "(jax compilation cache under spark.rapids.tpu.compile.cacheDir's "
    "topology-scoped subdirectory).")

COMPILE_PERSISTENT_MISSES = REGISTRY.gauge(
    "tpu_compile_cache_persistent_misses",
    "XLA compiles that consulted the persistent cache and missed "
    "(requests minus hits — maintained as a gauge: +1 per cache-using "
    "compile request, -1 when the request resolves to a hit).  0 on a "
    "fully warmed process: the zero-XLA-compiles replay proof.")

COMPILE_BG_MS = REGISTRY.histogram(
    "tpu_compile_background_ms",
    "Wall milliseconds of each background compile-service task "
    "(speculative split-plan segment compiles, --compile-only warmup), "
    "log2 buckets (runtime/compile_service.py).")

SCAN_UPLOAD_EVICTIONS = REGISTRY.counter(
    "tpu_scan_upload_evictions_total",
    "Hot-table device uploads evicted from the byte-capped shared "
    "scan-upload cache (spark.rapids.tpu.sql.scan.uploadCacheBytes).")

FAULTS_INJECTED = REGISTRY.counter(
    "tpu_faults_injected_total",
    "Chaos-harness faults fired, by injection site and kind.",
    ("site", "kind"))

CRASH_DUMPS = REGISTRY.counter(
    "tpu_crash_dumps_total",
    "Fatal-device crash dumps written by runtime/failure.py.")

GATHER_ROWS = REGISTRY.counter(
    "tpu_gather_rows_total",
    "Row gathers performed per site (rows x columns, capacity-based): "
    "probe/build = join-side payload gathers, late = deferred columns "
    "resolved at a pipeline sink through composed row-id lanes "
    "(columnar/lanes.py).",
    ("site",))

GATHER_BYTES = REGISTRY.counter(
    "tpu_gather_bytes_total",
    "Bytes moved by row gathers per site (data + validity + hi lanes at "
    "batch capacity) — the dominant device cost of join pipelines.",
    ("site",))

DEFERRED_GATHERS = REGISTRY.counter(
    "tpu_deferred_gathers_total",
    "Payload-column gathers a join SKIPPED by emitting a thin batch "
    "(late materialization): the column rides as a row-id lane and "
    "materializes at the pipeline sink — or never, if nothing "
    "references it.")

SEGMENT_DEVICE_MS = REGISTRY.histogram(
    "tpu_segment_device_ms",
    "Measured device wall milliseconds per compiled plan segment "
    "(dispatch + block_until_ready), log2 buckets, labeled by the "
    "segment's root operator class — populated only when "
    "spark.rapids.tpu.profile.segments is on (the attribution plane, "
    "exec/compiled.py).",
    ("segment",))

SEGMENT_ROWS = REGISTRY.counter(
    "tpu_segment_out_rows_total",
    "Output rows per compiled plan segment (root operator class), "
    "counted at the segment boundary when "
    "spark.rapids.tpu.profile.segments is on.",
    ("segment",))

SEGMENT_HBM_PEAK = REGISTRY.histogram(
    "tpu_segment_hbm_peak_bytes",
    "Measured HBM working set per compiled plan segment: the larger "
    "of the program's XLA memory_analysis() bytes (arguments + output "
    "+ temp + generated code) and the budget peak delta observed "
    "across its dispatch window, log2 buckets, labeled by the "
    "segment's root operator class — populated only when "
    "spark.rapids.tpu.profile.segments is on (the memory-attribution "
    "plane, obs/memattr.py).",
    ("segment",))

OVERHEAD_MS = REGISTRY.histogram(
    "tpu_overhead_ms",
    "Per-query wall milliseconds attributed to a fixed-overhead "
    "category by the wall-decomposition plane (exec/compiled.py, "
    "obs/profile.py wall_breakdown): `dispatch` = measured per-backend "
    "dispatch floor x program launches, `seam` = host sync + re-bucket "
    "at every SplitCompiledPlan boundary, `pad_waste` = the "
    "bucket-quantization tax (padded-minus-live rows priced at the "
    "segment's per-row device cost).  One observation per finished "
    "query per nonzero category, log2 buckets.",
    ("category",))

PAD_ROWS = REGISTRY.counter(
    "tpu_pad_rows_total",
    "Padded-minus-live rows per site: `upload` counts padding added "
    "when host batches are bucketed onto the device "
    "(columnar/device.py to_device, always-on), `segment` counts the "
    "padded input rows each profiled compiled-segment dispatch "
    "computed over (exec/compiled.py).",
    ("site",))

PAD_WASTE_MS = REGISTRY.histogram(
    "tpu_pad_waste_ms",
    "Estimated device milliseconds a profiled compiled segment spent "
    "computing over padding (device wall x padded input fraction), "
    "log2 buckets, labeled by the segment's root operator class — "
    "populated only when spark.rapids.tpu.profile.segments is on.",
    ("segment",))

HBM_RESIDUAL = REGISTRY.counter(
    "tpu_hbm_residual_bytes",
    "Naked (directly reserved, non-Spillable) budget bytes still live "
    "at query end — the leak check (obs/memattr.py): every completed "
    "query whose direct reserve/release pairs did not balance adds "
    "its residual here and flags memory.residual_naked_bytes in the "
    "profile.  Should stay 0.")

HBM_PREDICTION_ERROR = REGISTRY.histogram(
    "tpu_hbm_prediction_error_ratio",
    "Working-set-prediction calibration of the admission oracle: one "
    "observation per executed query that carried an admission-time "
    "working_set_bytes prediction, of max(predicted, measured) / "
    "min(predicted, measured) HBM bytes (>= 1; 1 = perfect), log2 "
    "buckets, labeled by estimate basis — the reservation-vs-actual "
    "curve scripts/history_report.py renders offline.",
    ("basis",))

SERVING_QUEUE_DEPTH = REGISTRY.gauge(
    "tpu_serving_queue_depth",
    "Admitted-but-unfinished queries in the ServingRuntime (the bounded "
    "admission queue's current depth, serving/runtime.py).")

SERVING_ADMIT_WAIT_MS = REGISTRY.histogram(
    "tpu_serving_admission_wait_ms",
    "Milliseconds one submit() blocked for an admission slot, log2 "
    "buckets, one observation per successful admission — queue "
    "backpressure shows up in the tail.")

SERVING_TENANT_DEVICE_US = REGISTRY.counter(
    "tpu_serving_tenant_device_us_total",
    "Measured device-execute MICROseconds per serving tenant (integer, "
    "so concurrent publication order cannot perturb the total — the "
    "fair-share hammer asserts exact equality against per-ticket sums).",
    ("tenant",))

SERVING_QUERIES = REGISTRY.counter(
    "tpu_serving_queries_total",
    "Serving-plane queries by tenant and terminal status (ok | error | "
    "admission_timeout | cache_hit).",
    ("tenant", "status"))

SERVING_RESULT_CACHE = REGISTRY.counter(
    "tpu_serving_result_cache_total",
    "Plan+result cache outcomes (serving/cache.py): hit, miss, store, "
    "evict (byte-cap LRU), invalidate (source-table anchor died), "
    "corrupt (checksum verification rejected a damaged payload — "
    "treated as a miss and recomputed).",
    ("outcome",))

SERVING_DEVICE_BUSY_US = REGISTRY.counter(
    "tpu_serving_device_busy_us_total",
    "Microseconds a serving device-execute grant was active (summed "
    "across slots) — device utilization is this over wall time, the "
    "overlap-is-real number bench.py --serving reports.")

HISTORY_RECORDS = REGISTRY.counter(
    "tpu_history_records_total",
    "Performance-history store outcomes per completed query "
    "(obs/history.py): ok = one JSONL record appended and folded into "
    "the structure's decay aggregate; io_error = the write failed (or "
    "a `history` chaos ioerror fired) and the entry was SKIPPED with "
    "the query unaffected; unkeyed = the plan produced no structure "
    "key (nothing recorded).",
    ("outcome",))

HISTORY_ESTIMATES = REGISTRY.counter(
    "tpu_history_estimates_total",
    "Cost-oracle estimate calls by basis (obs/estimator.py): "
    "exact_history = the structure key hit the persistent history and "
    "the decay-weighted measurement answered; static_cost = never-seen "
    "structure, answered from the static source-byte cost scaled by "
    "the continuously-fitted us-per-byte coefficient — the per-basis "
    "hit/miss/fallback counters of the admission oracle.",
    ("basis",))

HISTORY_PREDICTION_ERROR = REGISTRY.histogram(
    "tpu_history_prediction_error_ratio",
    "Prediction-vs-actual calibration of the cost oracle: one "
    "observation per executed query that carried an admission-time "
    "prediction, of max(predicted, measured) / min(predicted, "
    "measured) device-us (>= 1; 1 = perfect), log2 buckets, labeled "
    "by estimate basis — the how-wrong-is-the-oracle histogram "
    "stats(), the heartbeat and the Prometheus endpoint expose.",
    ("basis",))

SERVING_TENANT_PREDICTED_US = REGISTRY.counter(
    "tpu_serving_tenant_predicted_device_us_total",
    "Admission-time PREDICTED device microseconds per serving tenant "
    "(integer, summed over admitted queries) — read next to "
    "tpu_serving_tenant_device_us_total, the measured counter, for the "
    "per-tenant predicted-vs-measured calibration view.",
    ("tenant",))

SERVING_WORKERS_LIVE = REGISTRY.gauge(
    "tpu_serving_workers_live",
    "Live worker processes in the supervised serving pool "
    "(serving/workers.py): heartbeating and accepting dispatches. "
    "Dips below serving.pool.processes only for the crash-to-restart "
    "window.")

SERVING_WORKER_RESTARTS = REGISTRY.counter(
    "tpu_serving_worker_restarts_total",
    "Worker-process deaths handled by the supervisor, by reason: "
    "crash = the process died or its connection dropped (SIGKILL, "
    "segfault, injected worker:kill), hang = the heartbeat-miss window "
    "elapsed and the supervisor killed it, fatal = the worker "
    "self-terminated after a classified FATAL_DEVICE crash dump. Each "
    "death redrives the worker's in-flight queries; with pool.restart "
    "a replacement is spawned.",
    ("reason",))

SERVING_REDRIVES = REGISTRY.counter(
    "tpu_serving_redrives_total",
    "Queries re-dispatched onto a surviving worker after losing their "
    "worker process mid-flight (serving.redrive.maxAttempts bounds "
    "attempts per query; results stay bit-identical — queries are "
    "read-only and deterministic).",
    ("reason",))

SERVING_DEADLINE_CANCELS = REGISTRY.counter(
    "tpu_serving_deadline_cancellations_total",
    "Serving queries cancelled at a cooperative cancellation "
    "checkpoint: deadline = serving.deadlineMs (or the per-submit "
    "override) elapsed, injected = the deadline:timeout chaos site "
    "fired, drain = cancelled by an explicit cancel event. The "
    "cancelled ticket's full device reservation is released "
    "(DeviceCensus shows zero residual).",
    ("reason",))

SERVING_WORKER_HEARTBEATS = REGISTRY.counter(
    "tpu_serving_worker_heartbeats_total",
    "Worker-pool heartbeat frames the supervisor consumed (each "
    "carries the worker's pid, in-flight query and DeviceCensus "
    "live/peak bytes — the cross-process HBM picture admission "
    "reconciles against).")

DICT_REMAPS = REGISTRY.counter(
    "tpu_join_dict_remaps_total",
    "Host dictionary remap/unification computations (index_in + "
    "uniqueness unify). Cached per dictionary identity pair, so this "
    "counts cache MISSES — per-probe-batch recomputation regressions "
    "show up here.")

OOC_ELECTIONS = REGISTRY.counter(
    "tpu_ooc_elections_total",
    "Out-of-core tier elections by operator (join | agg | sort | "
    "query) and mode: bytes = the measured working set exceeded the "
    "resident window at execution time, rows = the legacy row-count "
    "gate tripped, forced = sql.ooc.force / an escalated context, "
    "proactive = the cost oracle's measured-basis working set elected "
    "OOC at plan time, admission = serving admitted an oversized query "
    "in OOC mode instead of running it solo, reactive = the "
    "TpuSplitAndRetryOOM ladder escalated into the OOC rung.",
    ("op", "mode"))

OOC_PARTITIONS = REGISTRY.counter(
    "tpu_ooc_partitions_total",
    "Spill partitions created by out-of-core join/aggregation passes "
    "(one increment per bucket per pass, recursive re-partitions "
    "included), by operator.",
    ("op",))

OOC_BYTES = REGISTRY.counter(
    "tpu_ooc_bytes_total",
    "Bytes routed through budget-registered spillable partitions by "
    "the out-of-core tier (both join sides, scattered aggregation "
    "partials), by operator — the degraded-but-running volume.",
    ("op",))

OOC_RECURSIONS = REGISTRY.counter(
    "tpu_ooc_recursions_total",
    "Out-of-core buckets that still exceeded the resident window and "
    "re-partitioned recursively with a re-salted hash (key skew), by "
    "operator.  Depth is bounded by sql.ooc.maxDepth; past it the "
    "split-retry ladder owns the remainder.",
    ("op",))


FLEET_FRAMES = REGISTRY.counter(
    "tpu_fleet_frames_total",
    "Heartbeat telemetry frames the supervisor processed into the "
    "fleet-view registry, by outcome: folded = the worker's registry "
    "snapshot merged into the per-worker tpu_fleet_* series, dropped = "
    "the frame was discarded whole (fleet chaos site: ioerror loses "
    "one frame, fatal additionally writes a classified dump; cumulative "
    "set semantics converge on the next beat either way), error = the "
    "snapshot failed to fold (malformed frame) and was skipped.",
    ("outcome",))


# ---------------------------------------------------------------------------
# Fleet-view registry (metrics federation, serving/workers.py).
#
# Worker heartbeat frames carry the worker's full cumulative
# REGISTRY.snapshot(); the supervisor folds each family into this
# SEPARATE registry under the name `tpu_fleet_` + <name minus tpu_> with
# a leading `worker` label.  Separate because (a) the per-worker shape
# (extra label) would collide with the supervisor's own identically-
# named families in one registry, and (b) these families are DYNAMIC —
# whatever the workers publish — so they stay out of the
# REGISTRY.family_names() docs lint.  Cumulative-SET folding makes the
# federation idempotent and self-healing: a dropped frame (fleet chaos
# site) just means the next beat lands the same-or-later totals, and
# per-worker counter series sum EXACTLY to the workers' own registries.
# ---------------------------------------------------------------------------

FLEET = MetricsRegistry(max_series=256)


def fleet_family_name(name: str) -> str:
    """`tpu_serving_x_total` -> `tpu_fleet_serving_x_total`."""
    return "tpu_fleet_" + (name[4:] if name.startswith("tpu_") else name)


def fold_fleet_snapshot(worker: str, snapshot: dict) -> None:
    """Fold one worker's cumulative registry snapshot into FLEET.
    Counters and gauges SET per-worker series; histograms set their
    full bucket state.  A family whose shape conflicts with an earlier
    fold is skipped — federation never raises into the reader loop."""
    for fam in (snapshot or {}).get("families") or ():
        try:
            name = fleet_family_name(fam["name"])
            kind = fam.get("kind") or "gauge"
            labelnames = ("worker",) + tuple(fam.get("labels") or ())
            reg = {"counter": FLEET.counter, "gauge": FLEET.gauge,
                   "histogram": FLEET.histogram}[kind]
            m = reg(name, fam.get("help", ""), labelnames)
        except (ValueError, KeyError, TypeError, AttributeError):
            continue
        for s in fam.get("series") or ():
            labels = dict(s.get("labels") or {})
            labels["worker"] = str(worker)
            try:
                if "value" in s:
                    m.set(s["value"], **labels)
                else:
                    m.set_histogram(s.get("count", 0), s.get("sum", 0.0),
                                    s.get("buckets"), **labels)
            except (TypeError, ValueError):
                continue


def drop_fleet_worker(worker: str) -> None:
    """A worker died: its GAUGE series (point-in-time state — HBM live,
    in-flight) died with the process, so drop them.  Counter and
    histogram series are CUMULATIVE WORK the fleet already did — they
    stay, and a restarted replacement publishes under a fresh worker
    id."""
    w = str(worker)
    for name in FLEET.family_names():
        m = FLEET.get(name)
        if m is None or m.kind != "gauge" or "worker" not in m.labelnames:
            continue
        widx = m.labelnames.index("worker")
        with m._lock:
            for key in [k for k in m._series if k[widx] == w]:
                del m._series[key]


_QUERY_SEQ_LOCK = threading.Lock()
_QUERY_SEQ = 0


def next_query_seq() -> int:
    """Process-monotonic query sequence number — the always-on query id
    the flight recorder tags lifecycle events with (the tracer's own
    query ids only exist when tracing is enabled)."""
    global _QUERY_SEQ
    with _QUERY_SEQ_LOCK:
        _QUERY_SEQ += 1
        return _QUERY_SEQ
