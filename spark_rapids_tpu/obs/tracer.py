"""Query-scoped span tracer — the NVTX-range + event-log role.

Reference: NvtxWithMetrics.scala wraps operator work in NVTX ranges nsys
consumes; Spark's event log feeds the history server and the RAPIDS
profiling tool replays it offline (SURVEY §5).  Here one `QueryTracer`
rides the ExecContext through a query: lifecycle phases (plan, compile,
execute, transitions, shuffle) record `Span`s, runtime incidents (OOM
retry, batch split, spill, semaphore wait, whole-plan fallback) record
instant events, and data-movement accounting (H2D/D2H/shuffle/ICI bytes)
accumulates in counters.

Serialization is two-way:
  * a JSONL structured event log per query under
    `spark.rapids.tpu.eventLog.dir` (`query_<id>.jsonl`) — parse it back
    with `read_event_log()`;
  * a Chrome trace-event JSON (`query_<id>.trace.json`) openable in
    perfetto / chrome://tracing.

Tracing is OFF by default (`NULL_TRACER` no-ops keep the disabled path
near-free); enable with `spark.rapids.tpu.trace.enabled` (in-memory, for
`TpuSession.last_query_profile()`) or by setting the event-log dir.
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from contextlib import contextmanager, nullcontext
from typing import Any, Dict, List, Optional

from ..config import EVENT_LOG_DIR, TRACE_ENABLED, TpuConf
from .recorder import FLIGHT_RECORDER
from .registry import DATA_BYTES, RUNTIME_EVENTS

#: tracer byte-counter key -> always-on registry data-movement channel
_BYTE_CHANNELS = {
    "h2d_bytes": "h2d",
    "d2h_bytes": "d2h",
    "shuffle_bytes_written": "shuffle_write",
    "shuffle_bytes_read": "shuffle_read",
    "ici_exchange_bytes": "ici_exchange",
}


def _publish_instant(name: str, cat: str, attrs: dict,
                     query=None) -> None:
    """Always-on half of every instant: the flight-recorder ring and
    the process registry see the incident whether or not a per-query
    tracer is collecting it."""
    FLIGHT_RECORDER.record("instant", name, cat, attrs, query=query)
    RUNTIME_EVENTS.inc(1, event=name, cat=cat)


def _publish_bytes(key: str, n: int) -> None:
    DATA_BYTES.inc(int(n), channel=_BYTE_CHANNELS.get(key, key))


@dataclasses.dataclass
class Span:
    """One timed range. t0/t1 are time.perf_counter() seconds; `node` is
    the stable plan-node id (`ClassName#preorder`) for operator spans."""
    sid: int
    parent: Optional[int]
    name: str
    cat: str                      # plan | compile | execute | operator |
                                  # transition | shuffle | query
    t0: float
    t1: float
    node: Optional[str] = None
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def dur_ms(self) -> float:
        return (self.t1 - self.t0) * 1000.0


@dataclasses.dataclass
class Event:
    """An instant incident (OOM retry, spill, fallback, ...)."""
    name: str
    cat: str
    t: float
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)


def _jsonable(v):
    """Numbers stay numbers (numpy scalars included), everything else
    stringifies — the event log must always serialize."""
    if isinstance(v, bool) or v is None or isinstance(v, (int, float, str)):
        return v
    item = getattr(v, "item", None)
    if item is not None:
        try:
            return item()
        except Exception:                        # noqa: BLE001
            pass
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    return str(v)


class QueryTracer:
    """Span/event/counter collector for ONE query execution.

    Thread-safe: shuffle writer/reader threads and spill workers record
    into the same tracer; parent attribution uses a per-thread span
    stack (a worker thread's spans parent to the root query span)."""

    def __init__(self, query_id: int):
        self.query_id = query_id
        self.enabled = True
        self.spans: List[Span] = []
        self.events: List[Event] = []
        self.counters: Dict[str, float] = {}
        self.meta: Dict[str, Any] = {}
        self.metrics: Optional[dict] = None   # bound to ctx.metrics
        self.wall_start_unix = time.time()
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._next_sid = 0
        self._root_sid: Optional[int] = None

    # -- recording ---------------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _parent(self) -> Optional[int]:
        st = self._stack()
        return st[-1] if st else self._root_sid

    def add_span(self, name: str, cat: str, t0: float, t1: float,
                 node: Optional[str] = None, parent: Optional[int] = None,
                 **attrs) -> Span:
        """Record an already-measured range (operator wrappers time
        themselves and report at stream exhaustion)."""
        with self._lock:
            sid = self._next_sid
            self._next_sid += 1
            sp = Span(sid, parent if parent is not None else self._parent(),
                      name, cat, t0, t1, node,
                      {k: _jsonable(v) for k, v in attrs.items()})
            self.spans.append(sp)
        FLIGHT_RECORDER.record(
            "span", name, cat,
            {"dur_ms": round(sp.dur_ms, 3),
             **({"node": node} if node else {})}, query=self.query_id)
        return sp

    @contextmanager
    def span(self, name: str, cat: str, node: Optional[str] = None,
             **attrs):
        """Time a range; nested spans parent to it (per-thread)."""
        t0 = time.perf_counter()
        with self._lock:
            sid = self._next_sid
            self._next_sid += 1
        parent = self._parent()
        if cat == "query" and self._root_sid is None:
            self._root_sid = sid
        self._stack().append(sid)
        try:
            yield
        finally:
            self._stack().pop()
            t1 = time.perf_counter()
            with self._lock:
                self.spans.append(Span(
                    sid, parent, name, cat, t0, t1, node,
                    {k: _jsonable(v) for k, v in attrs.items()}))
            FLIGHT_RECORDER.record(
                "span", name, cat,
                {"dur_ms": round((t1 - t0) * 1e3, 3),
                 **({"node": node} if node else {})},
                query=self.query_id)

    def instant(self, name: str, cat: str, **attrs) -> None:
        with self._lock:
            self.events.append(Event(name, cat, time.perf_counter(),
                                     {k: _jsonable(v)
                                      for k, v in attrs.items()}))
        _publish_instant(name, cat, attrs, query=self.query_id)

    def add_bytes(self, key: str, n: int) -> None:
        with self._lock:
            self.counters[key] = self.counters.get(key, 0) + int(n)
        _publish_bytes(key, n)

    def finish(self, metrics: Optional[dict] = None) -> None:
        """Snapshot the query's final metrics (call after lazy device
        metric coercion so every value is a host number)."""
        if metrics is not None:
            snap = {k: _jsonable(v) for k, v in metrics.items()}
            with self._lock:
                self.metrics = snap

    # -- serialization -----------------------------------------------------
    def _origin(self) -> float:
        ts = [s.t0 for s in self.spans] + [e.t for e in self.events]
        return min(ts) if ts else 0.0

    def to_jsonl_lines(self) -> List[str]:
        """The structured event log: one JSON object per line, starting
        with a query_start header and ending with query_end (metrics +
        counters + meta)."""
        org = self._origin()
        lines = [json.dumps({
            "type": "query_start", "query_id": self.query_id,
            "wall_start_unix": self.wall_start_unix})]
        for s in sorted(self.spans, key=lambda s: s.t0):
            rec = {"type": "span", "id": s.sid, "parent": s.parent,
                   "name": s.name, "cat": s.cat,
                   "t0_ms": round((s.t0 - org) * 1e3, 3),
                   "dur_ms": round(s.dur_ms, 3)}
            if s.node is not None:
                rec["node"] = s.node
            if s.attrs:
                rec["attrs"] = s.attrs
            lines.append(json.dumps(rec))
        for e in self.events:
            rec = {"type": "instant", "name": e.name, "cat": e.cat,
                   "t_ms": round((e.t - org) * 1e3, 3)}
            if e.attrs:
                rec["attrs"] = e.attrs
            lines.append(json.dumps(rec))
        from .registry import REGISTRY
        lines.append(json.dumps(_jsonable({
            "type": "query_end", "query_id": self.query_id,
            "metrics": self.metrics or {}, "counters": self.counters,
            "meta": self.meta,
            # the process metrics-plane snapshot at log-write time, so
            # one event log is post-mortem self-contained
            "registry": REGISTRY.flat()})))
        return lines

    def to_chrome_trace(self) -> dict:
        """Chrome trace-event JSON (ph=X complete events, ph=i instants)
        — open in perfetto.  Operator spans get their own tid so the
        per-node lanes render side by side."""
        org = self._origin()
        tids = {}                # node id -> stable small tid

        def tid_for(s: Span) -> int:
            if s.node is None:
                return 0
            return tids.setdefault(s.node, len(tids) + 1)

        evs = [{"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
                "args": {"name": f"query_{self.query_id}"}}]
        for s in sorted(self.spans, key=lambda s: s.t0):
            evs.append({"name": s.name, "cat": s.cat, "ph": "X",
                        "ts": round((s.t0 - org) * 1e6, 1),
                        "dur": round((s.t1 - s.t0) * 1e6, 1),
                        "pid": 1, "tid": tid_for(s),
                        "args": {**s.attrs,
                                 **({"node": s.node} if s.node else {})}})
        for e in self.events:
            evs.append({"name": e.name, "cat": e.cat, "ph": "i",
                        "ts": round((e.t - org) * 1e6, 1), "pid": 1,
                        "tid": 0, "s": "p", "args": e.attrs})
        return {"traceEvents": evs, "displayTimeUnit": "ms"}

    def write(self, dir_path: str) -> Dict[str, str]:
        """Write both artifacts under dir_path; returns their paths.

        Collision-proof: query ids are process-unique and monotonic
        (make_tracer allocates under one lock), but several PROCESSES —
        or a process restart — may share one event-log directory, so an
        existing `query_<id>.jsonl` gets a monotonic `-<n>` suffix
        instead of being overwritten (the crash-dump filename rule,
        runtime/failure.py)."""
        os.makedirs(dir_path, exist_ok=True)
        with _WRITE_LOCK:
            base = os.path.join(dir_path, f"query_{self.query_id}")
            n = 0
            while os.path.exists(base + ".jsonl"):
                n += 1
                base = os.path.join(
                    dir_path, f"query_{self.query_id}-{n}")
            jsonl = base + ".jsonl"
            with open(jsonl, "w") as f:
                f.write("\n".join(self.to_jsonl_lines()) + "\n")
        trace = base + ".trace.json"
        with open(trace, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        return {"jsonl": jsonl, "chrome_trace": trace}


@dataclasses.dataclass
class EventLog:
    """Parsed form of one query's JSONL event log."""
    query_id: int
    wall_start_unix: float
    spans: List[Span]
    events: List[Event]
    counters: Dict[str, float]
    metrics: Dict[str, Any]
    meta: Dict[str, Any]
    #: metrics-plane snapshot from the query_end record (PR 5)
    registry: Dict[str, Any] = dataclasses.field(default_factory=dict)
    #: the final line failed to parse (crash-time logs end mid-write);
    #: spans/events hold the intact prefix
    truncated: bool = False

    def span_tree(self) -> set:
        """Structural fingerprint for round-trip tests: one (id, parent,
        name, cat, node) tuple per span."""
        return {(s.sid, s.parent, s.name, s.cat, s.node)
                for s in self.spans}


def read_event_log(path: str) -> EventLog:
    """Parse a query_<id>.jsonl event log back into spans/events/metrics
    (the profiling tool's input — see scripts/profile_report.py).

    Crash-time logs end mid-write: a final line that fails to JSON-parse
    is tolerated — the intact prefix is returned with `truncated=True`
    instead of surfacing a raw json.JSONDecodeError.  A malformed line
    ANYWHERE ELSE still raises (that is corruption, not truncation)."""
    spans: List[Span] = []
    events: List[Event] = []
    qid, start = 0, 0.0
    counters: Dict[str, float] = {}
    metrics: Dict[str, Any] = {}
    meta: Dict[str, Any] = {}
    registry: Dict[str, Any] = {}
    truncated = False
    with open(path) as f:
        lines = [ln.strip() for ln in f]
    lines = [ln for ln in lines if ln]
    for i, line in enumerate(lines):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            if i == len(lines) - 1:
                truncated = True
                break
            raise
        typ = rec.get("type")
        if typ == "query_start":
            qid = rec.get("query_id", 0)
            start = rec.get("wall_start_unix", 0.0)
        elif typ == "span":
            t0 = rec.get("t0_ms", 0.0) / 1e3
            spans.append(Span(rec.get("id", len(spans)),
                              rec.get("parent"),
                              rec.get("name", "?"), rec.get("cat", "?"),
                              t0, t0 + rec.get("dur_ms", 0.0) / 1e3,
                              rec.get("node"), rec.get("attrs", {})))
        elif typ == "instant":
            events.append(Event(rec.get("name", "?"), rec.get("cat", "?"),
                                rec.get("t_ms", 0.0) / 1e3,
                                rec.get("attrs", {})))
        elif typ == "query_end":
            counters = rec.get("counters", {})
            metrics = rec.get("metrics", {})
            meta = rec.get("meta", {})
            registry = rec.get("registry", {})
    return EventLog(qid, start, spans, events, counters, metrics, meta,
                    registry=registry, truncated=truncated)


class NullTracer:
    """Disabled-path tracer: span collection is a no-op (no timing, no
    allocation — what keeps default-conf overhead under the <2% budget),
    but instants and byte counters still feed the ALWAYS-ON metrics
    plane (flight recorder + process registry, PR 5): incidents and
    data movement stay visible with tracing off, at the cost of one
    enabled-flag check plus a dict/deque append per event."""

    enabled = False
    metrics: Optional[dict] = None
    meta: Dict[str, Any] = {}
    _null_cm = nullcontext()

    def span(self, name: str, cat: str, node=None, **attrs):
        return self._null_cm

    def add_span(self, *a, **k):
        return None

    def instant(self, name: str, cat: str, **attrs) -> None:
        _publish_instant(name, cat, attrs)

    def add_bytes(self, key: str, n: int) -> None:
        _publish_bytes(key, n)

    def finish(self, *a, **k):
        return None


NULL_TRACER = NullTracer()

_QUERY_ID_LOCK = threading.Lock()
_NEXT_QUERY_ID = 0
_WRITE_LOCK = threading.Lock()

# The ACTIVE tracer: runtime subsystems that have no ExecContext in
# reach (shuffle manager threads, the ICI exchange, the retry/spill
# machinery) report here.  Set for the duration of a query's
# instrumented scope (plan/overrides.py); NULL outside it.
#
# Concurrency (the serving plane runs many instrumented scopes at once):
# the binding is THREAD-LOCAL — each query's own thread (semaphore
# waits, retry ladders, spill chains all run on it) always attributes to
# its own tracer, and one query finishing can no longer null out another
# query's active binding.  Threads with no binding of their own (shared
# shuffle/spill/compile pool workers) fall back to the single active
# tracer when exactly ONE query is in scope process-wide — the
# single-query behavior every existing call site was built on — and to
# NULL_TRACER when several are (ambiguous attribution is dropped, never
# misassigned; the always-on registry still sees those events).
_TLS_ACTIVE = threading.local()
_ACTIVE_LOCK = threading.Lock()
_ACTIVE_SET: dict = {}            # id(tracer) -> tracer, currently in scope
_FALLBACK: object = NULL_TRACER   # the unique in-scope tracer, else NULL


def set_active(tracer) -> None:
    """Bind `tracer` as the calling thread's active tracer
    (NULL_TRACER unbinds).  Balanced bind/unbind pairs per scope keep
    the process-wide fallback exact."""
    global _FALLBACK
    prev = getattr(_TLS_ACTIVE, "tracer", None)
    _TLS_ACTIVE.tracer = tracer
    with _ACTIVE_LOCK:
        if prev is not None and getattr(prev, "enabled", False):
            _ACTIVE_SET.pop(id(prev), None)
        if getattr(tracer, "enabled", False):
            _ACTIVE_SET[id(tracer)] = tracer
        _FALLBACK = (next(iter(_ACTIVE_SET.values()))
                     if len(_ACTIVE_SET) == 1 else NULL_TRACER)


def get_active():
    tracer = getattr(_TLS_ACTIVE, "tracer", None)
    if tracer is not None and tracer is not NULL_TRACER:
        return tracer
    return _FALLBACK


def make_tracer(conf: TpuConf):
    """A real tracer when tracing is on for this conf (trace.enabled or
    an event-log dir), else the shared NULL_TRACER."""
    if not (conf.get(TRACE_ENABLED) or conf.get(EVENT_LOG_DIR)):
        return NULL_TRACER
    global _NEXT_QUERY_ID
    with _QUERY_ID_LOCK:
        _NEXT_QUERY_ID += 1
        qid = _NEXT_QUERY_ID
    return QueryTracer(qid)
