"""Metrics-plane export: JSONL heartbeat + Prometheus HTTP endpoint.

Reference: the plugin's metrics ride Spark's always-on sink pipeline
(console/CSV/JMX sinks on a reporting interval) and its UI/history
endpoints (SURVEY §5).  The TPU-native pair:

  * `Heartbeat` — a daemon thread appending one JSON line
    ({ts, registry, flight_len}) to
    `spark.rapids.tpu.metrics.heartbeatPath` every
    `spark.rapids.tpu.metrics.reportIntervalS` seconds, so an operator
    tailing one file sees the live registry between queries (and the
    last line before a death is a crash-adjacent snapshot).
  * `MetricsHttpServer` — a stdlib `http.server` thread behind
    `spark.rapids.tpu.metrics.port` serving `/metrics` (Prometheus
    exposition text), `/metrics.json` (the structured snapshot) and
    `/flight` (the flight-recorder tail) for scrape-on-demand.

`configure_plane(conf)` is the single idempotent entry point
(TpuSession.__init__ and every query's instrumented scope call it): it
applies the enabled flag + recorder capacity and starts whichever
exporters the conf asks for, exactly once per process.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import List, Optional

from ..config import (METRICS_ENABLED, METRICS_FLIGHT_EVENTS,
                      METRICS_HEARTBEAT_PATH, METRICS_PORT,
                      METRICS_REPORT_INTERVAL_S, TpuConf)
from .recorder import FLIGHT_RECORDER
from .registry import FLEET, REGISTRY

#: worker-id env var (serving/workers.py sets it in worker processes) —
#: read here so the export plane self-labels without a serving import
_ENV_WORKER_ID = "SPARK_RAPIDS_TPU_WORKER_ID"


def _worker_id() -> Optional[str]:
    return os.environ.get(_ENV_WORKER_ID) or None


def worker_suffixed_path(path: str) -> str:
    """Pool-mode heartbeat-path de-collision: supervisor and N workers
    inherit ONE `metrics.heartbeatPath`, so a worker process rewrites
    it to `<stem>-<worker_id><ext>` — every process appends to its own
    file and `profile_report.py` merges the mixed directory."""
    wid = _worker_id()
    if not path or not wid:
        return path
    root, ext = os.path.splitext(path)
    return f"{root}-{wid}{ext or '.jsonl'}"


def registry_snapshot(compact: bool = False) -> dict:
    """The process registry as a dict: structured families, or the
    compact `name{labels} -> value` form (`compact=True`) that
    heartbeat lines, bench output and event-log query_end records
    embed."""
    return REGISTRY.flat() if compact else REGISTRY.snapshot()


def flight_record(n: Optional[int] = None) -> List[dict]:
    """The newest `n` flight-recorder events (all when None)."""
    return FLIGHT_RECORDER.tail(n)


def prometheus_text() -> str:
    return REGISTRY.prometheus_text()


class Heartbeat:
    """Appends registry snapshots to a JSONL file on an interval."""

    def __init__(self, path: str, interval_s: float):
        self.path = path
        self.interval_s = max(float(interval_s), 0.01)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpu-metrics-heartbeat")

    def start(self) -> "Heartbeat":
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            self.beat()
            self._stop.wait(self.interval_s)

    def beat(self) -> None:
        """Write one snapshot line (also called directly by tests)."""
        wid = _worker_id()
        rec = {"ts": time.time(), "type": "heartbeat",
               "role": "worker" if wid else "supervisor",
               "worker": wid,
               "pid": os.getpid(),
               "metrics_port": bound_metrics_port(),
               "registry": REGISTRY.flat(),
               "flight_len": len(FLIGHT_RECORDER)}
        fleet = FLEET.flat()
        if fleet:
            rec["fleet"] = fleet
        line = json.dumps(rec, default=str)
        try:
            with open(self.path, "a") as f:
                f.write(line + "\n")
        except OSError:
            # the sink must never take the engine down (full disk,
            # unlinked dir); the next beat retries
            pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=2.0)


class MetricsHttpServer:
    """On-demand Prometheus endpoint on a daemon thread."""

    def __init__(self, port: int):
        self.port = port
        self._httpd = None
        self._thread = None

    def start(self) -> int:
        """Bind + serve; returns the actual port (port 0 binds an
        ephemeral one — tests use that)."""
        import http.server

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):                    # noqa: N802
                if self.path.startswith("/metrics.json"):
                    snap = REGISTRY.snapshot()
                    fl = FLEET.snapshot()
                    if fl["families"]:
                        snap["fleet"] = fl
                    body = json.dumps(snap, default=str).encode()
                    ctype = "application/json"
                elif self.path.startswith("/flight"):
                    body = json.dumps(FLIGHT_RECORDER.tail(),
                                      default=str).encode()
                    ctype = "application/json"
                elif self.path.startswith("/metrics"):
                    # ONE endpoint serves the whole pool: the
                    # supervisor's own families plus the per-worker
                    # tpu_fleet_* federation (distinct names, so the
                    # concatenation stays valid exposition text)
                    text = REGISTRY.prometheus_text()
                    if FLEET.family_names():
                        text += FLEET.prometheus_text()
                    body = text.encode()
                    ctype = "text/plain; version=0.0.4"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):            # silence per-request spam
                return

        self._httpd = http.server.ThreadingHTTPServer(
            ("127.0.0.1", self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="tpu-metrics-http")
        self._thread.start()
        return self.port

    def stop(self) -> None:
        """Shut down AND join: after stop() returns, the serving thread
        is gone and the port is closed — repeated open/close in one
        process cannot accumulate threads or leak listen sockets
        (TpuSession.close / shutdown_exporters)."""
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            if self._thread.is_alive():
                self._thread.join(timeout=2.0)
            self._thread = None


_EXPORT_LOCK = threading.Lock()
_HEARTBEAT: Optional[Heartbeat] = None
_HTTP: Optional[MetricsHttpServer] = None


def configure_plane(conf: TpuConf) -> None:
    """Apply a conf to the process metrics plane (idempotent, cheap:
    conf reads are cached per TpuConf).  Enabled flag and recorder
    capacity follow the MOST RECENT conf applied (the plane is
    process-wide); exporters start once per process on first demand."""
    global _HEARTBEAT, _HTTP
    enabled = bool(conf.get(METRICS_ENABLED))
    REGISTRY.enabled = enabled
    FLIGHT_RECORDER.enabled = enabled
    FLIGHT_RECORDER.resize(conf.get(METRICS_FLIGHT_EVENTS))
    if not enabled:
        return
    hb_path = worker_suffixed_path(
        str(conf.get(METRICS_HEARTBEAT_PATH) or ""))
    port = int(conf.get(METRICS_PORT))
    if hb_path or port >= 0:
        with _EXPORT_LOCK:
            if hb_path and _HEARTBEAT is None:
                _HEARTBEAT = Heartbeat(
                    hb_path,
                    float(conf.get(METRICS_REPORT_INTERVAL_S))).start()
            # port 0 binds an EPHEMERAL port (concurrent worker
            # processes on one host never race a fixed port); the
            # bound port is reported by bound_metrics_port()
            if port >= 0 and _HTTP is None:
                try:
                    srv = MetricsHttpServer(port)
                    srv.start()
                    _HTTP = srv
                except OSError:
                    # a busy port must not fail queries; the snapshot
                    # surfaces remain available in-process
                    pass


def bound_metrics_port() -> Optional[int]:
    """The ACTUALLY BOUND Prometheus endpoint port of this process, or
    None when no server runs — with metrics.port=0 (ephemeral) this is
    the only way to learn the port; heartbeat lines, worker-pool
    heartbeat frames and ServingRuntime.stats() embed it."""
    srv = _HTTP
    return srv.port if srv is not None else None


def shutdown_exporters() -> None:
    """Stop the process exporters (tests / clean embedding teardown)."""
    global _HEARTBEAT, _HTTP
    with _EXPORT_LOCK:
        if _HEARTBEAT is not None:
            _HEARTBEAT.stop()
            _HEARTBEAT = None
        if _HTTP is not None:
            _HTTP.stop()
            _HTTP = None
