"""Device-memory attribution — the measured-HBM half of the
observability stack.

PR 9/12 attribute device *time* end to end; device *memory* was still
guessed: serving admission sized working sets as `admitWorkingSetFactor
x source bytes`, compiled programs never reported what they actually
hold, and spills/OOMs left no record of WHO owned the pressure.
Sparkle's memory-tier placement and Theseus' data-movement scheduling
(PAPERS.md) both start from measured per-stage footprints — this module
is that measurement layer, the prerequisite for ROADMAP 2b (mesh budget
integration) and 4 (the out-of-core tier):

  * `DeviceCensus` — the process-wide truth about budget-admitted HBM.
    Every `MemoryBudget` feeds its live-byte DELTAS here, so the
    `tpu_hbm_live_bytes` / `tpu_hbm_peak_bytes` gauges report the SUM
    across all concurrent queries (serving tenants included) instead
    of whichever budget wrote last.  Per-query peaks stay per-budget
    (`memory.peak_bytes`): a concurrent tenant's reservations can
    never inflate another query's reported peak, and the global gauge
    stays the global gauge.
  * `MemAttrRecorder` — the per-query HBM timeline: a bounded sequence
    of watermark samples (reserve / release / spill / oom / segment
    brackets / exchange footprints) each stamped with the live level
    and the plan-node range that owned the pressure at that instant.
    Active only under `spark.rapids.tpu.profile.segments` (+
    `profile.memory`); the disabled path stays one conf check per
    dispatch.  Segment BRACKETS wrap each compiled program dispatch:
    the budget census at open, the peak delta across the window, and
    the program's XLA `memory_analysis()` bytes together are the
    segment's measured working set (`segment.<id>.hbm_*` metrics,
    `tpu_segment_hbm_peak_bytes`, the EXPLAIN ANALYZE `hbm=` column).
  * forensics — crash dumps embed the recorder's timeline tail
    (runtime/failure.py), every spill/OOM event carries its owning
    node range, and the query-end leak check flags nonzero residual
    naked reservations (`tpu_hbm_residual_bytes`,
    `memory.residual_naked_bytes` in the profile).

The `memattr` chaos site fires on each segment census read: an injected
`ioerror` skips that sample (query bit-identical), `fatal` propagates
through crash capture as a classified dump embedding the partial
timeline (runtime/faults.py SITES).
"""
from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Dict, List, Optional

from ..config import (PROFILE_MEMORY, PROFILE_MEMORY_TIMELINE_EVENTS,
                      PROFILE_SEGMENTS, TpuConf)


# ---------------------------------------------------------------------------
# The process-wide census: budget-admitted bytes summed across queries
# ---------------------------------------------------------------------------

class DeviceCensus:
    """Aggregate live-byte accounting over every MemoryBudget in the
    process.  Budgets report deltas (they already hold their own lock);
    a finalizer retires a collected budget's remaining live bytes so a
    leaked context cannot pin the census."""

    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0
        self.peak = 0

    def register(self, budget) -> list:
        """-> a mutable cell `[live_bytes, device_label]` the budget
        keeps mirrored; retired automatically when the budget is GC'd."""
        cell = [0, getattr(budget, "_device", "0")]
        weakref.finalize(budget, self._retire, cell)
        return cell

    def _retire(self, cell: list) -> None:
        self.adjust(-int(cell[0]), cell[1])
        cell[0] = 0

    def adjust(self, delta: int, device: str) -> int:
        """Apply one budget's live-byte delta; returns the new process
        total.  Feeds the per-device registry gauges — the GLOBAL view,
        kept deliberately separate from per-query peak deltas."""
        from .registry import HBM_LIVE_BYTES, HBM_PEAK_BYTES
        with self._lock:
            self.total += int(delta)
            if self.total < 0:
                self.total = 0
            if self.total > self.peak:
                self.peak = self.total
            total = self.total
        HBM_LIVE_BYTES.set(total, device=device)
        HBM_PEAK_BYTES.max(total, device=device)
        return total

    def totals(self) -> Dict[str, int]:
        with self._lock:
            return {"live_bytes": self.total, "peak_bytes": self.peak}


#: THE census every MemoryBudget reports into
CENSUS = DeviceCensus()


# ---------------------------------------------------------------------------
# The per-query recorder: HBM timeline + segment brackets
# ---------------------------------------------------------------------------

class MemAttrRecorder:
    """HBM timeline + per-segment memory attribution for ONE query.

    Thread-safe (spill chains and shuffle workers report budget events
    from their own threads).  The event list is bounded: past
    `max_events` further samples are dropped and counted, so a
    pathological reserve storm cannot grow query memory."""

    enabled = True

    def __init__(self, max_events: int = 512):
        self._lock = threading.Lock()
        self.max_events = int(max_events)
        self._t0 = time.perf_counter()
        self.events: List[Dict[str, Any]] = []
        self.dropped = 0
        self.skipped = 0               # census samples chaos skipped
        #: node key of the segment bracket currently open (attribution
        #: for budget events landing inside the window)
        self._bracket: Optional[str] = None
        self._bracket_pre = 0          # budget live at bracket open
        self._bracket_peak = 0         # max budget live inside the window
        #: per-segment measured rows: key -> {resident_pre, peak_delta,
        #: analysis_bytes, hbm_peak_bytes}
        self.segments: Dict[str, Dict[str, int]] = {}
        #: the query's measured HBM peak: max over budget watermarks and
        #: bracket (resident + program analysis) candidates
        self.query_peak_bytes = 0
        self._event("start", 0, 0, 0)

    # -- events ------------------------------------------------------------
    def _event(self, ev: str, nbytes: int, live: int, naked: int,
               **extra) -> None:
        rec = {"t_ms": round((time.perf_counter() - self._t0) * 1e3, 3),
               "ev": ev, "bytes": int(nbytes), "live": int(live)}
        if naked:
            rec["naked"] = int(naked)
        if self._bracket is not None:
            rec["node"] = self._bracket
        rec.update(extra)
        if len(self.events) < self.max_events:
            self.events.append(rec)
        else:
            self.dropped += 1

    def on_budget_event(self, ev: str, nbytes: int, live: int,
                        naked: int) -> None:
        """One budget watermark sample (reserve/release/spill/oom),
        attributed to the open segment bracket when one exists."""
        with self._lock:
            self._event(ev, nbytes, live, naked)
            if live > self.query_peak_bytes:
                self.query_peak_bytes = live
            if self._bracket is not None and live > self._bracket_peak:
                self._bracket_peak = live

    def on_external(self, ev: str, **attrs) -> None:
        """Non-budget footprint events (mesh exchange slab/recv
        buffers) ride the same timeline."""
        with self._lock:
            self._event(ev, int(attrs.pop("bytes", 0)), 0, 0, **attrs)

    # -- segment brackets --------------------------------------------------
    def open_segment(self, key: str, resident_pre: int) -> None:
        with self._lock:
            self._bracket = key
            self._bracket_pre = int(resident_pre)
            self._bracket_peak = int(resident_pre)
            self._event("segment_open", 0, resident_pre, 0)

    def close_segment(self, key: str, analysis_bytes: int,
                      resident_post: int) -> Dict[str, int]:
        """Close the bracket and fold the segment's measured working
        set: the larger of the program's XLA memory_analysis bytes and
        the budget peak delta observed across the dispatch window."""
        with self._lock:
            pre = self._bracket_pre
            peak_delta = max(self._bracket_peak - pre, 0,
                             int(resident_post) - pre)
            hbm_peak = max(int(analysis_bytes), peak_delta)
            self._event("segment_close", hbm_peak, resident_post, 0)
            self._bracket = None
            row = self.segments.setdefault(
                key, {"resident_pre": 0, "peak_delta": 0,
                      "analysis_bytes": 0, "hbm_peak_bytes": 0})
            row["resident_pre"] = max(row["resident_pre"], pre)
            row["peak_delta"] = max(row["peak_delta"], peak_delta)
            row["analysis_bytes"] = max(row["analysis_bytes"],
                                        int(analysis_bytes))
            row["hbm_peak_bytes"] = max(row["hbm_peak_bytes"], hbm_peak)
            # the query-level measured peak candidate: what the device
            # held while THIS program ran (resident batches + the
            # program's own arguments/outputs/scratch)
            cand = pre + max(int(analysis_bytes), peak_delta)
            if cand > self.query_peak_bytes:
                self.query_peak_bytes = cand
            return {"resident_pre": pre, "peak_delta": peak_delta,
                    "hbm_peak_bytes": hbm_peak}

    # -- read --------------------------------------------------------------
    def timeline(self, tail: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            evs = list(self.events)
        return evs[-tail:] if tail else evs

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {"query_peak_bytes": self.query_peak_bytes,
                    "events": len(self.events),
                    "dropped": self.dropped,
                    "skipped": self.skipped,
                    "segments": {k: dict(v)
                                 for k, v in self.segments.items()}}


def budget_census(ctx) -> Dict[str, int]:
    """Point-in-time census of a query's OWN budget: live bytes, naked
    (directly reserved) bytes, spillable-resident device bytes and the
    host spill tier.  Never creates a budget — a pure whole-plan query
    reports zeros."""
    b = getattr(ctx, "_budget", None)
    if b is None:
        return {"live": 0, "naked": 0, "spillable_resident": 0,
                "host_spill": 0}
    with b._lock:
        resident = sum(sp._nbytes for sp in b._spillables.values()
                       if sp.on_device)
        return {"live": int(b.live), "naked": int(b.naked_live),
                "spillable_resident": int(resident),
                "host_spill": int(b.host_live)}


# ---------------------------------------------------------------------------
# Active-recorder plumbing (mirrors obs/tracer.py set_active/get_active:
# thread-local binding + single-active-scope process fallback, so the
# serving plane's concurrent queries never cross-attribute samples)
# ---------------------------------------------------------------------------

_TLS_ACTIVE = threading.local()
_ACTIVE_LOCK = threading.Lock()
_ACTIVE_SET: dict = {}
_FALLBACK: Optional[MemAttrRecorder] = None
_UNBOUND = object()


def set_active(rec: Optional[MemAttrRecorder]) -> None:
    global _FALLBACK
    prev = getattr(_TLS_ACTIVE, "rec", None)
    _TLS_ACTIVE.rec = rec if rec is not None else _UNBOUND
    with _ACTIVE_LOCK:
        if isinstance(prev, MemAttrRecorder):
            _ACTIVE_SET.pop(id(prev), None)
        if rec is not None:
            _ACTIVE_SET[id(rec)] = rec
        _FALLBACK = (next(iter(_ACTIVE_SET.values()))
                     if len(_ACTIVE_SET) == 1 else None)


def get_active_recorder() -> Optional[MemAttrRecorder]:
    rec = getattr(_TLS_ACTIVE, "rec", None)
    if isinstance(rec, MemAttrRecorder):
        return rec
    if rec is _UNBOUND:
        return None
    return _FALLBACK


def make_recorder(conf: TpuConf) -> Optional[MemAttrRecorder]:
    """A recorder when the memory-attribution plane is on for this conf
    (profile.segments AND profile.memory), else None — checked once per
    query, never per dispatch."""
    if not (conf.get(PROFILE_SEGMENTS) and conf.get(PROFILE_MEMORY)):
        return None
    return MemAttrRecorder(conf.get(PROFILE_MEMORY_TIMELINE_EVENTS))
