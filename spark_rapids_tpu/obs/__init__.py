"""Query-lifecycle observability: span tracer, event log, profiles.

The reference surfaces behavior through three channels — per-operator
`GpuMetric` sets in the Spark UI, `GpuTaskMetrics` accumulators
(semaphore-wait / spill / retry), and NVTX ranges consumed by nsys plus
the offline profiling tool (SURVEY §5).  This package is the TPU-native
consolidation of all three:

  tracer.py  — `QueryTracer` span/event collection threaded through the
               whole lifecycle (plan, compile, execute, transitions,
               shuffle, runtime events), serialized as a per-query JSONL
               event log (`spark.rapids.tpu.eventLog.dir`, the
               history-server event-log analogue) and a Chrome
               trace-event JSON openable in perfetto (the NVTX/nsys
               analogue).
  profile.py — `QueryProfile` aggregate over the spans + metrics: the
               compile/execute/transition/shuffle wall split, the
               per-node-id operator table, fallback summary and memory
               high-water (the offline profiling-tool analogue;
               `scripts/profile_report.py` is its CLI).
"""
from .tracer import (NULL_TRACER, EventLog, QueryTracer, Span, get_active,
                     make_tracer, read_event_log, set_active)
from .profile import QueryProfile

__all__ = ["NULL_TRACER", "EventLog", "QueryTracer", "QueryProfile",
           "Span", "get_active", "make_tracer", "read_event_log",
           "set_active"]
