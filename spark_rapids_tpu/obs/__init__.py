"""Observability: always-on metrics plane + query-lifecycle tracing.

The reference surfaces behavior through three channels — per-operator
`GpuMetric` sets in the Spark UI, `GpuTaskMetrics` accumulators
(semaphore-wait / spill / retry), and NVTX ranges consumed by nsys plus
the offline profiling tool (SURVEY §5) — and all of them ride Spark's
*always-on* metric sinks, not just opt-in traces.  This package is the
TPU-native consolidation:

  registry.py — `MetricsRegistry`: the process-wide always-on plane
               (counters, gauges, bounded log2-bucket histograms with
               bounded label cardinality) every runtime subsystem
               publishes into; the single source of truth the per-query
               dicts are compat views over (docs/METRICS.md catalog).
  recorder.py — `FlightRecorder`: a fixed-memory ring of the last N
               spans/instants across ALL queries, embedded verbatim in
               crash dumps (runtime/failure.py) — the black box.
  export.py  — JSONL heartbeat snapshots every
               `spark.rapids.tpu.metrics.reportIntervalS` seconds plus
               the on-demand Prometheus text endpoint behind
               `spark.rapids.tpu.metrics.port` (the metrics-sink /
               UI-endpoint role).
  tracer.py  — `QueryTracer` span/event collection threaded through the
               whole lifecycle (plan, compile, execute, transitions,
               shuffle, runtime events), serialized as a per-query JSONL
               event log (`spark.rapids.tpu.eventLog.dir`, the
               history-server event-log analogue) and a Chrome
               trace-event JSON openable in perfetto (the NVTX/nsys
               analogue).  OFF by default; its instants and byte
               counters feed the always-on plane either way.
  profile.py — `QueryProfile` aggregate over the spans + metrics: the
               compile/execute/transition/shuffle wall split, the
               per-node-id operator table, fallback summary and memory
               high-water (the offline profiling-tool analogue;
               `scripts/profile_report.py` is its CLI).
"""
from .recorder import FLIGHT_RECORDER, FlightRecorder
from .registry import REGISTRY, MetricsRegistry, bucket_index, bucket_le
from .tracer import (NULL_TRACER, EventLog, QueryTracer, Span, get_active,
                     make_tracer, read_event_log, set_active)
from .export import (configure_plane, flight_record, prometheus_text,
                     registry_snapshot)
from .profile import QueryProfile

__all__ = ["FLIGHT_RECORDER", "FlightRecorder", "MetricsRegistry",
           "NULL_TRACER", "EventLog", "QueryProfile", "QueryTracer",
           "REGISTRY", "Span", "bucket_index", "bucket_le",
           "configure_plane", "flight_record", "get_active",
           "make_tracer", "prometheus_text", "read_event_log",
           "registry_snapshot", "set_active"]
