"""CostEstimator: the admission-time cost oracle over the history store.

`estimate(pq)` answers, BEFORE a query runs, what it will cost:

    {device_us, overhead_us, wall_ms, compile_ms, working_set_bytes,
     confidence, basis, key, runs, segments}

`overhead_us` is the wall-decomposition plane's admission signal: the
structure's measured fixed-overhead tail (dispatch floor x launches +
seam wall + pad waste, obs/history.py overhead fields) — nonzero on
`exact_history` once a run has measured it (`overhead_basis:
"measured"`), always 0.0 with basis "none" for static answers.

Two bases, counted per call in `tpu_history_estimates_total`:

  * `exact_history` — the structure key (obs/history.py: PR 7 canonical
    plan structure + kernel tier + shape bucket) hit the persistent
    store: the answer is the structure's decay-weighted measured
    history, per-segment device ms included.  Confidence grows with
    run count and is cut when the structure's own newest measurement
    drifted >2x from its history (a drifting structure is exactly when
    the oracle should not be trusted blindly).
  * `static_cost` — never-seen structure: the static source-byte cost
    scaled by the store's continuously-fitted us-per-byte coefficient
    (decayed over every recorded execution), falling back to a
    documented default coefficient when the store is empty.  Never
    errors: a cold oracle answers with low confidence, it does not
    block admission.

The serving plane calls this at admission (serving/runtime.py), stamps
the prediction into the ticket / tracer / event log, and the eventual
execution record closes the loop: `tpu_history_prediction_error_ratio`
and the store's per-basis calibration curves report how wrong the
oracle currently is (`scripts/history_report.py`, `stats()`,
heartbeat, Prometheus).
"""
from __future__ import annotations

from typing import Dict, Optional

from ..config import SERVING_ADMIT_WORKING_SET_FACTOR
from .history import PerfHistoryStore, get_store, history_key, source_bytes

#: us/byte used by static_cost when the store has never measured
#: anything (a cold oracle): ~200 MB/s of device progress — deliberately
#: pessimistic so an uncalibrated admission over-reserves rather than
#: over-commits; one recorded run replaces it with the fitted value
DEFAULT_US_PER_BYTE = 5e-3

#: drift beyond which an exact-history estimate loses confidence
DRIFT_CUT = 2.0


class CostEstimator:
    def __init__(self, store: PerfHistoryStore):
        self.store = store

    def estimate(self, pq) -> Dict[str, object]:
        """The oracle's answer for one PhysicalQuery (see module doc)."""
        from .registry import HISTORY_ESTIMATES
        key = history_key(pq)
        agg = self.store.get(key) if key is not None else None
        if agg is not None and agg.runs > 0:
            out = self._from_history(key, agg, pq)
        else:
            out = self._static(key, pq)
        HISTORY_ESTIMATES.inc(basis=out["basis"])
        return out

    def _from_history(self, key, agg, pq) -> Dict[str, object]:
        # warm runs carry the trust: a history of only cold runs still
        # answers (better than static) but at half weight
        if agg.warm_runs > 0:
            confidence = min(1.0, agg.warm_runs / 4.0)
        else:
            confidence = min(0.5, agg.runs / 8.0)
        drift = agg.drift_ratio()
        if drift is not None and (drift >= DRIFT_CUT
                                  or drift <= 1.0 / DRIFT_CUT):
            confidence = min(confidence, 0.25)
        # working set: a MEASURED history (memattr query peaks / XLA
        # memory_analysis floors folded at record time) beats the
        # reserved-peak/source-bytes heuristic — ws_basis tells the
        # serving admission gate which one it is getting
        if agg.ws_runs > 0 and agg.ws_bytes > 0:
            ws = agg.ws_bytes
            ws_basis = "measured"
        else:
            ws = max(agg.peak_bytes, agg.src_bytes)
            ws_basis = "reserved"
        out = {"basis": "exact_history", "key": key,
               "device_us": max(round(agg.predicted_us(), 1), 1.0),
               "wall_ms": round(agg.wall_ms, 3),
               "compile_ms": round(agg.compile_ms, 3),
               "working_set_bytes": int(ws),
               "ws_basis": ws_basis,
               "confidence": round(confidence, 3),
               "runs": agg.runs, "warm_runs": agg.warm_runs,
               "drift_ratio": None if drift is None else round(drift, 3),
               "segments": dict(agg.segments)}
        # the wall-decomposition plane's admission signal (ROADMAP 1b):
        # this structure's measured fixed-overhead tail — dispatch floor
        # x launches + seam wall + pad waste — next to its device_us, so
        # a small-plan fast-path election can see a query that is mostly
        # overhead BEFORE running it.  overhead_basis marks it measured.
        out["overhead_us"] = round(agg.overhead_us, 1) \
            if agg.overhead_runs > 0 else 0.0
        out["overhead_basis"] = "measured" if agg.overhead_runs > 0 \
            else "none"
        if agg.seam_count:
            out["seam_count"] = agg.seam_count
            out["seam_ms"] = round(agg.seam_ms, 3)
        if agg.dispatch_floor_ms:
            out["dispatch_floor_ms"] = round(agg.dispatch_floor_ms, 4)
        return out

    def _static(self, key, pq) -> Dict[str, object]:
        src = source_bytes(pq.root)
        coef = self.store.us_per_byte
        fitted = coef is not None and coef > 0
        if not fitted:
            coef = DEFAULT_US_PER_BYTE
        ws_factor = float(pq.conf.get(SERVING_ADMIT_WORKING_SET_FACTOR))
        return {"basis": "static_cost", "key": key,
                "device_us": max(round(src * coef, 1), 1.0),
                "wall_ms": None,
                "compile_ms": None,
                "working_set_bytes": int(src * ws_factor),
                "ws_basis": "source",
                "confidence": 0.25 if fitted else 0.0,
                "runs": 0,
                "overhead_us": 0.0,
                "overhead_basis": "none",
                "segments": {}}


def estimate_query(pq) -> Optional[Dict[str, object]]:
    """Admission-time estimate for a PhysicalQuery, or None when the
    history plane is disabled (spark.rapids.tpu.history.dir unset) —
    the disabled path is one cached conf check."""
    store = get_store(pq.conf)
    if store is None:
        return None
    return CostEstimator(store).estimate(pq)


def prediction_stats() -> Dict[str, object]:
    """Oracle trustworthiness from the always-on registry: per-basis
    estimate counts + the prediction-error histogram summary — the
    block ServingRuntime.stats() exposes."""
    from .registry import HISTORY_ESTIMATES, HISTORY_PREDICTION_ERROR
    estimates = {}
    for s in HISTORY_ESTIMATES.series():
        basis = s["labels"].get("basis", "?")
        estimates[basis] = estimates.get(basis, 0) + s["value"]
    n = 0
    total = 0.0
    for s in HISTORY_PREDICTION_ERROR.series():
        n += s["count"]
        total += s["sum"]
    return {"estimates": estimates,
            "calibration": {"count": n,
                            "mean_error_ratio": round(total / n, 3)
                            if n else None}}
