"""Persistent performance history — the structure-keyed cost substrate.

PR 9's attribution plane measures per-segment device time, but every
measurement dies with the process.  ROADMAP 3(a) (predictive, SLA-aware
admission) and 5 (adaptive replanning) both need the opposite: a
*persistent*, structure-keyed history of measured device time the engine
can consult BEFORE running a query — the measured-cost feedback loop
that lets a scheduler place queries by predicted cost instead of
arrival order ("Accelerating Presto with GPUs", PAPERS.md) and schedule
for data movement rather than per-query wall (Theseus, PAPERS.md).

This module is that substrate:

  * `history_key(pq)` — the canonical identity of a query's *work*:
    PR 7's constant-lifted `plan_structure_key` (literal values erased,
    resolved Pallas kernel-tier discriminant included) plus the leaf
    shape bucket, with observability-only conf keys (trace, eventLog,
    profile, metrics, history, serving, test) FILTERED OUT so an
    EXPLAIN ANALYZE run, a serving admission and a plain collect of the
    same query all share one history line.  Host-engine plans (no
    canonical key) fall back to a physical-tree digest.
  * `PerfHistoryStore` — a process-wide, on-disk JSONL store under
    `spark.rapids.tpu.history.dir`: one append per completed query
    (measured device wall, per-segment device ms, rows/bytes at seams,
    peak HBM reservation, compile ms), folded into per-structure
    DECAY-WEIGHTED aggregates in memory.  Loads tolerate corrupt or
    truncated lines exactly like `read_event_log` (the intact prefix
    wins; damage is counted, never fatal).  The file is byte/entry
    capped: past `history.maxBytes`/`history.maxEntries` the store
    COMPACTS — aggregates replace raw records and least-recently
    updated structures drop first (LRU) — via an atomic tmp+rename.
  * calibration state — when a record carries an admission-time
    prediction (serving stamps one), the store folds the
    prediction-vs-actual ratio into per-basis calibration curves and
    the `tpu_history_prediction_error_ratio` histogram, so the oracle
    reports how wrong it currently is (`scripts/history_report.py`
    renders the curve; drift >2x from a structure's own history is the
    regression-triage entry point).

Feeding is automatic (exec/metrics.record_history at query end, inside
the crash-capture scope so the `history` chaos site's fatal kind dumps
classified) and near-free when disabled: `get_store(conf)` caches None
on the conf instance, one dict hit per query.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from ..config import (HISTORY_DECAY, HISTORY_DIR, HISTORY_MAX_BYTES,
                      HISTORY_MAX_ENTRIES, TpuConf)

#: conf keys that change observability, not the traced program — erased
#: from the history key so profiled/traced/serving runs of one query
#: share a single history line with its plain collects
_KEY_NEUTRAL_PREFIXES = (
    "spark.rapids.tpu.trace.",
    "spark.rapids.tpu.eventLog.",
    "spark.rapids.tpu.profile.",
    "spark.rapids.tpu.metrics.",
    "spark.rapids.tpu.history.",
    "spark.rapids.tpu.serving.",
    "spark.rapids.tpu.test.",
    "spark.rapids.tpu.coredump.",
    "spark.rapids.tpu.compile.cacheDir",
)

#: on-disk file name inside spark.rapids.tpu.history.dir
HISTORY_FILE = "perf_history.jsonl"


def _neutral_conf(conf: TpuConf) -> TpuConf:
    raw = {k: v for k, v in conf._raw.items()
           if not k.startswith(_KEY_NEUTRAL_PREFIXES)}
    return TpuConf(raw)


def history_key(pq) -> Optional[str]:
    """Stable 16-hex structure digest of a PhysicalQuery, cached on the
    holder.  None only when the plan cannot be keyed at all."""
    key = pq.__dict__.get("_history_key", False)
    if key is not False:
        return key
    key = compute_history_key(pq.root, pq.conf, pq.kind)
    pq.__dict__["_history_key"] = key
    return key


def compute_history_key(root, conf: TpuConf, kind: str) -> Optional[str]:
    """The structure digest for one physical root: canonical
    plan_structure_key (kernel-tier discriminant included) + leaf shape
    bucket for device plans; a physical-tree digest for host plans."""
    neutral = _neutral_conf(conf)
    parts: List[Any] = [kind]
    skey = None
    if kind == "device":
        try:
            from ..exec.compiled import (_max_leaf_capacity,
                                         plan_structure_key)
            skey = plan_structure_key(root, neutral)
            parts.append(_max_leaf_capacity(root, neutral))
        except Exception:                    # noqa: BLE001
            skey = None
    if skey is not None:
        parts.append(skey)
    else:
        # host engine / uncovered node class: the physical tree is the
        # best stable identity available (literals included)
        try:
            import jax
            parts.append(("tree", root.tree_string(),
                          jax.default_backend(),
                          tuple(sorted((k, str(v))
                                       for k, v in neutral._raw.items()))))
        except Exception:                    # noqa: BLE001
            return None
    return hashlib.sha256(repr(tuple(parts)).encode()).hexdigest()[:16]


def _is_warm(rec: dict) -> bool:
    """A recorded run is WARM when it paid no meaningful compile: cold
    runs carry first-touch costs (XLA compile, first upload, helper-jit
    warmup) that would poison a warm-cost prediction — the oracle
    predicts warm device time and reports compile separately."""
    compile_ms = float(rec.get("compile_ms") or 0.0)
    wall_ms = float(rec.get("wall_ms") or 0.0)
    return compile_ms < max(1.0, 0.05 * wall_ms)


class _Agg:
    """Decay-weighted aggregate of one structure's recorded executions.

    Two device-time tracks: `device_us` folds EVERY run (report
    ranking, the only signal while a structure has never run warm) and
    `warm_device_us` folds only warm runs (`_is_warm`) — the value the
    estimator serves and the drift detector watches, so a process
    restart's cold run can neither inflate predictions nor fake a
    regression."""

    __slots__ = ("runs", "warm_runs", "last_ts", "device_us",
                 "warm_device_us", "prev_warm_us", "last_warm_us",
                 "wall_ms", "compile_ms", "src_bytes", "peak_bytes",
                 "ws_bytes", "ws_runs",
                 "overhead_us", "overhead_runs", "seam_count",
                 "seam_ms", "dispatch_floor_ms",
                 "total_device_us", "segments", "label", "kind",
                 "backend")

    def __init__(self):
        self.runs = 0
        self.warm_runs = 0
        self.last_ts = 0.0
        self.device_us = 0.0        # decayed, all runs
        self.warm_device_us = 0.0   # decayed, warm runs only
        self.prev_warm_us = 0.0     # warm ewma BEFORE the last warm fold
        self.last_warm_us = 0.0     # newest raw warm observation
        self.wall_ms = 0.0
        self.compile_ms = 0.0       # decayed over COLD runs (compile cost)
        self.src_bytes = 0.0
        self.peak_bytes = 0.0
        self.ws_bytes = 0.0         # decayed MEASURED working set
        self.ws_runs = 0            # runs that carried one (memattr /
                                    # XLA memory_analysis — not the
                                    # source-bytes heuristic)
        # the overhead plane (wall decomposition, exec/compiled.py):
        # decayed dispatch+seam+pad overhead of runs that measured it,
        # plus the structure's seam shape and the backend's measured
        # per-dispatch floor — the small-plan fast-path admission signal
        self.overhead_us = 0.0      # decayed, measured runs only
        self.overhead_runs = 0
        self.seam_count = 0         # newest observed seam count
        self.seam_ms = 0.0          # decayed seam wall
        self.dispatch_floor_ms = 0.0  # newest measured backend floor
        self.total_device_us = 0.0  # lifetime sum (report ranking)
        self.segments: Dict[str, float] = {}   # node -> decayed device ms
        self.label: Optional[str] = None
        self.kind: Optional[str] = None
        self.backend: Optional[str] = None

    @staticmethod
    def _ewma(cur: float, obs: float, first: bool, d: float) -> float:
        return obs if first else cur + d * (obs - cur)

    def fold(self, rec: dict, decay: float) -> None:
        dus = float(rec.get("device_us") or 0.0)
        self.total_device_us += dus
        self.device_us = self._ewma(self.device_us, dus,
                                    self.runs == 0, decay)
        self.wall_ms = self._ewma(self.wall_ms,
                                  float(rec.get("wall_ms") or 0.0),
                                  self.runs == 0, decay)
        self.src_bytes = self._ewma(self.src_bytes,
                                    float(rec.get("src_bytes") or 0.0),
                                    self.runs == 0, decay)
        self.peak_bytes = self._ewma(self.peak_bytes,
                                     float(rec.get("peak_bytes") or 0.0),
                                     self.runs == 0, decay)
        ws = float(rec.get("ws_bytes") or 0.0)
        if ws > 0:
            self.ws_bytes = self._ewma(self.ws_bytes, ws,
                                       self.ws_runs == 0, decay)
            self.ws_runs += 1
        ov = float(rec.get("overhead_us") or 0.0)
        if ov > 0:
            self.overhead_us = self._ewma(self.overhead_us, ov,
                                          self.overhead_runs == 0, decay)
            self.overhead_runs += 1
        if rec.get("seam_count"):
            self.seam_count = int(rec["seam_count"])
            self.seam_ms = self._ewma(self.seam_ms,
                                      float(rec.get("seam_ms") or 0.0),
                                      self.seam_ms == 0.0, decay)
        if rec.get("dispatch_floor_ms"):
            self.dispatch_floor_ms = float(rec["dispatch_floor_ms"])
        if _is_warm(rec):
            self.prev_warm_us = self.warm_device_us
            self.last_warm_us = dus
            self.warm_device_us = self._ewma(self.warm_device_us, dus,
                                             self.warm_runs == 0, decay)
            self.warm_runs += 1
        else:
            cms = float(rec.get("compile_ms") or 0.0)
            self.compile_ms = self._ewma(self.compile_ms, cms,
                                         self.compile_ms == 0.0, decay)
        for node, ms in (rec.get("segments") or {}).items():
            try:
                ms = float(ms)
            except (TypeError, ValueError):
                continue
            cur = self.segments.get(node)
            self.segments[node] = ms if cur is None \
                else cur + decay * (ms - cur)
        self.runs += 1
        self.last_ts = float(rec.get("ts") or time.time())
        if rec.get("label"):
            self.label = str(rec["label"])
        if rec.get("kind"):
            self.kind = str(rec["kind"])
        if rec.get("backend"):
            self.backend = str(rec["backend"])

    def predicted_us(self) -> float:
        """The device-us the oracle serves: warm history when any warm
        run exists, else the all-runs decayed value."""
        return self.warm_device_us if self.warm_runs > 0 \
            else self.device_us

    def drift_ratio(self) -> Optional[float]:
        """Newest WARM observation vs the warm history it arrived into
        (>1 = slower than its history).  None below 3 warm runs — cold
        restarts and first measurements are expected, not drift."""
        if self.warm_runs < 3 or self.prev_warm_us <= 0:
            return None
        return self.last_warm_us / self.prev_warm_us

    def to_dict(self) -> dict:
        out = {"runs": self.runs, "warm_runs": self.warm_runs,
               "last_ts": round(self.last_ts, 3),
               "device_us": round(self.device_us, 1),
               "warm_device_us": round(self.warm_device_us, 1),
               "prev_warm_us": round(self.prev_warm_us, 1),
               "last_warm_us": round(self.last_warm_us, 1),
               "wall_ms": round(self.wall_ms, 3),
               "compile_ms": round(self.compile_ms, 3),
               "src_bytes": round(self.src_bytes, 1),
               "peak_bytes": round(self.peak_bytes, 1),
               "ws_bytes": round(self.ws_bytes, 1),
               "ws_runs": self.ws_runs,
               "overhead_us": round(self.overhead_us, 1),
               "overhead_runs": self.overhead_runs,
               "seam_count": self.seam_count,
               "seam_ms": round(self.seam_ms, 3),
               "dispatch_floor_ms": round(self.dispatch_floor_ms, 4),
               "total_device_us": round(self.total_device_us, 1),
               "segments": {n: round(v, 3)
                            for n, v in self.segments.items()}}
        for k in ("label", "kind", "backend"):
            v = getattr(self, k)
            if v:
                out[k] = v
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "_Agg":
        a = cls()
        a.runs = int(d.get("runs") or 0)
        a.warm_runs = int(d.get("warm_runs") or 0)
        a.last_ts = float(d.get("last_ts") or 0.0)
        a.device_us = float(d.get("device_us") or 0.0)
        a.warm_device_us = float(d.get("warm_device_us") or 0.0)
        a.prev_warm_us = float(d.get("prev_warm_us") or a.warm_device_us)
        a.last_warm_us = float(d.get("last_warm_us") or a.warm_device_us)
        a.wall_ms = float(d.get("wall_ms") or 0.0)
        a.compile_ms = float(d.get("compile_ms") or 0.0)
        a.src_bytes = float(d.get("src_bytes") or 0.0)
        a.peak_bytes = float(d.get("peak_bytes") or 0.0)
        a.ws_bytes = float(d.get("ws_bytes") or 0.0)
        a.ws_runs = int(d.get("ws_runs") or 0)
        a.overhead_us = float(d.get("overhead_us") or 0.0)
        a.overhead_runs = int(d.get("overhead_runs") or 0)
        a.seam_count = int(d.get("seam_count") or 0)
        a.seam_ms = float(d.get("seam_ms") or 0.0)
        a.dispatch_floor_ms = float(d.get("dispatch_floor_ms") or 0.0)
        a.total_device_us = float(d.get("total_device_us")
                                  or a.device_us * a.runs)
        a.segments = {str(n): float(v)
                      for n, v in (d.get("segments") or {}).items()}
        a.label = d.get("label")
        a.kind = d.get("kind")
        a.backend = d.get("backend")
        return a


class PerfHistoryStore:
    """One on-disk history file + its in-memory aggregates.

    Thread-safe (the serving plane records from many worker threads);
    process-wide per directory (`get_store`), so hit counters and decay
    state are shared by every conf pointing at the same dir."""

    def __init__(self, path: str, max_bytes: int = 16 << 20,
                 max_entries: int = 4096, decay: float = 0.3):
        self.path = path
        self.max_bytes = int(max_bytes)
        self.max_entries = int(max_entries)
        self.decay = float(decay)
        self._lock = threading.RLock()
        #: key -> _Agg; insertion order IS the LRU order (folds re-insert)
        self._aggs: Dict[str, _Agg] = {}
        #: per-basis calibration: {"n", "sum_ratio", "buckets": {le: n}}
        self._calib: Dict[str, dict] = {}
        #: reservation-vs-actual WORKING-SET calibration, same shape —
        #: how far admission's working_set_bytes predictions land from
        #: the measured HBM footprint (tpu_hbm_prediction_error_ratio)
        self._calib_ws: Dict[str, dict] = {}
        self.corrupt_lines = 0
        self.loaded_records = 0          # raw records replayed from disk
        self.recorded = 0                # records appended live
        self.compactions = 0
        #: continuously-fitted static-cost coefficient (decayed us/byte
        #: over every record with source bytes) — the scale factor the
        #: estimator's static_cost fallback uses for never-seen plans
        self.us_per_byte: Optional[float] = None
        self._fit_n = 0
        self._load()

    # -- load --------------------------------------------------------------
    def _load(self) -> None:
        try:
            with open(self.path) as f:
                lines = f.readlines()
        except OSError:
            return
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                # crash-truncated tails and damaged lines are tolerated
                # (the read_event_log contract): the intact records win
                self.corrupt_lines += 1
                continue
            if not isinstance(rec, dict):
                self.corrupt_lines += 1
                continue
            self._apply(rec)

    def _apply(self, rec: dict) -> None:
        key = rec.get("k")
        if rec.get("fit"):
            fit = rec["fit"]
            if fit.get("us_per_byte"):
                self.us_per_byte = float(fit["us_per_byte"])
                self._fit_n = int(fit.get("n") or 1)
            return
        for field, target in (("calib", self._calib),
                              ("calib_ws", self._calib_ws)):
            if rec.get(field):
                for basis, c in rec[field].items():
                    target[basis] = {
                        "n": int(c.get("n") or 0),
                        "sum_ratio": float(c.get("sum_ratio") or 0.0),
                        "buckets": {int(k): int(v) for k, v in
                                    (c.get("buckets") or {}).items()}}
                return
        if not key:
            return
        if rec.get("agg"):
            # compaction summary: seeds (or replaces) the aggregate
            self._aggs.pop(key, None)
            self._aggs[key] = _Agg.from_dict(rec["agg"])
            return
        agg = self._aggs.pop(key, None)
        if agg is None:
            agg = _Agg()
        agg.fold(rec, self.decay)
        self._aggs[key] = agg                # re-insert: now MRU
        self.loaded_records += 1
        self._fit(rec)
        self._calibrate(rec)

    # -- calibration + static-coefficient fitting --------------------------
    def _fit(self, rec: dict) -> None:
        src = float(rec.get("src_bytes") or 0.0)
        dus = float(rec.get("device_us") or 0.0)
        if src <= 0 or dus <= 0 or not _is_warm(rec):
            return                 # cold runs would inflate the coefficient
        obs = dus / src
        if self.us_per_byte is None:
            self.us_per_byte = obs
        else:
            self.us_per_byte += self.decay * (obs - self.us_per_byte)
        self._fit_n += 1

    def _calibrate(self, rec: dict) -> None:
        from .registry import (HBM_PREDICTION_ERROR,
                               HISTORY_PREDICTION_ERROR, bucket_index)
        basis = str(rec.get("basis") or "?")
        pred = rec.get("predicted_us")
        dus = float(rec.get("device_us") or 0.0)
        if pred and float(pred) > 0 and dus > 0:
            pred = float(pred)
            ratio = max(pred, dus) / min(pred, dus)
            c = self._calib.setdefault(
                basis, {"n": 0, "sum_ratio": 0.0, "buckets": {}})
            c["n"] += 1
            c["sum_ratio"] += ratio
            b = bucket_index(ratio)
            c["buckets"][b] = c["buckets"].get(b, 0) + 1
            HISTORY_PREDICTION_ERROR.observe(ratio, basis=basis)
        # reservation-vs-actual: admission's working-set prediction vs
        # the run's measured HBM footprint (the curve that tells the
        # serving gate how much to trust the oracle's bytes)
        pred_ws = rec.get("predicted_ws")
        meas_ws = float(rec.get("ws_bytes") or rec.get("peak_bytes")
                        or 0.0)
        if pred_ws and float(pred_ws) > 0 and meas_ws > 0:
            pred_ws = float(pred_ws)
            ratio = max(pred_ws, meas_ws) / min(pred_ws, meas_ws)
            ws_basis = str(rec.get("ws_pred_basis") or basis)
            c = self._calib_ws.setdefault(
                ws_basis, {"n": 0, "sum_ratio": 0.0, "buckets": {}})
            c["n"] += 1
            c["sum_ratio"] += ratio
            b = bucket_index(ratio)
            c["buckets"][b] = c["buckets"].get(b, 0) + 1
            HBM_PREDICTION_ERROR.observe(ratio, basis=ws_basis)

    # -- record ------------------------------------------------------------
    def record(self, key: str, rec: dict, conf: Optional[TpuConf] = None
               ) -> bool:
        """Append one execution record and fold it into the aggregates.
        Returns False (entry SKIPPED, store unchanged) on any write
        failure — a history IO problem must never affect the query.
        The `history` chaos site fires on the write path; its `fatal`
        kind propagates (classified upstream), `ioerror` is the skip."""
        from .registry import HISTORY_RECORDS
        rec = {"k": key, "ts": rec.get("ts") or time.time(), **rec}
        line = json.dumps(rec, default=str)
        with self._lock:
            try:
                if conf is not None:
                    from ..runtime.faults import get_injector
                    get_injector(conf).fire("history", path=self.path)
                os.makedirs(os.path.dirname(self.path) or ".",
                            exist_ok=True)
                with open(self.path, "a") as f:
                    f.write(line + "\n")
            except OSError:
                HISTORY_RECORDS.inc(outcome="io_error")
                return False
            self._apply(rec)
            self.loaded_records -= 1         # _apply counted it as loaded
            self.recorded += 1
            HISTORY_RECORDS.inc(outcome="ok")
            self._maybe_compact()
        return True

    def record_query(self, pq, ctx, wall_ms: float) -> None:
        """Build + record one completed query's observation from its
        ExecContext — the automatic feed (exec/metrics.record_history).
        Only host numbers are read (lazy device metrics are skipped)."""
        from .registry import HISTORY_RECORDS
        key = history_key(pq)
        if key is None:
            HISTORY_RECORDS.inc(outcome="unkeyed")
            return
        m = ctx.metrics

        def num(name, default=0.0):
            v = m.get(name, default)
            return float(v) if isinstance(v, (int, float)) \
                and not isinstance(v, bool) else default

        compile_ms = num("compile_ms")
        # the measured device-side wall this structure cost: the query
        # wall net of compile, floored by the accumulated program
        # dispatch wall (exec/compiled.py exec_device_ms — exact when
        # profiling syncs, the dispatch floor otherwise)
        device_ms = max(wall_ms - compile_ms, num("exec_device_ms"), 1e-3)
        segments: Dict[str, dict] = {}
        import re
        seg_re = re.compile(r"^segment\.(?P<node>[\w#]+)\."
                            r"(?P<field>device_ms|rows|out_bytes)$")
        for k, v in m.items():
            sm = seg_re.match(k)
            if sm and isinstance(v, (int, float)):
                segments.setdefault(sm.group("node"), {})[
                    sm.group("field")] = v
        rec = {"kind": pq.kind,
               "wall_ms": round(wall_ms, 3),
               "device_us": round(device_ms * 1e3, 1),
               "compile_ms": round(compile_ms, 3),
               "src_bytes": source_bytes(pq.root),
               "peak_bytes": _peak_bytes(ctx),
               "segments": {n: round(float(f.get("device_ms", 0.0)), 3)
                            for n, f in segments.items()}}
        # the MEASURED working set, when this run produced one: the
        # memattr query peak (profiled runs) or the XLA
        # memory_analysis floor (every compiled run) — max'd with the
        # budget peak so spill-leg reservations count too.  ws_basis
        # marks it measured, the estimator's trust discriminant.
        ws = max(num("memory.hbm_measured_working_set"),
                 num("exec_hbm_bytes"))
        if ws > 0:
            rec["ws_bytes"] = int(max(ws, num("memory.peak_bytes")))
            rec["ws_basis"] = "measured"
        seg_rows = {n: int(f["rows"]) for n, f in segments.items()
                    if isinstance(f.get("rows"), (int, float))}
        if seg_rows:
            rec["segment_rows"] = seg_rows
        # the overhead plane's loop-closer: this structure's measured
        # fixed-overhead tail (dispatch floor x launches + seam wall +
        # pad waste) so the estimator can serve overhead_us next to
        # device_us (the ROADMAP 1(b) fast-path admission signal).
        # seam_ms is always-on; dispatch/pad need a profiled run, but an
        # unprofiled run still prices its launches when the floor has
        # been measured in this process.
        floor = num("overhead.dispatch_floor_ms")
        if not floor:
            try:                             # already-measured cache only:
                import jax                   # never runs the microbench
                from ..exec.compiled import _DISPATCH_FLOOR
                floor = _DISPATCH_FLOOR.get(jax.default_backend(), 0.0)
            except Exception:                # noqa: BLE001
                floor = 0.0
        dispatch_ms = num("overhead.dispatch_ms")
        if not dispatch_ms and floor:
            dispatch_ms = floor * num("exec_dispatches")
        seam_ms = num("overhead.seam_ms")
        overhead_us = (dispatch_ms + seam_ms
                       + num("overhead.pad_waste_ms")) * 1e3
        if overhead_us > 0:
            rec["overhead_us"] = round(overhead_us, 1)
        if num("overhead.seam_count"):
            rec["seam_count"] = int(num("overhead.seam_count"))
            rec["seam_ms"] = round(seam_ms, 3)
        if floor:
            rec["dispatch_floor_ms"] = round(floor, 4)
        try:
            import jax
            rec["backend"] = jax.default_backend()
        except Exception:                    # noqa: BLE001
            pass
        label = m.get("history.label")
        if isinstance(label, str) and label:
            rec["label"] = label
        tenant = m.get("serving.tenant")
        if isinstance(tenant, str) and tenant:
            rec["tenant"] = tenant
        pred = m.get("predicted.device_us")
        if isinstance(pred, (int, float)) and pred > 0:
            rec["predicted_us"] = float(pred)
            rec["basis"] = str(m.get("predicted.basis") or "?")
        pred_ws = m.get("predicted.working_set_bytes")
        if isinstance(pred_ws, (int, float)) and pred_ws > 0:
            rec["predicted_ws"] = float(pred_ws)
            wb = m.get("predicted.ws_basis")
            if isinstance(wb, str) and wb:
                rec["ws_pred_basis"] = wb
        self.record(key, rec, conf=ctx.conf)

    # -- compaction --------------------------------------------------------
    def _maybe_compact(self) -> None:
        over_entries = len(self._aggs) > self.max_entries
        over_bytes = False
        if not over_entries:
            try:
                over_bytes = os.path.getsize(self.path) > self.max_bytes
            except OSError:
                pass
        if over_entries or over_bytes:
            self._compact()

    def checkpoint(self) -> None:
        """Durably checkpoint the store NOW: rewrite the file as one
        atomic aggregate summary (tmp + os.replace, same primitive the
        cap-driven compaction uses).  Graceful drain calls this in
        every serving worker and in the supervisor, so a restart/deploy
        loses no folded history even mid-append."""
        with self._lock:
            self._compact()

    def _compact(self) -> None:
        """Rewrite the file as one aggregate summary per kept structure
        (+ the fit/calibration state), dropping least-recently-updated
        structures past the entry cap and then past the byte cap —
        atomic tmp+rename, fail-soft (the next record retries)."""
        keys = list(self._aggs)              # insertion order = LRU
        if len(keys) > self.max_entries:
            for k in keys[:len(keys) - self.max_entries]:
                self._aggs.pop(k, None)
            keys = list(self._aggs)
        lines = []
        head = []
        if self.us_per_byte is not None:
            head.append(json.dumps(
                {"fit": {"us_per_byte": self.us_per_byte,
                         "n": self._fit_n}}))
        if self._calib:
            head.append(json.dumps({"calib": self._calib}, default=str))
        if self._calib_ws:
            head.append(json.dumps({"calib_ws": self._calib_ws},
                                   default=str))
        for k in keys:
            lines.append(json.dumps({"k": k,
                                     "agg": self._aggs[k].to_dict()}))
        total = sum(len(x) + 1 for x in head + lines)
        while lines and total > self.max_bytes:
            dropped = lines.pop(0)           # oldest (LRU) first
            total -= len(dropped) + 1
            self._aggs.pop(keys.pop(0), None)
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w") as f:
                f.write("\n".join(head + lines)
                        + ("\n" if head or lines else ""))
            os.replace(tmp, self.path)
            self.compactions += 1
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass

    # -- read --------------------------------------------------------------
    def get(self, key: str) -> Optional[_Agg]:
        with self._lock:
            agg = self._aggs.pop(key, None)
            if agg is not None:
                self._aggs[key] = agg        # MRU touch
            return agg

    def aggregates(self) -> Dict[str, _Agg]:
        with self._lock:
            return dict(self._aggs)

    def calibration(self) -> Dict[str, dict]:
        """Per-basis calibration: {basis: {n, mean_ratio, buckets}}."""
        return self._render_calib(self._calib)

    def ws_calibration(self) -> Dict[str, dict]:
        """The reservation-vs-actual working-set curve: per basis, how
        far admission's predicted working_set_bytes landed from the
        measured HBM footprint (the offline
        tpu_hbm_prediction_error_ratio)."""
        return self._render_calib(self._calib_ws)

    def _render_calib(self, calib: Dict[str, dict]) -> Dict[str, dict]:
        with self._lock:
            out = {}
            for basis, c in calib.items():
                out[basis] = {
                    "n": c["n"],
                    "mean_ratio": round(c["sum_ratio"] / c["n"], 3)
                    if c["n"] else None,
                    "buckets": dict(sorted(c["buckets"].items()))}
            return out

    def drifted(self, threshold: float = 2.0) -> List[dict]:
        """Structures whose newest measurement shifted more than
        `threshold`x from their own decayed history (either direction;
        `slower=True` rows are the regression-triage entries)."""
        out = []
        with self._lock:
            items = list(self._aggs.items())
        for key, agg in items:
            r = agg.drift_ratio()
            if r is None:
                continue
            if r >= threshold or r <= 1.0 / threshold:
                out.append({"key": key, "label": agg.label,
                            "runs": agg.runs, "ratio": round(r, 3),
                            "slower": r >= threshold,
                            "history_us": round(agg.prev_warm_us, 1),
                            "last_us": round(agg.last_warm_us, 1)})
        return sorted(out, key=lambda d: -d["ratio"])

    def stats(self) -> dict:
        with self._lock:
            try:
                fsize = os.path.getsize(self.path)
            except OSError:
                fsize = 0
            return {"path": self.path,
                    "structures": len(self._aggs),
                    "records_loaded": self.loaded_records,
                    "records_appended": self.recorded,
                    "corrupt_lines": self.corrupt_lines,
                    "compactions": self.compactions,
                    "file_bytes": fsize,
                    "us_per_byte": round(self.us_per_byte, 6)
                    if self.us_per_byte else None,
                    "calibration": self.calibration(),
                    "ws_calibration": self.ws_calibration()}


def source_bytes(root) -> int:
    """Total host source-table bytes feeding a physical root (0 when
    none are discoverable) — the static working-set proxy."""
    total = 0
    stack, seen = [root], set()
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        tbl = getattr(n, "_source_table", None)
        if tbl is not None:
            try:
                total += int(tbl.nbytes)
            except Exception:                # noqa: BLE001
                pass
        stack.extend(getattr(n, "children", ()) or ())
        for attr in ("host_child", "device_child"):
            c = getattr(n, attr, None)
            if c is not None:
                stack.append(c)
    return total


def _peak_bytes(ctx) -> int:
    b = getattr(ctx, "_budget", None)
    if b is None:
        return 0
    try:
        return int(b.metrics.get("peak_bytes", 0) or 0)
    except Exception:                        # noqa: BLE001
        return 0


# ---------------------------------------------------------------------------
# The process-wide store registry
# ---------------------------------------------------------------------------

_STORES: Dict[str, PerfHistoryStore] = {}
_STORES_LOCK = threading.Lock()
_MISS = object()


def get_store(conf: TpuConf) -> Optional[PerfHistoryStore]:
    """The history store for this conf, or None when the plane is off
    (spark.rapids.tpu.history.dir unset).  Cached on the conf instance:
    the disabled path is one dict hit per query."""
    st = conf._cache.get("__history_store", _MISS)
    if st is not _MISS:
        return st
    d = str(conf.get(HISTORY_DIR) or "")
    if not d:
        conf._cache["__history_store"] = None
        return None
    path = os.path.join(d, HISTORY_FILE)
    with _STORES_LOCK:
        st = _STORES.get(path)
        if st is None:
            st = _STORES[path] = PerfHistoryStore(
                path,
                max_bytes=conf.get(HISTORY_MAX_BYTES),
                max_entries=conf.get(HISTORY_MAX_ENTRIES),
                decay=conf.get(HISTORY_DECAY))
    conf._cache["__history_store"] = st
    return st


def configure_history(conf: TpuConf) -> Optional[PerfHistoryStore]:
    """Session-init hook (TpuSession.__init__/set_conf): warms the
    store for a conf'd history dir so the first query pays no load."""
    return get_store(conf)
