"""EXPLAIN ANALYZE — the device-time attribution plane's user surface.

Reference: Spark's SQL UI renders per-operator GPU metrics from the
plugin (GpuExec metric sets, SURVEY §5) so slow plans are diagnosable in
production; Flare (PAPERS.md) argues whole-stage-compiled engines need
compiler-level cost surfaces next to measured time.  This module is the
TPU-native pair of both ideas:

  * `run_explain_analyze(physical_query)` executes ONE profiled collect
    (`trace.enabled` + `profile.segments` forced on — whole-plan
    programs re-split at the seam boundaries the split compiler knows,
    every program dispatch blocks and records measured device wall) and
    renders the physical plan tree annotated with measured ms, rows,
    bytes, gather volume and % of query wall per segment;
  * the XLA static cost overlay (`cost_analysis()`/`memory_analysis()`
    captured at compile time) renders next to measured time, and
    predicted-vs-actual skew (time share wildly off FLOP share) flags
    mis-fused segments.

Surfaced as `DataFrame.explain_analyze()` and
`TpuSession.explain_analyze(df)`; `docs/PROFILING.md` has the
walkthrough.

The ATTRIBUTION_COVERED / ATTRIBUTION_EXEMPT sets below are the lint
contract (`scripts/check_docs.py`): every registered exec node class
must be in one of them, so a new operator cannot ship outside the
attribution plane unnoticed.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

# ---------------------------------------------------------------------------
# Attribution coverage contract (linted by scripts/check_docs.py)
# ---------------------------------------------------------------------------

#: exec classes the attribution plane covers by construction: they are
#: instrumented with stable node ids (exec/metrics.py), their time lands
#: in per-node operator metrics, and compiled segments anchor at them
ATTRIBUTION_COVERED = frozenset({
    # device execs
    "HostScanExec", "ProjectExec", "FilterExec", "HashAggregateExec",
    "SortExec", "TopNExec", "GlobalLimitExec", "LocalLimitExec",
    "UnionExec", "CoalesceBatchesExec", "RangeExec", "SampleExec",
    "ExpandExec", "HashJoinExec", "CrossJoinExec",
    "AdaptiveShuffledJoinExec", "BroadcastExchangeExec",
    "ShuffleExchangeExec", "ShuffleReadExec", "CollectAggregateExec",
    "DistinctAggregateExec", "PercentileAggregateExec", "WindowExec",
    "GenerateExec", "ParquetScanExec", "TextScanExec", "OrcScanExec",
    # host execs (eager/CPU path — attributed via per-node metrics)
    "HostSourceExec", "CpuProjectExec", "CpuFilterExec",
    "CpuAggregateExec", "CpuSortExec", "CpuLimitExec", "CpuJoinExec",
    "CpuUnionExec", "CpuRangeExec", "CpuExpandExec", "CpuSampleExec",
    "CpuWindowExec", "CpuGenerateExec", "CpuParquetScanExec",
    "CpuTextScanExec", "CpuOrcScanExec", "HostToDeviceExec",
    "DeviceToHostExec", "CachedHostScan", "MapInPandasExec",
    "ArrowEvalPythonExec", "FlatMapGroupsInPandasExec",
    "FlatMapCoGroupsInPandasExec", "AggregateInPandasExec",
    "WindowInPandasExec",
})

#: exec classes deliberately OUTSIDE per-node attribution, with the
#: reason — the lint accepts these but a reviewer sees why
ATTRIBUTION_EXEMPT: Dict[str, str] = {
    "DeviceResidentScanExec": "split-seam leaf standing in for an "
                              "already-measured upstream segment's "
                              "output; its time IS the seam segment's",
    "_ReplayStage": "adaptive-join internal replay of an already-"
                    "materialized side; its wall lands on the owning "
                    "AdaptiveShuffledJoinExec node",
    "_BloomFilterStage": "adaptive-join internal probe-side stage; "
                         "composed into the owning join's time",
    "PartitionReadExec": "shuffle-manager internal per-partition "
                         "reader; attributed to ShuffleReadExec",
    "_GroupedPandasExec": "python-worker plumbing base; time lands on "
                          "the concrete pandas exec nodes",
    "_FrameSource": "python-worker frame feeder; time lands on the "
                    "cogrouped pandas exec",
}


def registered_exec_classes() -> List[str]:
    """Every concrete exec node class the engine can place in a
    physical plan, discovered from the live class hierarchies (device
    PlanNode + host HostNode subclasses) after importing the exec/io
    modules — the enumeration the attribution lint checks against."""
    # import every module that defines exec classes so the hierarchies
    # are complete (the same trick config's docs lint uses)
    from ..exec import (adaptive, cache, collect, compiled, distinct,  # noqa: F401
                        exchange, generate, host_exec, percentile,
                        plan, python_exec, window)
    from ..io import avro, iceberg, orc, parquet, text  # noqa: F401
    from ..exec.plan import PlanNode
    from ..exec.host_exec import HostNode

    def walk(cls, out):
        for sub in cls.__subclasses__():
            out.add(sub.__name__)
            walk(sub, out)

    names: set = set()
    walk(PlanNode, names)
    walk(HostNode, names)
    # abstract/base helpers that never appear as plan nodes
    names -= {"PlanNode", "HostNode"}
    return sorted(names)


def attribution_coverage_gaps() -> List[str]:
    """Registered exec classes in neither ATTRIBUTION_COVERED nor
    ATTRIBUTION_EXEMPT — must be [] (tier-1 lint via check_docs)."""
    known = ATTRIBUTION_COVERED | set(ATTRIBUTION_EXEMPT)
    return [n for n in registered_exec_classes() if n not in known]


# ---------------------------------------------------------------------------
# The EXPLAIN ANALYZE report
# ---------------------------------------------------------------------------

#: |log2(time share / flop share)| beyond which a segment is flagged as
#: predicted-vs-actual skewed (possible mis-fusion / padding blowup)
_SKEW_LOG2 = 2.0


@dataclasses.dataclass
class ExplainAnalyzeReport:
    """One profiled execution's attribution: the annotated plan tree
    plus the structured tables behind it."""
    tree: str                       # rendered annotated plan tree
    segments: List[Dict[str, Any]]
    attributed_pct: Optional[float]  # 0..100, None when not measurable
    wall_ms: float
    device_ms: float                # union of measured execute spans
    gathers: Dict[str, int]         # gather volume delta over the run
    mesh_timeline: Dict[str, Any]
    metrics: Dict[str, Any]
    profile: object                 # the QueryProfile
    #: the memory-attribution view (obs/memattr.py): measured query
    #: peak, sum of per-segment HBM peaks and the attributed fraction
    #: (the acceptance bar: summed segment peaks account for >=90% of
    #: the measured peak); {} when the plane was off
    hbm: Dict[str, Any] = dataclasses.field(default_factory=dict)
    #: admission-style cost-oracle estimate taken BEFORE the profiled
    #: run (obs/estimator.py) — the predicted column next to measured;
    #: None when the history plane is off
    predicted: Optional[Dict[str, Any]] = None
    #: node id -> resolved Pallas kernel-tier decision (kernel_plan())
    kernel_tiers: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: out-of-core tier activity of the profiled run (exec/ooc.py):
    #: per-op election/partition/byte/recursion counters from
    #: ctx.metrics `ooc.*` entries; {} when the tier never engaged
    ooc: Dict[str, Any] = dataclasses.field(default_factory=dict)
    #: the wall-decomposition plane (QueryProfile.wall_breakdown): the
    #: end-to-end wall split into named categories — device compute,
    #: dispatch floor, seam time, compile, fetch, host prep — with an
    #: unattributed residual and the pad-waste overlay; {} when the
    #: profile carried no query span
    wall_breakdown: Dict[str, Any] = dataclasses.field(
        default_factory=dict)
    #: attributed_wall_pct over the FULL query span (0..100) — the
    #: honest bar next to attributed_pct's execute-span-only view
    attributed_wall_pct: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        return {"tree": self.tree, "segments": self.segments,
                "attributed_device_pct": self.attributed_pct,
                "attributed_wall_pct": self.attributed_wall_pct,
                "wall_ms": self.wall_ms, "device_ms": self.device_ms,
                "wall_breakdown": self.wall_breakdown,
                "gathers": self.gathers,
                "mesh_timeline": self.mesh_timeline,
                "predicted": self.predicted,
                "kernel_tiers": self.kernel_tiers,
                "ooc": self.ooc,
                "hbm": self.hbm}

    def render(self) -> str:
        head = [f"== EXPLAIN ANALYZE ==",
                f"query wall        {self.wall_ms:.1f} ms",
                f"device wall       {self.device_ms:.1f} ms (measured, "
                f"union of program executions)"]
        if self.predicted:
            p = self.predicted
            head.append(
                f"predicted device  {p['device_us'] / 1e3:.1f} ms "
                f"(basis={p['basis']}, confidence="
                f"{p.get('confidence', 0)}, runs={p.get('runs', 0)} — "
                f"the history oracle's admission-time answer)")
        if self.attributed_pct is not None:
            head.append(f"attributed        {self.attributed_pct:.1f}% "
                        f"of device wall to named plan segments")
        if self.attributed_wall_pct is not None:
            head.append(f"attributed (wall) {self.attributed_wall_pct:.1f}"
                        f"% of end-to-end wall to named categories")
        if self.predicted and self.predicted.get("overhead_us"):
            ov_ms = self.predicted["overhead_us"] / 1e3
            head.append(f"predicted overhead {ov_ms:.2f} ms "
                        f"(dispatch+seam+pad, history oracle)")
        if self.wall_breakdown:
            from .profile import render_wall_breakdown
            head.extend(render_wall_breakdown(self.wall_breakdown))
        if self.hbm.get("measured_peak_bytes"):
            h = self.hbm
            head.append(
                f"hbm peak          {h['measured_peak_bytes']} bytes "
                f"measured (segment peaks sum "
                f"{h.get('segment_sum_bytes', 0)}, "
                f"{h.get('attributed_pct', 0):.1f}% attributed)")
        if self.ooc:
            o = self.ooc
            parts = []
            for op in ("join", "agg", "sort"):
                if o.get(f"{op}_elections") or o.get(f"{op}_partitions"):
                    s = f"{op} k={o.get(f'{op}_partitions', 0)}"
                    if o.get(f"{op}_bytes"):
                        s += f" spilled={o[f'{op}_bytes']}B"
                    if o.get(f"{op}_recursions"):
                        s += f" recursions={o[f'{op}_recursions']}"
                    parts.append(s)
            if o.get("query_elections"):
                parts.append("query-escalated")
            head.append("ooc               " + "; ".join(parts) +
                        " (budget-driven out-of-core tier)")
        if self.gathers.get("gather_bytes"):
            head.append(f"gather volume     "
                        f"{self.gathers['gather_bytes']} bytes / "
                        f"{self.gathers.get('gather_rows', 0)} row-gathers"
                        + (f" ({self.gathers['deferred_gathers']} deferred)"
                           if self.gathers.get("deferred_gathers")
                           else ""))
        out = "\n".join(head) + "\n" + self.tree
        mesh = self.mesh_timeline
        if mesh.get("exchanges"):
            lines = ["-- mesh timeline --"]
            for ex in mesh["exchanges"]:
                if ex.get("kind") == "dict_gather":
                    lines.append(f"  dict_gather bytes="
                                 f"{ex.get('bytes', 0)}")
                    continue
                lines.append(
                    f"  exchange rounds={ex.get('rounds', 0)} "
                    f"quota={ex.get('quota', 0)} "
                    f"wire={ex.get('bytes', 0)}B "
                    f"(pre-compress {ex.get('bytes_pre_compress', 0)}B) "
                    f"stage={ex.get('stage_ms_total', 0)}ms "
                    f"collective={ex.get('collective_ms_total', 0)}ms "
                    f"arrivals={ex.get('arrivals', '?')}")
            if mesh.get("skew_splits"):
                lines.append(f"  skew splits: {len(mesh['skew_splits'])}")
            out += "\n" + "\n".join(lines)
        return out

    def __str__(self) -> str:
        return self.render()


def _flag_skew(segments: List[Dict[str, Any]]) -> None:
    """Predicted-vs-actual overlay: a segment whose share of measured
    device time is wildly off its share of static FLOPs gets flagged —
    the mis-fused / padding-bound smell explain_analyze exists to
    surface."""
    import math
    with_flops = [s for s in segments if s.get("flops")]
    tot_ms = sum(s.get("device_ms", 0.0) for s in with_flops)
    tot_fl = sum(s["flops"] for s in with_flops)
    if len(with_flops) < 2 or not tot_ms or not tot_fl:
        return
    for s in with_flops:
        ms_share = s.get("device_ms", 0.0) / tot_ms
        fl_share = s["flops"] / tot_fl
        if not ms_share or not fl_share:
            continue
        ratio = ms_share / fl_share
        if abs(math.log2(ratio)) >= _SKEW_LOG2:
            s["cost_skew"] = round(ratio, 2)


def _render_tree(root, metrics: Dict[str, Any],
                 seg_by_node: Dict[str, Dict[str, Any]],
                 wall_ms: float,
                 kernel_tiers: Optional[Dict[str, str]] = None,
                 pred_segments: Optional[Dict[str, float]] = None) -> str:
    """The annotated physical tree: every node with its measured per-node
    metrics, segment anchors with device time / % of wall / rows /
    bytes / static cost / predicted-from-history ms, and the resolved
    Pallas kernel-tier decision where one applies."""
    from ..exec.metrics import _child_nodes
    kernel_tiers = kernel_tiers or {}
    pred_segments = pred_segments or {}
    lines: List[str] = []

    def annotate(n) -> str:
        nid = getattr(n, "_node_id", None) or type(n).__name__
        parts = [nid]
        seg = seg_by_node.get(nid)
        if seg is not None:
            rng = ""
            if seg.get("node_lo") is not None:
                rng = f" nodes #{seg['node_lo']}-#{seg.get('node_hi')}"
            s = (f"<segment{rng}: {seg['device_ms']:.1f} ms device"
                 f" ({seg['pct']:.1f}%)")
            if nid in pred_segments:
                s += f", pred={pred_segments[nid]:.1f} ms"
            if seg.get("rows"):
                s += f", rows={seg['rows']}"
            if seg.get("out_bytes"):
                s += f", bytes={seg['out_bytes']}"
            if seg.get("hbm_peak_bytes"):
                # the memory-attribution column: this segment's
                # measured HBM working set; the largest one carries
                # the query's peak flag
                s += f", hbm={int(seg['hbm_peak_bytes'])}"
                if seg.get("hbm_peak_segment"):
                    s += " <-- hbm peak"
            cost = []
            if seg.get("flops"):
                cost.append(f"flops={seg['flops']:.3g}")
            if seg.get("bytes_accessed"):
                cost.append(f"bytes_accessed={seg['bytes_accessed']:.3g}")
            if seg.get("peak_temp_bytes"):
                cost.append(f"peak_temp={seg['peak_temp_bytes']:.3g}")
            if cost:
                s += " | " + " ".join(cost)
            if seg.get("cost_skew"):
                s += (f" | SKEW x{seg['cost_skew']:g} vs predicted "
                      f"(mis-fused?)")
            parts.append(s + ">")
        kt = kernel_tiers.get(nid)
        if kt is not None:
            parts.append(f"[kernel: {kt}]")
        op_ms = metrics.get(f"{nid}.op_time_ms")
        rows = metrics.get(f"{nid}.output_rows")
        ann = []
        if op_ms is not None:
            ann.append(f"op {float(op_ms):.1f} ms")
            if wall_ms:
                ann.append(f"{100.0 * float(op_ms) / wall_ms:.1f}% of wall")
        if rows is not None:
            ann.append(f"rows={int(rows)}")
        if ann:
            parts.append("[" + ", ".join(ann) + "]")
        return "  ".join(parts)

    def walk(n, depth):
        lines.append("  " * depth + annotate(n))
        for c in _child_nodes(n):
            walk(c, depth + 1)

    walk(root, 0)
    return "\n".join(lines)


def run_explain_analyze(pq, conf_overrides: Optional[dict] = None
                        ) -> ExplainAnalyzeReport:
    """Execute one PROFILED collect of a PhysicalQuery and build the
    attribution report.  The profiled run uses a fresh plan holder so
    whole-plan programs re-split at the known seam boundaries
    (profile.segments) without disturbing the caller's cached plan."""
    from ..config import PROFILE_SEGMENTS, TRACE_ENABLED, TpuConf
    from ..exec.metrics import assign_node_ids
    from ..exec.plan import ExecContext
    from ..obs.profile import QueryProfile
    from ..obs.registry import DEFERRED_GATHERS, GATHER_BYTES, GATHER_ROWS
    from ..plan.overrides import PhysicalQuery

    raw = dict(pq.conf._raw)
    raw[TRACE_ENABLED.key] = True
    raw[PROFILE_SEGMENTS.key] = True
    for k, v in (conf_overrides or {}).items():
        raw[getattr(k, "key", k)] = v
    prof_conf = TpuConf(raw)
    assign_node_ids(pq.root)

    # the history oracle's admission-time answer, taken BEFORE the run
    # so the report shows prediction next to what actually happened
    predicted = None
    try:
        from .estimator import estimate_query
        predicted = estimate_query(pq)
    except Exception:                        # noqa: BLE001
        predicted = None

    # resolved Pallas kernel-tier decision per node (PR 11 kernel_plan)
    kernel_tiers: Dict[str, str] = {}
    if pq.kind == "device":
        try:
            from ..plan.overrides import kernel_tier_decisions
            for node, decision in kernel_tier_decisions(pq.root, pq.conf):
                nid = getattr(node, "_node_id", None)
                if nid:
                    kernel_tiers[nid] = decision
        except Exception:                    # noqa: BLE001
            pass

    def _gather_totals() -> Dict[str, int]:
        out = {}
        for name, fam in (("gather_rows", GATHER_ROWS),
                          ("gather_bytes", GATHER_BYTES),
                          ("deferred_gathers", DEFERRED_GATHERS)):
            out[name] = int(sum(s["value"] for s in fam.series()))
        return out

    q = PhysicalQuery(pq.meta, pq.kind, pq.root, prof_conf)
    q.plan_phases = list(pq.plan_phases)
    ctx = ExecContext(prof_conf)
    g0 = _gather_totals()
    q.collect(ctx)
    g1 = _gather_totals()
    gathers = {k: g1[k] - g0[k] for k in g1 if g1[k] - g0[k]}

    profile = QueryProfile.from_context(ctx)
    segments = profile.segments()
    _flag_skew(segments)
    # memory attribution (obs/memattr.py): flag the peak segment and
    # compute the acceptance ratio — summed per-segment HBM peaks vs
    # the query's measured peak (resident + in-flight program)
    hbm: Dict[str, Any] = {}
    with_hbm = [s for s in segments if s.get("hbm_peak_bytes")]
    if with_hbm:
        max(with_hbm,
            key=lambda s: s["hbm_peak_bytes"])["hbm_peak_segment"] = True
        seg_sum = int(sum(s["hbm_peak_bytes"] for s in with_hbm))
        measured = int(ctx.metrics.get("memory.hbm_measured_working_set")
                       or 0)
        measured = max(measured,
                       int(ctx.metrics.get("memory.peak_bytes") or 0))
        hbm = {"measured_peak_bytes": measured,
               "segment_sum_bytes": seg_sum,
               "attributed_pct": round(
                   min(seg_sum / measured, 1.0) * 100, 1)
               if measured else 0.0}
    seg_by_node = {s["node"]: s for s in segments}
    split = profile.time_split()
    from ..obs.profile import _union_ms
    device_ms = _union_ms([(s.t0, s.t1) for s in profile.spans
                           if s.cat == "execute"])
    pct = profile.attributed_device_pct()
    pred_segments = {}
    if predicted:
        pred_segments = {n: float(v) for n, v in
                         (predicted.get("segments") or {}).items()}
    tree = _render_tree(pq.root, ctx.metrics, seg_by_node,
                        split["wall_ms"], kernel_tiers=kernel_tiers,
                        pred_segments=pred_segments)
    # out-of-core tier activity: the ctx.metrics `ooc.*` counters the
    # operators bump (exec/ooc.py) plus the query-rung escalation count
    ooc = {k[len("ooc."):]: v for k, v in ctx.metrics.items()
           if k.startswith("ooc.") and v}
    breakdown = profile.wall_breakdown()
    wpct = profile.attributed_wall_pct()
    return ExplainAnalyzeReport(
        tree=tree, segments=segments,
        attributed_pct=None if pct is None else round(pct * 100, 1),
        wall_ms=split["wall_ms"], device_ms=round(device_ms, 3),
        gathers=gathers, mesh_timeline=profile.mesh_timeline(),
        metrics=dict(ctx.metrics), profile=profile,
        predicted=predicted, kernel_tiers=kernel_tiers, hbm=hbm, ooc=ooc,
        wall_breakdown=breakdown if breakdown.get("wall_ms") else {},
        attributed_wall_pct=None if wpct is None
        else round(wpct * 100, 1))
