"""Flight recorder: a fixed-memory ring of the last N observability
events across ALL queries — the black box a post-mortem reads.

Reference motivation (SURVEY §5): GpuCoreDumpHandler streams a GPU core
dump out as the executor dies so the driver can do a post-mortem; the
dump shows device state but not *what the runtime was doing* in the
seconds before death.  The tracer (obs/tracer.py) knows, but it is
query-scoped and off by default — at crash time under default conf
there is nothing to read.

`FlightRecorder` closes that gap: a bounded `collections.deque` ring
that every tracer instant (tracing on or off), every span from an
enabled tracer, and the always-on query lifecycle markers
(plan/overrides.py) append to.  Overhead is one lock + dict + deque
append per event; memory is capped by `maxlen`
(`spark.rapids.tpu.metrics.flightRecorderEvents`), so it stays on
permanently.  `runtime/failure.py` embeds `tail()` verbatim in crash
dumps: under default conf the last record of a chaos-injected fatal
crash is the `fault_injected` instant itself (with tracing enabled,
operator spans unwinding over the fault close after it and trail it).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional


def _plain(v):
    """Ring records must always JSON-serialize later: numbers/strings
    pass through (numpy scalars coerce), everything else stringifies."""
    if isinstance(v, bool) or v is None or isinstance(v, (int, float, str)):
        return v
    item = getattr(v, "item", None)
    if item is not None:
        try:
            return item()
        except Exception:                        # noqa: BLE001
            pass
    return str(v)


class FlightRecorder:
    """Bounded ring buffer of observability events (newest last)."""

    def __init__(self, capacity: int = 1024):
        self.enabled = True
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=max(int(capacity), 1))

    @property
    def capacity(self) -> int:
        return self._buf.maxlen

    def resize(self, capacity: int) -> None:
        """Adjust the ring size, keeping the newest events."""
        capacity = max(int(capacity), 1)
        with self._lock:
            if capacity != self._buf.maxlen:
                self._buf = deque(self._buf, maxlen=capacity)

    def record(self, kind: str, name: str, cat: str,
               attrs: Optional[Dict[str, Any]] = None,
               query: Optional[int] = None) -> None:
        """Append one event; `kind` is "instant" or "span"."""
        if not self.enabled:
            return
        rec: Dict[str, Any] = {"kind": kind, "name": name, "cat": cat,
                               "t": time.time()}
        if query is not None:
            rec["query"] = query
        if attrs:
            rec["attrs"] = {str(k): _plain(v) for k, v in attrs.items()}
        with self._lock:
            self._buf.append(rec)

    def tail(self, n: Optional[int] = None) -> List[dict]:
        """The newest `n` events (all when n is None), oldest first —
        the crash-dump payload."""
        with self._lock:
            out = list(self._buf)
        return out if n is None else out[-int(n):]

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()


def tail_bounded(recorder: FlightRecorder, n: int,
                 max_bytes: int) -> List[dict]:
    """The newest <= n events whose JSON serialization fits max_bytes —
    the heartbeat-frame black-box snapshot (serving/workers.py).  Drops
    OLDEST events first; the bound is on the serialized batch, so one
    pathological event can at worst empty the snapshot, never bloat the
    frame."""
    import json
    events = recorder.tail(int(n))
    while events and len(json.dumps(events, default=str)) > int(max_bytes):
        events = events[max(1, len(events) // 4):]
    return events


#: THE process-wide recorder (independent instances only in tests)
FLIGHT_RECORDER = FlightRecorder()
