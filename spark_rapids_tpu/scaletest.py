"""Scale/stress harness — the integration_tests ScaleTest role.

Reference: integration_tests/src/main/scala/.../scaletest/ — QuerySpecs
(~30 join/agg/window queries over generated a-f tables), per-query
timeout, TestReport with timings.  Data comes from the datagen DSL
(datagen/bigDataGen.scala) with key-groups for join correlation.

Usage:
    python -m spark_rapids_tpu.scaletest --rows 100000 --timeout 120
or programmatically: `run_scale_test(rows=...)` -> report dict.
"""
from __future__ import annotations

import json
import time
from typing import Callable, Dict, List, Optional

import numpy as np
import pyarrow as pa

from .datagen import (BooleanGen, DateGen, DecimalGen, DoubleGen, IntGen,
                      KeyGroupGen, LongGen, StringGen, gen_table)
from .plan import expressions as E
from .plan.aggregates import Average, Count, Max, Min, Sum
from .plan.window import Rank, RowNumber, WindowFrame, WinSum
from .session import TpuSession, col


def build_tables(rows: int, seed: int = 0) -> Dict[str, pa.Table]:
    """Tables a/b/c with correlated keys (key-groups) and mixed types."""
    kg = KeyGroupGen(num_keys=max(rows // 20, 10), nullable=0.05)
    small_kg = KeyGroupGen(num_keys=50, nullable=0.02)
    a = gen_table([("key", kg), ("grp", small_kg),
                   ("i", IntGen()), ("l", LongGen(-10**9, 10**9)),
                   ("d", DoubleGen()), ("s", StringGen()),
                   ("dec", DecimalGen(12, 2)), ("dt", DateGen()),
                   ("b", BooleanGen())], rows, seed=seed)
    b = gen_table([("key", kg), ("v", LongGen(-10**6, 10**6)),
                   ("w", DoubleGen())], max(rows // 2, 10), seed=seed + 1)
    c = gen_table([("grp", small_kg), ("name", StringGen(1, 8))],
                  60, seed=seed + 2)
    return {"a": a, "b": b, "c": c}


def query_specs(s: TpuSession, t: Dict[str, pa.Table]) -> Dict[str, Callable]:
    a = lambda: s.from_arrow(t["a"])          # noqa: E731
    b = lambda: s.from_arrow(t["b"])          # noqa: E731
    c = lambda: s.from_arrow(t["c"])          # noqa: E731
    return {
        "full_agg": lambda: a().agg(
            (Sum(col("l")), "sl"), (Average(col("d")), "ad"),
            (Min(col("i")), "mi"), (Max(col("i")), "ma"),
            (Count(None), "n")),
        "group_agg": lambda: a().group_by("grp").agg(
            (Sum(col("dec")), "sd"), (Count(col("s")), "cs")),
        "high_card_agg": lambda: a().group_by("key").agg(
            (Count(None), "n"), (Sum(col("l")), "sl")),
        "filter_project": lambda: a().filter(
            E.GreaterThan(col("d"), E.Literal(0.0))).select(
            E.Multiply(col("l"), E.Literal(2)), col("s"),
            names=["l2", "s"]),
        "inner_join": lambda: a().join(
            b(), left_on=["key"], right_on=["key"]),
        "outer_join_agg": lambda: a().join(
            b(), how="left_outer", left_on=["key"], right_on=["key"])
            .group_by("grp").agg((Count(col("v")), "cv")),
        "broadcastish_join": lambda: a().join(
            c(), left_on=["grp"], right_on=["grp"]),
        "sort": lambda: a().sort(("l", False, False), ("i", True, True)),
        "topn": lambda: a().sort(("d", False, False)).limit(100),
        "window": lambda: a().window(
            [(RowNumber(), "rn"), (Rank(), "rk"),
             (WinSum(col("l"), WindowFrame("rows", None, 0)), "rs")],
            partition_by=["grp"], order_by=[("l", True, True)]),
        "distinctish": lambda: a().group_by("grp", "b").agg(
            (Count(None), "n")),
    }


def run_scale_test(rows: int = 50_000, seed: int = 0,
                   timeout_s: float = 300.0,
                   queries: Optional[List[str]] = None) -> dict:
    """Run every query spec with a per-query wall clock; returns the
    TestReport-shaped dict (name, status, rows, seconds)."""
    tables = build_tables(rows, seed)
    s = TpuSession()
    specs = query_specs(s, tables)
    if queries:
        specs = {k: v for k, v in specs.items() if k in queries}
    import threading
    report = {"rows": rows, "seed": seed, "results": []}
    for name, build in specs.items():
        t0 = time.perf_counter()
        entry = {"name": name}
        res: dict = {}

        def work(b=build, res=res):
            try:
                res["out"] = b().collect()
            except Exception as e:               # noqa: BLE001
                res["err"] = e

        # daemon thread: python cannot kill a hung query, but a daemon is
        # not joined at interpreter exit, so a TIMEOUT never wedges the
        # process and abandoned workers need no pool bookkeeping
        th = threading.Thread(target=work, daemon=True,
                              name=f"scaletest-{name}")
        th.start()
        th.join(timeout_s)
        if th.is_alive():
            entry.update(status="TIMEOUT", seconds=round(timeout_s, 3))
        elif "err" in res:
            entry.update(status="FAIL", error=repr(res["err"]),
                         seconds=round(time.perf_counter() - t0, 3))
        else:
            entry.update(status="OK", out_rows=res["out"].num_rows,
                         seconds=round(time.perf_counter() - t0, 3))
        report["results"].append(entry)
    report["passed"] = sum(r["status"] == "OK" for r in report["results"])
    report["total"] = len(report["results"])
    return report


def main():
    import argparse
    p = argparse.ArgumentParser()
    p.add_argument("--rows", type=int, default=50_000)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--timeout", type=float, default=300.0)
    p.add_argument("--queries", nargs="*", default=None)
    args = p.parse_args()
    report = run_scale_test(args.rows, args.seed, args.timeout,
                            args.queries)
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
