"""Deterministic typed data generators — the data_gen.py DSL role.

Reference: integration_tests/src/main/python/data_gen.py (StringGen /
IntegerGen / DecimalGen / ... with seeds, special values, null fractions)
and datagen/ (bigDataGen.scala seed-mapped scale generation,
FlatDistribution/ExponentialDistribution, key-groups for join
correlation).

Generators are composable specs: `gen_table([("a", IntGen(nullable=0.1)),
("b", StringGen())], rows=10_000, seed=7)` yields the same pyarrow table
for the same seed on every run.  Special values (type extremes, NaN, ±0.0,
epoch edges) are injected at a fixed ratio so kernels meet them in every
suite run, mirroring the reference's _special_case machinery.
"""
from __future__ import annotations

import datetime as pydt
import decimal as pydec
import string as _string
from typing import List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa


class Gen:
    """Base generator: produce(rng, n) -> pyarrow array."""
    nullable: float = 0.08          # default null fraction

    def __init__(self, nullable: Optional[float] = None):
        if nullable is not None:
            self.nullable = nullable

    def arrow_type(self) -> pa.DataType:
        raise NotImplementedError

    def _values(self, rng: np.random.Generator, n: int):
        raise NotImplementedError

    def specials(self) -> List:
        return []

    def produce(self, rng: np.random.Generator, n: int) -> pa.Array:
        vals = list(self._values(rng, n))
        sp = self.specials()
        if sp and n >= 4:
            # plant every special value at deterministic slots
            slots = rng.choice(n, size=min(len(sp), n), replace=False)
            for s, i in zip(sp, slots):
                vals[int(i)] = s
        if self.nullable:
            mask = rng.random(n) < self.nullable
            vals = [None if m else v for v, m in zip(vals, mask)]
        return pa.array(vals, self.arrow_type())


class BooleanGen(Gen):
    def arrow_type(self):
        return pa.bool_()

    def _values(self, rng, n):
        return rng.random(n) < 0.5


class _IntegralGen(Gen):
    lo: int
    hi: int
    pa_type: pa.DataType

    def __init__(self, lo=None, hi=None, nullable=None):
        super().__init__(nullable)
        if lo is not None:
            self.lo = lo
        if hi is not None:
            self.hi = hi

    def arrow_type(self):
        return self.pa_type

    def _values(self, rng, n):
        return [int(v) for v in rng.integers(self.lo, self.hi + 1, n)]

    def specials(self):
        return [self.lo, self.hi, 0]


class ByteGen(_IntegralGen):
    lo, hi, pa_type = -128, 127, pa.int8()


class ShortGen(_IntegralGen):
    lo, hi, pa_type = -(2 ** 15), 2 ** 15 - 1, pa.int16()


class IntGen(_IntegralGen):
    lo, hi, pa_type = -(2 ** 31), 2 ** 31 - 1, pa.int32()


class LongGen(_IntegralGen):
    lo, hi, pa_type = -(2 ** 63), 2 ** 63 - 1, pa.int64()


class FloatGen(Gen):
    pa_type = pa.float32()
    _specials = [0.0, -0.0, 1.0, -1.0, float("inf"), float("-inf"),
                 float("nan")]

    def arrow_type(self):
        return self.pa_type

    def _values(self, rng, n):
        mag = rng.integers(-30, 30, n).astype(np.float64)
        return (rng.standard_normal(n) * np.power(10.0, mag)).astype(
            np.dtype(self.pa_type.to_pandas_dtype())).tolist()

    def specials(self):
        return list(self._specials)


class DoubleGen(FloatGen):
    pa_type = pa.float64()


class StringGen(Gen):
    """Random strings from a charset with length range; pattern-free (the
    reference's regex-pattern StringGen can layer on)."""

    def __init__(self, min_len=0, max_len=12, charset=None, nullable=None):
        super().__init__(nullable)
        self.min_len = min_len
        self.max_len = max_len
        self.charset = charset or (_string.ascii_letters + _string.digits
                                   + " _-")

    def arrow_type(self):
        return pa.string()

    def _values(self, rng, n):
        chars = np.array(list(self.charset))
        lens = rng.integers(self.min_len, self.max_len + 1, n)
        out = []
        for ln in lens:
            out.append("".join(chars[rng.integers(0, len(chars), ln)]))
        return out

    def specials(self):
        return ["", " ", "\t", "√unicode✓", "UPPER lower"]


class DecimalGen(Gen):
    def __init__(self, precision=9, scale=2, nullable=None):
        super().__init__(nullable)
        self.precision = precision
        self.scale = scale

    def arrow_type(self):
        return pa.decimal128(self.precision, self.scale)

    def _values(self, rng, n):
        hi = 10 ** min(self.precision, 18) - 1
        unscaled = rng.integers(-hi, hi, n)
        q = pydec.Decimal(1).scaleb(-self.scale)
        return [pydec.Decimal(int(u)).scaleb(-self.scale).quantize(q)
                for u in unscaled]

    def specials(self):
        q = pydec.Decimal(1).scaleb(-self.scale)
        hi = pydec.Decimal(10 ** min(self.precision, 18) - 1).scaleb(
            -self.scale)
        return [pydec.Decimal(0).quantize(q), hi, -hi]


class DateGen(Gen):
    def __init__(self, lo=pydt.date(1800, 1, 1), hi=pydt.date(2200, 1, 1),
                 nullable=None):
        super().__init__(nullable)
        self.lo = lo
        self.hi = hi

    def arrow_type(self):
        return pa.date32()

    def _values(self, rng, n):
        epoch = pydt.date(1970, 1, 1)
        lo = (self.lo - epoch).days
        hi = (self.hi - epoch).days
        return [epoch + pydt.timedelta(days=int(d))
                for d in rng.integers(lo, hi, n)]

    def specials(self):
        return [pydt.date(1970, 1, 1), pydt.date(2000, 2, 29), self.lo]


class TimestampGen(Gen):
    def arrow_type(self):
        return pa.timestamp("us", tz="UTC")

    def _values(self, rng, n):
        us = rng.integers(-10**15, 4 * 10**15, n)
        return [int(v) for v in us]

    def produce(self, rng, n):
        vals = self._values(rng, n)
        if self.nullable:
            mask = rng.random(n) < self.nullable
            vals = [None if m else v for v, m in zip(vals, mask)]
        return pa.array(vals, pa.int64()).cast(self.arrow_type())


class KeyGroupGen(Gen):
    """Low-cardinality keys for join/groupby correlation (the datagen
    key-groups role): values drawn from a fixed pool so two tables built
    with the same pool parameters join."""

    def __init__(self, num_keys=100, base: Gen = None, nullable=None):
        super().__init__(nullable)
        self.num_keys = num_keys
        self.base = base or LongGen(0, 10 ** 9, nullable=0.0)

    def arrow_type(self):
        return self.base.arrow_type()

    def _values(self, rng, n):
        pool_rng = np.random.default_rng(12345 + self.num_keys)
        pool = list(self.base._values(pool_rng, self.num_keys))
        idx = rng.integers(0, self.num_keys, n)
        return [pool[i] for i in idx]


def gen_table(cols: Sequence[Tuple[str, Gen]], rows: int,
              seed: int = 0) -> pa.Table:
    """Deterministic table: one independent child seed per column, so
    adding a column never perturbs the others (seed-mapped generation,
    bigDataGen.scala)."""
    ss = np.random.SeedSequence(seed)
    child = ss.spawn(len(cols))
    arrays, names = [], []
    for (name, g), cs in zip(cols, child):
        arrays.append(g.produce(np.random.default_rng(cs), rows))
        names.append(name)
    return pa.table(dict(zip(names, arrays)))


ALL_SIMPLE_GENS = [BooleanGen(), ByteGen(), ShortGen(), IntGen(),
                   LongGen(), FloatGen(), DoubleGen(), StringGen(),
                   DecimalGen(9, 2), DateGen(), TimestampGen()]
