"""Native host-runtime bindings (ctypes over spillio.cpp).

The reference's runtime-around-the-kernels is native (JNI serialization,
disk stores, host allocator tooling); here the disk spill / shuffle block
IO path is a small C++ library — checksummed block framing with
xxhash64, single-block spill files and multi-block shuffle appenders.
ctypes calls release the GIL, so spill/shuffle worker threads overlap
file IO with device work.

Built on first use with g++ (cached as _build/libspillio.so); when no
toolchain is available a pure-python fallback provides identical framing
(same files, interchangeable), so the package never hard-requires the
native build.
"""
from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading
from typing import List, Optional, Tuple

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "spillio.cpp")
_SO = os.path.join(_DIR, "_build", "libspillio.so")
_MAGIC = 0x53525450554C4F42

_lock = threading.Lock()
_lib = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        try:
            if not os.path.exists(_SO) or \
                    os.path.getmtime(_SO) < os.path.getmtime(_SRC):
                os.makedirs(os.path.dirname(_SO), exist_ok=True)
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", _SRC, "-o", _SO],
                    check=True, capture_output=True, timeout=120)
            lib = ctypes.CDLL(_SO)
            lib.spill_write.restype = ctypes.c_int64
            lib.spill_write.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                        ctypes.c_int64]
            lib.spill_read.restype = ctypes.c_int64
            lib.spill_read.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                       ctypes.c_int64]
            lib.spill_length.restype = ctypes.c_int64
            lib.spill_length.argtypes = [ctypes.c_char_p]
            lib.spill_xxhash64.restype = ctypes.c_uint64
            lib.spill_xxhash64.argtypes = [ctypes.c_char_p,
                                           ctypes.c_int64,
                                           ctypes.c_uint64]
            lib.shuffle_open.restype = ctypes.c_void_p
            lib.shuffle_open.argtypes = [ctypes.c_char_p]
            lib.shuffle_append.restype = ctypes.c_int64
            lib.shuffle_append.argtypes = [ctypes.c_void_p,
                                           ctypes.c_char_p,
                                           ctypes.c_int64]
            lib.shuffle_close.restype = ctypes.c_int64
            lib.shuffle_close.argtypes = [ctypes.c_void_p]
            lib.shuffle_read_block.restype = ctypes.c_int64
            lib.shuffle_read_block.argtypes = [ctypes.c_char_p,
                                               ctypes.c_int64,
                                               ctypes.c_void_p,
                                               ctypes.c_int64]
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def native_available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# Python fallback (identical on-disk format)
# ---------------------------------------------------------------------------

def _py_hash(data: bytes) -> int:
    # xxhash64 via the ops/hashing host helpers would differ; reuse the
    # C library when present.  The fallback uses a stable stand-in only
    # when no native lib exists ANYWHERE in the deployment — files are
    # not exchanged between native and fallback processes with different
    # hash impls, so a process-stable checksum suffices.
    import zlib
    return (zlib.crc32(data) << 32 | zlib.adler32(data)) & (2**64 - 1)


def _checksum(data: bytes) -> int:
    lib = _load()
    if lib is not None:
        return lib.spill_xxhash64(data, len(data), 0)
    return _py_hash(data)


def spill_write(path: str, data) -> int:
    """Write one checksummed spill block; returns bytes written.

    pyarrow Buffers pass their address zero-copy (spilling happens under
    memory pressure — no extra host copy of the payload); bytes pass
    directly.  The source object stays referenced for the call, so the
    address cannot dangle."""
    lib = _load()
    if lib is not None:
        if hasattr(data, "address") and hasattr(data, "size"):
            addr, n = int(data.address), int(data.size)   # pyarrow Buffer
            r = lib.spill_write(path.encode(), addr, n)
        else:
            raw = bytes(data) if not isinstance(data, bytes) else data
            r = lib.spill_write(path.encode(), raw, len(raw))
        if r < 0:
            raise IOError(f"native spill_write failed for {path}")
        return r
    raw = data.to_pybytes() if hasattr(data, "to_pybytes") else bytes(data)
    with open(path, "wb") as f:
        f.write(struct.pack("<QQQ", _MAGIC, len(raw), _py_hash(raw)))
        f.write(raw)
    return len(raw) + 24


def spill_read(path: str) -> bytes:
    """Read + verify one spill block; raises on corruption."""
    lib = _load()
    if lib is not None:
        n = lib.spill_length(path.encode())
        if n < 0:
            raise IOError(f"bad spill file {path} ({n})")
        buf = ctypes.create_string_buffer(max(int(n), 1))
        r = lib.spill_read(path.encode(), buf, n)
        if r < 0:
            raise IOError(f"spill read failed for {path} (code {r}; "
                          "-4 = checksum mismatch)")
        return buf.raw[:r]
    with open(path, "rb") as f:
        magic, n, h = struct.unpack("<QQQ", f.read(24))
        if magic != _MAGIC:
            raise IOError(f"bad spill magic in {path}")
        data = f.read(n)
        if len(data) != n or _py_hash(data) != h:
            raise IOError(f"spill checksum mismatch in {path}")
        return data


class ShuffleBlockWriter:
    """Appends framed blocks to one shuffle data file; returns per-block
    offsets (the sort-shuffle index-file role)."""

    def __init__(self, path: str):
        self.path = path
        self.offsets: List[int] = []
        self._lib = _load()
        if self._lib is not None:
            self._h = self._lib.shuffle_open(path.encode())
            if not self._h:
                raise IOError(f"cannot open {path}")
            self._f = None
        else:
            self._h = None
            self._f = open(path, "wb")
            self._off = 0

    def append(self, data: bytes) -> int:
        if self._h is not None:
            off = self._lib.shuffle_append(self._h, data, len(data))
            if off < 0:
                raise IOError("shuffle append failed")
        else:
            off = self._off
            self._f.write(struct.pack("<QQQ", _MAGIC, len(data),
                                      _py_hash(data)))
            self._f.write(data)
            self._off += 24 + len(data)
        self.offsets.append(off)
        return off

    def close(self) -> int:
        if self._h is not None:
            total = self._lib.shuffle_close(self._h)
            self._h = None
            if total < 0:
                raise IOError("shuffle close failed")
            return total
        self._f.close()
        return self._off


def read_shuffle_block(path: str, offset: int) -> bytes:
    lib = _load()
    if lib is not None:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            f.seek(offset)
            hdr = f.read(24)
        if len(hdr) < 24:
            raise IOError(f"truncated shuffle block header in {path} "
                          f"at {offset}")
        magic, n, _h = struct.unpack("<QQQ", hdr)
        if magic != _MAGIC:
            raise IOError(f"bad shuffle block magic in {path} at {offset}")
        if n > size - offset - 24:
            raise IOError(f"shuffle block length {n} exceeds file size "
                          f"({path} at {offset})")
        buf = ctypes.create_string_buffer(max(int(n), 1))
        r = lib.shuffle_read_block(path.encode(), offset, buf, n)
        if r < 0:
            raise IOError(f"shuffle block read failed (code {r})")
        return buf.raw[:r]
    with open(path, "rb") as f:
        f.seek(offset)
        magic, n, h = struct.unpack("<QQQ", f.read(24))
        if magic != _MAGIC:
            raise IOError("bad shuffle block magic")
        data = f.read(n)
        if len(data) != n or _py_hash(data) != h:
            raise IOError("shuffle block checksum mismatch")
        return data
