// Native spill/shuffle block IO — the host-runtime role the reference
// fills with native code (JCudfSerialization framing, RapidsDiskStore
// writes, dev/host_memory_leaks tooling are its native-adjacent layer).
//
// Block format: [magic u64][payload_len u64][xxhash64 u64][payload...]
// An appender handle writes many blocks to one file (the multithreaded
// shuffle writer's data-file shape: index = (offset, len) list returned
// to the caller).  All calls are GIL-free from Python's point of view
// (ctypes releases the GIL), so spill/shuffle worker threads overlap
// their IO with device work.
//
// Build: g++ -O2 -shared -fPIC spillio.cpp -o libspillio.so
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>

static const uint64_t MAGIC = 0x53525450554C4F42ULL; // "SRTPULOB"

// ---------------------------------------------------------------------------
// xxhash64 (public algorithm; straightforward implementation)
// ---------------------------------------------------------------------------
static const uint64_t P1 = 0x9E3779B185EBCA87ULL;
static const uint64_t P2 = 0xC2B2AE3D27D4EB4FULL;
static const uint64_t P3 = 0x165667B19E3779F9ULL;
static const uint64_t P4 = 0x85EBCA77C2B2AE63ULL;
static const uint64_t P5 = 0x27D4EB2F165667C5ULL;

static inline uint64_t rotl(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}

static inline uint64_t read64(const uint8_t* p) {
  uint64_t v;
  memcpy(&v, p, 8);
  return v;
}

static inline uint64_t read32(const uint8_t* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return (uint64_t)v;
}

static inline uint64_t round1(uint64_t acc, uint64_t input) {
  acc += input * P2;
  acc = rotl(acc, 31);
  acc *= P1;
  return acc;
}

static inline uint64_t merge_round(uint64_t acc, uint64_t val) {
  val = round1(0, val);
  acc ^= val;
  acc = acc * P1 + P4;
  return acc;
}

extern "C" uint64_t spill_xxhash64(const uint8_t* data, int64_t len,
                                   uint64_t seed) {
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  uint64_t h;
  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2, v2 = seed + P2, v3 = seed,
             v4 = seed - P1;
    const uint8_t* limit = end - 32;
    do {
      v1 = round1(v1, read64(p)); p += 8;
      v2 = round1(v2, read64(p)); p += 8;
      v3 = round1(v3, read64(p)); p += 8;
      v4 = round1(v4, read64(p)); p += 8;
    } while (p <= limit);
    h = rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18);
    h = merge_round(h, v1);
    h = merge_round(h, v2);
    h = merge_round(h, v3);
    h = merge_round(h, v4);
  } else {
    h = seed + P5;
  }
  h += (uint64_t)len;
  while (p + 8 <= end) {
    h ^= round1(0, read64(p));
    h = rotl(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    h ^= read32(p) * P1;
    h = rotl(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= (*p) * P5;
    h = rotl(h, 11) * P1;
    p++;
  }
  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

// ---------------------------------------------------------------------------
// Single-block spill files
// ---------------------------------------------------------------------------

extern "C" int64_t spill_write(const char* path, const uint8_t* data,
                               int64_t len) {
  FILE* f = fopen(path, "wb");
  if (!f) return -1;
  uint64_t header[3] = {MAGIC, (uint64_t)len,
                        spill_xxhash64(data, len, 0)};
  int64_t out = -1;
  if (fwrite(header, 8, 3, f) == 3 &&
      (len == 0 || fwrite(data, 1, (size_t)len, f) == (size_t)len)) {
    out = len + 24;
  }
  if (fclose(f) != 0) out = -1;
  return out;
}

// Returns payload length; negative on error:
//   -1 open/short-read, -2 bad magic, -3 capacity too small,
//   -4 checksum mismatch
extern "C" int64_t spill_read(const char* path, uint8_t* out,
                              int64_t cap) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  uint64_t header[3];
  int64_t r = -1;
  if (fread(header, 8, 3, f) == 3) {
    if (header[0] != MAGIC) {
      r = -2;
    } else if ((int64_t)header[1] > cap) {
      r = -3;
    } else if (header[1] == 0 ||
               fread(out, 1, (size_t)header[1], f) == header[1]) {
      if (spill_xxhash64(out, (int64_t)header[1], 0) == header[2]) {
        r = (int64_t)header[1];
      } else {
        r = -4;
      }
    }
  }
  fclose(f);
  return r;
}

// Peek the payload length (for buffer sizing); negative on error.
extern "C" int64_t spill_length(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  uint64_t header[3];
  int64_t r = -1;
  if (fread(header, 8, 3, f) == 3 && header[0] == MAGIC) {
    r = (int64_t)header[1];
  }
  fclose(f);
  return r;
}

// ---------------------------------------------------------------------------
// Multi-block appender (shuffle data-file shape)
// ---------------------------------------------------------------------------

struct Appender {
  FILE* f;
  int64_t offset;
};

extern "C" void* shuffle_open(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  Appender* a = (Appender*)malloc(sizeof(Appender));
  a->f = f;
  a->offset = 0;
  return a;
}

// Appends one framed block; returns its starting offset, or -1.
extern "C" int64_t shuffle_append(void* handle, const uint8_t* data,
                                  int64_t len) {
  Appender* a = (Appender*)handle;
  uint64_t header[3] = {MAGIC, (uint64_t)len,
                        spill_xxhash64(data, len, 0)};
  if (fwrite(header, 8, 3, a->f) != 3) return -1;
  if (len && fwrite(data, 1, (size_t)len, a->f) != (size_t)len) return -1;
  int64_t at = a->offset;
  a->offset += 24 + len;
  return at;
}

extern "C" int64_t shuffle_close(void* handle) {
  Appender* a = (Appender*)handle;
  int64_t total = a->offset;
  int rc = fclose(a->f);
  free(a);
  return rc == 0 ? total : -1;
}

// Reads the framed block at `offset`; same return codes as spill_read.
extern "C" int64_t shuffle_read_block(const char* path, int64_t offset,
                                      uint8_t* out, int64_t cap) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  int64_t r = -1;
  if (fseek(f, (long)offset, SEEK_SET) == 0) {
    uint64_t header[3];
    if (fread(header, 8, 3, f) == 3) {
      if (header[0] != MAGIC) {
        r = -2;
      } else if ((int64_t)header[1] > cap) {
        r = -3;
      } else if (header[1] == 0 ||
                 fread(out, 1, (size_t)header[1], f) == header[1]) {
        r = spill_xxhash64(out, (int64_t)header[1], 0) == header[2]
                ? (int64_t)header[1] : -4;
      }
    }
  }
  fclose(f);
  return r;
}
