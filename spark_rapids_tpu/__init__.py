"""spark-rapids-tpu: a TPU-native columnar SQL execution engine.

A from-scratch framework with the capabilities of NVIDIA's RAPIDS Accelerator
for Apache Spark (reference: /root/reference, v24.06.0-SNAPSHOT), re-designed
for TPU hardware: JAX/XLA for the compute path (jit-traced expression trees,
static-shape bucketed columnar batches, sort/segment-based aggregation,
Pallas kernels for hot ops), `jax.sharding.Mesh` + shard_map collectives for
distributed exchange, Arrow as the host/wire columnar format.

Layer map (mirrors SURVEY.md §1):
  runtime/   - device manager, semaphore, retry/spill (ref L1)
  columnar/  - host (Arrow) + device (bucketed jnp) batches (ref L2)
  plan/      - expressions, logical plan, overrides/tagging engine (ref L3)
  exec/      - physical operators (ref L4)
  io/        - parquet/csv/json scans + writers (ref L5)
  shuffle/   - partitioners + multithreaded host shuffle + ICI exchange (ref L6)
  parallel/  - mesh management, distributed query steps (ref §2.10)
  ops/       - the kernel library: the cuDF/JNI role, played by jnp/Pallas (ref L0)
"""

__version__ = "0.1.0"

# Spark semantics require 64-bit ints (LongType) and doubles (DoubleType).
# TPUs emulate s64/f64 (two-lane), which XLA handles; correctness first, with
# optional f32 compute modes where compatibility.md-style deviations are OK.
import jax as _jax

_jax.config.update("jax_enable_x64", True)

from . import types
from .config import TpuConf, DEFAULT_CONF
from .session import DataFrame, TpuSession, col, lit
