"""Device-side partitioners (GpuPartitioning analogues).

Reference: GpuHashPartitioningBase.scala:28 (Spark murmur3_32 then pmod),
GpuRoundRobinPartitioning, GpuRangePartitioner.scala:173,
GpuSinglePartitioning — all split device tables into per-partition slices.

TPU-first: only the partition-id lane is computed on device (one fused
program using the same murmur3 kernels the aggregation hash uses); the
physical split happens wherever the rows are headed — host-side slicing
for the host shuffle (the rows are being downloaded anyway), bucket
compaction for the ICI all_to_all path (parallel/exchange.py).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from .. import types as t
from ..columnar.device import DeviceBatch, DeviceColumn
from ..config import TpuConf, DEFAULT_CONF
from ..ops.hashing import hash_column, dict_hash_array
from ..plan import expressions as E


class Partitioning:
    num_partitions: int = 1

    def partition_ids(self, db: DeviceBatch, conf: TpuConf) -> np.ndarray:
        """Host int32 array (num_rows,) of target partitions."""
        raise NotImplementedError


class SinglePartitioning(Partitioning):
    def __init__(self):
        self.num_partitions = 1

    def partition_ids(self, db, conf):
        return np.zeros(int(db.num_rows), np.int32)


class RoundRobinPartitioning(Partitioning):
    """Spark round-robin: rows cycle through partitions, starting position
    varies per task — we start at 0 (deterministic for tests)."""

    def __init__(self, num_partitions: int):
        self.num_partitions = num_partitions
        self._next_start = 0

    def partition_ids(self, db, conf):
        n = int(db.num_rows)
        ids = (np.arange(n, dtype=np.int64) + self._next_start) \
            % self.num_partitions
        self._next_start = int((self._next_start + n) % self.num_partitions)
        return ids.astype(np.int32)


_HASH_CACHE = {}


class HashPartitioning(Partitioning):
    """Spark HashPartitioning: pmod(murmur3_32(keys, seed=42), n)."""

    def __init__(self, key_exprs: Sequence[E.Expression], num_partitions: int):
        self.key_exprs = list(key_exprs)
        self.num_partitions = num_partitions

    def bind(self, schema: t.StructType) -> "HashPartitioning":
        self.key_exprs = [e.bind(schema) for e in self.key_exprs]
        return self

    def _key_cols(self, db: DeviceBatch, conf) -> List[DeviceColumn]:
        # plain column keys use the raw storage lanes (keeps DOUBLE as its
        # bit-exact int64 lane, which Spark-compatible hashing requires)
        cols = []
        for e in self.key_exprs:
            inner = e.children[0] if isinstance(e, E.Alias) else e
            if isinstance(inner, E.ColumnRef):
                cols.append(db.column_by_name(inner.name))
            else:
                from ..exec.evaluator import evaluate_projection
                kb = evaluate_projection([e], ["_k"], db, conf)
                cols.append(kb.columns[0])
        for i, c in enumerate(cols):
            if isinstance(c.dtype, t.StringType) and i > 0:
                raise NotImplementedError(
                    "string partition key after position 0: chained-seed "
                    "string hashing needs the byte-level device kernel")
        return cols

    def partition_ids(self, db, conf):
        kb_columns = self._key_cols(db, conf)
        kb = DeviceBatch(kb_columns, db.num_rows,
                         [f"_k{i}" for i in range(len(kb_columns))])
        sig = ("hashpart", db.capacity, self.num_partitions,
               tuple((c.dtype.simple_string, str(c.data.dtype))
                     for c in kb.columns))
        fn = _HASH_CACHE.get(sig)
        if fn is None:
            dtypes = [c.dtype for c in kb.columns]

            def run(datas, valids, dhashes):
                h = jnp.full((datas[0].shape[0],), 42, jnp.uint32)
                for d, v, dt, i in zip(datas, valids, dtypes,
                                       range(len(dtypes))):
                    h = hash_column(d, v, dt, h, dhashes.get(i))
                p = h.astype(jnp.int32) % jnp.int32(self.num_partitions)
                return jnp.where(p < 0, p + self.num_partitions, p)
            fn = jax.jit(run)
            _HASH_CACHE[sig] = fn
        dhashes = {}
        for i, c in enumerate(kb.columns):
            if isinstance(c.dtype, t.StringType):
                dhashes[i] = jnp.asarray(dict_hash_array(c.dictionary, 42))
        ids = fn(tuple(c.data for c in kb.columns),
                 tuple(c.validity for c in kb.columns), dhashes)
        return np.asarray(jax.device_get(ids))[:int(db.num_rows)]


class RangePartitioning(Partitioning):
    """Spark RangePartitioning: sampled boundaries, searchsorted placement.
    Boundaries are computed once from the first batch (reference samples
    the whole RDD; single-process build samples the stream head)."""

    def __init__(self, sort_col: int, num_partitions: int,
                 ascending: bool = True):
        self.sort_col = sort_col
        self.num_partitions = num_partitions
        self.ascending = ascending
        self._bounds: Optional[np.ndarray] = None

    def _string_ids(self, col, n: int, side: str) -> np.ndarray:
        """Strings: bounds are VALUES (strings), not per-batch dictionary
        ranks — rank positions are meaningless across batches with
        different dictionaries.  Placement maps each (small) dictionary
        entry to its bound interval once, then indexes by code."""
        codes = np.asarray(jax.device_get(col.data))[:n]
        dict_np = np.asarray(col.dictionary.cast(pa.string())
                             .to_numpy(zero_copy_only=False)) \
            if col.dictionary is not None and len(col.dictionary) \
            else np.array([""], object)
        codes = np.clip(codes, 0, len(dict_np) - 1)
        if self._bounds is None:
            valid = np.asarray(jax.device_get(col.validity))[:n]
            live = np.sort(dict_np[codes[valid]].astype(str))
            qs = np.linspace(0, 1, self.num_partitions + 1)[1:-1]
            self._bounds = (live[(qs * (len(live) - 1)).astype(int)]
                            if live.size else np.array([""] * max(
                                self.num_partitions - 1, 1), object))
        pos = np.searchsorted(np.asarray(self._bounds, dtype=str),
                              dict_np.astype(str), side=side)
        return pos.astype(np.int32)[codes]

    def partition_ids(self, db, conf):
        col = db.columns[self.sort_col]
        n = int(db.num_rows)
        side = "right" if self.ascending else "left"
        valid = np.asarray(jax.device_get(col.validity))[:n]
        if isinstance(col.dtype, t.StringType):
            ids = self._string_ids(col, n, side)
            ids[~valid] = 0
            return ids
        vals = np.asarray(jax.device_get(col.data))[:n]
        if isinstance(col.dtype, t.DoubleType) and vals.dtype == np.int64:
            # int64 IEEE-bit storage lane: signed-int order reverses for
            # negative doubles — compare as float64 values
            vals = vals.view(np.float64)
        isnan = np.isnan(vals) if np.issubdtype(vals.dtype, np.floating) \
            else np.zeros(len(vals), bool)
        if self._bounds is None:
            live = vals[valid & ~isnan]
            qs = np.linspace(0, 1, self.num_partitions + 1)[1:-1]
            self._bounds = np.quantile(live, qs) if live.size \
                else np.zeros(self.num_partitions - 1)
        ids = np.searchsorted(self._bounds, vals, side=side).astype(np.int32)
        # Spark float order: NaN greatest -> last (asc) / first (desc)
        ids[isnan] = self.num_partitions - 1 if self.ascending else 0
        ids[~valid] = 0          # nulls first -> partition 0
        return ids
