"""In-process multithreaded shuffle (reference MULTITHREADED mode).

Reference: RapidsShuffleThreadedWriterBase/ReaderBase
(RapidsShuffleInternalManagerBase.scala:238,569) parallelize sort-shuffle
file IO with thread pools; batches ride the JCudfSerialization host wire
format.  Here the wire format is Arrow IPC (the TPU build's host columnar
format IS Arrow, so serialization is zero-copy buffer framing), partitions
live in an in-memory block store (spill-to-disk belongs to the runtime
spill store), and a thread pool overlaps per-map-task serialization.

The ICI path (parallel/exchange.py) replaces this entirely when the data
is already device-resident across a mesh; this manager is the host path
between independent processes/stages.
"""
from __future__ import annotations

import io
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np
import pyarrow as pa

from ..columnar.host import HostBatch
from ..obs.registry import SHUFFLE_BYTES, SHUFFLE_PARTITION_BYTES


class ShuffleBlockStore:
    """Partition-id -> list of serialized Arrow IPC payloads."""

    def __init__(self):
        self._blocks: Dict[Tuple[int, int], List[bytes]] = {}
        self._lock = threading.Lock()

    def put(self, shuffle_id: int, part_id: int, payload: bytes) -> None:
        with self._lock:
            self._blocks.setdefault((shuffle_id, part_id), []).append(payload)

    def put_all(self, shuffle_id: int, payloads: Dict[int, bytes]) -> None:
        """Publish every partition of one map-task write as a single
        store transaction: the lock is held across all of them and the
        in-memory appends cannot fail partway, so a retried write_batch
        never observes — or duplicates — a half-published call."""
        with self._lock:
            for part_id, payload in payloads.items():
                self._blocks.setdefault((shuffle_id, part_id),
                                        []).append(payload)

    def get(self, shuffle_id: int, part_id: int) -> List[bytes]:
        with self._lock:
            return list(self._blocks.get((shuffle_id, part_id), []))

    def remove_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            for k in [k for k in self._blocks if k[0] == shuffle_id]:
                del self._blocks[k]

    def bytes_stored(self) -> int:
        with self._lock:
            return sum(len(p) for ps in self._blocks.values() for p in ps)

    def partition_sizes(self, shuffle_id: int) -> Dict[int, int]:
        """part_id -> stored bytes (the MapStatus sizes AQE plans with)."""
        with self._lock:
            out: Dict[int, int] = {}
            for (sid, pid), ps in self._blocks.items():
                if sid == shuffle_id:
                    out[pid] = sum(len(p) for p in ps)
            return out

    def block_sizes(self, shuffle_id: int, part_id: int) -> List[int]:
        """Per stored map-block bytes of one partition — the split
        points skewed-read planning slices on."""
        with self._lock:
            return [len(p) for p in
                    self._blocks.get((shuffle_id, part_id), [])]


def serialize_batch(rb: pa.RecordBatch, codec: str = "none") -> bytes:
    """Arrow IPC wire format, optionally buffer-compressed (the nvcomp
    LZ4/ZSTD codec role, TableCompressionCodec.scala:42 — compression
    happens in the IPC layer so readers are codec-agnostic)."""
    sink = io.BytesIO()
    options = None
    if codec not in ("none", None, ""):
        options = pa.ipc.IpcWriteOptions(compression=codec)
    with pa.ipc.new_stream(sink, rb.schema, options=options) as w:
        w.write_batch(rb)
    return sink.getvalue()


def deserialize_batches(payloads: Iterable[bytes]) -> List[pa.RecordBatch]:
    out: List[pa.RecordBatch] = []
    for p in payloads:
        with pa.ipc.open_stream(io.BytesIO(p)) as r:
            out.extend(r)
    return out


class ShuffleManager:
    """Process-wide shuffle service: map-side writes split host batches by
    a precomputed partition-id lane; reduce-side reads concatenate."""

    def __init__(self, num_threads: int = 6):
        self.store = ShuffleBlockStore()
        self.pool = ThreadPoolExecutor(max_workers=num_threads,
                                       thread_name_prefix="shuffle")
        self._next_id = 0
        self._lock = threading.Lock()

    def new_shuffle(self) -> int:
        with self._lock:
            self._next_id += 1
            return self._next_id

    def write_batch(self, shuffle_id: int, hb: HostBatch,
                    part_ids: np.ndarray, num_partitions: int,
                    codec: str = "none") -> int:
        """Split one host batch by partition id and store each slice
        (serialization + compression fan out on the thread pool).
        Returns the total serialized bytes written — the MapStatus-bytes
        number the shuffle metrics and AQE planning both consume.

        Writes are transactional per call: every slice serializes first,
        then all payloads publish in one atomic store transaction
        (put_all) — a failure anywhere leaves nothing behind, so the IO
        retry ladder (runtime/retry.py retry_io) can replay the whole
        call without duplicating partitions."""
        rb = hb.rb
        if len(part_ids) and part_ids.min() == part_ids.max():
            # single-destination batch (small dim table under hash
            # partitioning, a range boundary case): no row movement
            # needed — serialize the batch whole, skip the sort + take
            out = {int(part_ids[0]): serialize_batch(rb, codec)}
        else:
            order = np.argsort(part_ids, kind="stable")
            sorted_ids = part_ids[order]
            bounds = np.searchsorted(sorted_ids,
                                     np.arange(num_partitions + 1))
            idx_arr = pa.array(order)

            def ser(p: int):
                s, e = bounds[p], bounds[p + 1]
                if s == e:
                    return None
                sl = rb.take(idx_arr.slice(s, e - s))
                return serialize_batch(sl, codec)

            payloads = list(self.pool.map(ser, range(num_partitions)))
            out = {p: payload for p, payload in enumerate(payloads)
                   if payload is not None}
        self.store.put_all(shuffle_id, out)
        total = sum(len(p) for p in out.values())
        # always-on telemetry: per-partition byte-SKEW distribution (one
        # observation per written slice) + the write-direction total
        for payload in out.values():
            SHUFFLE_PARTITION_BYTES.observe(len(payload))
        SHUFFLE_BYTES.inc(total, direction="written")
        return total

    def read_partition(self, shuffle_id: int, part_id: int,
                       block_range=None) -> List[pa.RecordBatch]:
        """All of one partition, or a [lo, hi) slice of its stored
        map-blocks (skewed-partition sub-reads)."""
        payloads = self.store.get(shuffle_id, part_id)
        if block_range is not None:
            lo, hi = block_range
            payloads = payloads[lo:hi]
        SHUFFLE_BYTES.inc(sum(len(p) for p in payloads),
                          direction="read")
        return deserialize_batches(payloads)

    def partition_sizes(self, shuffle_id: int) -> Dict[int, int]:
        return self.store.partition_sizes(shuffle_id)

    def block_sizes(self, shuffle_id: int, part_id: int) -> List[int]:
        return self.store.block_sizes(shuffle_id, part_id)


_MANAGER: Optional[ShuffleManager] = None


def get_shuffle_manager() -> ShuffleManager:
    global _MANAGER
    if _MANAGER is None:
        _MANAGER = ShuffleManager()
    return _MANAGER
