"""Multi-chip benchmark suite: mesh primitives + a sharded TPC-H run.

The MULTICHIP_r* trajectory used to be microbenchmarks only; this
module raises it to a real suite (ROADMAP item 4 / Theseus: distributed
engines win or lose on data movement at scale):

  1. **Primitive timings** with the r05-compatible keys — the fused
     distributed groupby at 1M rows/device (now the compressed
     quota-scheduled ragged pipeline), the 65k ragged groupby, the
     distributed window rank — so the regression gate
     (scripts/check_regression.py) compares rounds apples-to-apples;
  2. **Mesh TPC-H microqueries** (q1/q6/q12 at the r05 scale) for the
     same reason;
  3. **The sharded suite**: TPC-H at a real scale factor with fact
     tables *generated in per-shard chunks* (bounded per-chunk datagen,
     globally consistent key spaces), executed SPMD over the mesh
     (`spark.rapids.tpu.sql.mesh.enabled`) with a finite HBM budget so
     the spill tier engages; per-query wall, oracle check (budget
     gated), spill/exchange telemetry from the always-on registry.

Run via `python bench.py --multichip-suite [--multichip-sf N]` — bench
owns the CLI; this module owns the measurement so tests can drive it
at toy scale.
"""
from __future__ import annotations

import json
import os
import sys
import time
from typing import Dict, List, Optional

import numpy as np


def _setup_devices(n_devices: int) -> None:
    """Secure n virtual CPU devices BEFORE backend init (the
    __graft_entry__.dryrun_multichip / tests-conftest recipe)."""
    import jax
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") +
        f" --xla_force_host_platform_device_count={n_devices}")
    try:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_enable_x64", True)
        jax.config.update("jax_num_cpu_devices", n_devices)
    except (RuntimeError, AttributeError):
        pass                    # backend already up, or pre-0.5 jax


def gen_tables_sharded(scale: float, n_shards: int, seed: int = 20240706
                       ) -> Dict[str, "object"]:
    """TPC-H tables with the FACT volume of `scale`, generated in
    `n_shards` independent per-shard chunks (bounded chunk datagen, the
    sharded-ingest shape of a real cluster load) and re-keyed into one
    coherent key space: shard s owns order keys [s*N, (s+1)*N).  Fact
    foreign keys draw from the shard-scale dimension tables, so every
    join has full referential coverage.  Dimensions come from chunk 0
    (`dims_scale = scale / n_shards` — fact-heavy, the data-movement
    stress shape)."""
    import pyarrow as pa
    import pyarrow.compute as pc
    from . import tpch
    per = scale / n_shards
    shards = [tpch.gen_tables(scale=per, seed=seed + 7919 * s)
              for s in range(n_shards)]
    n_ord_s = shards[0]["orders"].num_rows
    orders, lineitem = [], []
    for s, t in enumerate(shards):
        off = s * n_ord_s
        o, li = t["orders"], t["lineitem"]
        orders.append(o.set_column(
            o.schema.get_field_index("o_orderkey"), "o_orderkey",
            pc.add(o["o_orderkey"], off)))
        lineitem.append(li.set_column(
            li.schema.get_field_index("l_orderkey"), "l_orderkey",
            pc.add(li["l_orderkey"], off)))
    out = dict(shards[0])
    out["orders"] = pa.concat_tables(orders).combine_chunks()
    out["lineitem"] = pa.concat_tables(lineitem).combine_chunks()
    return out


def _timed(timings: dict, name: str):
    class _T:
        def __enter__(self):
            self.t0 = time.perf_counter()

        def __exit__(self, *a):
            timings[name] = round(time.perf_counter() - self.t0, 2)
    return _T()


class _mesh_traced:
    """Collect the mesh exchange timeline of one measured block: a
    QueryTracer is made ACTIVE for the block so every ragged-exchange
    round / dictionary gather / skew split lands in it, and the parsed
    timeline (QueryProfile.mesh_timeline) is stored under `name` —
    the per-round exchange telemetry the MULTICHIP records embed."""

    def __init__(self, timelines: dict, name: str):
        self.timelines = timelines
        self.name = name

    def __enter__(self):
        from .obs.tracer import QueryTracer, set_active
        self.tr = QueryTracer(0)
        set_active(self.tr)
        return self.tr

    def __exit__(self, *a):
        from .obs.profile import QueryProfile
        from .obs.tracer import NULL_TRACER, set_active
        set_active(NULL_TRACER)
        prof = QueryProfile(self.tr.spans, self.tr.events,
                            self.tr.counters, {}, {})
        tl = prof.mesh_timeline()
        tl["ici_exchange_bytes"] = int(
            self.tr.counters.get("ici_exchange_bytes", 0))
        self.timelines[self.name] = tl


def _primitives(mesh, timings: dict, scale: float = 1.0,
                timelines: Optional[dict] = None) -> None:
    """The r05-compatible primitive benchmarks: fused groupby at 1M
    rows/device (the retired bucket stack's headline case), ragged
    groupby + window rank at 64k rows/device."""
    import jax
    import jax.numpy as jnp
    from . import types as t
    from .ops import groupby as G
    from .parallel.exchange import (distributed_groupby_ragged,
                                    distributed_groupby_step,
                                    distributed_window_rank)
    n_devices = mesh.devices.size
    big_cap = max(1024, int((1 << 20) * scale))
    local_cap = max(64, int((1 << 16) * scale))
    rng = np.random.default_rng(3)
    specs = [G.AggSpec(G.SUM, 0, t.LONG), G.AggSpec(G.COUNT, 0, t.LONG)]

    def check(kd, outs, ngroups, keys, key_valid, vals):
        total = int(np.asarray(ngroups).sum())
        distinct = len(set(keys[key_valid].tolist())) + \
            int((~key_valid).any())
        assert total == distinct, (total, distinct)
        sums = np.asarray(outs[0][0])
        ng = np.asarray(ngroups)
        mcap = np.asarray(kd).shape[0] // n_devices
        got = sum(sums[p * mcap: p * mcap + int(ng[p])].sum()
                  for p in range(n_devices))
        assert got == vals.sum(), got

    # fused groupby, 1M rows/device, hot-key skew (the r05 fixture)
    nb = n_devices * big_cap
    bkeys = rng.integers(0, 5000, nb).astype(np.int64)
    bkeys[rng.random(nb) < 0.4] = 3
    bkey_valid = rng.random(nb) < 0.9
    bvals = rng.integers(-10, 10, nb).astype(np.int64)
    timelines = {} if timelines is None else timelines
    fn, shard = distributed_groupby_step(mesh, t.LONG, specs, big_cap)
    with _timed(timings, f"groupby_{big_cap}_rows_per_device"), \
            _mesh_traced(timelines, f"groupby_{big_cap}_rows_per_device"):
        (kd, kv), outs, ngroups = fn(
            jax.device_put(jnp.asarray(bkeys), shard),
            jax.device_put(jnp.asarray(bkey_valid), shard),
            [jax.device_put(jnp.asarray(bvals), shard)],
            [jax.device_put(jnp.ones(nb, bool), shard)])
        jax.block_until_ready((kd, ngroups))
    check(kd, outs, ngroups, bkeys, bkey_valid, bvals)
    del kd, kv, outs, ngroups, bkeys, bkey_valid, bvals

    n = n_devices * local_cap
    keys = rng.integers(0, 7, n).astype(np.int64)
    keys[rng.random(n) < 0.4] = 3
    key_valid = rng.random(n) < 0.9
    vals = rng.integers(-10, 10, n).astype(np.int64)
    run, shard2 = distributed_groupby_ragged(mesh, t.LONG, specs,
                                             local_cap)
    with _timed(timings, f"ragged_groupby_{local_cap}_rows_per_device"), \
            _mesh_traced(timelines,
                         f"ragged_groupby_{local_cap}_rows_per_device"):
        (kd2, _), outs2, ngroups2 = run(
            jax.device_put(jnp.asarray(keys), shard2),
            jax.device_put(jnp.asarray(key_valid), shard2),
            [jax.device_put(jnp.asarray(vals), shard2)],
            [jax.device_put(jnp.ones(n, bool), shard2)])
        jax.block_until_ready((kd2, ngroups2))
    check(kd2, outs2, ngroups2, keys, key_valid, vals)

    wpk = rng.integers(0, 200, n).astype(np.int64)
    wpk[rng.random(n) < 0.4] = 7
    wok = rng.integers(0, 50, n).astype(np.int64)
    wlv = rng.random(n) < 0.9
    with _timed(timings, f"window_rank_{local_cap}_rows_per_device"), \
            _mesh_traced(timelines,
                         f"window_rank_{local_cap}_rows_per_device"):
        _, _, rank, _ = distributed_window_rank(
            mesh, jax.device_put(jnp.asarray(wpk), shard2),
            jax.device_put(jnp.asarray(wok), shard2),
            jax.device_put(jnp.asarray(wlv), shard2))
        jax.block_until_ready(rank)


def _approx_equal(a, b) -> bool:
    da, db = a.to_pydict(), b.to_pydict()
    if set(da) != set(db):
        return False
    for k in da:
        if len(da[k]) != len(db[k]):
            return False
        for x, y in zip(da[k], db[k]):
            if x == y:
                continue
            if isinstance(x, float) and isinstance(y, float) and \
                    abs(x - y) <= 1e-6 * max(1.0, abs(x), abs(y)):
                continue
            return False
    return True


def run_multichip_suite(n_devices: int = 8, sf: float = 10.0,
                        queries: Optional[List[str]] = None,
                        budget_s: float = 1800.0,
                        hbm_budget_bytes: int = 1 << 30,
                        micro_scale: float = 1.0,
                        oracle_budget_s: float = 120.0) -> dict:
    """The full multichip round: primitives + r05 mesh microqueries +
    the sharded TPC-H suite.  Prints a running JSON line after every
    stage (the bench.py lossless-kill discipline) and returns the final
    document."""
    _setup_devices(n_devices)
    import jax
    from .config import (COMPILE_CACHE_DIR, HBM_BUDGET_BYTES,
                         MESH_DEVICES, MESH_ENABLED)
    from .exec.plan import ExecContext
    from .parallel.mesh import make_mesh
    from .session import DataFrame, TpuSession
    from . import tpch

    t_start = time.perf_counter()

    def left():
        return budget_s - (time.perf_counter() - t_start)

    doc: dict = {"suite": "multichip", "n_devices": n_devices,
                 "backend": jax.default_backend(),
                 "multichip_sf": sf, "final": False}
    timings: dict = {}
    doc["multichip_timings_s"] = timings

    def emit(final=False):
        doc["final"] = final
        doc["elapsed_s"] = round(time.perf_counter() - t_start, 1)
        try:
            import resource
            doc["peak_rss_mb"] = resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss // 1024
        except Exception:                        # noqa: BLE001
            doc["peak_rss_mb"] = -1
        print(json.dumps(doc), flush=True)

    # topology-scoped persistent compile cache (the bench.py discipline:
    # cold numbers report cache loads; the per-round pcache delta below
    # is the proof of what was compiled vs replayed)
    cache_root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache_bench")
    from .config import TpuConf
    from .exec.compiled import (configure_persistent_cache,
                                persistent_cache_stats)
    configure_persistent_cache(TpuConf(
        {COMPILE_CACHE_DIR.key: cache_root}))
    pc0 = persistent_cache_stats()

    mesh = make_mesh(n_devices)
    doc["rows_per_device"] = {
        "fused_groupby": max(1024, int((1 << 20) * micro_scale)),
        "other_primitives": max(64, int((1 << 16) * micro_scale))}
    # per-round exchange timelines (round quotas, wire bytes pre/post
    # compress, arrival counts, staging vs collective ms) ride the
    # record next to the wall timings they explain
    prim_timelines: Dict[str, dict] = {}
    doc["primitives_mesh_timeline"] = prim_timelines
    _primitives(mesh, timings, scale=micro_scale,
                timelines=prim_timelines)
    from .obs.registry import REGISTRY
    doc["exchange"] = {
        k: REGISTRY.get(f"tpu_exchange_wire_bytes_{k}_compress_total")
        .value() for k in ("pre", "post")}
    emit()

    # -- r05-comparable mesh microqueries (tiny SF, same keys) ------------
    micro_tables = tpch.gen_tables(scale=0.002)
    mesh_conf = {MESH_ENABLED.key: True, MESH_DEVICES.key: n_devices,
                 COMPILE_CACHE_DIR.key: cache_root}
    s = TpuSession(mesh_conf)
    cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
    for qname in ("q1", "q6", "q12"):
        dfq = tpch.QUERIES[qname](s, micro_tables)
        ctx = ExecContext(s.conf)
        with _timed(timings, f"mesh_query_{qname}"):
            out = dfq.physical().collect(ctx)
        assert ctx.metrics.get("whole_plan_compiled_queries", 0) == 1
        oracle = DataFrame(dfq._plan, cpu).collect()
        assert _approx_equal(out, oracle), f"mesh {qname} oracle mismatch"
    emit()

    # -- the sharded suite ------------------------------------------------
    t0 = time.perf_counter()
    tables = gen_tables_sharded(sf, n_devices)
    doc["datagen_s"] = round(time.perf_counter() - t0, 1)
    doc["lineitem_rows"] = tables["lineitem"].num_rows
    # finite HBM budget so the spill tier engages at suite scale
    suite_conf = dict(mesh_conf)
    suite_conf[HBM_BUDGET_BYTES.key] = hbm_budget_bytes
    sdev = TpuSession(suite_conf)
    scpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
    names = queries or sorted(tpch.QUERIES, key=lambda q: int(q[1:]))
    per_q: Dict[str, dict] = {}
    doc["multichip_suite_queries"] = per_q
    spill0 = REGISTRY.get("tpu_spill_batches_total")
    spill_before = sum(s_["value"] for s_ in spill0.series()) \
        if spill0.series() else 0
    for name in names:
        if left() < 30:
            doc.setdefault("skipped", []).append(name)
            continue
        rec: dict = {}
        per_q[name] = rec
        try:
            dfq = tpch.QUERIES[name](sdev, tables)
            q = dfq.physical()
            # cold collect runs TRACED so the record embeds the query's
            # mesh exchange timeline + per-query ICI byte attribution
            # (cold wall includes compile anyway; tracer cost is noise)
            from .config import TRACE_ENABLED
            ctx = ExecContext(TpuConf({**sdev.conf._raw,
                                       TRACE_ENABLED.key: True}))
            t0 = time.perf_counter()
            out = q.collect(ctx)
            rec["cold_s"] = round(time.perf_counter() - t0, 2)
            rec["compiled"] = bool(
                ctx.metrics.get("whole_plan_compiled_queries", 0))
            from .obs.profile import QueryProfile
            prof = QueryProfile.from_context(ctx)
            tl = prof.mesh_timeline()
            if tl["exchanges"] or tl["skew_splits"]:
                rec["mesh_timeline"] = tl
            ici = prof.counters.get("ici_exchange_bytes", 0)
            if ici:
                rec["ici_exchange_bytes"] = int(ici)
            # per-query HBM attribution from the traced cold collect:
            # the budget peak + the XLA memory_analysis working-set
            # floor ride the record so check_regression.py can gate
            # HBM-peak regressions on the mesh suite too
            hbm_peak = max(int(ctx.metrics.get("memory.peak_bytes")
                               or 0),
                           int(ctx.metrics.get("exec_hbm_bytes") or 0))
            if hbm_peak:
                rec["hbm_peak_bytes"] = hbm_peak
            mws = int(ctx.metrics.get("exec_hbm_bytes") or 0)
            if mws:
                rec["hbm_measured_working_set"] = mws
            t0 = time.perf_counter()
            q.collect(ExecContext(sdev.conf))
            warm = time.perf_counter() - t0
            # wall_ms, NOT device_ms: these are mesh-suite timings at
            # --multichip-sf scale — the regression gate compares them
            # via the mc:mesh_sf* keys, never against single-chip qN
            rec["wall_ms"] = round(warm * 1e3, 1)
            timings[f"mesh_sf{sf:g}_{name}"] = round(warm, 2)
            if left() > oracle_budget_s:
                cq = DataFrame(dfq._plan, scpu).physical()
                t0 = time.perf_counter()
                oracle = cq.collect()
                rec["cpu_wall_ms"] = round(
                    (time.perf_counter() - t0) * 1e3, 1)
                rec["match"] = _approx_equal(out, oracle)
            else:
                rec["match"] = None              # oracle budget-gated
        except Exception as e:                   # noqa: BLE001
            rec["error"] = f"{type(e).__name__}: {e}"[:200]
        print(f"# multichip {name}: {rec}", file=sys.stderr)
        emit()
    # -- spill leg: the same sharded tables through the eager engine
    # under a finite HBM budget, so the memory-tiering plane is
    # EXERCISED at suite volume (the mesh whole-plan path keeps its
    # working set inside the XLA program and never consults the budget
    # — integrating the two is a ROADMAP item, so the suite proves the
    # tier on the engine that owns it)
    spill_conf = {"spark.rapids.tpu.sql.compile.wholePlan": "OFF",
                  HBM_BUDGET_BYTES.key: min(hbm_budget_bytes, 1 << 23),
                  "spark.rapids.tpu.sql.batchSizeRows": 1 << 16}
    sspill = TpuSession(spill_conf)
    for name in ("q3", "q18"):
        if left() < 60 or name not in tpch.QUERIES:
            continue
        rec = per_q.setdefault(name, {})
        try:
            sctx = ExecContext(sspill.conf)
            t0 = time.perf_counter()
            tpch.QUERIES[name](sspill, tables).physical().collect(sctx)
            rec["spill_leg_wall_ms"] = round(
                (time.perf_counter() - t0) * 1e3, 1)
            # the spill leg's budget peak is the interesting HBM
            # number at suite scale (the eager engine actually
            # reserves): ride it next to the wall
            speak = int(sctx.metrics.get("memory.peak_bytes") or 0)
            if speak:
                rec["spill_leg_hbm_peak_bytes"] = speak
        except Exception as e:                   # noqa: BLE001
            rec["spill_leg_error"] = f"{type(e).__name__}: {e}"[:200]
    spill_after = sum(s_["value"] for s_ in spill0.series()) \
        if spill0.series() else 0
    doc["spill_batches"] = spill_after - spill_before
    doc["exchange"] = {
        k: REGISTRY.get(f"tpu_exchange_wire_bytes_{k}_compress_total")
        .value() for k in ("pre", "post")}
    doc["queries_measured"] = len(per_q)
    doc["errors"] = sum(1 for v in per_q.values() if "error" in v)
    pc1 = persistent_cache_stats()
    doc["pcache"] = {"hits": pc1["hits"] - pc0["hits"],
                     "misses": pc1["misses"] - pc0["misses"]}
    emit(final=True)
    return doc
