"""UDF acceleration — the RapidsUDF / row-based UDF roles (SURVEY §2.8).

Reference: `com.nvidia.spark.RapidsUDF` lets users hand-write columnar GPU
UDFs (evaluateColumnar over cuDF ColumnVectors); untranslatable JVM UDFs
run row-by-row on the host inside the columnar pipeline
(GpuRowBasedUserDefinedFunction); the udf-compiler decompiles simple
lambdas to Catalyst.

TPU-first translation:
  * **TpuUDF** — the user writes a jax-traceable function over jnp arrays.
    Because expression evaluation IS jit tracing here (exec/evaluator.py),
    the UDF body inlines into the operator's single XLA program: it fuses
    with the surrounding projection/filter/aggregation for free — a
    *stronger* form of the reference's evaluateColumnar, which still pays
    per-kernel launches.  Null semantics: result row is NULL when any
    input row is NULL (Spark's default for non-primitive-safe UDFs);
    `needs_validity=True` hands the fn (data, validity) pairs instead for
    custom null handling.
  * **PythonUDF** — arbitrary per-row python callable; tagged off-device
    so the enclosing operator falls back to the CPU path (the row-based
    host UDF contract).  The udf-compiler's bytecode-to-expression role
    has no analogue yet (users can compose Expression trees directly,
    which is what its output would be).
"""
from __future__ import annotations

from typing import Callable, Sequence

import numpy as np
import pyarrow as pa

from .. import types as t
from ..ops.kernels import merge_validity
from .expressions import DevVal, Expression, HostVal


# Pins every UDF fn for process lifetime so id(fn) in jit-cache keys can
# never alias a garbage-collected function's recycled address (the cache
# itself is process-lifetime, so the pin adds no real retention).
_UDF_PIN: dict = {}


class TpuUDF(Expression):
    """Columnar device UDF over jax arrays (the RapidsUDF analogue)."""

    def __init__(self, fn: Callable, return_type: t.DataType,
                 *args: Expression, name: str = None,
                 needs_validity: bool = False):
        self.children = tuple(args)
        self.fn = fn
        _UDF_PIN[id(fn)] = fn
        self.return_type = return_type
        self.udf_name = name or getattr(fn, "__name__", "udf")
        self.needs_validity = needs_validity

    def _resolve(self):
        self.dtype = self.return_type
        self.nullable = True

    def _fp_extra(self):
        # identity-keyed: each distinct fn object traces its own program
        return f"{self.udf_name}@{id(self.fn)};{self.needs_validity}"

    def unsupported_reasons(self, conf):
        out = []
        for c in self.children:
            if isinstance(c.dtype, (t.StringType, t.BinaryType,
                                    t.ArrayType, t.MapType, t.StructType)):
                out.append(f"TpuUDF over {c.dtype.simple_string} input "
                           "(jax lanes are numeric)")
        if isinstance(self.return_type,
                      (t.StringType, t.ArrayType, t.MapType, t.StructType)):
            out.append("TpuUDF returning "
                       f"{self.return_type.simple_string}")
        return out

    def _prepare(self, pctx, kids):
        return HostVal()

    def _eval_dev(self, ctx, kids):
        if self.needs_validity:
            out = self.fn(*[(k.data, k.validity) for k in kids])
            if isinstance(out, tuple):
                data, valid = out
            else:
                data, valid = out, merge_validity(
                    *[k.validity for k in kids])
        else:
            data = self.fn(*[k.data for k in kids])
            valid = merge_validity(*[k.validity for k in kids])
        return DevVal(data, valid, self.dtype)

    def _eval_cpu(self, rb, kids):
        """Oracle path: run the same traceable fn over numpy lanes."""
        import jax.numpy as jnp
        from ..columnar.host import dtype_to_arrow
        import pyarrow.compute as pc
        datas, valids = [], []
        for k, c in zip(kids, self.children):
            valids.append(pc.is_valid(k).to_numpy(zero_copy_only=False))
            np_dt = t.physical_np_dtype(c.dtype)
            if isinstance(c.dtype, (t.FloatType, t.DoubleType)):
                np_dt = np.float64 if isinstance(c.dtype, t.DoubleType) \
                    else np.float32
            a = k.cast(pa.float64()) if isinstance(
                c.dtype, (t.FloatType, t.DoubleType)) else k
            datas.append(np.asarray(
                a.fill_null(0).to_numpy(zero_copy_only=False)).astype(
                np_dt, copy=False))
        if self.needs_validity:
            out = self.fn(*[(jnp.asarray(d), jnp.asarray(v))
                            for d, v in zip(datas, valids)])
            data, valid = out if isinstance(out, tuple) else \
                (out, np.logical_and.reduce(valids) if valids else None)
        else:
            data = self.fn(*[jnp.asarray(d) for d in datas])
            valid = np.logical_and.reduce(valids) if valids else \
                np.ones(rb.num_rows, bool)
        data = np.asarray(data)
        valid = np.asarray(valid)
        want = dtype_to_arrow(self.dtype)
        if isinstance(self.dtype, (t.FloatType, t.DoubleType)):
            return pa.array(data.astype(np.float64), pa.float64(),
                            mask=~valid).cast(want)
        return pa.array(data, mask=~valid).cast(want)

    def __repr__(self):
        return f"{self.udf_name}({', '.join(map(repr, self.children))})"


class PythonUDF(Expression):
    """Row-at-a-time python UDF: CPU path only (the row-based host UDF
    contract, rowBasedHiveUDFs/GpuRowBasedUserDefinedFunction role)."""

    def __init__(self, fn: Callable, return_type: t.DataType,
                 *args: Expression, name: str = None,
                 null_safe: bool = True):
        self.children = tuple(args)
        self.fn = fn
        self.return_type = return_type
        self.udf_name = name or getattr(fn, "__name__", "py_udf")
        self.null_safe = null_safe     # any-null input -> null, fn skipped

    def _resolve(self):
        self.dtype = self.return_type
        self.nullable = True

    def _fp_extra(self):
        return f"{self.udf_name}@{id(self.fn)}"

    def unsupported_reasons(self, conf):
        return ["python UDFs run row-at-a-time on the CPU path"]

    def _eval_cpu(self, rb, kids):
        from ..columnar.host import dtype_to_arrow
        cols = [k.to_pylist() for k in kids]
        rows = zip(*cols) if cols else (() for _ in range(rb.num_rows))
        out = []
        for row in rows:
            if self.null_safe and any(v is None for v in row):
                out.append(None)
            else:
                out.append(self.fn(*row))
        return pa.array(out, dtype_to_arrow(self.dtype))

    def __repr__(self):
        return f"{self.udf_name}({', '.join(map(repr, self.children))})"
