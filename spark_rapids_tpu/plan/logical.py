"""Logical plan nodes — the Catalyst-physical-plan analogue the overrides
engine rewrites.

In the reference, Spark hands the plugin a *physical* plan whose nodes are
wrapped into `RapidsMeta` trees, tagged, and converted
(GpuOverrides.scala:4364 wrapAndTagPlan, RapidsMeta.scala:83).  This engine
owns its own planner, so the pre-rewrite representation is this small
logical algebra: each node declares its schema (resolving expressions
against children) and nothing else — placement (TPU vs CPU), transitions,
and physical operator choice are decided entirely by plan/overrides.py.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import pyarrow as pa

from .. import types as t
from . import expressions as E
from .aggregates import AggregateFunction


class LogicalPlan:
    """Base logical operator. Schema resolves lazily, children first."""

    def __init__(self, *children: "LogicalPlan"):
        self.children = list(children)
        self._schema: Optional[t.StructType] = None

    @property
    def child(self) -> "LogicalPlan":
        return self.children[0]

    @property
    def schema(self) -> t.StructType:
        if self._schema is None:
            self._schema = self._resolve_schema()
        return self._schema

    def _resolve_schema(self) -> t.StructType:
        raise NotImplementedError(type(self).__name__)

    def name(self) -> str:
        return type(self).__name__.removeprefix("Logical")

    def describe(self) -> str:
        return self.name()

    def tree_string(self, indent: int = 0) -> str:
        lines = ["  " * indent + self.describe()]
        for c in self.children:
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)


def _as_expr(e) -> E.Expression:
    return E.ColumnRef(e) if isinstance(e, str) else e


def _out_name(e: E.Expression, i: int) -> str:
    if isinstance(e, E.Alias):
        return e.name
    if isinstance(e, E.ColumnRef):
        return e.name
    return f"col{i}"


class LogicalScan(LogicalPlan):
    """Leaf over an in-memory Arrow table (the InMemoryScan / LocalTableScan
    analogue).  File scans are LogicalFileScan (io/)."""

    def __init__(self, table: pa.Table):
        super().__init__()
        self.table = table

    def _resolve_schema(self):
        from ..columnar.host import schema_to_struct
        return schema_to_struct(self.table.schema)

    def describe(self):
        return f"Scan[{self.table.num_rows} rows]"


class LogicalProject(LogicalPlan):
    def __init__(self, exprs: Sequence, child: LogicalPlan,
                 names: Optional[Sequence[str]] = None):
        super().__init__(child)
        self.exprs = [_as_expr(e) for e in exprs]
        self.names = list(names) if names is not None else \
            [_out_name(e, i) for i, e in enumerate(self.exprs)]

    def _resolve_schema(self):
        bound = [e.bind(self.child.schema) for e in self.exprs]
        return t.StructType([t.StructField(n, e.dtype, e.nullable)
                             for n, e in zip(self.names, bound)])

    def describe(self):
        return f"Project[{', '.join(self.names)}]"


class LogicalFilter(LogicalPlan):
    def __init__(self, condition: E.Expression, child: LogicalPlan):
        super().__init__(child)
        self.condition = _as_expr(condition)

    def _resolve_schema(self):
        return self.child.schema

    def describe(self):
        return f"Filter[{self.condition!r}]"


class LogicalAggregate(LogicalPlan):
    """group-by keys + aggregate list.  keys may be arbitrary expressions;
    aggs are (AggregateFunction, output name) pairs."""

    def __init__(self, keys: Sequence, aggs: Sequence[Tuple[AggregateFunction, str]],
                 child: LogicalPlan, key_names: Optional[Sequence[str]] = None):
        super().__init__(child)
        self.keys = [_as_expr(k) for k in keys]
        self.key_names = list(key_names) if key_names is not None else \
            [_out_name(k, i) for i, k in enumerate(self.keys)]
        self.aggs = list(aggs)

    def _resolve_schema(self):
        schema = self.child.schema
        fields = []
        for n, k in zip(self.key_names, self.keys):
            fields.append(t.StructField(n, k.bind(schema).dtype))
        for fn, n in self.aggs:
            fields.append(t.StructField(n, fn.bind(schema).dtype))
        return t.StructType(fields)

    def describe(self):
        return (f"Aggregate[keys={self.key_names}, "
                f"aggs={[n for _, n in self.aggs]}]")


class LogicalSort(LogicalPlan):
    """orders: sequence of (expr-or-name, ascending, nulls_first)."""

    def __init__(self, orders: Sequence, child: LogicalPlan,
                 global_sort: bool = True):
        super().__init__(child)
        norm = []
        for o in orders:
            if isinstance(o, (str, E.Expression)):
                norm.append((_as_expr(o), True, True))
            else:
                e, *rest = o
                asc = rest[0] if rest else True
                nf = rest[1] if len(rest) > 1 else asc
                norm.append((_as_expr(e), asc, nf))
        self.orders = norm
        self.global_sort = global_sort

    def _resolve_schema(self):
        return self.child.schema

    def describe(self):
        ks = [(e.name if isinstance(e, E.ColumnRef) else repr(e),
               "asc" if a else "desc") for e, a, _ in self.orders]
        return f"Sort[{ks}]"


class LogicalLimit(LogicalPlan):
    def __init__(self, limit: int, child: LogicalPlan):
        super().__init__(child)
        self.limit = limit

    def _resolve_schema(self):
        return self.child.schema

    def describe(self):
        return f"Limit[{self.limit}]"


class LogicalJoin(LogicalPlan):
    """Equi-join on key expression pairs.  join_type: inner, left_outer,
    right_outer, full_outer, left_semi, left_anti, cross."""

    _MIRROR = {"inner": "inner", "left_outer": "right_outer",
               "right_outer": "left_outer", "full_outer": "full_outer",
               "cross": "cross"}

    def __init__(self, join_type: str, left: LogicalPlan, right: LogicalPlan,
                 left_keys: Sequence = (), right_keys: Sequence = (),
                 broadcast: Optional[str] = None):
        """broadcast: None | "left" | "right" — the BROADCAST hint side.
        A "left" broadcast mirrors the join so the broadcast side becomes
        the build (right) side; non-mirrorable types (semi/anti) keep the
        hint only when it already points right."""
        if broadcast == "left" and join_type in self._MIRROR:
            left, right = right, left
            left_keys, right_keys = right_keys, left_keys
            join_type = self._MIRROR[join_type]
            broadcast = "right"
        super().__init__(left, right)
        self.join_type = join_type
        self.left_keys = [_as_expr(k) for k in left_keys]
        self.right_keys = [_as_expr(k) for k in right_keys]
        self.broadcast = broadcast if broadcast == "right" else None

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]

    def _resolve_schema(self):
        # analysis-time key type check: Spark coerces mismatched key
        # types in the analyzer; this engine (like the physical layer
        # the reference plugs into) requires equal types — callers cast
        # explicitly.  Both engine paths must fail identically, so the
        # error is raised here, not at execution.
        for lk, rk in zip(self.left_keys, self.right_keys):
            lt_ = lk.bind(self.left.schema).dtype
            rt_ = rk.bind(self.right.schema).dtype
            # field-wise inequality: decimal(10,2) vs decimal(10,4) must
            # also fail — join kernels compare raw unscaled lanes
            if lt_ != rt_:
                raise TypeError(
                    f"join key type mismatch: {lt_.simple_string} vs "
                    f"{rt_.simple_string} — add an explicit Cast")
        lf = list(self.left.schema.fields)
        if self.join_type in ("left_semi", "left_anti"):
            return t.StructType(lf)
        return t.StructType(lf + list(self.right.schema.fields))

    def describe(self):
        return f"Join[{self.join_type}, keys={len(self.left_keys)}]"


class LogicalSample(LogicalPlan):
    """Bernoulli row sample (reference GpuSampleExec,
    basicPhysicalOperators.scala:838): each row kept independently with
    probability `fraction`, decided by a counter-based hash of
    (seed, global row position) — deterministic for a given seed AND
    identical on the device and CPU paths."""

    def __init__(self, fraction: float, seed: int, child: LogicalPlan):
        super().__init__(child)
        if not 0.0 <= fraction <= 1.0:
            raise ValueError(f"sample fraction {fraction} not in [0, 1]")
        self.fraction = float(fraction)
        self.seed = int(seed)

    def _resolve_schema(self):
        return self.child.schema

    def describe(self):
        return f"Sample[{self.fraction}, seed={self.seed}]"


class LogicalUnion(LogicalPlan):
    def __init__(self, *children: LogicalPlan):
        super().__init__(*children)

    def _resolve_schema(self):
        return self.children[0].schema


class LogicalRange(LogicalPlan):
    def __init__(self, start: int, end: int, step: int = 1, name: str = "id"):
        super().__init__()
        self.start, self.end, self.step = start, end, step
        self.col_name = name

    def _resolve_schema(self):
        return t.StructType([t.StructField(self.col_name, t.LongType(), False)])

    def describe(self):
        return f"Range[{self.start},{self.end},{self.step}]"


class LogicalExpand(LogicalPlan):
    def __init__(self, projections: Sequence[Sequence], names: Sequence[str],
                 child: LogicalPlan):
        super().__init__(child)
        self.projections = [[_as_expr(e) for e in p] for p in projections]
        self.names = list(names)

    def _resolve_schema(self):
        bound = [e.bind(self.child.schema) for e in self.projections[0]]
        return t.StructType([t.StructField(n, e.dtype)
                             for n, e in zip(self.names, bound)])


class LogicalWindow(LogicalPlan):
    """Window functions over (partition keys, order keys).  window_exprs:
    (WindowFunctionSpec, output name) pairs appended to the child schema.
    See plan/window.py for specs."""

    def __init__(self, window_exprs: Sequence, partition_keys: Sequence,
                 order_keys: Sequence, child: LogicalPlan):
        from .window import check_window_analysis
        super().__init__(child)
        check_window_analysis(window_exprs, order_keys)
        self.window_exprs = list(window_exprs)
        self.partition_keys = [_as_expr(k) for k in partition_keys]
        norm = []
        for o in order_keys:
            if isinstance(o, (str, E.Expression)):
                norm.append((_as_expr(o), True, True))
            else:
                e, *rest = o
                asc = rest[0] if rest else True
                nf = rest[1] if len(rest) > 1 else asc
                norm.append((_as_expr(e), asc, nf))
        self.order_keys = norm

    def _resolve_schema(self):
        fields = list(self.child.schema.fields)
        for spec, name in self.window_exprs:
            bound = spec.bind(self.child.schema)
            fields.append(t.StructField(name, bound.dtype))
        return t.StructType(fields)

    def describe(self):
        return f"Window[{[n for _, n in self.window_exprs]}]"


class LogicalMapInPandas(LogicalPlan):
    """mapInPandas: iterator-of-pandas-DataFrames transform through a
    forked Arrow-IPC python worker (reference GpuMapInPandasExec)."""

    def __init__(self, fn, schema, child: LogicalPlan):
        super().__init__(child)
        self.fn = fn
        self.result_schema = schema

    def _resolve_schema(self):
        return self.result_schema

    def describe(self):
        return f"MapInPandas[{getattr(self.fn, '__name__', 'fn')}]"


class LogicalArrowEvalPython(LogicalPlan):
    """Scalar pandas-UDF projection outputs appended to the child
    (reference GpuArrowEvalPythonExec)."""

    def __init__(self, udfs, child: LogicalPlan):
        super().__init__(child)
        self.udfs = list(udfs)     # (fn, in_cols, name, dtype)

    def _resolve_schema(self):
        fields = list(self.child.schema.fields)
        for _fn, _cols, name, dt in self.udfs:
            fields.append(t.StructField(name, dt, True))
        return t.StructType(fields)

    def describe(self):
        return f"ArrowEvalPython[{[n for _f, _c, n, _t in self.udfs]}]"


class LogicalGenerate(LogicalPlan):
    """Generator (explode/posexplode) appending generated columns to the
    child's rows — reference GpuGenerateExec (GpuGenerateExec.scala:829).
    Runs on the CPU path by placement (array inputs; plan/collections.py)."""

    def __init__(self, generator, child: LogicalPlan,
                 output_names: Sequence[str] = ()):
        super().__init__(child)
        self.generator = generator
        self.output_names = list(output_names)

    def _resolve_schema(self):
        bound = self.generator.bind(self.child.schema)
        fields = list(self.child.schema.fields)
        gen_fields = bound.output_fields()
        names = self.output_names or [f.name for f in gen_fields]
        for f, n in zip(gen_fields, names):
            fields.append(t.StructField(n, f.data_type, f.nullable))
        return t.StructType(fields)

    def describe(self):
        return f"Generate[{self.generator!r}]"


class LogicalFlatMapGroupsInPandas(LogicalPlan):
    """groupBy(keys).applyInPandas(fn, schema) — reference
    GpuFlatMapGroupsInPandasExec."""

    def __init__(self, key_names, fn, schema, child: LogicalPlan):
        super().__init__(child)
        self.key_names = list(key_names)
        self.fn = fn
        self.result_schema = schema

    def _resolve_schema(self):
        return self.result_schema

    def describe(self):
        return (f"FlatMapGroupsInPandas[{self.key_names}, "
                f"{getattr(self.fn, '__name__', 'fn')}]")


class LogicalAggregateInPandas(LogicalPlan):
    """groupBy(keys).agg(pandas UDAFs) — reference
    GpuAggregateInPandasExec.  aggs: (fn, in_cols, name, dtype)."""

    def __init__(self, key_names, aggs, child: LogicalPlan):
        super().__init__(child)
        self.key_names = list(key_names)
        self.aggs = list(aggs)

    def _resolve_schema(self):
        schema = self.child.schema
        fields = [schema.fields[schema.field_index(n)]
                  for n in self.key_names]
        for _fn, _cols, name, dt in self.aggs:
            fields.append(t.StructField(name, dt, True))
        return t.StructType(fields)

    def describe(self):
        return f"AggregateInPandas[{[n for _f, _c, n, _t in self.aggs]}]"


class LogicalWindowInPandas(LogicalPlan):
    """Pandas window UDFs over unbounded partition frames — reference
    GpuWindowInPandasExec.  windows: (fn, in_cols, name, dtype)."""

    def __init__(self, partition_names, order_names, windows,
                 child: LogicalPlan):
        super().__init__(child)
        self.partition_names = list(partition_names)
        self.order_names = list(order_names)
        self.windows = list(windows)

    def _resolve_schema(self):
        fields = list(self.child.schema.fields)
        for _fn, _cols, name, dt in self.windows:
            fields.append(t.StructField(name, dt, True))
        return t.StructType(fields)

    def describe(self):
        return f"WindowInPandas[{[n for _f, _c, n, _t in self.windows]}]"


class LogicalFlatMapCoGroupsInPandas(LogicalPlan):
    """cogroup(l.groupBy(keys), r.groupBy(keys)).applyInPandas(fn, schema)
    — fn maps each key's (left DataFrame, right DataFrame) pair to a
    result DataFrame (reference GpuFlatMapCoGroupsInPandasExec)."""

    def __init__(self, left_keys, right_keys, fn, schema,
                 left: LogicalPlan, right: LogicalPlan):
        super().__init__(left, right)
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.fn = fn
        self.result_schema = schema

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]

    def _resolve_schema(self):
        return self.result_schema

    def describe(self):
        return (f"FlatMapCoGroupsInPandas[{self.left_keys}, "
                f"{getattr(self.fn, '__name__', 'fn')}]")
