"""udf-compiler: translate plain Python row functions into Expression
trees so "UDF" queries run fully on device.

Role of the reference's udf-compiler module (SURVEY §2.8): it decompiles
Scala lambda JVM bytecode (javassist CodeIterator, LambdaReflection.scala)
into a CFG (CFG.scala), symbolically executes basic blocks (State.scala)
and emits Catalyst expressions (CatalystExpressionBuilder.scala), falling
back to the JVM UDF when untranslatable.  The Python-native analogue
symbolically executes the function's bytecode (`dis`):

- values on the symbolic stack are Expression trees
- conditional jumps fork execution down both paths; each path runs to a
  RETURN and the fork folds into If(cond, then, else) — this one rule
  covers ``and``/``or``, ternaries, and if/elif/else statement chains
- arithmetic, comparisons, abs/min/max, math module fns, string methods
  (upper/lower/strip/startswith/endswith), ``x is None``/``is not None``
  (IsNull/IsNotNull), and ``in`` over literal tuples translate directly
- loops, attribute writes, non-literal globals, truthiness of non-boolean
  values -> UntranslatableUDF, and `udf()` falls back to the row-based
  PythonUDF host path exactly as the reference falls back to the JVM UDF

The compiled tree inherits the engine's whole-operator jit tracing, so a
translated UDF fuses into the surrounding XLA program — zero per-row or
per-kernel overhead.
"""
from __future__ import annotations

import dis
import math
import sys
from typing import Callable, List, Optional, Sequence

from .. import types as t
from . import expressions as E
from . import strings as S


class UntranslatableUDF(Exception):
    """Raised when bytecode uses features with no Expression analogue."""


_MAX_FORKS = 64


class _Callable:
    """Marker for a resolved callable sitting on the symbolic stack."""

    def __init__(self, name: str, self_expr=None):
        self.name = name
        self.self_expr = self_expr


class _Null:
    """CPython 3.11+ NULL stack sentinel."""


_BINARY = {
    "+": E.Add, "-": E.Subtract, "*": E.Multiply, "/": E.Divide,
    "//": E.IntegralDivide, "%": E.Remainder, "**": E.Pow,
}
# CPython 3.11 folded the per-operator opcodes into BINARY_OP; 3.10
# still emits one opcode per operator (and the INPLACE_ twins for
# augmented assignment, which on immutable Expression values are the
# same pure operation).
_LEGACY_BINOPS = {
    "BINARY_ADD": "+", "BINARY_SUBTRACT": "-", "BINARY_MULTIPLY": "*",
    "BINARY_TRUE_DIVIDE": "/", "BINARY_FLOOR_DIVIDE": "//",
    "BINARY_MODULO": "%", "BINARY_POWER": "**",
    "INPLACE_ADD": "+", "INPLACE_SUBTRACT": "-", "INPLACE_MULTIPLY": "*",
    "INPLACE_TRUE_DIVIDE": "/", "INPLACE_FLOOR_DIVIDE": "//",
    "INPLACE_MODULO": "%", "INPLACE_POWER": "**",
}
# 3.11+ oparg low bits carry push-NULL flags on LOAD_GLOBAL/LOAD_ATTR;
# on 3.10 the arg is a plain name index and must not be bit-tested.
_PY311 = sys.version_info >= (3, 11)
_COMPARE = {
    "==": E.EqualTo, "!=": E.NotEqual, "<": E.LessThan,
    "<=": E.LessThanOrEqual, ">": E.GreaterThan, ">=": E.GreaterThanOrEqual,
}
_GLOBAL_FNS = {
    "abs": lambda a: E.Abs(a),
    "min": lambda *a: E.Least(*a),
    "max": lambda *a: E.Greatest(*a),
}
_MATH_FNS = {
    "sqrt": E.Sqrt, "exp": E.Exp, "log": E.Log, "log10": E.Log10,
    "log2": E.Log2, "sin": E.Sin, "cos": E.Cos, "tan": E.Tan,
    "asin": E.Asin, "acos": E.Acos, "atan": E.Atan, "sinh": E.Sinh,
    "cosh": E.Cosh, "tanh": E.Tanh, "floor": E.Floor, "ceil": E.Ceil,
    "atan2": E.Atan2, "pow": E.Pow,
}
_STR_METHODS = {
    "upper": lambda s: S.Upper(s),
    "lower": lambda s: S.Lower(s),
    "strip": lambda s: S.StringTrim(s),
    "lstrip": lambda s: S.StringTrimLeft(s),
    "rstrip": lambda s: S.StringTrimRight(s),
    "startswith": lambda s, p: S.StartsWith(s, _lit_str(p)),
    "endswith": lambda s, p: S.EndsWith(s, _lit_str(p)),
}
_MATH_CONSTS = {"pi": math.pi, "e": math.e, "inf": math.inf,
                "nan": math.nan}


def _lit_str(e) -> str:
    if isinstance(e, E.Literal) and isinstance(e.value, str):
        return e.value
    raise UntranslatableUDF("string-method argument must be a literal")


def _as_literal(v) -> E.Expression:
    if isinstance(v, (bool, int, float, str)):
        return E.Literal(v)
    raise UntranslatableUDF(f"unsupported constant {v!r}")


def _as_bool(e: E.Expression, schema: t.StructType) -> E.Expression:
    """Conditions must already be boolean (no silent truthiness)."""
    try:
        dt = e.bind(schema).dtype
    except Exception as ex:               # noqa: BLE001
        raise UntranslatableUDF(f"cannot type condition: {ex}") from ex
    if not isinstance(dt, t.BooleanType):
        raise UntranslatableUDF(
            f"non-boolean truthiness ({dt}) — write an explicit comparison")
    return e


class _Compiler:
    def __init__(self, fn: Callable, args: Sequence[E.Expression],
                 schema: t.StructType):
        self.fn = fn
        code = fn.__code__
        if code.co_argcount != len(args):
            raise UntranslatableUDF(
                f"{fn.__name__} takes {code.co_argcount} args, "
                f"{len(args)} given")
        self.locals0 = {code.co_varnames[i]: args[i]
                        for i in range(len(args))}
        self.instrs: List[dis.Instruction] = list(dis.get_instructions(fn))
        self.by_offset = {ins.offset: i
                          for i, ins in enumerate(self.instrs)}
        self.schema = schema
        self.forks = 0

    def run(self) -> E.Expression:
        return self._exec(0, [], dict(self.locals0))

    # -- the symbolic interpreter ------------------------------------------

    def _exec(self, i: int, stack: list, lcls: dict) -> E.Expression:
        while i < len(self.instrs):
            ins = self.instrs[i]
            op = ins.opname
            if op in ("RESUME", "CACHE", "PRECALL", "NOP", "EXTENDED_ARG",
                      "MAKE_CELL", "COPY_FREE_VARS"):
                pass
            elif op in ("LOAD_FAST", "LOAD_FAST_CHECK",
                        "LOAD_FAST_AND_CLEAR"):
                if ins.argval not in lcls:
                    raise UntranslatableUDF(
                        f"read of unassigned local {ins.argval}")
                stack.append(lcls[ins.argval])
            elif op == "STORE_FAST":
                lcls[ins.argval] = stack.pop()
            elif op == "LOAD_CONST":
                v = ins.argval
                if v is None or isinstance(v, (tuple, frozenset)):
                    stack.append(v)        # for IS_OP / CONTAINS_OP
                else:
                    stack.append(_as_literal(v))
            elif op == "RETURN_CONST":
                v = ins.argval
                if v is None:
                    raise UntranslatableUDF("returning None")
                return _as_literal(v)
            elif op == "RETURN_VALUE":
                v = stack.pop()
                if not isinstance(v, E.Expression):
                    raise UntranslatableUDF(f"returning {v!r}")
                return v
            elif op == "LOAD_GLOBAL":
                if _PY311 and ins.arg & 1:   # 3.11+: pushes NULL too
                    stack.append(_Null())
                name = ins.argval
                if name in _GLOBAL_FNS:
                    stack.append(_Callable(name))
                elif name == "math":
                    stack.append(_Callable("__module_math__"))
                else:
                    glb = self.fn.__globals__.get(name)
                    if isinstance(glb, (bool, int, float, str)):
                        stack.append(_as_literal(glb))
                    elif glb is math:
                        stack.append(_Callable("__module_math__"))
                    else:
                        raise UntranslatableUDF(f"global {name!r}")
            elif op in ("LOAD_ATTR", "LOAD_METHOD"):
                obj = stack.pop()
                name = ins.argval
                if isinstance(obj, _Callable) and \
                        obj.name == "__module_math__":
                    if name in _MATH_FNS:
                        stack.append(_Callable(f"math.{name}"))
                        if _PY311 and not (op == "LOAD_ATTR"
                                           and not (ins.arg & 1)):
                            stack.append(_Null())
                    elif name in _MATH_CONSTS:
                        stack.append(E.Literal(_MATH_CONSTS[name]))
                    else:
                        raise UntranslatableUDF(f"math.{name}")
                elif isinstance(obj, E.Expression) and name in _STR_METHODS:
                    stack.append(_Callable(name, self_expr=obj))
                    if _PY311 and op == "LOAD_ATTR" and (ins.arg & 1):
                        stack.append(_Null())
                else:
                    raise UntranslatableUDF(f"attribute {name!r}")
            elif op == "PUSH_NULL":
                stack.append(_Null())
            elif op == "CALL":
                n = ins.arg
                args = stack[len(stack) - n:]
                del stack[len(stack) - n:]
                frame = []
                while stack and not isinstance(stack[-1], _Callable):
                    top = stack.pop()
                    if isinstance(top, _Null):
                        continue
                    frame.append(top)
                if not stack:
                    raise UntranslatableUDF("call of non-callable")
                fn = stack.pop()
                if stack and isinstance(stack[-1], _Null):
                    stack.pop()
                if frame:                  # bound self pushed after fn
                    args = frame[::-1] + args
                stack.append(self._call(fn, args))
            elif op in ("CALL_FUNCTION", "CALL_METHOD"):
                # 3.10 call shape: [callable, (NULL,) args...]; the
                # bound self (string methods) lives inside _Callable
                n = ins.arg
                args = stack[len(stack) - n:]
                del stack[len(stack) - n:]
                while stack and isinstance(stack[-1], _Null):
                    stack.pop()
                if not stack or not isinstance(stack[-1], _Callable):
                    raise UntranslatableUDF("call of non-callable")
                stack.append(self._call(stack.pop(), args))
            elif op == "BINARY_OP":
                rhs, lhs = stack.pop(), stack.pop()
                sym = ins.argrepr.rstrip("=")
                if ins.argrepr.endswith("=") and \
                        ins.argrepr not in ("<=", ">=", "==", "!="):
                    sym = ins.argrepr[:-1]     # in-place += etc.
                cls = _BINARY.get(sym)
                if cls is None:
                    raise UntranslatableUDF(f"operator {ins.argrepr!r}")
                stack.append(cls(lhs, rhs))
            elif op in _LEGACY_BINOPS:
                rhs, lhs = stack.pop(), stack.pop()
                stack.append(_BINARY[_LEGACY_BINOPS[op]](lhs, rhs))
            elif op == "COMPARE_OP":
                rhs, lhs = stack.pop(), stack.pop()
                sym = ins.argval if isinstance(ins.argval, str) \
                    else ins.argrepr
                sym = sym.replace(" ", "")
                cls = _COMPARE.get(sym)
                if cls is None:
                    raise UntranslatableUDF(f"comparison {sym!r}")
                stack.append(cls(lhs, rhs))
            elif op == "IS_OP":
                rhs, lhs = stack.pop(), stack.pop()
                if rhs is not None and lhs is not None:
                    raise UntranslatableUDF("is only supports None")
                expr = lhs if rhs is None else rhs
                stack.append(E.IsNotNull(expr) if ins.arg
                             else E.IsNull(expr))
            elif op == "CONTAINS_OP":
                container, needle = stack.pop(), stack.pop()
                items = self._literal_tuple(container)
                res = E.In(needle, items)
                stack.append(E.Not(res) if ins.arg else res)
            elif op == "UNARY_NEGATIVE":
                stack.append(E.UnaryMinus(stack.pop()))
            elif op == "UNARY_NOT":
                stack.append(E.Not(_as_bool(stack.pop(), self.schema)))
            elif op == "TO_BOOL":
                stack[-1] = _as_bool(stack[-1], self.schema)
            elif op == "POP_TOP":
                stack.pop()
            elif op == "COPY":
                stack.append(stack[-ins.arg])
            elif op == "DUP_TOP":
                stack.append(stack[-1])
            elif op == "SWAP":
                stack[-1], stack[-ins.arg] = stack[-ins.arg], stack[-1]
            elif op == "ROT_TWO":
                stack[-1], stack[-2] = stack[-2], stack[-1]
            elif op == "ROT_THREE":
                stack[-1], stack[-2], stack[-3] = \
                    stack[-2], stack[-3], stack[-1]
            elif op in ("JUMP_FORWARD", "JUMP_BACKWARD_NO_INTERRUPT"):
                i = self.by_offset[ins.argval]
                continue
            elif op == "JUMP_BACKWARD":
                raise UntranslatableUDF("loops are not translatable")
            elif op == "JUMP_ABSOLUTE":
                # 3.10 spells both loop back-edges and if/else merges as
                # absolute jumps; only the backward ones are loops
                tgt = self.by_offset[ins.argval]
                if tgt <= i:
                    raise UntranslatableUDF("loops are not translatable")
                i = tgt
                continue
            elif op in ("JUMP_IF_TRUE_OR_POP", "JUMP_IF_FALSE_OR_POP"):
                # 3.10/3.11 and/or in value position: the jump path keeps
                # the condition as the expression value
                self.forks += 1
                if self.forks > _MAX_FORKS:
                    raise UntranslatableUDF("too many branches")
                tgt = self.by_offset[ins.argval]
                if tgt <= i:
                    raise UntranslatableUDF("loops are not translatable")
                cond = _as_bool(stack.pop(), self.schema)
                taken = self._exec(tgt, list(stack) + [cond], dict(lcls))
                fallthrough = self._exec(i + 1, list(stack), dict(lcls))
                if op == "JUMP_IF_TRUE_OR_POP":
                    return E.If(cond, taken, fallthrough)
                return E.If(cond, fallthrough, taken)
            elif op in ("POP_JUMP_IF_FALSE", "POP_JUMP_IF_TRUE",
                        "POP_JUMP_IF_NONE", "POP_JUMP_IF_NOT_NONE"):
                self.forks += 1
                if self.forks > _MAX_FORKS:
                    raise UntranslatableUDF("too many branches")
                if self.by_offset[ins.argval] <= i:
                    raise UntranslatableUDF("loops are not translatable")
                raw = stack.pop()
                if op.endswith("_NONE"):
                    cond = E.IsNull(raw) if op.endswith("IF_NONE") \
                        else E.IsNotNull(raw)
                    jump_when = True
                else:
                    cond = _as_bool(raw, self.schema)
                    jump_when = op == "POP_JUMP_IF_TRUE"
                tgt = self.by_offset[ins.argval]
                taken = self._exec(tgt, list(stack), dict(lcls))
                fallthrough = self._exec(i + 1, list(stack), dict(lcls))
                if jump_when:
                    return E.If(cond, taken, fallthrough)
                return E.If(cond, fallthrough, taken)
            else:
                raise UntranslatableUDF(f"opcode {op}")
            i += 1
        raise UntranslatableUDF("fell off the end of the bytecode")

    def _literal_tuple(self, container) -> list:
        if isinstance(container, E.Literal):
            container = container.value
        if isinstance(container, (tuple, list, frozenset, set)):
            return list(container)
        raise UntranslatableUDF("`in` requires a literal tuple/list")

    def _call(self, fn: _Callable, args: list) -> E.Expression:
        if fn.self_expr is not None:       # string method
            m = _STR_METHODS[fn.name]
            return m(fn.self_expr, *args)
        if fn.name in _GLOBAL_FNS:
            return _GLOBAL_FNS[fn.name](*args)
        if fn.name.startswith("math."):
            return _MATH_FNS[fn.name[5:]](*args)
        raise UntranslatableUDF(f"call to {fn.name}")


def compile_udf(fn: Callable, args: Sequence[E.Expression],
                schema: Optional[t.StructType] = None) -> E.Expression:
    """Translate `fn`'s bytecode applied to `args` into an Expression.
    `schema` types the arguments for boolean-condition checking (pass the
    input schema when args contain ColumnRefs)."""
    schema = schema or t.StructType([])
    return _Compiler(fn, args, schema).run()


def udf(fn: Callable, return_type: t.DataType,
        *args: E.Expression, schema: Optional[t.StructType] = None
        ) -> E.Expression:
    """Compile fn to a device expression; fall back to the row-based
    PythonUDF host path when untranslatable (the reference's
    udf-compiler -> JVM-UDF fallback)."""
    try:
        return compile_udf(fn, args, schema)
    except UntranslatableUDF:
        from .udf import PythonUDF
        return PythonUDF(fn, return_type, *args)
