"""Window function specs — the GpuWindowExpression / frame model.

Reference: window/GpuWindowExpression.scala translates Spark window specs
(partition keys, order keys, frame boundaries) into cuDF RollingAggregation
windows; five exec variants pick scan-based/batched strategies
(GpuWindowExec.scala:146, GpuRunningWindowExec.scala:220).

TPU-first realization: a window is a *segmented scan/reduce over the
partition-sorted batch* — running frames are segmented prefix scans
(`lax.associative_scan` with boundary resets), unbounded frames are segment
reductions broadcast back to rows, and bounded ROWS frames are prefix-sum
differences (sum/count/avg) or static shift-stacks (min/max).  One jit
program evaluates every window expression of an operator in a single
dispatch (ops/window.py).

Frames follow Spark semantics:
  * explicit ROWS BETWEEN a AND b — offsets relative to the current row
    (negative = preceding), None = unbounded in that direction;
  * explicit RANGE supports the UNBOUNDED/CURRENT-ROW shapes AND literal
    value offsets (RANGE BETWEEN x PRECEDING AND y FOLLOWING) over a
    single integer-lane order key — bounds found by a merge-rank sort
    per side, min/max answered from a sparse table (the
    GpuBatchedBoundedWindowExec.scala:220 role);
  * default frame: RANGE UNBOUNDED PRECEDING..CURRENT ROW when order keys
    exist (includes peer rows), else the whole partition.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from .. import types as t
from ..config import TpuConf
from . import expressions as E


UNBOUNDED = None      # frame bound sentinel
CURRENT = 0


@dataclasses.dataclass(frozen=True)
class WindowFrame:
    """kind: "rows" | "range"; lower/upper: int offset or None (unbounded).
    RANGE offsets are VALUE deltas on the single order key (0 = current
    peer group); ROWS offsets are row counts."""
    kind: str = "range"
    lower: Optional[int] = UNBOUNDED
    upper: Optional[int] = CURRENT

    def fp(self) -> str:
        return f"{self.kind}:{self.lower}:{self.upper}"

    @property
    def is_unbounded_both(self) -> bool:
        return self.lower is None and self.upper is None

    @property
    def is_running(self) -> bool:
        return self.lower is None and self.upper == 0

    @property
    def is_value_offset(self) -> bool:
        """RANGE frame with at least one literal value offset bound."""
        return self.kind == "range" and (
            self.lower not in (None, 0) or self.upper not in (None, 0))


def default_frame(has_order: bool) -> WindowFrame:
    return WindowFrame("range", UNBOUNDED, CURRENT if has_order else UNBOUNDED)


# Shift-stack bound for bounded-frame min/max (each offset is one shifted
# candidate lane at trace time; beyond this the program gets too large).
MINMAX_FRAME_CAP = 256


class WindowFunctionSpec:
    """Base window function.  Subclasses declare their input expression
    (or None), result type, and the kernel kind ops/window.py dispatches on."""
    name = "window_fn"
    kind = None                  # ops/window.py dispatch tag
    needs_order = False

    def __init__(self, child: Optional[E.Expression] = None,
                 frame: Optional[WindowFrame] = None):
        self.child = child
        self.frame = frame       # None -> default frame at exec time

    def bind(self, schema: t.StructType) -> "WindowFunctionSpec":
        import copy
        b = copy.copy(self)
        if self.child is not None:
            b.child = self.child.bind(schema)
        b._resolve()
        return b

    def _resolve(self):
        self.dtype = self.result_type(None)

    def result_type(self, schema) -> t.DataType:
        raise NotImplementedError

    def inputs(self) -> List[E.Expression]:
        return [] if self.child is None else [self.child]

    def fingerprint(self) -> str:
        fr = self.frame.fp() if self.frame is not None else "default"
        kid = self.child.fingerprint() if self.child is not None else ""
        return f"{type(self).__name__}({self._fp_extra()};{fr};{kid})"

    def _fp_extra(self) -> str:
        return ""

    def unsupported_reasons(self, conf: TpuConf) -> List[str]:
        out = []
        if self.child is not None:
            out += self.child.tree_unsupported(conf)
            if isinstance(self.child.dtype, (t.ArrayType, t.StructType,
                                             t.MapType, t.BinaryType)):
                out.append(f"{self.name} over "
                           f"{self.child.dtype.simple_string}")
        if self.frame is not None:
            f = self.frame
            # value-offset RANGE frames are supported on device (merge-
            # rank bounds over the single int-lane order key); the
            # order-key shape check lives in WindowMeta, which sees the
            # order keys
            if f.lower is not None and f.upper is not None and \
                    f.lower > f.upper:
                out.append("frame lower bound above upper bound")
        return out

    def __repr__(self):
        return self.fingerprint()


# ---------------------------------------------------------------------------
# Ranking family (frame-less; operate on partition/peer structure)
# ---------------------------------------------------------------------------

class RowNumber(WindowFunctionSpec):
    name = "row_number"
    kind = "row_number"
    needs_order = True

    def result_type(self, schema):
        return t.INT


class Rank(WindowFunctionSpec):
    name = "rank"
    kind = "rank"
    needs_order = True

    def result_type(self, schema):
        return t.INT


class DenseRank(WindowFunctionSpec):
    name = "dense_rank"
    kind = "dense_rank"
    needs_order = True

    def result_type(self, schema):
        return t.INT


class PercentRank(WindowFunctionSpec):
    name = "percent_rank"
    kind = "percent_rank"
    needs_order = True

    def result_type(self, schema):
        return t.DOUBLE


class CumeDist(WindowFunctionSpec):
    name = "cume_dist"
    kind = "cume_dist"
    needs_order = True

    def result_type(self, schema):
        return t.DOUBLE


class NTile(WindowFunctionSpec):
    name = "ntile"
    kind = "ntile"
    needs_order = True

    def __init__(self, n: int):
        super().__init__(None)
        assert n >= 1
        self.n = n

    def _fp_extra(self):
        return str(self.n)

    def result_type(self, schema):
        return t.INT


# ---------------------------------------------------------------------------
# Offset family
# ---------------------------------------------------------------------------

class Lead(WindowFunctionSpec):
    """lead(expr, offset, default) — value `offset` rows after the current
    row within the partition, `default` (literal) outside it."""
    name = "lead"
    kind = "lead"
    needs_order = True
    _sign = 1

    def __init__(self, child: E.Expression, offset: int = 1, default=None):
        super().__init__(child)
        self.offset = offset
        self.default = default       # python literal or None

    def _fp_extra(self):
        return f"{self.offset};{self.default!r}"

    def result_type(self, schema):
        return self.child.dtype

    def unsupported_reasons(self, conf):
        out = super().unsupported_reasons(conf)
        if self.default is not None and \
                isinstance(self.child.dtype, (t.StringType, t.BinaryType)):
            out.append(f"{self.name} default value over "
                       f"{self.child.dtype.simple_string}")
        return out


class Lag(Lead):
    name = "lag"
    kind = "lag"
    _sign = -1


# ---------------------------------------------------------------------------
# Aggregates over frames
# ---------------------------------------------------------------------------

def _win_sum_type(dt: t.DataType) -> t.DataType:
    if t.is_integral(dt):
        return t.LONG
    if isinstance(dt, (t.FloatType, t.DoubleType)):
        return t.DOUBLE
    if isinstance(dt, t.DecimalType):
        return t.DecimalType(min(38, dt.precision + 10), dt.scale)
    raise TypeError(f"window sum over {dt.simple_string}")


class WinSum(WindowFunctionSpec):
    name = "sum"
    kind = "agg_sum"

    def result_type(self, schema):
        return _win_sum_type(self.child.dtype)

    def unsupported_reasons(self, conf):
        out = super().unsupported_reasons(conf)
        dt = self.child.dtype
        if not (t.is_numeric(dt) or isinstance(dt, t.DecimalType)):
            out.append(f"sum over {dt.simple_string}")
        elif isinstance(dt, t.DecimalType) and \
                _win_sum_type(dt).is_wide:
            out.append("window sum result beyond decimal(18) "
                       "not yet on device")
        return out


class WinCount(WindowFunctionSpec):
    """count(expr) over frame; child None = count(*)/count(1)."""
    name = "count"
    kind = "agg_count"

    def result_type(self, schema):
        return t.LONG


class WinMin(WindowFunctionSpec):
    name = "min"
    kind = "agg_min"

    def result_type(self, schema):
        return self.child.dtype

    def unsupported_reasons(self, conf):
        out = super().unsupported_reasons(conf)
        f = self.frame
        if f is not None and f.kind == "rows" and f.lower is not None \
                and f.upper is not None and \
                (f.upper - f.lower + 1) > MINMAX_FRAME_CAP:
            out.append(f"bounded min/max frame wider than {MINMAX_FRAME_CAP}")
        if isinstance(self.child.dtype, (t.StringType, t.BinaryType)):
            out.append(f"window {self.name} over "
                       f"{self.child.dtype.simple_string} (dictionary codes "
                       "are not value-ordered)")
        return out


class WinMax(WinMin):
    name = "max"
    kind = "agg_max"


class WinAverage(WindowFunctionSpec):
    name = "avg"
    kind = "agg_avg"

    def result_type(self, schema):
        dt = self.child.dtype
        if isinstance(dt, t.DecimalType):
            return t.DecimalType(min(38, dt.precision + 4),
                                 min(38, dt.scale + 4))
        return t.DOUBLE

    def unsupported_reasons(self, conf):
        out = super().unsupported_reasons(conf)
        dt = self.child.dtype
        if not (t.is_numeric(dt) or isinstance(dt, t.DecimalType)):
            out.append(f"avg over {dt.simple_string}")
        elif isinstance(dt, t.DecimalType) and self.result_type(None).is_wide:
            out.append("window avg result beyond decimal(18) "
                       "not yet on device")
        return out


class FirstValue(WindowFunctionSpec):
    """first_value(expr) — value at the frame's first row
    (ignoreNulls=False semantics)."""
    name = "first_value"
    kind = "first_value"

    def result_type(self, schema):
        return self.child.dtype


class LastValue(FirstValue):
    name = "last_value"
    kind = "last_value"


RANKING = (RowNumber, Rank, DenseRank, PercentRank, CumeDist, NTile)
OFFSET = (Lead, Lag)
FRAMED = (WinSum, WinCount, WinMin, WinMax, WinAverage, FirstValue, LastValue)


class WindowAnalysisError(ValueError):
    """Spark AnalysisException analogue for invalid window definitions."""


def check_window_analysis(window_exprs, order_keys) -> None:
    """Structural checks every backend shares (raise, don't fall back —
    Spark rejects these at analysis time)."""
    for spec, _name in window_exprs:
        if spec.needs_order and not order_keys:
            raise WindowAnalysisError(
                f"window function {spec.name}() requires a window "
                "ORDER BY")
