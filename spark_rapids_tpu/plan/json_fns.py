"""JSON expressions (reference GpuGetJsonObject / JNI JSONUtils role).

get_json_object evaluates as a dictionary transform (plan/strings.py
DictTransform): each distinct string parses once on host; device work is
code/validity pass-through.  The JSONPath subset is `$`, `.field`,
`['field']`, `[index]` — wildcards and recursive descent are tagged
unsupported (the transpile-or-reject contract, like the regex engine)."""
from __future__ import annotations

import json
from typing import List, Optional, Tuple, Union

from .. import types as t
from .strings import DictTransform


INVALID_PATH = "INVALID"    # Spark rejects it -> always-NULL, no fallback


def parse_json_path(path: str) -> Union[None, str,
                                        List[Union[str, int]]]:
    """JSONPath -> list of field/index steps; None when outside the
    subset (tagged for fallback); INVALID_PATH when Spark itself rejects
    the path (always-NULL results, no fallback tag)."""
    if not path.startswith("$"):
        return INVALID_PATH
    steps: List[Union[str, int]] = []
    i = 1
    n = len(path)
    while i < n:
        c = path[i]
        if c == ".":
            i += 1
            if i < n and path[i] == ".":
                return None               # recursive descent
            j = i
            while j < n and path[j] not in ".[":
                j += 1
            name = path[i:j]
            if not name or name == "*":
                return None
            steps.append(name)
            i = j
        elif c == "[":
            j = path.find("]", i)
            if j < 0:
                return None
            inner = path[i + 1:j].strip()
            if inner.startswith("'") and inner.endswith("'"):
                steps.append(inner[1:-1])
            elif inner == "*":
                return None
            else:
                try:
                    idx = int(inner)
                except ValueError:
                    return None
                if idx < 0:
                    # Spark's path grammar rejects negative subscripts
                    # (get_json_object returns NULL for them)
                    return INVALID_PATH
                steps.append(idx)
            i = j + 1
        else:
            return None
    return steps


def _render(v) -> Optional[str]:
    """Spark's get_json_object rendering: scalars bare, structures as
    compact JSON, null -> SQL NULL."""
    if v is None:
        return None
    if isinstance(v, str):
        return v
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return json.dumps(v)
    return json.dumps(v, separators=(",", ":"))


class GetJsonObject(DictTransform):
    def __init__(self, child, path: str):
        self.children = (child,)
        self.path = path
        self._steps = parse_json_path(path)

    def unsupported_reasons(self, conf):
        out = super().unsupported_reasons(conf)
        if self._steps is None:
            out.append(f"JSONPath {self.path!r} outside the supported "
                       "subset ($, .field, ['field'], [index])")
        return out

    def _fp_extra(self):
        return repr(self.path)

    def _transform_value(self, s, args):
        if self._steps is None or self._steps == INVALID_PATH:
            return None
        try:
            obj = json.loads(s)
        except (ValueError, TypeError):
            return None
        for step in self._steps:
            if isinstance(step, str):
                if not isinstance(obj, dict) or step not in obj:
                    return None
                obj = obj[step]
            else:
                if not isinstance(obj, list) or step >= len(obj) \
                        or step < -len(obj):
                    return None
                obj = obj[step]
        return _render(obj)
