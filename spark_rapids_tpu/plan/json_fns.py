"""JSON expressions (reference GpuGetJsonObject / JNI JSONUtils role).

get_json_object evaluates as a dictionary transform (plan/strings.py
DictTransform): each distinct string parses once on host; device work is
code/validity pass-through.  The JSONPath subset is `$`, `.field`,
`['field']`, `[index]` — wildcards and recursive descent are tagged
unsupported (the transpile-or-reject contract, like the regex engine)."""
from __future__ import annotations

import json
from typing import List, Optional, Tuple, Union

from .. import types as t
from .expressions import Expression
from .strings import DictTransform


INVALID_PATH = "INVALID"    # Spark rejects it -> always-NULL, no fallback


def parse_json_path(path: str) -> Union[None, str,
                                        List[Union[str, int]]]:
    """JSONPath -> list of field/index steps; None when outside the
    subset (tagged for fallback); INVALID_PATH when Spark itself rejects
    the path (always-NULL results, no fallback tag)."""
    if not path.startswith("$"):
        return INVALID_PATH
    steps: List[Union[str, int]] = []
    i = 1
    n = len(path)
    while i < n:
        c = path[i]
        if c == ".":
            i += 1
            if i < n and path[i] == ".":
                return None               # recursive descent
            j = i
            while j < n and path[j] not in ".[":
                j += 1
            name = path[i:j]
            if not name or name == "*":
                return None
            steps.append(name)
            i = j
        elif c == "[":
            j = path.find("]", i)
            if j < 0:
                return None
            inner = path[i + 1:j].strip()
            if inner.startswith("'") and inner.endswith("'"):
                steps.append(inner[1:-1])
            elif inner == "*":
                return None
            else:
                try:
                    idx = int(inner)
                except ValueError:
                    return None
                if idx < 0:
                    # Spark's path grammar rejects negative subscripts
                    # (get_json_object returns NULL for them)
                    return INVALID_PATH
                steps.append(idx)
            i = j + 1
        else:
            return None
    return steps


def _render(v) -> Optional[str]:
    """Spark's get_json_object rendering: scalars bare, structures as
    compact JSON, null -> SQL NULL."""
    if v is None:
        return None
    if isinstance(v, str):
        return v
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return json.dumps(v)
    return json.dumps(v, separators=(",", ":"))


class GetJsonObject(DictTransform):
    def __init__(self, child, path: str):
        self.children = (child,)
        self.path = path
        self._steps = parse_json_path(path)

    def unsupported_reasons(self, conf):
        out = super().unsupported_reasons(conf)
        if self._steps is None:
            out.append(f"JSONPath {self.path!r} outside the supported "
                       "subset ($, .field, ['field'], [index])")
        return out

    def _fp_extra(self):
        return repr(self.path)

    def _transform_value(self, s, args):
        if self._steps is None or self._steps == INVALID_PATH:
            return None
        try:
            obj = json.loads(s)
        except (ValueError, TypeError):
            return None
        for step in self._steps:
            if isinstance(step, str):
                if not isinstance(obj, dict) or step not in obj:
                    return None
                obj = obj[step]
            else:
                if not isinstance(obj, list) or step >= len(obj) \
                        or step < -len(obj):
                    return None
                obj = obj[step]
        return _render(obj)


# ---------------------------------------------------------------------------
# json_tuple / from_json / to_json (reference GpuJsonTuple,
# GpuJsonToStructs, GpuStructsToJson — JNI JSONUtils/MapUtils role)
# ---------------------------------------------------------------------------

def json_tuple(child, *fields: str):
    """json_tuple(json, f1, ..., fk) as k device-capable projections —
    each field is a top-level GetJsonObject('$.f') dictionary transform,
    so the whole tuple runs on the device path (the reference's
    GpuJsonTuple evaluates all fields in one JNI pass; here each distinct
    json string parses once per field on host, device work is code
    gathers)."""
    return [GetJsonObject(child, f"$.{f}") for f in fields]


class JsonTupleGen:
    """Generator spec (LogicalGenerate) for json_tuple in LATERAL VIEW
    position: one output row per input row with k string columns."""

    def __init__(self, child, fields: List[str]):
        self.child = child
        self.fields = list(fields)
        self.pos = False
        self.outer = False

    def bind(self, schema):
        import copy
        b = copy.copy(self)
        b.child = self.child.bind(schema)
        if not isinstance(b.child.dtype, (t.StringType, t.NullType)):
            raise TypeError("json_tuple requires a string input")
        return b

    def output_fields(self):
        return [t.StructField(f"c{i}", t.STRING, True)
                for i in range(len(self.fields))]

    def __repr__(self):
        return f"json_tuple({self.child!r}, {', '.join(self.fields)})"


class FromJson(Expression):
    """from_json(json, schema) -> STRUCT (Spark JsonToStructs,
    PERMISSIVE mode: malformed rows yield a struct of nulls, null input
    yields null).  Struct values have no device lane — CPU path by
    per-expression tagging, the same contract the reference applies via
    its TypeSig (GpuJsonToStructs allows-nested gating)."""

    def __init__(self, child, schema: t.StructType):
        self.children = (child,)
        self.schema = schema

    def _resolve(self):
        self.dtype = self.schema
        self.nullable = True

    def _fp_extra(self):
        return self.schema.simple_string

    def unsupported_reasons(self, conf):
        return ["STRUCT results have no device lane (CPU path)"]

    def _coerce(self, v, dt):
        import datetime as _dt
        if v is None:
            return None
        try:
            if isinstance(dt, t.StringType):
                return v if isinstance(v, str) else json.dumps(v)
            if isinstance(dt, t.BooleanType):
                return v if isinstance(v, bool) else None
            if t.is_integral(dt):
                # JSON float tokens don't coerce to integral (Spark's
                # Jackson parser rejects them)
                if isinstance(v, bool) or not isinstance(v, int):
                    return None
                return int(v)
            if t.is_floating(dt):
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    return None
                return float(v)
            if isinstance(dt, t.ArrayType):
                if not isinstance(v, list):
                    return None
                return [self._coerce(x, dt.element_type) for x in v]
            if isinstance(dt, t.StructType):
                if not isinstance(v, dict):
                    return None
                return {f.name: self._coerce(v.get(f.name), f.data_type)
                        for f in dt.fields}
        except (ValueError, TypeError):
            return None
        return None

    def _eval_cpu(self, rb, kids):
        import pyarrow as pa
        from ..columnar.host import dtype_to_arrow
        out = []
        for v in kids[0].cast(pa.string()).to_pylist():
            if v is None:
                out.append(None)
                continue
            try:
                obj = json.loads(v)
            except (ValueError, TypeError):
                obj = None
            if not isinstance(obj, dict):
                # PERMISSIVE: corrupt record -> struct of nulls
                out.append({f.name: None for f in self.schema.fields})
                continue
            out.append({f.name: self._coerce(obj.get(f.name), f.data_type)
                        for f in self.schema.fields})
        return pa.array(out, dtype_to_arrow(self.schema))


class ToJson(Expression):
    """to_json(struct) -> json string (Spark StructsToJson): null struct
    -> null; null fields are OMITTED (Spark default ignoreNullFields)."""

    def __init__(self, child):
        self.children = (child,)

    def _resolve(self):
        self.dtype = t.STRING
        self.nullable = True

    def unsupported_reasons(self, conf):
        return ["STRUCT inputs have no device lane (CPU path)"]

    @staticmethod
    def _jsonable(v):
        import datetime as _dt
        import decimal as _dec
        if isinstance(v, dict):
            return {k: ToJson._jsonable(x) for k, x in v.items()
                    if x is not None}
        if isinstance(v, list):
            return [ToJson._jsonable(x) for x in v]
        if isinstance(v, _dec.Decimal):
            return float(v)
        if isinstance(v, (_dt.date, _dt.datetime)):
            return v.isoformat()
        return v

    def _eval_cpu(self, rb, kids):
        import pyarrow as pa
        out = []
        for v in kids[0].to_pylist():
            if v is None:
                out.append(None)
            else:
                out.append(json.dumps(self._jsonable(v),
                                      separators=(",", ":")))
        return pa.array(out, pa.string())
