"""Array/collection expressions (reference collectionOperations.scala,
complexTypeCreator/Extractors).

TPU-first placement decision: device lanes are FLAT (data + validity per
column; no ragged tensors — SURVEY §7 hard part (c)), so array-typed
values live only on the CPU side of the plan.  Every expression here
evaluates through `eval_cpu` over pyarrow and tags itself off-device; the
overrides engine splices the enclosing operator onto the CPU path with
transitions, and downstream scalar results return to the device.  This is
the same per-operator-fallback contract the reference applies to its own
unsupported type/op combinations (GpuOverrides tagging), applied to a
whole type family.

Explode/posexplode (the GpuGenerateExec role) live in exec/host_exec.py
CpuGenerateExec over the LogicalGenerate node.
"""
from __future__ import annotations

from typing import List, Optional

import pyarrow as pa
import pyarrow.compute as pc

from .. import types as t
from .expressions import Expression, Literal

_OFF_DEVICE = ("ARRAY values live on the CPU path (device lanes are flat)")


class ArrayExpression(Expression):
    """Base: CPU-evaluated; never placed on device."""

    def unsupported_reasons(self, conf):
        return [_OFF_DEVICE]

    def eval_dev(self, ctx):          # pragma: no cover - tag prevents this
        raise NotImplementedError(_OFF_DEVICE)


class CreateArray(ArrayExpression):
    """array(e1, e2, ...) — Spark CreateArray."""

    def __init__(self, *items: Expression):
        self.children = tuple(items)

    def _resolve(self):
        et = self.children[0].dtype if self.children else t.NULL
        self.dtype = t.ArrayType(et)
        self.nullable = False

    def _eval_cpu(self, rb, kids):
        n = rb.num_rows
        cols = [k.to_pylist() for k in kids]
        return pa.array([[c[i] for c in cols] for i in range(n)],
                        pa.list_(_arrow_elem(self.dtype)))


def _arrow_elem(dt: t.ArrayType):
    from ..columnar.host import dtype_to_arrow
    return dtype_to_arrow(dt.element_type)


class Size(ArrayExpression):
    """size(array) — Spark: null input -> -1 with legacy conf, null
    otherwise; modern default (spark.sql.legacy.sizeOfNull=false) -> null."""

    def __init__(self, child: Expression):
        self.children = (child,)

    def _resolve(self):
        self.dtype = t.INT
        self.nullable = True

    def _eval_cpu(self, rb, kids):
        return pc.list_value_length(kids[0]).cast(pa.int32())


class GetArrayItem(ArrayExpression):
    """array[idx] (0-based, Spark GetArrayItem): out-of-range -> null."""

    def __init__(self, child: Expression, index: int):
        self.children = (child,)
        self.index = index

    def _resolve(self):
        self.dtype = self.children[0].dtype.element_type
        self.nullable = True

    def _fp_extra(self):
        return str(self.index)

    def _eval_cpu(self, rb, kids):
        out = []
        for v in kids[0].to_pylist():
            if v is None or self.index < 0 or self.index >= len(v):
                out.append(None)
            else:
                out.append(v[self.index])
        from ..columnar.host import dtype_to_arrow
        return pa.array(out, dtype_to_arrow(self.dtype))


class ArrayContains(ArrayExpression):
    """array_contains(arr, value): Spark null semantics — null array ->
    null; no match with nulls present -> null; else false."""

    def __init__(self, child: Expression, value):
        self.children = (child,)
        self.value = value

    def _resolve(self):
        self.dtype = t.BOOLEAN
        self.nullable = True

    def _fp_extra(self):
        return repr(self.value)

    def _eval_cpu(self, rb, kids):
        out = []
        for v in kids[0].to_pylist():
            if v is None:
                out.append(None)
            elif self.value in [x for x in v if x is not None]:
                out.append(True)
            elif any(x is None for x in v):
                out.append(None)
            else:
                out.append(False)
        return pa.array(out, pa.bool_())


class SortArray(ArrayExpression):
    """sort_array(arr, asc): nulls first when ascending, last when
    descending (Spark)."""

    def __init__(self, child: Expression, ascending: bool = True):
        self.children = (child,)
        self.ascending = ascending

    def _resolve(self):
        self.dtype = self.children[0].dtype
        self.nullable = self.children[0].nullable

    def _fp_extra(self):
        return str(self.ascending)

    def _eval_cpu(self, rb, kids):
        out = []
        for v in kids[0].to_pylist():
            if v is None:
                out.append(None)
                continue
            nn = sorted(x for x in v if x is not None)
            nulls = [None] * (len(v) - len(nn))
            if self.ascending:
                out.append(nulls + nn)
            else:
                out.append(list(reversed(nn)) + nulls)
        return pa.array(out, pa.list_(_arrow_elem(self.dtype)))


class ArrayMin(ArrayExpression):
    name = "array_min"
    _pick = staticmethod(min)

    def __init__(self, child: Expression):
        self.children = (child,)

    def _resolve(self):
        self.dtype = self.children[0].dtype.element_type
        self.nullable = True

    def _eval_cpu(self, rb, kids):
        out = []
        for v in kids[0].to_pylist():
            nn = [] if v is None else [x for x in v if x is not None]
            out.append(self._pick(nn) if nn else None)
        from ..columnar.host import dtype_to_arrow
        return pa.array(out, dtype_to_arrow(self.dtype))


class ArrayMax(ArrayMin):
    name = "array_max"
    _pick = staticmethod(max)


class ExplodeGen:
    """Generator spec for LogicalGenerate: explode(col) / posexplode(col).
    (reference GpuGenerateExec generators, GpuGenerateExec.scala:829)."""

    def __init__(self, child: Expression, pos: bool = False,
                 outer: bool = False):
        self.child = child
        self.pos = pos
        self.outer = outer

    def bind(self, schema):
        import copy
        b = copy.copy(self)
        b.child = self.child.bind(schema)
        if not isinstance(b.child.dtype, t.ArrayType):
            raise TypeError(
                f"explode requires an array input, got "
                f"{b.child.dtype.simple_string}")
        return b

    def output_fields(self) -> List[t.StructField]:
        et = self.child.dtype.element_type
        fields = []
        if self.pos:
            # outer rows with null/empty arrays carry a NULL pos
            fields.append(t.StructField("pos", t.INT, self.outer))
        fields.append(t.StructField("col", et, True))
        return fields

    def __repr__(self):
        name = "posexplode" if self.pos else "explode"
        return f"{name}{'_outer' if self.outer else ''}({self.child!r})"


# ---------------------------------------------------------------------------
# Higher-order functions (reference higherOrderFunctions.scala:
# transform/filter/exists with bound-lambda batching)
# ---------------------------------------------------------------------------

class LambdaVar(Expression):
    """The lambda-bound element variable inside a higher-order body —
    resolves against the synthetic one-column schema the parent builds."""

    def __init__(self, name: str = "x"):
        self.children = ()
        self.name = name

    def bind(self, schema):
        import copy
        b = copy.copy(self)
        f = schema[self.name]
        b.dtype = f.data_type
        b.nullable = f.nullable
        return b

    def _fp_extra(self):
        return self.name

    def _eval_cpu(self, rb, kids):
        return rb.column(rb.schema.names.index(self.name))


class _HigherOrder(ArrayExpression):
    """Base: flatten every row's elements into ONE batch, evaluate the
    lambda body over it vectorized (the reference's bound-lambda batching,
    higherOrderFunctions.scala), then reassemble per-row results.  Outer
    column references inside the body are not supported (tagged)."""

    def __init__(self, arr: Expression, body: Expression, var: str = "x"):
        self.children = (arr,)
        self.body = body
        self.var = var

    def bind(self, schema):
        import copy
        b = copy.copy(self)
        b.children = tuple(c.bind(schema) for c in self.children)
        elem = b.children[0].dtype.element_type
        lam_schema = t.StructType([t.StructField(b.var, elem, True)])
        b.body = b.body.bind(lam_schema)
        b._resolve()
        return b

    def _fp_extra(self):
        return f"{self.var};{self.body.fingerprint()}"

    def unsupported_reasons(self, conf):
        return [_OFF_DEVICE]

    def _flat_eval(self, kids):
        """(lists, flat body results) for the single array child."""
        lists = kids[0].to_pylist()
        flat = [v for row in lists if row is not None for v in row]
        from ..columnar.host import dtype_to_arrow
        elem_t = _arrow_elem(self.children[0].dtype)
        rb = pa.RecordBatch.from_arrays([pa.array(flat, elem_t)],
                                        names=[self.var])
        out = self.body.eval_cpu(rb)
        if isinstance(out, pa.ChunkedArray):
            out = out.combine_chunks()
        if isinstance(out, pa.Scalar):
            out = pa.array([out.as_py()] * rb.num_rows, out.type)
        return lists, out.to_pylist()


class ArrayTransform(_HigherOrder):
    """transform(arr, x -> body)."""

    def _resolve(self):
        self.dtype = t.ArrayType(self.body.dtype)
        self.nullable = self.children[0].nullable

    def _eval_cpu(self, rb, kids):
        lists, flat = self._flat_eval(kids)
        from ..columnar.host import dtype_to_arrow
        out, i = [], 0
        for row in lists:
            if row is None:
                out.append(None)
            else:
                out.append(flat[i:i + len(row)])
                i += len(row)
        return pa.array(out, pa.list_(dtype_to_arrow(self.body.dtype)))


class ArrayFilter(_HigherOrder):
    """filter(arr, x -> predicate)."""

    def _resolve(self):
        self.dtype = self.children[0].dtype
        self.nullable = self.children[0].nullable

    def _eval_cpu(self, rb, kids):
        lists, flat = self._flat_eval(kids)
        out, i = [], 0
        for row in lists:
            if row is None:
                out.append(None)
            else:
                keep = flat[i:i + len(row)]
                i += len(row)
                out.append([v for v, k in zip(row, keep) if k is True])
        return pa.array(out, pa.list_(_arrow_elem(self.dtype)))


class ArrayExists(_HigherOrder):
    """exists(arr, x -> predicate): Spark three-valued semantics — true if
    any true; else null if any null; else false."""
    _default = False
    _hit = True

    def _resolve(self):
        self.dtype = t.BOOLEAN
        self.nullable = True

    def _eval_cpu(self, rb, kids):
        lists, flat = self._flat_eval(kids)
        out, i = [], 0
        for row in lists:
            if row is None:
                out.append(None)
                continue
            vals = flat[i:i + len(row)]
            i += len(row)
            if self._hit in [bool(v) if v is not None else None
                             for v in vals]:
                out.append(self._hit)
            elif any(v is None for v in vals):
                out.append(None)
            else:
                out.append(self._default)
        return pa.array(out, pa.bool_())


class ArrayForAll(ArrayExists):
    """forall(arr, x -> predicate): false if any false; else null if any
    null; else true — the _hit/_default inversion of exists."""
    _default = True
    _hit = False
